#!/usr/bin/env python
"""Render a run's observability event log into a summary.

Reads the JSONL event log written by the monitor / observability layer
(``events.jsonl``: scalar rows ``{"tag", "value", "step"}`` plus
structured rows ``{"event", ...}`` — schema pinned by
tests/unit/test_monitor.py) and prints the run report:

- step-time p50/p95, samples/s
- model FLOPs per step, MFU
- comm bytes per step & compression ratio
- recompile count (+ per-function compile wall time)
- host overhead (async step pipeline): dispatches per step, forced
  host syncs, host-gap time — flagged when the host gap exceeds a
  threshold fraction of step time (--host-gap-threshold)
- memory watermarks (peak / last in-use)
- checkpoint events (saves / loads / fallbacks)
- elastic plane: snapshot-vs-write split of async saves, writer
  backlog, supervisor restart count, and the
  preemption -> relaunch -> resume chain from the event rows
- serving section (inference-engine runs): requests, TTFT p50/p95,
  per-token latency p50/p95, tokens/s, slot occupancy, queue depth
- serving SLO section (``--serve`` renders it standalone): queue-wait /
  TTFT / TBT p50/p95/p99, SLO attainment, goodput vs raw throughput,
  evictions, and the page-pool / prefix-cache snapshot from the last
  ``serve_state`` event
- health section (``--health`` renders the postmortem standalone):
  numeric-anomaly alerts by pinned reason, the watchdog's stall
  diagnosis (last phase + flight.json location), black-box dump trail
- loss trajectory (first -> last)

``--diff RUN_A RUN_B`` compares two runs metric-by-metric (step-time
p50/p95, samples/s, MFU, goodput, recompiles, health alerts/stalls)
with threshold-based REGRESSED / IMPROVED / OK verdicts and exits
nonzero on any regression — the bench-trajectory regression gate.

``--fleet DIR [DIR ...]`` merges one fleet run's router log plus its
replica logs into per-request end-to-end timelines: replica rows are
moved onto the router's clock via the recorded ``clock_sync`` offsets,
each request's hops (router dispatch -> rpc wire -> replica queue ->
prefill -> decode, with any live migrations in between) are stitched
by trace id, and ``--trace-out`` writes the merged Chrome trace with
one process lane per replica.

Usage::

    python tools/obs_report.py <events.jsonl | dir> [--json] [--serve]
                               [--health]
    python tools/obs_report.py --diff RUN_A RUN_B [--json]
    python tools/obs_report.py --fleet DIR [DIR ...] [--json]
                               [--trace-out trace.json]

Rotated event logs (``observability.events_max_mb``) are read as one
stream: ``events.jsonl.1``, ``.2``, ... in sequence order, then the
live file. The ``--json`` output is versioned by a top-level
``"schema"`` key (currently 3 — bumped when existing keys move or
change meaning; additive keys don't bump it), so CI consumers can pin
what they parse.

Pure-stdlib and device-free: runnable on a laptop against a log rsync'd
off a pod. ``summarize()`` is importable for programmatic use (the
tier-1 smoke test drives both the function and the CLI).
"""

import argparse
import json
import math
import os
import sys
from collections import defaultdict

# scalar tags (must match deepspeed_tpu/profiling/__init__.py and
# utils/monitor.py)
T_STEP_MS = "Train/Samples/step_time_ms"
T_SPS = "Train/Samples/samples_per_sec"
T_LOSS = "Train/Samples/train_loss"
T_COMM_BYTES = "Train/Samples/comm_bytes_per_step"
T_COMM_RATIO = "Train/Samples/comm_compression_ratio"
T_FLOPS = "Observability/flops_per_step"
T_BYTES = "Observability/bytes_accessed"
T_MFU = "Observability/mfu"
T_RECOMPILES = "Observability/recompiles"
T_COMPILE_MS = "Observability/compile_ms_total"
T_DISPATCHES = "Observability/dispatches"
T_HOST_SYNCS = "Observability/host_syncs"
T_HOST_GAP = "Observability/host_gap_ms"
T_MEM_PEAK = "Memory/peak_bytes_in_use"
T_MEM_USE = "Memory/bytes_in_use"
# serving telemetry (inference engine; utils/monitor.py
# write_serving_metrics — one ttft row per admitted request, one
# latency/occupancy row per decode step)
T_TTFT = "Serve/ttft_ms"
T_TOK_LAT = "Serve/token_latency_ms"
T_TPS = "Serve/tokens_per_sec"
T_QDEPTH = "Serve/queue_depth"
T_OCC = "Serve/batch_occupancy"
T_KV_PAGES = "Serve/kv_pages_in_use"
T_TOKENS_IN_FLIGHT = "Serve/tokens_in_flight"
T_PREFIX_HIT = "Serve/prefix_hit_rate"
T_DECODE_ATTN = "Serve/decode_attn_path"
# request-granular serving plane (inference/tracing.py): latency
# decomposition + SLO/goodput accounting
T_QUEUE_WAIT = "Serve/queue_wait_ms"
T_TBT = "Serve/tbt_ms"
T_SLO = "Serve/slo_attainment"
T_GOODPUT = "Serve/goodput_tokens_per_s"
# disagg + speculative decoding plane (ISSUE 13): draft acceptance per
# verify dispatch, prefill->decode handoff leg of TTFT
T_SPEC_ACCEPT = "Serve/spec_accept_rate"
T_HANDOFF = "Serve/handoff_ms"
# fleet plane (inference/fleet.py FleetRouter): SLO-shed rate, waiting
# work across replicas, the serving weight ordinal (bumped per swap)
T_SHED_RATE = "Serve/shed_rate"
T_FLEET_QDEPTH = "Serve/fleet_queue_depth"
T_WEIGHT_VERSION = "Serve/weight_version"
# process-fleet plane (ISSUE 16): live KV-page migrations between
# replicas, supervised child relaunches; the `fleet_replica_state` /
# `serve_migration` / `fleet_flight_salvage` event rows carry the
# per-replica process health and per-move details
T_MIGRATIONS = "Serve/migrations"
T_REPLICA_RESTARTS = "Serve/replica_restarts"
# quantized-serving plane (ISSUE 17): static KV pool bytes per token
# of capacity, and the offline quantized-vs-fp max-logit-error probe
T_KV_POOL_BPT = "Serve/kv_pool_bytes_per_token"
T_QUANT_LOGIT_ERR = "Serve/quant_logit_err"
# chunked-prefill plane (ISSUE 19): chunk dispatch counter + per-step
# WORST time-between-tokens (the bound chunked prefill pins); the
# `serve_prefill_chunk` event rows carry the per-chunk detail
T_CHUNK_DISPATCHES = "Serve/chunk_dispatches"
T_TBT_MAX = "Serve/tbt_max_ms"
# elastic / async-checkpoint plane (utils/monitor.py
# write_elastic_metrics): snapshot-vs-write decomposition of each save,
# async writer backlog, supervisor restart count; the `preemption` /
# `resume` event rows carry the drain / relaunch chain
T_CKPT_SNAPSHOT = "Checkpoint/snapshot_ms"
T_CKPT_WRITE = "Checkpoint/write_ms"
T_CKPT_PENDING = "Checkpoint/pending_saves"
T_CKPT_RESTARTS = "Checkpoint/restarts"
# health plane (utils/health.py): cumulative anomaly-alert counter; the
# `health` / `stall_detected` / `flight_dump` event rows carry the
# per-alert reason (pinned HEALTH_REASONS), the watchdog postmortem,
# and the black-box dump locations
T_HEALTH_ALERTS = "Health/alerts"

# --json output schema version: bumped when existing keys move or
# change meaning (additive keys don't bump it). v2 = ISSUE 9 (serving
# SLO section + this key itself); v3 = ISSUE 15 (health + diff
# sections — every v2 key is unchanged).
SCHEMA_VERSION = 3

# host gap above this fraction of step time flags the run: the device
# is waiting on the host often enough to cost real throughput
DEFAULT_HOST_GAP_THRESHOLD = 0.1


def find_events_file(path):
    """Accept the file itself or any directory above it (first match in
    a sorted walk, so runs with one log resolve deterministically)."""
    if os.path.isfile(path):
        return path
    if os.path.isdir(path):
        direct = os.path.join(path, "events.jsonl")
        if os.path.isfile(direct):
            return direct
        for dirpath, _dirnames, filenames in sorted(os.walk(path)):
            if "events.jsonl" in filenames:
                return os.path.join(dirpath, "events.jsonl")
    raise FileNotFoundError(f"no events.jsonl under {path!r}")


def segment_files(path):
    """The event stream's files in write order: rotated segments
    (``events.jsonl.<n>``, numeric order — the ``_JsonlWriter``
    size-rotation scheme) first, the live file last."""
    d = os.path.dirname(os.path.abspath(path))
    base = os.path.basename(path)
    segs = []
    for name in os.listdir(d):
        if name.startswith(base + "."):
            suffix = name[len(base) + 1:]
            if suffix.isdigit():
                segs.append((int(suffix), os.path.join(d, name)))
    return [p for _, p in sorted(segs)] + [path]


def load_events(path):
    """(scalars_by_tag, event_rows): scalars as [(step, value)] per tag,
    malformed lines skipped (a crash can tear the final line). Rotated
    segments are folded in ahead of the live file, so a size-capped
    log reads back as one ordered stream."""
    scalars = defaultdict(list)
    events = []
    for seg in segment_files(path):
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "tag" in row and "value" in row:
                    try:
                        scalars[str(row["tag"])].append(
                            (int(row.get("step", 0)), float(row["value"])))
                    except (TypeError, ValueError):
                        continue
                elif "event" in row:
                    events.append(row)
    return dict(scalars), events


def percentile(values, q):
    """Linear-interpolation percentile (numpy-free)."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = (len(vs) - 1) * q
    lo = math.floor(pos)
    hi = math.ceil(pos)
    return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)


def _vals(scalars, tag):
    return [v for _, v in scalars.get(tag, [])]


def _last(scalars, tag):
    vs = scalars.get(tag)
    return vs[-1][1] if vs else None


def summarize(path, host_gap_threshold=DEFAULT_HOST_GAP_THRESHOLD):
    """The report as a plain dict (``render`` turns it into text)."""
    events_file = find_events_file(path)
    scalars, events = load_events(events_file)

    step_ms = _vals(scalars, T_STEP_MS)
    sps = _vals(scalars, T_SPS)
    loss = _vals(scalars, T_LOSS)
    mfu = _vals(scalars, T_MFU)

    # host overhead (async step pipeline): dispatches is a cumulative
    # counter — the per-step rate is its spread over the steps observed
    dispatches = _vals(scalars, T_DISPATCHES)
    disp_per_step = None
    if len(dispatches) >= 2:
        disp_per_step = ((dispatches[-1] - dispatches[0]) /
                         (len(dispatches) - 1))
    elif dispatches:
        disp_per_step = dispatches[0]
    host_gap = _vals(scalars, T_HOST_GAP)
    gap_p50 = percentile(host_gap, 0.50)
    step_p50 = percentile(step_ms, 0.50)
    gap_fraction = (gap_p50 / step_p50
                    if gap_p50 is not None and step_p50 else None)
    host_flagged = bool(gap_fraction is not None
                        and gap_fraction > host_gap_threshold)

    compile_events = [e for e in events if e.get("event") == "compile"]
    per_fn = defaultdict(lambda: {"count": 0, "wall_ms": 0.0})
    for e in compile_events:
        fn = str(e.get("fn", "?"))
        per_fn[fn]["count"] += 1
        try:
            per_fn[fn]["wall_ms"] += float(e.get("wall_ms", 0.0))
        except (TypeError, ValueError):
            pass
    recompiles = _last(scalars, T_RECOMPILES)
    if recompiles is None and compile_events:
        recompiles = float(len(compile_events))

    mem_peak = _vals(scalars, T_MEM_PEAK)

    # serving section (inference engine runs): p50/p95 latency is the
    # serving headline — step-time percentiles mean nothing to a user
    # waiting on a token
    ttft = _vals(scalars, T_TTFT)
    tok_lat = _vals(scalars, T_TOK_LAT)
    tps = _vals(scalars, T_TPS)
    occ = _vals(scalars, T_OCC)
    qdepth = _vals(scalars, T_QDEPTH)
    queue_wait = _vals(scalars, T_QUEUE_WAIT)
    tbt = _vals(scalars, T_TBT)
    serve_finish = [e for e in events if e.get("event") == "serve_finish"]
    serve_evict = [e for e in events if e.get("event") == "serve_evict"]
    # the last serve_state event is the engine's closing
    # debug_state() snapshot: page pool, prefix cache, SLO histograms
    serve_state = next((e for e in reversed(events)
                        if e.get("event") == "serve_state"), None)

    def pctls(vs):
        return {"p50": percentile(vs, 0.50), "p95": percentile(vs, 0.95),
                "p99": percentile(vs, 0.99)}

    slo_att = _last(scalars, T_SLO)
    goodput = _last(scalars, T_GOODPUT)
    state_slo = (serve_state or {}).get("slo") or {}
    if slo_att is None and state_slo.get("attainment") is not None:
        slo_att = state_slo["attainment"]
    serving = {
        "requests": len(ttft) or len(serve_finish),
        "evictions": len(serve_evict),
        "decode_steps": len(tok_lat),
        # queue_wait/ttft rows are per admitted request (full
        # fidelity); tbt rows are per-dispatch means of that step's
        # per-request TBT samples (the request-exact percentiles live
        # in the serve_state histogram snapshot)
        "queue_wait_ms": pctls(queue_wait),
        "ttft_ms": pctls(ttft),
        "tbt_ms": pctls(tbt),
        "token_latency_ms": pctls(tok_lat),
        "tokens_per_sec": {"last": tps[-1] if tps else None,
                           "best": max(tps) if tps else None},
        "slo": {
            "thresholds": state_slo.get("slo"),
            "attainment": slo_att,
            "goodput_tokens_per_s": goodput,
            "throughput_tokens_per_s": tps[-1] if tps else None,
        },
        "batch_occupancy_mean": (sum(occ) / len(occ)) if occ else None,
        "queue_depth_max": max(qdepth) if qdepth else None,
        "pool": (serve_state or {}).get("page_pool"),
        "histograms": state_slo.get("latency"),
    }
    # paged-KV view (absent on dense-cache runs: no rows, keys -> None)
    pages = _vals(scalars, T_KV_PAGES)
    in_flight = _vals(scalars, T_TOKENS_IN_FLIGHT)
    prefix_hit = _vals(scalars, T_PREFIX_HIT)
    # which decode attention ran (1.0 = pallas paged kernel, 0.0 =
    # gather fallback); the decode_attn_path event row carries the WHY
    attn_path = _vals(scalars, T_DECODE_ATTN)
    attn_event = next((e for e in reversed(events)
                       if e.get("event") == "decode_attn_path"), None)
    serving["paged_kv"] = {
        "pages_in_use_peak": max(pages) if pages else None,
        "tokens_in_flight_peak": max(in_flight) if in_flight else None,
        "prefix_hit_rate": prefix_hit[-1] if prefix_hit else None,
        "decode_attn_path": (
            ("pallas" if attn_path[-1] >= 0.5 else "gather")
            if attn_path else
            (str(attn_event.get("path")) if attn_event else None)),
        "decode_attn_reason": (str(attn_event.get("reason"))
                               if attn_event else None),
    }
    # quantized-serving view (ISSUE 17; absent on fp runs -> None).
    # The pool byte rate is a static gauge; logit error comes from the
    # offline engine.record_quant_logit_err probe, and the serve_state
    # "quantization" block carries the resident-format detail.
    kv_bpt = _vals(scalars, T_KV_POOL_BPT)
    qerr = _vals(scalars, T_QUANT_LOGIT_ERR)
    state_quant = (serve_state or {}).get("quantization") or {}
    serving["quantization"] = {
        "weights_resident": state_quant.get("weights_resident"),
        "kv_dtype": state_quant.get("kv_dtype"),
        "kv_quant_block": state_quant.get("kv_quant_block"),
        "kv_pool_bytes_per_token": (kv_bpt[-1] if kv_bpt else
                                    state_quant.get(
                                        "kv_pool_bytes_per_token")),
        "quant_logit_err": (max(qerr) if qerr else
                            state_quant.get("quant_logit_err")),
        "weight_bytes": state_quant.get("weight_bytes"),
        "weight_bytes_dense": state_quant.get("weight_bytes_dense"),
    }
    # disagg + speculation view (ISSUE 13; absent -> counts 0, keys
    # None). Accept-rate percentiles come from the per-verify-dispatch
    # scalar rows; the serve_state "spec" block carries the lifetime
    # accepted/proposed counters for mean-accepted-per-dispatch.
    spec_rows = _vals(scalars, T_SPEC_ACCEPT)
    handoff_rows = _vals(scalars, T_HANDOFF)
    handoff_events = [e for e in events
                      if e.get("event") == "serve_handoff"]
    spec_windows = [e for e in events
                    if e.get("event") == "serve_spec_window"]
    state_spec = ((serve_state or {}).get("spec")
                  or state_slo.get("spec") or {})
    spec_disp = state_spec.get("dispatches") or len(spec_rows)
    accepted = state_spec.get("accepted")
    serving["speculation"] = {
        "dispatches": spec_disp,
        "proposed": state_spec.get("proposed"),
        "accepted": accepted,
        "accept_rate": {"p50": percentile(spec_rows, 0.50),
                        "p95": percentile(spec_rows, 0.95),
                        "lifetime": state_spec.get("accept_rate")},
        # accepted drafts per verify dispatch; +1 target token always
        # rides on top, so tokens/dispatch = this + 1
        "accepted_per_dispatch": (accepted / spec_disp
                                  if accepted is not None and spec_disp
                                  else None),
        "window_rows": len(spec_windows),
    }
    if not handoff_rows:       # scalar plane absent: use the event rows
        handoff_rows = [float(e["handoff_ms"]) for e in handoff_events
                        if e.get("handoff_ms") is not None]
    serving["disagg"] = {
        "handoffs": ((serve_state or {}).get("handoffs")
                     or state_slo.get("handoffs")
                     or len(handoff_events) or len(handoff_rows)),
        "handoff_ms": {"p50": percentile(handoff_rows, 0.50),
                       "p95": percentile(handoff_rows, 0.95)},
        "requeues": sum(1 for e in events
                        if e.get("event") == "serve_defer"
                        and e.get("reason") == "handoff"),
    }
    # chunked-prefill view (ISSUE 19; absent -> counts 0, keys None).
    # Chunk walls come from the serve_prefill_chunk rows; chunks-per-
    # request from the per-request max chunk ordinal; TBT-max from the
    # per-step worst-TBT scalar (vs the mean in tbt_ms above — the
    # spike a whole-prompt prefill would have caused shows HERE).
    chunk_rows = [e for e in events
                  if e.get("event") == "serve_prefill_chunk"]
    tbt_max_rows = _vals(scalars, T_TBT_MAX)
    chunk_disp = _vals(scalars, T_CHUNK_DISPATCHES)
    per_req: dict = {}
    for e in chunk_rows:
        u = e.get("uid")
        per_req[u] = max(per_req.get(u, 0), int(e.get("chunk", 0)) + 1)
    cpr = sorted(per_req.values())
    walls = [float(e["wall_ms"]) for e in chunk_rows
             if e.get("wall_ms") is not None]
    rejects = sum(1 for e in events
                  if e.get("event") in ("serve_finish", "serve_evict")
                  and e.get("reason") == "reject_too_long")
    serving["chunked_prefill"] = {
        "dispatches": (int(chunk_disp[-1]) if chunk_disp
                       else len(chunk_rows)),
        "chunked_requests": len(per_req),
        "chunks_per_request": {"p50": percentile(cpr, 0.50),
                               "p95": percentile(cpr, 0.95)},
        "chunk_ms": {"p50": percentile(walls, 0.50),
                     "p95": percentile(walls, 0.95)},
        "cp_chunks": sum(1 for e in chunk_rows
                         if int(e.get("cp_shards", 1) or 1) > 1),
        "tbt_max_ms": max(tbt_max_rows) if tbt_max_rows else None,
        "rejected_too_long": rejects,
    }

    # fleet view (multi-replica router; absent on single-engine runs:
    # None). The last fleet_state row is the router's closing
    # debug_state() — per-replica occupancy/status/weight version —
    # and the fleet_shed / fleet_drain / fleet_swap(_push) rows carry
    # the shed ledger and the swap/drain timeline.
    fleet_state = next((e for e in reversed(events)
                        if e.get("event") == "fleet_state"), None)
    shed_rows = [e for e in events if e.get("event") == "fleet_shed"]
    drain_rows = [e for e in events if e.get("event") == "fleet_drain"]
    swap_rows = [e for e in events
                 if e.get("event") in ("fleet_swap", "fleet_swap_push")]
    # process-mode rows (ISSUE 16): per-replica process health snapshots
    # (keep the last per replica), live migrations, deaths/restarts,
    # and salvaged flight recorders
    proc_rows: dict = {}
    for e in events:
        if e.get("event") == "fleet_replica_state":
            proc_rows[e.get("replica")] = e
    mig_rows = [e for e in events
                if e.get("event") == "serve_migration"]
    death_rows = [e for e in events
                  if e.get("event") == "fleet_replica_death"]
    restart_rows = [e for e in events
                    if e.get("event") == "fleet_replica_restart"]
    salvage_rows = [e for e in events
                    if e.get("event") == "fleet_flight_salvage"]
    scale_rows = [e for e in events
                  if e.get("event") == "fleet_autoscale"]
    if fleet_state is not None or shed_rows or drain_rows or swap_rows:
        shed_by_reason = defaultdict(int)
        for e in shed_rows:
            shed_by_reason[str(e.get("reason", "?"))] += 1
        fs_shed = (fleet_state or {}).get("shed") or {}
        timeline = []
        for e in drain_rows:
            timeline.append({"kind": "drain", "phase": e.get("phase"),
                             "replica": e.get("replica"),
                             "reason": e.get("reason"),
                             "queued": e.get("queued"),
                             "in_flight": e.get("in_flight")})
        for e in swap_rows:
            timeline.append({
                "kind": "swap",
                "version": (e.get("weight_version") or e.get("tag")),
                "ok": e.get("ok"),
                "rolled_back": e.get("rolled_back"),
            })
        for e in mig_rows:
            timeline.append({"kind": "migration", "uid": e.get("uid"),
                             "src": e.get("src"), "dst": e.get("dst"),
                             "pages": e.get("pages"),
                             "nbytes": e.get("nbytes")})
        for e in death_rows:
            timeline.append({"kind": "death",
                             "replica": e.get("replica"),
                             "reason": e.get("reason"),
                             "exit_code": e.get("exit_code"),
                             "exports": e.get("exports")})
        for e in restart_rows:
            timeline.append({"kind": "restart",
                             "replica": e.get("replica"),
                             "decision": e.get("decision"),
                             "exit_code": e.get("exit_code")})
        for e in scale_rows:
            timeline.append({"kind": "autoscale",
                             "action": e.get("action"),
                             "replica": e.get("replica"),
                             "live": e.get("live")})
        fs_mig = (fleet_state or {}).get("migrations") or {}
        process = None
        if proc_rows or mig_rows or restart_rows or salvage_rows:
            process = {
                "replicas": [proc_rows[k] and {
                    "replica": proc_rows[k].get("replica"),
                    "status": proc_rows[k].get("status"),
                    "pid": proc_rows[k].get("pid"),
                    "restarts": proc_rows[k].get("restarts"),
                    "last_exit_code":
                        proc_rows[k].get("last_exit_code"),
                    "migrations_in": proc_rows[k].get("migrations_in"),
                    "migrations_out":
                        proc_rows[k].get("migrations_out"),
                    "migration_bytes":
                        proc_rows[k].get("migration_bytes"),
                    "migration_priced_ms":
                        proc_rows[k].get("migration_priced_ms"),
                } for k in sorted(proc_rows,
                                  key=lambda x: (x is None, x))],
                "migrations": {
                    "count": fs_mig.get("total", len(mig_rows)),
                    "bytes": fs_mig.get("bytes", sum(
                        int(e.get("nbytes") or 0) for e in mig_rows)),
                    "priced_ms": fs_mig.get("priced_ms"),
                },
                "restarts": ((fleet_state or {}).get("restarts")
                             if (fleet_state or {}).get("restarts")
                             is not None
                             else _last(scalars, T_REPLICA_RESTARTS)),
                "deaths": len(death_rows),
                "salvaged_flights": len(salvage_rows),
            }
        serving["fleet"] = {
            "replicas": (fleet_state or {}).get("replicas"),
            "routing": (fleet_state or {}).get("routing"),
            "submitted": (fleet_state or {}).get("submitted"),
            "shed": {
                "total": fs_shed.get("total", len(
                    [e for e in shed_rows
                     if e.get("reason") in ("shed_slo",
                                            "shed_capacity")])),
                "rate": (fs_shed.get("rate")
                         if fs_shed.get("rate") is not None
                         else _last(scalars, T_SHED_RATE)),
                "by_reason": (fs_shed.get("by_reason")
                              or dict(shed_by_reason)),
                "by_priority": fs_shed.get("by_priority"),
            },
            "redistributed": (fleet_state or {}).get("redistributed"),
            "reroutes": (fleet_state or {}).get("reroutes"),
            "process": process,
            "slo": (fleet_state or {}).get("slo"),
            "queue_depth_peak": (max(_vals(scalars, T_FLEET_QDEPTH))
                                 if _vals(scalars, T_FLEET_QDEPTH)
                                 else None),
            "weight_ordinal_last": _last(scalars, T_WEIGHT_VERSION),
            "timeline": timeline,
        }
    else:
        serving["fleet"] = None

    ckpt = {"saves": 0, "loads": 0, "fallbacks": 0, "save_ms": []}
    for tag, rows in scalars.items():
        if tag.endswith("checkpoint_save_ok"):
            ckpt["saves"] += len(rows)
        elif tag.endswith("checkpoint_load_ok"):
            ckpt["loads"] += len(rows)
        elif tag.endswith("checkpoint_fallback_ok"):
            ckpt["fallbacks"] += len(rows)
        elif tag.endswith("checkpoint_save_ms"):
            ckpt["save_ms"].extend(v for _, v in rows)

    # elastic plane: snapshot/write split of the saves, async backlog,
    # preempt->relaunch->resume chain (ISSUE 10)
    snap_ms = _vals(scalars, T_CKPT_SNAPSHOT)
    write_ms = _vals(scalars, T_CKPT_WRITE)
    pending = _vals(scalars, T_CKPT_PENDING)
    preempt_events = [e for e in events if e.get("event") == "preemption"]
    resume_events = [e for e in events if e.get("event") == "resume"]

    # health plane (utils/health.py): the numeric-anomaly alert rows,
    # the watchdog's stall postmortems, and the black-box dump trail
    health_rows = [e for e in events if e.get("event") == "health"]
    stall_rows = [e for e in events
                  if e.get("event") == "stall_detected"]
    dump_rows = [e for e in events if e.get("event") == "flight_dump"]
    by_reason = defaultdict(int)
    for e in health_rows:
        by_reason[str(e.get("reason", "?"))] += 1
    alerts_scalar = _last(scalars, T_HEALTH_ALERTS)
    last_stall = stall_rows[-1] if stall_rows else None
    health = {
        "alerts": (int(alerts_scalar) if alerts_scalar is not None
                   else len(health_rows)),
        "by_reason": dict(by_reason),
        "rows": [{k: e.get(k) for k in ("reason", "step", "component")}
                 for e in health_rows],
        "stalls": len(stall_rows),
        "last_stall": ({k: last_stall.get(k)
                        for k in ("phase", "silent_s", "timeout_s",
                                  "component", "flight")}
                       if last_stall else None),
        "flight_dumps": [{k: e.get(k)
                          for k in ("trigger", "flight", "component")}
                         for e in dump_rows],
    }

    elastic = {
        "snapshot_ms_mean": (sum(snap_ms) / len(snap_ms)
                             if snap_ms else None),
        "write_ms_mean": (sum(write_ms) / len(write_ms)
                          if write_ms else None),
        "pending_saves_peak": max(pending) if pending else None,
        "restarts": _last(scalars, T_CKPT_RESTARTS),
        "preemptions": len(preempt_events),
        "resumes": len(resume_events),
        "last_preemption": ({k: preempt_events[-1].get(k)
                             for k in ("reason", "step", "tag",
                                       "committed")}
                            if preempt_events else None),
    }

    return {
        "schema": SCHEMA_VERSION,
        "events_file": events_file,
        "steps": len(step_ms),
        "step_time_ms": {
            "p50": percentile(step_ms, 0.50),
            "p95": percentile(step_ms, 0.95),
            "mean": sum(step_ms) / len(step_ms) if step_ms else None,
            "min": min(step_ms) if step_ms else None,
        },
        "samples_per_sec": {
            "last": sps[-1] if sps else None,
            "best": max(sps) if sps else None,
        },
        "mfu": {
            "last": mfu[-1] if mfu else None,
            "best": max(mfu) if mfu else None,
        },
        "flops_per_step": _last(scalars, T_FLOPS),
        "bytes_accessed": _last(scalars, T_BYTES),
        "comm": {
            "bytes_per_step": _last(scalars, T_COMM_BYTES),
            "compression_ratio": _last(scalars, T_COMM_RATIO),
            # which exchange produced the bytes (comm autotuner /
            # static quantized_comm): last comm_mode event + the full
            # comm_plan decision row when the autotuner ran
            "mode": next((str(e.get("mode")) for e in reversed(events)
                          if e.get("event") == "comm_mode"), None),
            "plan": next((
                {k: e.get(k) for k in ("algo", "block", "hierarchical",
                                       "world", "topo_intra", "reason",
                                       "overridden")}
                for e in reversed(events)
                if e.get("event") == "comm_plan"), None),
        },
        "recompiles": {
            "count": int(recompiles) if recompiles is not None else 0,
            "total_compile_ms": _last(scalars, T_COMPILE_MS),
            "per_fn": {k: dict(v) for k, v in sorted(per_fn.items())},
        },
        "host_overhead": {
            "dispatches_per_step": disp_per_step,
            "host_syncs": _last(scalars, T_HOST_SYNCS),
            "gap_ms_p50": gap_p50,
            "gap_fraction_of_step": gap_fraction,
            "threshold": host_gap_threshold,
            "flagged": host_flagged,
        },
        "memory": {
            "peak_bytes_in_use": max(mem_peak) if mem_peak else None,
            "last_bytes_in_use": _last(scalars, T_MEM_USE),
        },
        "serving": serving,
        "checkpoints": {
            "saves": ckpt["saves"], "loads": ckpt["loads"],
            "fallbacks": ckpt["fallbacks"],
            "save_ms_mean": (sum(ckpt["save_ms"]) / len(ckpt["save_ms"])
                             if ckpt["save_ms"] else None),
        },
        "elastic": elastic,
        "health": health,
        "loss": {
            "first": loss[0] if loss else None,
            "last": loss[-1] if loss else None,
        },
    }


def _fmt(v, spec="{:.2f}", none="-"):
    return none if v is None else spec.format(v)


def _fmt_bytes(v):
    if v is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.1f} {unit}"
        v /= 1024
    return f"{v:.1f} TiB"


def render(s):
    st = s["step_time_ms"]
    lines = [
        f"run report: {s['events_file']}",
        f"  steps             : {s['steps']}",
        f"  step_time_ms      : p50={_fmt(st['p50'])} "
        f"p95={_fmt(st['p95'])} mean={_fmt(st['mean'])}",
        f"  samples_per_sec   : last={_fmt(s['samples_per_sec']['last'])} "
        f"best={_fmt(s['samples_per_sec']['best'])}",
        f"  mfu               : last={_fmt(s['mfu']['last'], '{:.4f}')} "
        f"best={_fmt(s['mfu']['best'], '{:.4f}')}",
        f"  flops_per_step    : "
        f"{_fmt(s['flops_per_step'], '{:.3e}')}",
        f"  comm_bytes_per_step: "
        f"{_fmt_bytes(s['comm']['bytes_per_step'])} "
        f"(compression {_fmt(s['comm']['compression_ratio'])}x"
        + (f", mode={s['comm'].get('mode')}"
           if s['comm'].get('mode') else "") + ")",
        f"  recompiles        : {s['recompiles']['count']}"
        + (f" (total {_fmt(s['recompiles']['total_compile_ms'], '{:.0f}')}"
           " ms)" if s['recompiles']['total_compile_ms'] else ""),
    ]
    plan = s["comm"].get("plan")
    if plan:
        hier = plan.get("hierarchical") or 0
        # anchored to the comm-bytes line it annotates, not a position
        idx = next((i for i, l in enumerate(lines)
                    if l.startswith("  comm_bytes_per_step")),
                   len(lines) - 1)
        lines.insert(idx + 1, "  comm_plan         : "
                     f"{'hier%s-' % hier if hier else ''}{plan.get('algo')}"
                     f"/b{plan.get('block')} "
                     f"({'pinned' if plan.get('overridden') else 'autotuned'}"
                     f"; {plan.get('reason')})")
    for fn, d in s["recompiles"]["per_fn"].items():
        lines.append(f"    - {fn}: {d['count']} compile(s), "
                     f"{d['wall_ms']:.0f} ms")
    ho = s.get("host_overhead", {})
    if any(v is not None for k, v in ho.items()
           if k not in ("threshold", "flagged")):
        line = (f"  host_overhead     : "
                f"dispatches/step={_fmt(ho.get('dispatches_per_step'))} "
                f"syncs={_fmt(ho.get('host_syncs'), '{:.0f}')} "
                f"gap_p50={_fmt(ho.get('gap_ms_p50'))} ms "
                f"({_fmt(ho.get('gap_fraction_of_step'), '{:.1%}')} "
                f"of step)")
        if ho.get("flagged"):
            line += (f"  ** WARNING: host gap > "
                     f"{ho['threshold']:.0%} of step time — the device "
                     "is waiting on the host (check prefetch depth / "
                     "per-step syncs) **")
        lines.append(line)
    sv = s.get("serving") or {}
    if sv.get("requests"):
        evict_note = (f" evictions={sv['evictions']}"
                      if sv.get("evictions") else "")
        lines += [
            f"  serving           : requests={sv['requests']} "
            f"decode_steps={sv['decode_steps']}{evict_note} "
            f"tokens/s last={_fmt(sv['tokens_per_sec']['last'])} "
            f"best={_fmt(sv['tokens_per_sec']['best'])}",
            f"    queue_wait_ms   : p50={_fmt(sv['queue_wait_ms']['p50'])} "
            f"p95={_fmt(sv['queue_wait_ms']['p95'])} "
            f"p99={_fmt(sv['queue_wait_ms']['p99'])}",
            f"    ttft_ms         : p50={_fmt(sv['ttft_ms']['p50'])} "
            f"p95={_fmt(sv['ttft_ms']['p95'])} "
            f"p99={_fmt(sv['ttft_ms']['p99'])}",
            f"    tbt_ms          : p50={_fmt(sv['tbt_ms']['p50'])} "
            f"p95={_fmt(sv['tbt_ms']['p95'])} "
            f"p99={_fmt(sv['tbt_ms']['p99'])}",
            f"    token_latency_ms: "
            f"p50={_fmt(sv['token_latency_ms']['p50'])} "
            f"p95={_fmt(sv['token_latency_ms']['p95'])}",
            f"    occupancy       : "
            f"mean={_fmt(sv['batch_occupancy_mean'], '{:.1%}')} "
            f"queue_depth_max="
            f"{_fmt(sv['queue_depth_max'], '{:.0f}')}",
        ]
        slo = sv.get("slo") or {}
        if slo.get("attainment") is not None:
            lines.append(
                f"    slo             : "
                f"attainment={_fmt(slo['attainment'], '{:.1%}')} "
                f"goodput={_fmt(slo['goodput_tokens_per_s'])} tok/s "
                f"(throughput "
                f"{_fmt(slo['throughput_tokens_per_s'])} tok/s)")
        pk = sv.get("paged_kv") or {}
        if pk.get("pages_in_use_peak") is not None:
            lines.append(
                f"    paged_kv        : "
                f"pages_peak={_fmt(pk['pages_in_use_peak'], '{:.0f}')} "
                f"tokens_in_flight_peak="
                f"{_fmt(pk['tokens_in_flight_peak'], '{:.0f}')} "
                f"prefix_hit_rate="
                f"{_fmt(pk['prefix_hit_rate'], '{:.1%}')}")
        if pk.get("decode_attn_path"):
            line = f"    decode_attn     : {pk['decode_attn_path']}"
            if pk.get("decode_attn_reason"):
                line += f" ({pk['decode_attn_reason']})"
            if pk["decode_attn_path"] == "gather":
                line += "  ** fallback: decode reads are stripe-wide, " \
                        "not O(live tokens) **"
            lines.append(line)
    lines += [
        f"  memory            : "
        f"peak={_fmt_bytes(s['memory']['peak_bytes_in_use'])} "
        f"last={_fmt_bytes(s['memory']['last_bytes_in_use'])}",
        f"  checkpoints       : saves={s['checkpoints']['saves']} "
        f"loads={s['checkpoints']['loads']} "
        f"fallbacks={s['checkpoints']['fallbacks']}"
        + (f" save_ms_mean={_fmt(s['checkpoints']['save_ms_mean'])}"
           if s['checkpoints']['save_ms_mean'] is not None else ""),
    ]
    el = s.get("elastic") or {}
    if any(v not in (None, 0) for k, v in el.items()
           if k != "last_preemption"):
        line = (f"  elastic           : "
                f"restarts={_fmt(el.get('restarts'), '{:.0f}', '0')} "
                f"preemptions={el.get('preemptions', 0)} "
                f"resumes={el.get('resumes', 0)}")
        if el.get("snapshot_ms_mean") is not None:
            line += (f" snapshot_ms_mean={_fmt(el['snapshot_ms_mean'])}"
                     f" write_ms_mean={_fmt(el.get('write_ms_mean'))}"
                     f" pending_peak="
                     f"{_fmt(el.get('pending_saves_peak'), '{:.0f}')}")
        lines.append(line)
        lp = el.get("last_preemption")
        if lp:
            lines.append(
                f"    last_preemption : {lp.get('reason')} at step "
                f"{lp.get('step')} -> tag={lp.get('tag')} "
                f"(committed={lp.get('committed')})")
    hl = s.get("health") or {}
    if hl.get("alerts") or hl.get("stalls"):
        parts = ", ".join(f"{k}={v}" for k, v in
                          sorted((hl.get("by_reason") or {}).items()))
        lines.append(
            f"  health            : alerts={hl.get('alerts', 0)} "
            f"stalls={hl.get('stalls', 0)}"
            + (f" ({parts})" if parts else "")
            + "  ** see --health for the postmortem **")
    lines += [
        f"  loss              : first={_fmt(s['loss']['first'], '{:.4f}')} "
        f"last={_fmt(s['loss']['last'], '{:.4f}')}",
    ]
    return "\n".join(lines)


def render_serve(s):
    """The serving-plane report (``--serve``): the request-granular
    latency/SLO view plus the live-pool snapshot — what an on-call
    person wants first when a serving alarm fires."""
    sv = s.get("serving") or {}
    lines = [f"serving report: {s['events_file']}"]
    if not sv.get("requests") and not sv.get("fleet"):
        lines.append("  (no serving telemetry in this log)")
        return "\n".join(lines)
    if not sv.get("requests"):
        # a router-only event log (process-mode fleet: request rows
        # live in each replica child's own log) still has a fleet
        # plane worth rendering — fall through to it
        lines.append("  (no request-level serving telemetry; "
                     "fleet plane only)")

    def pline(label, d, note=""):
        return (f"  {label:<18}: p50={_fmt(d['p50'])} "
                f"p95={_fmt(d['p95'])} p99={_fmt(d['p99'])} ms{note}")
    if sv.get("requests"):
        lines.append(
            f"  requests          : {sv['requests']} "
            f"(evictions={sv.get('evictions', 0)}) "
            f"decode_steps={sv['decode_steps']}")
        lines += [
            pline("queue_wait", sv["queue_wait_ms"]),
            pline("ttft", sv["ttft_ms"]),
            pline("tbt", sv["tbt_ms"], "  (per-dispatch means)"),
        ]
        slo = sv.get("slo") or {}
        thr = slo.get("thresholds") or {}
        if slo.get("attainment") is not None:
            lines.append(
                f"  slo_attainment    : {_fmt(slo['attainment'], '{:.1%}')}"
                + (f"  (ttft<={_fmt(thr.get('ttft_ms'), '{:.0f}')} ms, "
                   f"tbt<={_fmt(thr.get('tbt_ms'), '{:.0f}')} ms)"
                   if thr else ""))
            lines.append(
                f"  goodput           : "
                f"{_fmt(slo['goodput_tokens_per_s'])} tok/s within SLO "
                f"(raw throughput "
                f"{_fmt(slo['throughput_tokens_per_s'])} tok/s)")
        hist = sv.get("histograms") or {}
        tb = hist.get("tbt_ms")
        if tb and tb.get("count"):
            lines.append(
                f"  tbt (per request) : p50={_fmt(tb['p50'])} "
                f"p95={_fmt(tb['p95'])} p99={_fmt(tb['p99'])} ms "
                f"({tb['count']} samples, histogram)")
        pool = sv.get("pool")
        if pool:
            pc = pool.get("prefix_cache") or {}
            seen = pc.get("hit_tokens", 0) + pc.get("miss_tokens", 0)
            lines += [
                f"  page_pool         : {pool['pages_in_use']}/"
                f"{pool['num_pages'] - 1} pages in use "
                f"({pool['pages_free']} free, page_size "
                f"{pool['page_size']}, shared={pool.get('pages_shared', 0)}, "
                f"internal_frag="
                f"{_fmt(pool.get('internal_fragmentation'), '{:.1%}')})",
                f"  prefix_cache      : {pc.get('entries', 0)} entries, "
                f"{pc.get('hit_requests', 0)} hit requests, "
                f"hit_rate={_fmt(pc.get('hit_tokens', 0) / seen if seen else None, '{:.1%}')} "
                f"of prompt tokens, {pc.get('evictions', 0)} evictions",
            ]
            if pool.get("decode_attn_path") == "gather":
                lines.append("  decode_attn       : gather  ** fallback: "
                             "decode reads are stripe-wide, not "
                             "O(live tokens) **")
        occ = sv.get("batch_occupancy_mean")
        lines.append(f"  occupancy         : mean={_fmt(occ, '{:.1%}')} "
                     f"queue_depth_max="
                     f"{_fmt(sv.get('queue_depth_max'), '{:.0f}')}")
        spec = sv.get("speculation") or {}
        if spec.get("dispatches"):
            ar = spec.get("accept_rate") or {}
            lines.append(
                f"  speculation       : "
                f"{_fmt(spec.get('accepted_per_dispatch'), '{:.2f}')} "
                f"accepted drafts/dispatch over {spec['dispatches']} verify "
                f"dispatches (accept_rate p50="
                f"{_fmt(ar.get('p50'), '{:.1%}')} "
                f"p95={_fmt(ar.get('p95'), '{:.1%}')}, "
                f"lifetime={_fmt(ar.get('lifetime'), '{:.1%}')})")
        dg = sv.get("disagg") or {}
        if dg.get("handoffs"):
            hm = dg.get("handoff_ms") or {}
            lines.append(
                f"  disagg_handoff    : {dg['handoffs']} handoffs, "
                f"p50={_fmt(hm.get('p50'))} p95={_fmt(hm.get('p95'))} ms, "
                f"requeues={dg.get('requeues', 0)}")
        ck = sv.get("chunked_prefill") or {}
        if ck.get("dispatches") or ck.get("rejected_too_long"):
            cpr = ck.get("chunks_per_request") or {}
            cm = ck.get("chunk_ms") or {}
            lines.append(
                f"  chunked_prefill   : {ck.get('dispatches', 0)} chunk "
                f"dispatches over {ck.get('chunked_requests', 0)} "
                f"requests (chunks/req p50={_fmt(cpr.get('p50'), '{:.0f}')} "
                f"p95={_fmt(cpr.get('p95'), '{:.0f}')}, chunk p50="
                f"{_fmt(cm.get('p50'))} p95={_fmt(cm.get('p95'))} ms, "
                f"cp_chunks={ck.get('cp_chunks', 0)})")
            lines.append(
                f"    tbt_max         : {_fmt(ck.get('tbt_max_ms'))} ms "
                f"worst step TBT; rejected_too_long="
                f"{ck.get('rejected_too_long', 0)}")
    fl = sv.get("fleet")
    if fl:
        shed = fl.get("shed") or {}
        line = (f"  fleet             : routing={fl.get('routing')} "
                f"submitted={_fmt(fl.get('submitted'), '{:.0f}')} "
                f"shed={shed.get('total', 0)} "
                f"(rate {_fmt(shed.get('rate'), '{:.1%}')}) "
                f"redistributed={_fmt(fl.get('redistributed'), '{:.0f}')} "
                f"reroutes={_fmt(fl.get('reroutes'), '{:.0f}')}")
        lines.append(line)
        slo = fl.get("slo") or {}
        if slo.get("budget_ms") is not None:
            lines.append(
                f"    slo_shed        : p95_ttft="
                f"{_fmt(slo.get('p95_ttft_ms'))} ms vs budget "
                f"{_fmt(slo.get('budget_ms'), '{:.0f}')} ms "
                f"({_fmt(slo.get('samples'), '{:.0f}')} samples)")
        by_reason = shed.get("by_reason") or {}
        if by_reason:
            parts = ", ".join(f"{k}={v}"
                              for k, v in sorted(by_reason.items()))
            lines.append(f"    shed_by_reason  : {parts}")
        by_prio = shed.get("by_priority") or {}
        if by_prio:
            parts = ", ".join(f"tier{k}={v}"
                              for k, v in sorted(by_prio.items()))
            lines.append(f"    shed_by_tier    : {parts}")
        for r in fl.get("replicas") or []:
            lines.append(
                f"    replica {r.get('replica')}       : "
                f"{r.get('status'):<8} "
                f"occ={_fmt(r.get('occupancy'), '{:.1%}')} "
                f"q={r.get('queue_depth')} routed={r.get('routed')} "
                f"weights={r.get('weight_version')} "
                f"recompiles={r.get('steady_state_recompiles')}"
                + (f" drain={r.get('drain_reason')}"
                   if r.get("drain_reason") else ""))
        proc = fl.get("process")
        if proc:
            mig = proc.get("migrations") or {}
            lines.append(
                f"    process_fleet   : "
                f"migrations={_fmt(mig.get('count'), '{:.0f}')} "
                f"({_fmt(mig.get('bytes'), '{:.0f}')} B, priced "
                f"{_fmt(mig.get('priced_ms'))} ms) "
                f"restarts={_fmt(proc.get('restarts'), '{:.0f}')} "
                f"deaths={proc.get('deaths', 0)} "
                f"salvaged_flights={proc.get('salvaged_flights', 0)}")
            for r in proc.get("replicas") or []:
                lines.append(
                    f"    proc replica {r.get('replica')}  : "
                    f"pid={r.get('pid')} "
                    f"restarts={r.get('restarts')} "
                    f"last_exit={r.get('last_exit_code')} "
                    f"mig_in={r.get('migrations_in')} "
                    f"mig_out={r.get('migrations_out')} "
                    f"mig_bytes={r.get('migration_bytes')} "
                    f"priced_ms={r.get('migration_priced_ms')}")
        for t in fl.get("timeline") or []:
            if t["kind"] == "drain":
                lines.append(
                    f"    drain           : replica {t.get('replica')} "
                    f"{t.get('phase')} ({t.get('reason')}"
                    + (f", queued={t.get('queued')} "
                       f"in_flight={t.get('in_flight')}"
                       if t.get("phase") == "begin" else "") + ")")
            elif t["kind"] == "migration":
                lines.append(
                    f"    migration       : uid {t.get('uid')} "
                    f"replica {t.get('src')} -> {t.get('dst')} "
                    f"({t.get('pages')} pages, {t.get('nbytes')} B)")
            elif t["kind"] == "death":
                lines.append(
                    f"    death           : replica {t.get('replica')} "
                    f"({t.get('reason')}, exit={t.get('exit_code')}, "
                    f"exports={t.get('exports')})")
            elif t["kind"] == "restart":
                lines.append(
                    f"    restart         : replica {t.get('replica')} "
                    f"{t.get('decision')} "
                    f"(exit={t.get('exit_code')})")
            elif t["kind"] == "autoscale":
                lines.append(
                    f"    autoscale       : {t.get('action')} replica "
                    f"{t.get('replica')} (live={t.get('live')})")
            else:
                if t.get("rolled_back") is not None:
                    ver = t.get("version")
                    lines.append(
                        f"    swap_push       : tag={ver} "
                        f"rolled_back={t['rolled_back']}")
                else:
                    lines.append(
                        f"    swap            : "
                        f"-> {t.get('version')} "
                        f"(ok={t.get('ok')})")
    return "\n".join(lines)


def render_health(s):
    """The health-plane postmortem (``--health``): anomaly alerts by
    pinned reason, the watchdog's stall diagnosis (phase + flight.json
    location), and the black-box dump trail — what you read FIRST when
    a run died or wedged."""
    hl = s.get("health") or {}
    lines = [f"health report: {s['events_file']}"]
    if not (hl.get("alerts") or hl.get("stalls")
            or hl.get("flight_dumps")):
        lines.append("  (no health events in this log — clean run, or "
                     "observability.health not enabled)")
        return "\n".join(lines)
    lines.append(f"  alerts            : {hl.get('alerts', 0)}")
    for reason, n in sorted((hl.get("by_reason") or {}).items()):
        lines.append(f"    - {reason}: {n}")
    for row in hl.get("rows") or []:
        lines.append(
            f"    alert           : {row.get('reason')} at step "
            f"{row.get('step')} ({row.get('component')})")
    lines.append(f"  stalls            : {hl.get('stalls', 0)}")
    ls = hl.get("last_stall")
    if ls:
        lines.append(
            f"    last_stall      : phase={ls.get('phase')} "
            f"silent={_fmt(ls.get('silent_s'), '{:.1f}')}s "
            f"(timeout {_fmt(ls.get('timeout_s'), '{:.1f}')}s, "
            f"{ls.get('component')})")
        if ls.get("flight"):
            lines.append(f"    flight          : {ls['flight']}")
    for d in hl.get("flight_dumps") or []:
        lines.append(
            f"  flight_dump       : trigger={d.get('trigger')} -> "
            f"{d.get('flight')} ({d.get('component')})")
    return "\n".join(lines)


# ------------------------------------------------------------------- #
# cross-run regression diffing (--diff RUN_A RUN_B)
# ------------------------------------------------------------------- #

# (name, extractor, direction, relative threshold). Directions:
# "lower"  = lower is better (latency)   — regressed when B/A - 1 > thr
# "higher" = higher is better (rate)     — regressed when 1 - B/A > thr
# "counter"= should not grow (failures)  — regressed on ANY increase
# p95 gets a looser threshold than p50: the tail is noisier by nature.
DIFF_METRICS = (
    ("step_time_ms_p50", lambda s: s["step_time_ms"]["p50"],
     "lower", 0.10),
    ("step_time_ms_p95", lambda s: s["step_time_ms"]["p95"],
     "lower", 0.15),
    ("samples_per_sec_best", lambda s: s["samples_per_sec"]["best"],
     "higher", 0.10),
    ("mfu_best", lambda s: s["mfu"]["best"], "higher", 0.10),
    ("goodput_tokens_per_s",
     lambda s: ((s.get("serving") or {}).get("slo")
                or {}).get("goodput_tokens_per_s"), "higher", 0.10),
    # quantized-serving error budget (ISSUE 17): the offline
    # quantized-vs-fp max-logit-error probe must not drift up across
    # runs, and the static pool cost per token must never grow
    ("quant_logit_err",
     lambda s: ((s.get("serving") or {}).get("quantization")
                or {}).get("quant_logit_err"), "lower", 0.10),
    ("kv_pool_bytes_per_token",
     lambda s: ((s.get("serving") or {}).get("quantization")
                or {}).get("kv_pool_bytes_per_token"), "counter", 0.0),
    ("recompiles", lambda s: s["recompiles"]["count"], "counter", 0.0),
    ("health_alerts",
     lambda s: (s.get("health") or {}).get("alerts", 0), "counter",
     0.0),
    ("stalls", lambda s: (s.get("health") or {}).get("stalls", 0),
     "counter", 0.0),
)


def diff_runs(path_a, path_b):
    """Compare two runs' event logs metric-by-metric; A is the
    baseline, B the candidate. Returns the versioned diff dict
    (``render_diff`` turns it into text; any REGRESSED metric makes
    the CLI exit nonzero — the bench-trajectory regression gate)."""
    sa = summarize(path_a)
    sb = summarize(path_b)
    metrics = []
    regressed = []
    for name, extract, direction, thr in DIFF_METRICS:
        a, b = extract(sa), extract(sb)
        entry = {"metric": name, "a": a, "b": b,
                 "direction": direction, "threshold": thr,
                 "rel_change": None, "verdict": "OK"}
        if a is None or b is None:
            entry["verdict"] = "N/A" if a is None and b is None \
                else "OK"   # one-sided metric (e.g. no serving plane)
            metrics.append(entry)
            continue
        a, b = float(a), float(b)
        if direction == "counter":
            if b > a:
                entry["verdict"] = "REGRESSED"
            elif b < a:
                entry["verdict"] = "IMPROVED"
        else:
            rel = (b - a) / a if a else (0.0 if b == a else None)
            entry["rel_change"] = rel
            if rel is None:
                entry["verdict"] = "N/A"
            elif direction == "lower":
                if rel > thr:
                    entry["verdict"] = "REGRESSED"
                elif rel < -thr:
                    entry["verdict"] = "IMPROVED"
            else:   # higher is better
                if rel < -thr:
                    entry["verdict"] = "REGRESSED"
                elif rel > thr:
                    entry["verdict"] = "IMPROVED"
        if entry["verdict"] == "REGRESSED":
            regressed.append(name)
        metrics.append(entry)
    return {
        "schema": SCHEMA_VERSION,
        "run_a": sa["events_file"],
        "run_b": sb["events_file"],
        "metrics": metrics,
        "regressed": regressed,
        "verdict": "REGRESSED" if regressed else "OK",
    }


def render_diff(d):
    lines = [
        f"run diff: A={d['run_a']}",
        f"          B={d['run_b']}",
    ]
    for m in d["metrics"]:
        rel = (f" ({m['rel_change']:+.1%})"
               if m.get("rel_change") is not None else "")
        lines.append(
            f"  {m['metric']:<22}: A={_fmt(m['a'], '{:.4g}')} "
            f"B={_fmt(m['b'], '{:.4g}')}{rel}  {m['verdict']}")
    if d["regressed"]:
        lines.append(
            f"verdict: REGRESSED ({', '.join(d['regressed'])})")
    else:
        lines.append("verdict: OK")
    return "\n".join(lines)


# ------------------------------------------------------------------- #
# fleet-wide merged tracing (--fleet DIR [DIR ...])
# ------------------------------------------------------------------- #

# --fleet JSON schema version (independent of SCHEMA_VERSION: the
# per-run report and the merged-fleet view evolve separately)
FLEET_SCHEMA_VERSION = 1

# aligned timestamps may legitimately disagree by the clock-sync
# uncertainty plus a little scheduling noise; reordering beyond
# combined uncertainty + this slack is flagged as a real anomaly
OUT_OF_ORDER_SLACK_MS = 1.0


def _fold_finish(hop, row):
    hop["finish"] = {k: row.get(k) for k in (
        "reason", "new_tokens", "ttft_ms", "latency_ms",
        "queue_wait_ms", "prefill_ms", "tbt_ms", "tbt_ms_max",
        "slo_ok")}
    hop["t_finish"] = row.get("_t_aligned")


def _fold_decode(hop, row):
    hop["decode_tokens"] = hop.get("decode_tokens", 0) + \
        int(row.get("tokens") or 0)
    hop["tbt_ms"] = row.get("tbt_ms")


def _fold_spec(hop, row):
    hop["spec_proposed"] = hop.get("spec_proposed", 0) + \
        int(row.get("proposed") or 0)
    hop["spec_accepted"] = hop.get("spec_accepted", 0) + \
        int(row.get("accepted") or 0)


# every serve-plane event kind the tracer can emit, and how the fleet
# merger folds it into a per-(trace, hop) record. The schema-drift
# test (tests/unit/test_serve_trace.py) walks ServeTracer.EVENT_KINDS
# and asserts each has a handler here AND a TRAIL_SCHEMA entry — a new
# tracer event that the merged report would silently drop fails CI.
EVENT_HANDLERS = {
    "serve_submit": lambda hop, row: hop.update(
        t_submit=row.get("_t_aligned"),
        prompt_tokens=row.get("prompt_tokens")),
    "serve_defer": lambda hop, row: hop.update(
        defers=hop.get("defers", 0) + 1),
    "serve_prefix_hit": lambda hop, row: hop.update(
        prefix_tokens=row.get("tokens")),
    "serve_admit": lambda hop, row: hop.update(
        queue_wait_ms=row.get("queue_wait_ms"),
        slot=row.get("slot")),
    "serve_prefill": lambda hop, row: hop.update(
        prefill_wall_ms=row.get("wall_ms")),
    "serve_prefill_chunk": lambda hop, row: hop.update(
        chunks=int(row.get("chunk", 0) or 0) + 1,
        chunk_cum_ms=row.get("cum_ms")),
    "serve_handoff": lambda hop, row: hop.update(
        handoff_ms=row.get("handoff_ms")),
    "serve_spec_window": _fold_spec,
    "serve_first_token": lambda hop, row: hop.update(
        ttft_ms=row.get("ttft_ms"), prefill_ms=row.get("prefill_ms"),
        t_first_token=row.get("_t_aligned")),
    "serve_decode_window": _fold_decode,
    "serve_finish": _fold_finish,
    "serve_evict": lambda hop, row: hop.update(
        evict_reason=row.get("reason"),
        t_evict=row.get("_t_aligned")),
    "serve_migrate_out": lambda hop, row: hop.update(
        migrate_out={"position": row.get("position"),
                     "pages": row.get("pages"),
                     "nbytes": row.get("nbytes"),
                     "reason": row.get("reason"),
                     "t": row.get("_t_aligned")}),
    "serve_migrate_in": lambda hop, row: hop.update(
        migrate_in={"position": row.get("position"),
                    "pages": row.get("pages"),
                    "resumed_tokens": row.get("resumed_tokens"),
                    "t": row.get("_t_aligned")}),
}


def _load_fleet_logs(dirs):
    """Load every log, classify router vs replica. The router log is
    the one carrying ``fleet_dispatch``/``fleet_state``/``clock_sync``
    rows; replica logs are attributed by the ``replica_id`` field the
    tracer stamps on every row (never by directory name)."""
    logs = []
    for d in dirs:
        path = find_events_file(d)
        _scalars, events = load_events(path)
        logs.append({"dir": d, "path": path, "events": events})
    router = None
    for lg in logs:
        if any(r.get("event") in ("fleet_dispatch", "fleet_state",
                                  "clock_sync") for r in lg["events"]):
            router = lg
            break
    if router is None:
        raise ValueError(
            "no router log among the given dirs (need fleet_dispatch/"
            "fleet_state/clock_sync rows)")
    return logs, router


def _clock_offsets(router_events):
    """replica -> latest clock_sync estimate (seconds). Latest wins:
    offsets drift, and the router re-syncs periodically and after
    every relaunch."""
    offsets = {}
    for r in router_events:
        if r.get("event") == "clock_sync":
            offsets[int(r["replica"])] = {
                "offset_s": float(r.get("offset_ms") or 0.0) / 1e3,
                "uncertainty_s":
                    float(r.get("uncertainty_ms") or 0.0) / 1e3,
            }
    return offsets


def summarize_fleet(dirs):
    """Merge one router log + N replica logs into per-request
    end-to-end timelines. Replica timestamps are moved onto the
    router's clock via the ``clock_sync`` offsets (aligned t =
    t_row - offset); lifecycle order is NEVER resorted by timestamp —
    apparent reordering beyond the sync uncertainty is flagged in
    ``out_of_order`` instead of silently mis-ordered."""
    logs, router = _load_fleet_logs(dirs)
    offsets = _clock_offsets(router["events"])

    traces = {}

    def trace(tid):
        return traces.setdefault(tid, {
            "trace_id": tid, "uid": None, "hops": {},
            "dispatches": {}, "migrations": [], "flags": []})

    def hop_rec(tid, h, replica):
        t = trace(tid)
        return t["hops"].setdefault(int(h), {"hop": int(h),
                                             "replica": replica})

    # router spine: dispatches + migrations (router-clock timestamps
    # are already the reference frame — no alignment)
    for row in router["events"]:
        ev = row.get("event")
        tid = row.get("trace_id")
        if ev == "fleet_dispatch" and tid is not None:
            t = trace(tid)
            t["uid"] = row.get("uid")
            t["dispatches"][int(row.get("hop") or 0)] = {
                "replica": row.get("replica"),
                "route_ms": row.get("route_ms"),
                "t": row.get("t"),
            }
        elif ev == "serve_migration" and tid is not None:
            trace(tid)["migrations"].append({
                "src": row.get("src"), "dst": row.get("dst"),
                "pages": row.get("pages"), "nbytes": row.get("nbytes"),
                "transfer_ms": row.get("transfer_ms"),
                "priced_ms": row.get("priced_ms"), "t": row.get("t")})

    # replica rows: align, fold, and check ordering per (log, trace)
    out_of_order = []
    replicas_seen = set()
    for lg in logs:
        last_by_trace = {}
        for row in lg["events"]:
            ev = row.get("event")
            tid = row.get("trace_id")
            if ev not in EVENT_HANDLERS or tid is None:
                continue
            rid = row.get("replica_id")
            if rid is not None:
                replicas_seen.add(int(rid))
            off = offsets.get(rid, {})
            t_raw = row.get("t")
            unc_s = off.get("uncertainty_s", 0.0)
            row = dict(row)
            row["_t_aligned"] = (
                t_raw - off.get("offset_s", 0.0)
                if t_raw is not None else None)
            h = hop_rec(tid, row.get("hop") or 0, rid)
            EVENT_HANDLERS[ev](h, row)
            if trace(tid)["uid"] is None:
                trace(tid)["uid"] = row.get("uid")
            # ordering check: within one log's file order (the true
            # lifecycle order on that replica), aligned time must not
            # run backwards by more than the sync uncertainty
            prev = last_by_trace.get(tid)
            if prev is not None and row["_t_aligned"] is not None:
                prev_t, prev_unc, prev_ev = prev
                skew_ms = (prev_t - row["_t_aligned"]) * 1e3
                bound_ms = (prev_unc + unc_s) * 1e3 + \
                    OUT_OF_ORDER_SLACK_MS
                if skew_ms > bound_ms:
                    out_of_order.append({
                        "trace_id": tid, "event": ev,
                        "after": prev_ev,
                        "skew_ms": round(skew_ms, 3),
                        "bound_ms": round(bound_ms, 3),
                        "log": lg["path"]})
            if row["_t_aligned"] is not None:
                last_by_trace[tid] = (row["_t_aligned"], unc_s, ev)

    # per-trace assembly: decomposition + lineage flags
    requests = []
    for tid in sorted(traces):
        t = traces[tid]
        hops = [t["hops"][h] for h in sorted(t["hops"])]
        final = next((h for h in reversed(hops) if "finish" in h), None)
        fin = (final or {}).get("finish") or {}
        d0 = t["dispatches"].get(0) or {}
        first_hop = hops[0] if hops else {}
        rpc_wire_ms = None
        if d0.get("t") is not None and \
                first_hop.get("t_submit") is not None:
            rpc_wire_ms = max(
                0.0, (first_hop["t_submit"] - d0["t"]) * 1e3)
        ttft = fin.get("ttft_ms")
        latency = fin.get("latency_ms")
        decode_ms = (latency - ttft if latency is not None
                     and ttft is not None else None)
        # the pinned TTFT identity (tracing.py): queue_wait + prefill
        # (+ handoff) == ttft; decode = latency - ttft. A finish row
        # violating it is a tracer bug, not noise — flag it.
        decomp_ok = None
        if ttft is not None and fin.get("queue_wait_ms") is not None \
                and fin.get("prefill_ms") is not None:
            handoff = next((h.get("handoff_ms") for h in hops
                            if h.get("handoff_ms") is not None), 0.0)
            # the tracer rounds each term to 3 decimals independently,
            # so the sum may differ from ttft by up to 0.5e-3 per term
            decomp_ok = abs(fin["queue_wait_ms"] + fin["prefill_ms"]
                            + (handoff or 0.0) - ttft) < 2e-3
            if not decomp_ok:
                t["flags"].append("decomp_mismatch")
        # lineage: every hop past 0 must pair a migrate_out on the
        # source with a migrate_in on the destination. A hop whose
        # replica wrote no rows at all (child died before flushing,
        # log lost) is salvaged-only: the router's dispatch/migration
        # spine still reconstructs the path.
        for h in hops:
            if h["hop"] > 0 and "migrate_in" not in h:
                t["flags"].append(f"hop{h['hop']}_no_migrate_in")
        for dh, disp in t["dispatches"].items():
            if dh not in t["hops"] and disp.get("replica") is not None:
                t["flags"].append(f"hop{dh}_salvaged_only")
        requests.append({
            "trace_id": tid, "uid": t["uid"],
            "hops": hops, "migrations": t["migrations"],
            "path": [h.get("replica") for h in hops],
            "route_ms": d0.get("route_ms"),
            "rpc_wire_ms": (round(rpc_wire_ms, 3)
                            if rpc_wire_ms is not None else None),
            "replica_queue_ms": fin.get("queue_wait_ms"),
            "prefill_ms": fin.get("prefill_ms"),
            "decode_ms": (round(decode_ms, 3)
                          if decode_ms is not None else None),
            "migration_ms": round(sum(
                m.get("transfer_ms") or 0.0
                for m in t["migrations"]), 3),
            "migration_priced_ms": round(sum(
                m.get("priced_ms") or 0.0
                for m in t["migrations"]), 4),
            "ttft_ms": ttft, "latency_ms": latency,
            "slo_ok": fin.get("slo_ok"),
            "new_tokens": fin.get("new_tokens"),
            "finish_reason": fin.get("reason"),
            "decomp_exact": decomp_ok,
            "flags": t["flags"],
        })

    finished = [r for r in requests if r["latency_ms"] is not None]
    lat = [r["latency_ms"] for r in finished]
    ttfts = [r["ttft_ms"] for r in finished
             if r["ttft_ms"] is not None]
    slo_known = [r for r in finished if r["slo_ok"] is not None]
    migrated = [r for r in requests if r["migrations"]]
    # replica ids the router dispatched to but that wrote no rows in
    # ANY provided log — the whole log is missing, not just a hop
    dispatched_to = {d.get("replica")
                     for t in traces.values()
                     for d in t["dispatches"].values()
                     if d.get("replica") is not None}
    missing = sorted(int(r) for r in dispatched_to
                     if int(r) not in replicas_seen)
    return {
        "fleet_schema": FLEET_SCHEMA_VERSION,
        "router_log": router["path"],
        "logs": [lg["path"] for lg in logs],
        "clock_offsets": {
            str(k): {"offset_ms": round(v["offset_s"] * 1e3, 4),
                     "uncertainty_ms":
                         round(v["uncertainty_s"] * 1e3, 4)}
            for k, v in sorted(offsets.items())},
        "requests": requests,
        "rollup": {
            "traces": len(requests),
            "finished": len(finished),
            "migrated": len(migrated),
            "latency_ms": {"p50": percentile(lat, 0.5),
                           "p95": percentile(lat, 0.95)},
            "ttft_ms": {"p50": percentile(ttfts, 0.5),
                        "p95": percentile(ttfts, 0.95)},
            "slo_attainment": (
                sum(1 for r in slo_known if r["slo_ok"])
                / len(slo_known) if slo_known else None),
            "goodput_tokens": sum(
                r["new_tokens"] or 0 for r in slo_known
                if r["slo_ok"]),
        },
        "out_of_order": out_of_order,
        "missing_replica_logs": missing,
    }


def write_fleet_trace(s, out_path):
    """Chrome trace (chrome://tracing / Perfetto) of the merged fleet:
    one process lane per replica (pid = replica + 1; the router is
    pid 0), one thread per request uid, complete spans for the
    queue/prefill/decode phases on whichever replica hosted them."""
    spans = []
    pids = {None: 0}

    def pid(replica):
        return 0 if replica is None else int(replica) + 1

    spans.append({"ph": "M", "pid": 0, "name": "process_name",
                  "args": {"name": "router"}})
    t0 = None
    for r in s["requests"]:
        for h in r["hops"]:
            for key in ("t_submit", "t_first_token", "t_finish"):
                if h.get(key) is not None:
                    t0 = h[key] if t0 is None else min(t0, h[key])
    if t0 is None:
        t0 = 0.0

    def us(t):
        return round((t - t0) * 1e6, 1)

    for r in s["requests"]:
        tid = r["trace_id"]
        for h in r["hops"]:
            p = pid(h.get("replica"))
            if p not in pids.values():
                spans.append({"ph": "M", "pid": p,
                              "name": "process_name",
                              "args": {"name":
                                       f"replica {h.get('replica')}"}})
                pids[h.get("replica")] = p
            base = {"pid": p, "tid": r["uid"],
                    "args": {"trace_id": tid, "hop": h["hop"]}}
            if h.get("t_submit") is not None and \
                    h.get("queue_wait_ms") is not None:
                spans.append({**base, "ph": "X", "name": "queue",
                              "ts": us(h["t_submit"]),
                              "dur": h["queue_wait_ms"] * 1e3})
            if h.get("t_first_token") is not None and \
                    h.get("prefill_ms") is not None:
                spans.append({
                    **base, "ph": "X", "name": "prefill",
                    "ts": us(h["t_first_token"]
                             - h["prefill_ms"] / 1e3),
                    "dur": h["prefill_ms"] * 1e3})
            t_end = h.get("t_finish")
            t_start = h.get("t_first_token", h.get("t_submit"))
            if t_end is not None and t_start is not None:
                spans.append({**base, "ph": "X", "name": "decode",
                              "ts": us(t_start),
                              "dur": max(0.0,
                                         (t_end - t_start) * 1e6)})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": spans,
                   "displayTimeUnit": "ms"}, f)


def render_fleet(s):
    lines = [f"fleet report: {len(s['logs'])} logs "
             f"(router: {s['router_log']})"]
    if s["clock_offsets"]:
        lines.append("clock offsets (vs router):")
        for rid, o in s["clock_offsets"].items():
            lines.append(
                f"  replica {rid}: {o['offset_ms']:+.3f} ms "
                f"(± {o['uncertainty_ms']:.3f} ms)")
    ru = s["rollup"]
    lines.append(
        f"requests: {ru['traces']} traced, {ru['finished']} finished, "
        f"{ru['migrated']} migrated")
    lines.append(
        f"  latency p50/p95: {_fmt(ru['latency_ms']['p50'])} / "
        f"{_fmt(ru['latency_ms']['p95'])} ms   ttft p50/p95: "
        f"{_fmt(ru['ttft_ms']['p50'])} / "
        f"{_fmt(ru['ttft_ms']['p95'])} ms")
    att = ru["slo_attainment"]
    lines.append(
        f"  SLO attainment: "
        f"{_fmt(att * 100 if att is not None else None, '{:.1f}')}%   "
        f"goodput tokens: {ru['goodput_tokens']}")
    for r in s["requests"]:
        path = "->".join(str(p) for p in r["path"])
        lines.append(
            f"  {r['trace_id']} uid={r['uid']} path=[{path}] "
            f"route={_fmt(r['route_ms'], '{:.3f}')} "
            f"wire={_fmt(r['rpc_wire_ms'], '{:.3f}')} "
            f"queue={_fmt(r['replica_queue_ms'], '{:.3f}')} "
            f"prefill={_fmt(r['prefill_ms'], '{:.3f}')} "
            f"decode={_fmt(r['decode_ms'], '{:.3f}')} "
            f"migrate={_fmt(r['migration_ms'], '{:.3f}')} ms "
            f"-> {r['finish_reason'] or '?'}"
            + (f"  FLAGS: {','.join(r['flags'])}" if r["flags"]
               else ""))
    if s["out_of_order"]:
        lines.append(f"out-of-order events (beyond clock-sync "
                     f"uncertainty): {len(s['out_of_order'])}")
        for o in s["out_of_order"][:10]:
            lines.append(
                f"  {o['trace_id']}: {o['event']} after {o['after']} "
                f"(skew {o['skew_ms']} ms > bound {o['bound_ms']} ms)")
    if s["missing_replica_logs"]:
        lines.append(
            "missing replica logs (router dispatched there, no rows "
            f"found): {s['missing_replica_logs']} — those hops are "
            "reconstructed from the router spine only")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?",
                    help="events.jsonl file, or a directory "
                         "containing one (searched recursively)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary (or diff) as JSON instead "
                         "of text")
    ap.add_argument("--serve", action="store_true",
                    help="render the serving-plane report (request "
                         "percentiles, SLO attainment, goodput, pool "
                         "snapshot) instead of the training summary")
    ap.add_argument("--health", action="store_true",
                    help="render the health-plane postmortem (anomaly "
                         "alerts, stall diagnosis, flight-recorder "
                         "dumps) instead of the training summary")
    ap.add_argument("--diff", nargs=2, metavar=("RUN_A", "RUN_B"),
                    help="compare two runs' event logs (A = baseline, "
                         "B = candidate); exits 1 when any metric "
                         "REGRESSED past its threshold")
    ap.add_argument("--fleet", nargs="+", metavar="DIR",
                    help="merge one router log + N replica logs into "
                         "per-request end-to-end timelines (clock-"
                         "aligned via the router's clock_sync rows)")
    ap.add_argument("--trace-out", metavar="PATH",
                    help="with --fleet: also write a merged Chrome "
                         "trace (one process lane per replica) to "
                         "PATH")
    ap.add_argument("--host-gap-threshold", type=float,
                    default=DEFAULT_HOST_GAP_THRESHOLD,
                    help="flag the run when host-gap p50 exceeds this "
                         "fraction of step-time p50 (default %(default)s)")
    args = ap.parse_args(argv)
    if args.fleet:
        try:
            s = summarize_fleet(args.fleet)
        except (FileNotFoundError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.trace_out:
            write_fleet_trace(s, args.trace_out)
        print(json.dumps(s, indent=2) if args.json
              else render_fleet(s))
        return 0
    if args.diff:
        try:
            d = diff_runs(args.diff[0], args.diff[1])
        except FileNotFoundError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(d, indent=2) if args.json else render_diff(d))
        return 1 if d["regressed"] else 0
    if not args.path:
        ap.error("path is required unless --diff is given")
    try:
        summary = summarize(args.path,
                            host_gap_threshold=args.host_gap_threshold)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(summary, indent=2))
    elif args.serve:
        print(render_serve(summary))
    elif args.health:
        print(render_health(summary))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
