"""Sparse-kernel A/B matrix at the bench row (real chip).

Times every sparse-attention kernel family on the
sparse_attention_speedup_s8k geometry — Longformer w=3 (class default),
block=128, S=8192, H=16 — against dense flash and the vanilla O(S^2)
baseline, decomposing banded fwd vs fwd+bwd so the remaining gap to the
FLOP bound has a named location (VERDICT r4 #1's profile-first ask):

  flash        dense causal Pallas kernel (the vs_flash baseline)
  vanilla      XLA materialized-scores path (the reference-methodology
               baseline the 6.3x claim uses) — skipped if it OOMs
  banded(b,b)  the structured fast path at several walk-tile sizes
  v2-coarse    generic row-run walk, coarse 512 tiles (previous champ)
  v2-fine      generic row-run walk, fine tiles (banded+coarse off)

Run on hardware:
  PYTHONPATH=/root/repo python tools/ab_coarse_sparse.py
Prints ms/eval per variant, speedups, grad parity checks, and a
roofline summary (active-cell fraction vs dense).
"""
import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.platform import enable_compile_cache
from deepspeed_tpu.ops.sparse_attention import (
    BSLongformerSparsityConfig, block_sparse_attention)
from deepspeed_tpu.ops.sparse_attention import banded as bd
from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
from deepspeed_tpu.ops.attention.flash import flash_attention


def main():
    enable_compile_cache(None)
    B, H, S, D = 1, 16, 8192, 64
    # mirror the bench row's config (class-default window)
    cfg = BSLongformerSparsityConfig(num_heads=H, block=128,
                                     num_sliding_window_blocks=3)
    layout = cfg.make_layout(S)
    density = float(np.asarray(layout).mean())
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                 jnp.bfloat16) for i in range(3))

    from deepspeed_tpu.utils.benchtime import measure_rtt, scan_grad_seconds
    rtt = measure_rtt()
    print(f"rtt: {rtt * 1e3:.1f} ms | layout density {density:.3f} "
          f"(causal-dense ~0.5 -> FLOP bound ~{0.5 / density:.1f}x "
          "vs causal flash)", flush=True)

    def sparse_loss(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, layout)
                       .astype(jnp.float32))

    def timed_grad(tag, loss):
        grad_fn = jax.grad(loss, argnums=(0, 1, 2))
        r = jax.jit(grad_fn)(q, k, v)
        jax.tree_util.tree_map(np.asarray, r)
        sec, n = scan_grad_seconds(grad_fn, (q, k, v), rtt, start_len=16)
        print(f"{tag}: {sec * 1e3:.2f} ms/eval grad ({n}-chained)",
              flush=True)
        return sec, r

    def timed_fwd(tag, fwd):
        # fwd-only chain: feed the output back into all three operands
        def pseudo(*xs):
            o = fwd(*xs)
            return (o, o, o)
        sec, n = scan_grad_seconds(pseudo, (q, k, v), rtt, start_len=16)
        print(f"{tag}: {sec * 1e3:.2f} ms/eval fwd ({n}-chained)",
              flush=True)
        return sec

    # ---- baselines ----
    t_flash, r_flash = timed_grad(
        "flash dense causal",
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True)
                                .astype(jnp.float32)))
    t_flash_f = timed_fwd(
        "flash dense causal",
        lambda q, k, v: flash_attention(q, k, v, causal=True))

    def vanilla_loss(q, k, v):
        sm = D ** -0.5
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm
        idx = jnp.arange(S)
        s_ = jnp.where(idx[:, None] >= idx[None, :], s_, -1e30)
        p = jax.nn.softmax(s_, axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v)
                       .astype(jnp.float32))

    try:
        t_van, _ = timed_grad("vanilla O(S^2)", vanilla_loss)
    except Exception as e:
        print(f"vanilla: FAILED {type(e).__name__}", flush=True)
        t_van = None

    # reference grads for parity: the v2 fine walk (oldest kernel)
    results = {}

    def run_variant(tag, setup, teardown):
        setup()
        try:
            t, r = timed_grad(tag, sparse_loss)
            results[tag] = (t, r)
        except Exception as e:
            print(f"{tag}: FAILED {type(e).__name__}: {e}", flush=True)
        finally:
            teardown()
            bs._FN_CACHE.clear()

    # ---- banded at several walk tiles (the planned default first) ----
    plan = bd.plan(layout, 128, False)
    print(f"banded plan: {plan[1] if plan else None}", flush=True)
    # keep the variant list tight: each fresh (bq,bkv) compiles 7
    # pallas kernels through the tunnel; 'None' (the auto/table pick)
    # usually hits the autotune sweep's compile cache
    for blocks in [None, (128, 128), (256, 256), (256, 512),
                   (512, 512)]:
        tag = f"banded{blocks or '-auto'}"

        def setup(b=blocks):
            bd._FORCE_BLOCKS = b
            bs._FN_CACHE.clear()

        def teardown():
            bd._FORCE_BLOCKS = None
        run_variant(tag, setup, teardown)
        if blocks is None and tag in results:
            # fwd-vs-bwd split for the default pick
            bd._FORCE_BLOCKS = None
            t_f = timed_fwd("banded-auto", lambda q, k, v:
                            block_sparse_attention(q, k, v, layout))
            t_g = results[tag][0]
            print(f"banded-auto split: fwd {t_f*1e3:.2f} ms, bwd "
                  f"{(t_g - t_f)*1e3:.2f} ms (flash fwd {t_flash_f*1e3:.2f},"
                  f" bwd {(t_flash - t_flash_f)*1e3:.2f})", flush=True)

    # ---- optional device trace of one banded dispatch (VERDICT r3
    # weak #1: profile a splash dispatch on hardware). AB_TRACE=1
    # writes a jax.profiler trace to /tmp/tpu_round/splash_trace for
    # per-phase decomposition in xprof/tensorboard.
    import os as _os
    if _os.environ.get("AB_TRACE", "0") == "1":
        try:
            bs._FN_CACHE.clear()
            gfn = jax.jit(jax.grad(sparse_loss, argnums=(0, 1, 2)))
            jax.tree_util.tree_map(np.asarray, gfn(q, k, v))  # compile
            with jax.profiler.trace("/tmp/tpu_round/splash_trace"):
                jax.tree_util.tree_map(np.asarray, gfn(q, k, v))
            print("trace written to /tmp/tpu_round/splash_trace",
                  flush=True)
        except Exception as e:
            print(f"trace FAILED {type(e).__name__}: {e}", flush=True)

    # ---- generic kernels (banded off) ----
    def setup_coarse():
        bs.USE_BANDED = False
        bs._FORCE_COARSE_BLOCK = 512
        bs._FN_CACHE.clear()

    def setup_fine():
        bs.USE_BANDED = False
        bs._FORCE_COARSE_BLOCK = 0
        bs._FN_CACHE.clear()

    def teardown_generic():
        bs.USE_BANDED = True
        bs._FORCE_COARSE_BLOCK = None
    run_variant("v2-coarse512", setup_coarse, teardown_generic)
    run_variant("v2-fine", setup_fine, teardown_generic)

    # ---- parity + summary ----
    ref_tag = "v2-fine" if "v2-fine" in results else next(iter(results))
    _, r_ref = results[ref_tag]
    print("\n=== summary (grad ms/eval; parity vs "
          f"{ref_tag} grads) ===", flush=True)
    print(f"flash {t_flash*1e3:9.2f}" +
          (f" | vanilla {t_van*1e3:9.2f}" if t_van else ""), flush=True)
    best_tag, best_t = None, None
    for tag, (t, r) in sorted(results.items(), key=lambda kv: kv[1][0]):
        ok = True
        try:
            for a, b in zip(r, r_ref):
                np.testing.assert_allclose(np.asarray(a, np.float32),
                                           np.asarray(b, np.float32),
                                           atol=2e-2, rtol=2e-2)
        except AssertionError:
            ok = False
        line = (f"{tag:18s} {t*1e3:8.2f} ms  vs_flash "
                f"{t_flash/t:5.2f}x" +
                (f"  vs_vanilla {t_van/t:5.2f}x" if t_van else "") +
                ("  parity OK" if ok else "  PARITY FAIL"))
        print(line, flush=True)
        if ok and best_t is None:
            best_tag, best_t = tag, t
    if best_t is not None:
        print(f"\nbest: {best_tag} — vs_flash {t_flash/best_t:.2f}x" +
              (f", vs_vanilla {t_van/best_t:.2f}x" if t_van else "") +
              f"; FLOP bound vs flash ~{0.5/density:.1f}x "
              f"-> achieving {(t_flash/best_t)/(0.5/density)*100:.0f}% "
              "of bound", flush=True)
    # static roofline per banded tile choice (walk_stats is pure
    # arithmetic): names where the remaining gap to the bound goes.
    # Params come from the SAME plan() the dispatch used above.
    p = plan[0] if plan else None
    if p is not None:
        nnz = int(np.count_nonzero(np.asarray(layout)[0]))
        for blocks in [(128, 128), (256, 256), (256, 512), (512, 512)]:
            st = bd.walk_stats(S, 128, p, *blocks, n_active_blocks=nnz)
            print(f"walk_stats{blocks}: {sum(st['steps'].values())} "
                  f"steps, waste {st['waste']:.2f}x of exact-sparse",
                  flush=True)

    # ---- BigBird geometry: hybrid banded+residual vs the generic walk
    # (hybrid.py; the last layout family off the generic machinery) ----
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
    from deepspeed_tpu.ops.sparse_attention import hybrid as hy
    bb_cfg = BigBirdSparsityConfig(num_heads=H, block=128,
                                   num_random_blocks=1,
                                   num_sliding_window_blocks=3,
                                   num_global_blocks=1)
    bb_layout = bb_cfg.make_layout(S)
    bb_density = float(np.asarray(bb_layout).mean())
    hplan = hy.plan_hybrid(np.asarray(bb_layout), 128, False)
    planned_bb = bs.planned_kernel(bb_layout, 128)
    print(f"\n=== BigBird (density {bb_density:.3f}) — planned: "
          f"{planned_bb} | "
          + (f"hybrid coverage {hplan.coverage:.2f}" if hplan
             else "hybrid DECLINED"), flush=True)

    def bb_loss(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, bb_layout)
                       .astype(jnp.float32))

    bb_results = {}

    def bb_variant(tag, setup, teardown):
        setup()
        try:
            t, r = timed_grad(tag, bb_loss)
            bb_results[tag] = (t, r)
        except Exception as e:
            print(f"{tag}: FAILED {type(e).__name__}: {e}", flush=True)
        finally:
            teardown()
            bs._FN_CACHE.clear()

    # only time the 'hybrid' tag when the dispatcher will actually
    # build the hybrid — otherwise it would silently measure the same
    # generic kernel as the pair below and mislabel the log
    if hplan is not None and planned_bb == "hybrid":
        bb_variant("bigbird-hybrid", lambda: bs._FN_CACHE.clear(),
                   lambda: None)

    def bb_setup_generic():
        bs.USE_HYBRID = False
        bs._FN_CACHE.clear()

    def bb_teardown_generic():
        bs.USE_HYBRID = True
    bb_variant("bigbird-v2coarse", bb_setup_generic, bb_teardown_generic)

    if len(bb_results) == 2:
        (t_h, r_h), (t_g, r_g) = (bb_results["bigbird-hybrid"],
                                  bb_results["bigbird-v2coarse"])
        ok = True
        try:
            for a, b in zip(r_h, r_g):
                np.testing.assert_allclose(np.asarray(a, np.float32),
                                           np.asarray(b, np.float32),
                                           atol=2e-2, rtol=2e-2)
        except AssertionError:
            ok = False
        print(f"bigbird hybrid vs generic: {t_g/t_h:.2f}x  "
              f"vs_flash {t_flash/t_h:.2f}x  "
              f"(parity {'OK' if ok else 'FAIL'})", flush=True)
    if hplan is not None:
        st = hy.hybrid_stats(np.asarray(bb_layout), 128, hplan)
        print(f"hybrid_stats: waste {st['waste']:.2f}x of exact-sparse, "
              f"residual {st['residual_nnz_blocks']} blocks", flush=True)


if __name__ == "__main__":
    main()
