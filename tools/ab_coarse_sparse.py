"""A/B the coarse sparse walk vs the fine v2 walk on the bench config
(real chip): Longformer w=3 (class default), block=128, S=8192, H=16 —
the sparse_attention_speedup_s8k row. Run on hardware:
  PYTHONPATH=/root/repo python tools/ab_coarse_sparse.py
Prints both times, the speedup, and asserts on-chip grad parity."""
import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.platform import enable_compile_cache
from deepspeed_tpu.ops.sparse_attention import (
    BSLongformerSparsityConfig, block_sparse_attention)
from deepspeed_tpu.ops.sparse_attention import blocksparse as bs


def main():
    enable_compile_cache(None)
    B, H, S, D = 1, 16, 8192, 64
    # mirror the bench row's config (class-default window)
    cfg = BSLongformerSparsityConfig(num_heads=H, block=128,
                                     num_sliding_window_blocks=3)
    layout = cfg.make_layout(S)
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                 jnp.bfloat16) for i in range(3))

    from deepspeed_tpu.utils.benchtime import measure_rtt, scan_grad_seconds
    rtt = measure_rtt()
    print(f"rtt: {rtt * 1e3:.1f} ms", flush=True)

    def timed(tag, force):
        # Shared scan-amortized protocol (utils/benchtime.py): chained
        # grad evals in ONE dispatch, RTT-subtracted windows over a noise
        # floor — per-dispatch tunnel latency would otherwise dwarf the
        # ~10ms kernels being compared.
        bs._FORCE_COARSE_BLOCK = force
        bs._FN_CACHE.clear()

        def loss(q, k, v):
            return jnp.sum(block_sparse_attention(q, k, v, layout)
                           .astype(jnp.float32))
        grad_fn = jax.grad(loss, argnums=(0, 1, 2))
        r = jax.jit(grad_fn)(q, k, v)       # parity grads (one dispatch)
        jax.tree_util.tree_map(np.asarray, r)
        sec, n = scan_grad_seconds(grad_fn, (q, k, v), rtt, start_len=16)
        print(f"{tag}: {sec * 1e3:.1f} ms/eval ({n}-chained)", flush=True)
        return sec, r

    auto = bs._pick_coarse_block(layout, 128, has_am=False)
    print("cost model picks:", auto, flush=True)
    t_fine, r_fine = timed("fine v2 (forced off)", 0)
    results = {0: t_fine}
    for cb in (256, 512):
        try:
            t_cb, r_cb = timed(f"coarse {cb}", cb)
        except Exception as e:   # a forced tile may not divide/compile
            print(f"coarse {cb}: FAILED {type(e).__name__}", flush=True)
            continue
        results[cb] = t_cb
        for a, b, name in zip(r_fine, r_cb, "qkv"):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=2e-2, rtol=2e-2,
                                       err_msg=f"coarse {cb} d{name}")
        print(f"speedup coarse {cb} vs fine: {t_fine / t_cb:.2f}x "
              "(grad parity on-chip OK)", flush=True)
    best = min(results, key=results.get)
    print(f"best walk: {'fine' if best == 0 else f'coarse {best}'} "
          f"({results[best] * 1e3:.1f} ms/eval); cost model picked "
          f"{auto} -> {'AGREES' if best == (auto or 0) else 'DISAGREES'}",
          flush=True)


if __name__ == "__main__":
    main()
