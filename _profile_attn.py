"""Device-side attention kernel timing (immune to tunnel RTT): capture a
jax.profiler trace of dense flash vs sparse v1/v2 at S=8192 and report
per-kernel device times from the trace. Run when the TPU is free."""
import glob
import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention.flash import flash_attention
from deepspeed_tpu.ops.sparse_attention import (SparseSelfAttention,
                                                BSLongformerSparsityConfig)
from deepspeed_tpu.ops.sparse_attention import blocksparse as bs

B, H, S, D = 1, 16, 8192, 64
key = jax.random.PRNGKey(0)
q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                             jnp.bfloat16) for i in range(3))


def timed(tag, fn, iters=10):
    g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
    out = g(q, k, v)
    jax.tree_util.tree_map(np.asarray, out)
    best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, k, v)
        jax.tree_util.tree_map(np.asarray, out[0])
        w = (time.perf_counter() - t0) / iters
        best = w if best is None else min(best, w)
    print(f"{tag}: {best*1e3:.1f} ms")
    return best


def dense(q, k, v):
    return jnp.sum(flash_attention(q, k, v, causal=True)
                   .astype(jnp.float32))


sp = SparseSelfAttention(BSLongformerSparsityConfig(
    num_heads=H, block=128, num_sliding_window_blocks=9))


def sparse(q, k, v):
    return jnp.sum(sp(q, k, v).astype(jnp.float32))


t_dense = timed("dense", dense)
bs.USE_SPLASH_V2 = True
bs._FN_CACHE.clear()
t_v2 = timed("sparse_v2", sparse)
bs.USE_SPLASH_V2 = False
bs._FN_CACHE.clear()
t_v1 = timed("sparse_v1", sparse)
print(f"speedup v2/dense={t_dense/t_v2:.2f} v1/dense={t_dense/t_v1:.2f} "
      f"v2-vs-v1={t_v1/t_v2:.2f}")
