"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's single-node multi-process fixture strategy
(tests/unit/common.py:14 @distributed_test) but improves on it: instead of
forking NCCL processes we use XLA's host-platform device partitioning, so all
"distributed" logic (sharding, collectives, topology) runs in-process on CPU.

NB: this environment preloads jax via sitecustomize (axon TPU plugin), so
JAX_PLATFORMS in os.environ is too late — we must use jax.config.update.
XLA_FLAGS still works because backend initialization is lazy.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")
# hermetic comm-autotune planning: a measured wire_model.json — whether
# in the user cache OR exported via DSTPU_WIRE_MODEL in the shell — must
# not skew the golden decision tables, so pin unconditionally (tests
# that WANT an artifact monkeypatch this to a tmp file)
os.environ["DSTPU_WIRE_MODEL"] = "/nonexistent/dstpu_wire_model.json"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# jax < 0.5 has no top-level jax.shard_map; tests (and the package) use
# the modern spelling — install the adapter before any test imports it
from deepspeed_tpu.utils.jax_compat import install as _install  # noqa: E402

_install()

assert jax.device_count() == 8, (
    f"tests expect an 8-device CPU mesh, got {jax.device_count()} "
    f"{jax.default_backend()} devices")
