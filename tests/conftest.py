"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's single-node multi-process fixture strategy
(tests/unit/common.py:14 @distributed_test) but improves on it: instead of
forking NCCL processes we use XLA's host-platform device partitioning, so all
"distributed" logic (sharding, collectives, topology) runs in-process on CPU.
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("XLA_FLAGS",
                      os.environ.get("XLA_FLAGS", "") +
                      " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
