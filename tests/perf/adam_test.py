"""CPU Adam perf microbench (reference tests/perf/adam_test.py): native
AVX2 kernel vs the numpy oracle on a 10M-element parameter.

Run directly: python tests/perf/adam_test.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "..")))


def main(n=10_000_000, iters=5):
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(n).astype(np.float32)}
    grads = {"w": rng.randn(n).astype(np.float32)}

    opt = DeepSpeedCPUAdam(params, lr=1e-3)
    print(f"native kernel: {opt.uses_native_kernel}")
    opt.step(grads)  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        opt.step(grads)
    native = (time.perf_counter() - t0) / iters
    print(f"adam step ({n/1e6:.0f}M params): {native*1e3:.1f} ms "
          f"({n/native/1e9:.2f} Gparam/s)")

    if opt.uses_native_kernel:
        ref = DeepSpeedCPUAdam(params, lr=1e-3)
        ref._lib = None  # numpy fallback path
        ref.step(grads)
        t0 = time.perf_counter()
        for _ in range(iters):
            ref.step(grads)
        fallback = (time.perf_counter() - t0) / iters
        print(f"numpy fallback: {fallback*1e3:.1f} ms "
              f"(native speedup {fallback/native:.1f}x)")


if __name__ == "__main__":
    main()
