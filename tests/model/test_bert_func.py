"""BERT model-level CLI harness (reference tests/model/BingBertSquad):
launch the bing_bert workload as a subprocess, grep losses, compare
baseline-vs-feature."""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

_ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", ".."))
_TRAIN = os.path.join(_ROOT, "examples", "bing_bert", "train.py")


def _launch(*args, timeout=900):
    env = dict(os.environ)
    env.update({"DSTPU_PLATFORM": "cpu", "DSTPU_HOST_DEVICES": "8",
                "PYTHONPATH": _ROOT + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run(
        [sys.executable, _TRAIN, *args], env=env, cwd=_ROOT,
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"CLI failed:\nSTDOUT:{proc.stdout[-2000:]}\nSTDERR:{proc.stderr[-2000:]}"
    return [float(m) for m in re.findall(r"loss[ =]+([0-9.]+)", proc.stdout)]


def _cfg(tmp_path, name, **over):
    base = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    base.update(over)
    p = tmp_path / name
    p.write_text(json.dumps(base))
    return str(p)


def test_deterministic_and_zero_parity(tmp_path):
    """Two identical runs produce identical losses; ZeRO-2 matches the
    stage-0 baseline (the BingBertSquad baseline-vs-feature pattern)."""
    base = _cfg(tmp_path, "base.json")
    z2 = _cfg(tmp_path, "z2.json", zero_optimization={"stage": 2})
    a = _launch("--model", "tiny", "--steps", "3", "--seq", "64",
                "--deepspeed_config", base)
    b = _launch("--model", "tiny", "--steps", "3", "--seq", "64",
                "--deepspeed_config", base)
    c = _launch("--model", "tiny", "--steps", "3", "--seq", "64",
                "--deepspeed_config", z2)
    assert len(a) >= 2
    np.testing.assert_allclose(a, b, rtol=0)       # bitwise deterministic
    np.testing.assert_allclose(a, c, rtol=1e-4)    # ZeRO is a no-op on math
