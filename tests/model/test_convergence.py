"""Synthetic-task convergence gates (VERDICT r2 #7).

The reference's model tests gate on task metrics (SQuAD F1,
tests/model/BingBertSquad/test_e2e_squad.py); with no datasets in this
image, the equivalent gate is a LEARNABLE synthetic task: sequences
follow the deterministic affine map t_{i+1} = (3 t_i + 1) mod V, so
next-token loss starts at ~ln(V) and must fall near zero — any broken
optimizer semantics (mis-sharded moments, dropped grads, stale offload
masters, mis-routed experts) fails the threshold even when loss-parity
tests pass. One parametrized test per parallelism/optimizer mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_loss_fn,
                                       gpt2_moe_loss_fn, gpt2_sp_loss_fn,
                                       init_gpt2_moe_params,
                                       init_gpt2_params)

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

V, SEQ, BATCH = 32, 16, 16
CFG = GPT2Config(vocab_size=V, max_position_embeddings=SEQ + 1,
                 hidden_size=32, num_layers=2, num_heads=2,
                 embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)


def _affine_batch(rng, bs=BATCH):
    """(bs, SEQ+1) sequences following t_{i+1} = (3 t_i + 1) mod V."""
    t = rng.randint(0, V, size=(bs,)).astype(np.int64)
    cols = [t]
    for _ in range(SEQ):
        t = (3 * t + 1) % V
        cols.append(t)
    return {"input_ids": np.stack(cols, axis=1).astype(np.int32)}


def _train(loss_fn, params, config, steps=60, seed=0):
    eng, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                            config=config)
    rng = np.random.RandomState(seed)
    losses = [float(eng.train_batch(iter([_affine_batch(rng)])))
              for _ in range(steps)]
    eng.synchronize()  # drain any overlapped offload update
    return losses


def _base_config(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": BATCH // 8,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10**9,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
    }
    cfg.update(over)
    return cfg


THRESHOLD = 1.0   # from ~ln(32)=3.47 start; a healthy run reaches <0.5


def test_convergence_zero2():
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    losses = _train(gpt2_loss_fn(CFG, dtype=jnp.float32,
                                 deterministic=True),
                    params, _base_config(
                        zero_optimization={"stage": 2},
                        mesh={"axes": {"data": 8}}))
    assert losses[-1] < THRESHOLD, losses[::10]


def test_convergence_zero_offload():
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    losses = _train(gpt2_loss_fn(CFG, dtype=jnp.float32,
                                 deterministic=True),
                    params, _base_config(
                        zero_optimization={"stage": 2,
                                           "cpu_offload": True},
                        mesh={"axes": {"data": 8}}))
    assert losses[-1] < THRESHOLD, losses[::10]


def test_convergence_moe():
    from deepspeed_tpu.ops.moe import MoEConfig
    moe_cfg = MoEConfig(hidden_size=32, intermediate_size=64,
                        num_experts=4, top_k=2)
    params = init_gpt2_moe_params(CFG, moe_cfg, jax.random.PRNGKey(0))
    mesh_box = [None]

    def loss_fn(p, batch, rng):
        fn = gpt2_moe_loss_fn(CFG, moe_cfg, mesh=mesh_box[0],
                              dtype=jnp.float32, deterministic=True)
        return fn(p, batch, rng)

    eng, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config=_base_config(zero_optimization={"stage": 1},
                            mesh={"axes": {"data": 2, "expert": 4}},
                            train_micro_batch_size_per_gpu=BATCH // 2))
    mesh_box[0] = eng.mesh
    rng = np.random.RandomState(0)
    losses = [float(eng.train_batch(iter([_affine_batch(rng)])))
              for _ in range(60)]
    # the router aux losses keep a floor above the xent threshold; gate
    # on the drop from the ln(V) start instead
    assert losses[-1] < THRESHOLD + 0.5, losses[::10]


def test_convergence_sp():
    from deepspeed_tpu.parallel.mesh import build_mesh
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    losses = _train(gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.float32,
                                    deterministic=True),
                    params, _base_config(
                        zero_optimization={"stage": 1},
                        mesh={"axes": axes},
                        train_micro_batch_size_per_gpu=BATCH // 2))
    assert losses[-1] < THRESHOLD, losses[::10]


def test_convergence_llama_gqa_tp():
    """Llama family: GQA + RoPE + SwiGLU learns the affine map under
    data x model TP with ZeRO-2 (scanned layer layout)."""
    from deepspeed_tpu.models.llama import (LlamaConfig, init_llama_params,
                                            llama_loss_fn,
                                            llama_param_specs)
    cfg = LlamaConfig(vocab_size=V, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2,
                      max_position_embeddings=SEQ + 1, scan_layers=True)
    params = init_llama_params(cfg, jax.random.PRNGKey(0))
    eng, *_ = ds.initialize(
        model=llama_loss_fn(cfg, dtype=jnp.float32),
        model_parameters=params, param_specs=llama_param_specs(cfg),
        config=_base_config(zero_optimization={"stage": 2},
                            mesh={"axes": {"data": 4, "model": 2}}))
    rng = np.random.RandomState(0)
    losses = [float(eng.train_batch(iter([_affine_batch(rng)])))
              for _ in range(60)]
    assert losses[-1] < THRESHOLD, losses[::10]


# --------------------------------------------------------------------- #
# BERT MLM gates (reference model tests gate BERT on task metrics,
# tests/model/BingBertSquad; with no datasets in the image the gate is a
# learnable synthetic copy task — see _mlm_batch for why the causal
# gates' affine map doesn't transfer to bidirectional MLM)
# --------------------------------------------------------------------- #
BSEQ = 48  # multiple of the sparsity block below; divisible by 3


def _mlm_batch(rng, bs=BATCH):
    """Copy task in triples: tokens come as x x x and the MIDDLE of each
    triple is [MASK] — EITHER neighbor answers, so the attention circuit
    is not position-needle-in-a-haystack (a left-neighbor-only copy
    never escapes ln(V): with a content-free [MASK] query the expected
    information of random attention is ~0 and the landscape is flat).
    The causal gates' modular affine map is also unsuitable here: a
    bidirectional MLM groks only its low-2-bit submap within the budget
    (plateaus at exactly ln(8)) while a single-batch overfit reaches
    0.016 — task hardness, not optimizer semantics."""
    x = rng.randint(0, V, size=(bs, BSEQ // 3)).astype(np.int32)
    ids = np.repeat(x, 3, axis=1)                      # x0 x0 x0 x1 ...
    labels = np.full_like(ids, -100)
    mask = np.zeros((bs, BSEQ), bool)
    mask[:, 1::3] = True
    labels[mask] = ids[mask]
    ids = ids.copy()
    ids[mask] = V  # [MASK] id (vocab is V + 1 below)
    return {"input_ids": ids,
            "attention_mask": np.ones((bs, BSEQ), np.int32),
            "labels": labels}


def _bert_cfg():
    from deepspeed_tpu.models.bert import BertConfig
    return BertConfig(vocab_size=V + 1, hidden_size=32, num_layers=2,
                      num_heads=2, intermediate_size=64,
                      max_position_embeddings=BSEQ,
                      hidden_dropout=0.0, attn_dropout=0.0)


def _train_bert(sparsity_config=None):
    from deepspeed_tpu.models.bert import bert_mlm_loss_fn, init_bert_params
    cfg = _bert_cfg()
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    loss_fn = bert_mlm_loss_fn(cfg, dtype=jnp.float32, deterministic=True,
                               sparsity_config=sparsity_config)
    # MLM optimizes slower than the causal gates (only ~15% of positions
    # supervise after the no-adjacent constraint): higher lr, more steps
    eng, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config=_base_config(zero_optimization={"stage": 2},
                            mesh={"axes": {"data": 8}},
                            optimizer={"type": "Adam",
                                       "params": {"lr": 6e-3}}))
    rng = np.random.RandomState(0)
    return [float(eng.train_batch(iter([_mlm_batch(rng)])))
            for _ in range(150)]


def test_convergence_bert_mlm_zero2():
    losses = _train_bert()
    assert losses[-1] < THRESHOLD, losses[::10]


def test_convergence_bert_mlm_sparse_attention():
    """The JSON-schema default sparse config (fixed, block=16) must not
    break learnability: the task is local and the sliding/local window
    spans the informative neighbors."""
    from deepspeed_tpu.ops.sparse_attention import sparsity_config_from_dict
    from deepspeed_tpu.runtime.config import get_sparse_attention
    parsed = get_sparse_attention(
        {"sparse_attention": {"mode": "fixed", "block": 16,
                              "num_local_blocks": 2}})
    sc = sparsity_config_from_dict(parsed, num_heads=2)
    losses = _train_bert(sparsity_config=sc)
    assert losses[-1] < THRESHOLD, losses[::10]


def test_convergence_zero2_adam8bit():
    """8-bit optimizer states (TPU extension): the quantized-moment Adam
    must learn the task under ZeRO-2 like the fp32-state gate above."""
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    losses = _train(gpt2_loss_fn(CFG, dtype=jnp.float32,
                                 deterministic=True),
                    params, _base_config(
                        zero_optimization={"stage": 2},
                        mesh={"axes": {"data": 8}},
                        optimizer={"type": "Adam8bit",
                                   "params": {"lr": 3e-3}}))
    assert losses[-1] < THRESHOLD, losses[::10]
