"""Model-level functional harness (reference tests/model/Megatron_GPT2/
run_func_test.py): launch the actual CLI workload as a subprocess, grep
the LM loss from its stdout, and compare baseline-vs-feature runs —
the end-to-end tier the unit suite cannot cover in-process."""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

_ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", ".."))
_TRAIN = os.path.join(_ROOT, "examples", "megatron_gpt2", "train.py")


def _launch(*args, timeout=900):
    """Run the training CLI on a forced 8-device CPU mesh; return stdout."""
    env = dict(os.environ)
    env.update({"DSTPU_PLATFORM": "cpu", "DSTPU_HOST_DEVICES": "8",
                "PYTHONPATH": _ROOT + os.pathsep + env.get("PYTHONPATH", "")})
    proc = subprocess.run(
        [sys.executable, _TRAIN, *args], env=env, cwd=_ROOT,
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, \
        f"CLI run failed:\nSTDOUT:{proc.stdout[-2000:]}\n" \
        f"STDERR:{proc.stderr[-2000:]}"
    return proc.stdout


def grep_loss(stdout):
    """(reference run_func_test.py grep_loss_from_file:20-36)"""
    return [float(m) for m in
            re.findall(r"lm loss ([0-9.]+)", stdout)]


def _config_arg(tmp_path, name, cfg):
    import json
    p = tmp_path / name
    p.write_text(json.dumps(cfg))
    return str(p)


BASE = {
    "train_micro_batch_size_per_gpu": 2,
    "gradient_accumulation_steps": 1,
    "steps_per_print": 1000,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
}


@pytest.mark.parametrize("feature", [
    {"zero_optimization": {"stage": 1}},
    {"zero_optimization": {"stage": 2}},
], ids=["zero1", "zero2"])
def test_zero_matches_baseline_loss(tmp_path, feature):
    """ZeRO sharding must not change the math: CLI loss trajectories of
    the feature run match the stage-0 baseline (reference
    run_func_test.py baseline-vs-feature comparison)."""
    base_cfg = _config_arg(tmp_path, "base.json", BASE)
    feat_cfg = _config_arg(tmp_path, "feat.json", {**BASE, **feature})
    out_b = _launch("--mode", "zero2", "--tiny", "--steps", "4",
                    "--seq", "64", "--deepspeed_config", base_cfg)
    out_f = _launch("--mode", "zero2", "--tiny", "--steps", "4",
                    "--seq", "64", "--deepspeed_config", feat_cfg)
    lb, lf = grep_loss(out_b), grep_loss(out_f)
    assert len(lb) == 4 and len(lf) == 4
    np.testing.assert_allclose(lb, lf, rtol=1e-4)


def test_checkpoint_resume_matches_straight_run(tmp_path):
    """(reference run_checkpoint_test.py): train 2 steps + save, resume
    for 2 more; the resumed losses must equal steps 2-3 of an unbroken
    4-step run."""
    cfg = _config_arg(tmp_path, "cfg.json", BASE)
    save = str(tmp_path / "ckpt")
    straight = grep_loss(_launch(
        "--mode", "zero2", "--tiny", "--steps", "4", "--seq", "64",
        "--deepspeed_config", cfg))
    _launch("--mode", "zero2", "--tiny", "--steps", "2", "--seq", "64",
            "--deepspeed_config", cfg,
            "--save_dir", save, "--save_interval", "2")
    resumed = grep_loss(_launch(
        "--mode", "zero2", "--tiny", "--steps", "4", "--seq", "64",
        "--deepspeed_config", cfg, "--load_dir", save))
    assert len(straight) == 4 and len(resumed) == 2
    np.testing.assert_allclose(resumed, straight[2:], rtol=1e-4)


def test_offload_matches_in_hbm_loss(tmp_path):
    """ZeRO-Offload (host AVX2 Adam on the fp32 master state) must track
    the in-HBM Adam trajectory: the math is the same, only the residency
    of the master state changes. fp32-vs-bf16-accumulation and the
    round-to-nearest-even bf16 writeback give small per-step drift, so
    compare with a loose tolerance over a short run (reference
    run_func_test.py treats cpu-offload runs the same way)."""
    off_cfg = _config_arg(tmp_path, "off.json", {
        **BASE,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "cpu_offload": True,
                              "overlap_comm": True},
    })
    base_bf16 = _config_arg(tmp_path, "base_bf16.json", {
        **BASE, "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
    })
    out_b = _launch("--mode", "zero2", "--tiny", "--steps", "4",
                    "--seq", "64", "--deepspeed_config", base_bf16)
    out_f = _launch("--mode", "offload", "--tiny", "--steps", "4",
                    "--seq", "64", "--deepspeed_config", off_cfg)
    lb, lf = grep_loss(out_b), grep_loss(out_f)
    assert len(lb) == 4 and len(lf) == 4
    np.testing.assert_allclose(lb, lf, rtol=5e-2)
