"""Banded ("splash banded") sparse-attention fast path.

Structure detection + numerical parity of
deepspeed_tpu/ops/sparse_attention/banded.py against the dense-masked
oracle (blocksparse.block_sparse_attention_reference), across walk-tile
shapes, global/band geometries, causal clip, and key-padding masks.
Reference behavior being matched: block-level mask semantics of the
Triton sparse kernels (deepspeed/ops/sparse_attention/trsrc/
softmax_fwd.tr:100-119) for BSLongformer-class layouts
(sparsity_config.py:544).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import banded
from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig,
    FixedSparsityConfig)


def make_banded_layout(H, n, g_r, g_c, w, causal):
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    pred = (rb < g_r) | (cb < g_c) | (np.abs(rb - cb) <= w)
    if causal:
        pred = pred & (cb <= rb)
    return np.broadcast_to(pred.astype(np.int32), (H, n, n)).copy()


@pytest.fixture(autouse=True)
def _fresh_cache():
    # this module tests the LEGACY banded dispatch, kept as a numerics
    # oracle behind the flag since the unified masked kernel (PR 11)
    # became the default
    bs._FN_CACHE.clear()
    old = banded._FORCE_BLOCKS
    old_masked = bs.USE_MASKED_FLASH
    bs.USE_MASKED_FLASH = False
    yield
    banded._FORCE_BLOCKS = old
    bs.USE_MASKED_FLASH = old_masked
    bs._FN_CACHE.clear()


# --------------------------------------------------------------------- #
# detection
# --------------------------------------------------------------------- #
def test_detect_bslongformer_default():
    cfg = BSLongformerSparsityConfig(num_heads=4, block=64,
                                     num_sliding_window_blocks=3)
    p = banded.detect_banded(cfg.make_layout(1024))
    assert p is not None
    assert (p.g_r, p.g_c, p.w, p.causal) == (1, 1, 1, False)


def test_detect_reproduces_layout_exactly():
    """Whatever parameters detection returns, their predicate must
    reproduce the layout bit-for-bit (equivalent representations are
    fine; different layouts are not)."""
    for (g_r, g_c, w, causal) in [(1, 1, 1, False), (2, 2, 2, True),
                                  (0, 0, 1, False), (2, 0, 1, False),
                                  (0, 2, 1, True), (1, 1, 0, True)]:
        L = make_banded_layout(2, 16, g_r, g_c, w, causal)
        p = banded.detect_banded(L)
        assert p is not None, (g_r, g_c, w, causal)
        L2 = make_banded_layout(2, 16, p.g_r, p.g_c, p.w, p.causal)
        assert (L2 == L).all(), (g_r, g_c, w, causal, p)


def test_detect_declines_non_banded():
    # random blocks (BigBird) are not expressible as prefix+band
    bb = BigBirdSparsityConfig(num_heads=2, block=32).make_layout(512)
    assert banded.detect_banded(bb) is None
    # per-head-different layouts
    L = make_banded_layout(2, 8, 1, 1, 1, False)
    L[1, 3, 7] = 1
    assert banded.detect_banded(L) is None
    # fully dense should go to flash, not the banded walk
    assert banded.detect_banded(np.ones((2, 8, 8), np.int32)) is None
    # non-prefix global column
    L = make_banded_layout(1, 8, 0, 0, 1, False)
    L[0, :, 5] = 1
    assert banded.detect_banded(L) is None


def test_detect_declines_pure_global():
    """Global rows/cols with NO band: the w=-1 empty-band case must
    decline (a collapsed w=0 would add diagonal blocks the layout does
    not have — code-review r4 finding #1)."""
    n = 8
    idx = np.arange(n)
    rb, cb = idx[:, None], idx[None, :]
    for g_r, g_c in [(2, 0), (0, 2), (2, 2)]:
        L = np.broadcast_to(((rb < g_r) | (cb < g_c)).astype(np.int32),
                            (2, n, n)).copy()
        p = banded.detect_banded(L)
        if p is not None:       # only legal if predicate reproduces bits
            L2 = make_banded_layout(2, n, p.g_r, p.g_c, p.w, p.causal)
            assert (L2 == L).all(), (g_r, g_c, p)
        # dispatcher must stay correct either way
        o = bs.block_sparse_attention(
            *[jax.random.normal(jax.random.PRNGKey(i), (1, 2, 256, 16))
              for i in range(3)], L)
        o_ref = bs.block_sparse_attention_reference(
            *[jax.random.normal(jax.random.PRNGKey(i), (1, 2, 256, 16))
              for i in range(3)], L)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=5e-5, rtol=5e-5)


def test_bad_blocks_fall_back_to_heuristic():
    """An invalid force/table tile (not dividing S) must not disable the
    fast path — pick_blocks falls back to the heuristic."""
    p = banded.BandedParams(1, 1, 1, False)
    banded._FORCE_BLOCKS = (96, 96)      # does not divide 256
    got = banded.pick_blocks(256, 32, p, True)
    assert got is not None and 256 % got[0] == 0 and 256 % got[1] == 0


def test_dispatch_plans_banded_for_longformer():
    cfg = BSLongformerSparsityConfig(num_heads=2, block=32)
    L = cfg.make_layout(512)
    assert bs.planned_kernel(L, 32, interpret=True) == "banded"
    f = bs._sparse_attention_fn(L, 32, 0.125, has_am=False, interpret=True)
    assert getattr(f, "kernel_kind", None) == "banded"
    # attn-mask configurations stay on the generic kernels
    assert "banded" not in bs.planned_kernel(L, 32, has_am=True,
                                             interpret=True)


# --------------------------------------------------------------------- #
# numerical parity vs the dense-masked oracle
# --------------------------------------------------------------------- #
def _parity(L, fb, S, blocks, kpm_mode=None, dtype=jnp.float32, seed=0):
    banded._FORCE_BLOCKS = blocks
    bs._FN_CACHE.clear()
    key = jax.random.PRNGKey(seed)
    B, H, D = 2, L.shape[0], 16
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                 dtype) for i in range(3))
    kpm = None
    if kpm_mode == "add":
        kpm = (jax.random.normal(jax.random.fold_in(key, 7), (B, S))
               * 2).astype(jnp.float32)
    elif kpm_mode == "mul":
        kpm = (jax.random.uniform(jax.random.fold_in(key, 8), (B, S))
               > 0.2).astype(jnp.float32)
    kw = dict(key_padding_mask=kpm,
              key_padding_mask_mode=kpm_mode or "add")
    f = bs._sparse_attention_fn(L, fb, float(D) ** -0.5, has_am=False,
                                interpret=True)
    assert getattr(f, "kernel_kind", None) == "banded"

    o = bs.block_sparse_attention(q, k, v, L, **kw)
    o_ref = bs.block_sparse_attention_reference(q, k, v, L, **kw)
    tol = 5e-5 if dtype == jnp.float32 else 6e-2
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_ref, np.float32),
                               atol=tol, rtol=tol)

    def loss(q, k, v):
        return jnp.sum(
            bs.block_sparse_attention(q, k, v, L, **kw)
            .astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            bs.block_sparse_attention_reference(q, k, v, L, **kw)
            .astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    gtol = tol * 40
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=gtol, rtol=gtol)


@pytest.mark.parametrize("blocks", [(32, 32), (64, 128), (128, 64)])
def test_longformer_parity_tile_shapes(blocks):
    """The walk-tile size must never change results — including tiles
    larger than the fine block (multi-block tiles) and asymmetric
    bq != bkv walks."""
    cfg = BSLongformerSparsityConfig(num_heads=2, block=32)
    _parity(cfg.make_layout(256), 32, 256, blocks)


@pytest.mark.parametrize("g_r,g_c,w,causal", [
    (1, 1, 1, False), (2, 2, 2, True), (0, 0, 1, False),
    (0, 0, 2, True), (3, 3, 1, False), (2, 0, 1, False),
    (0, 2, 1, True), (1, 1, 0, True),
])
def test_geometry_parity(g_r, g_c, w, causal):
    """Global rows only / cols only / band only / causal clip / diag-only
    band, incl. multi-tile global prefixes (g_r * fb > bq)."""
    fb, S = 32, 512
    L = make_banded_layout(2, S // fb, g_r, g_c, w, causal)
    _parity(L, fb, S, (64, 64))


@pytest.mark.parametrize("mode", ["add", "mul"])
def test_key_padding_mask_parity(mode):
    cfg = BSLongformerSparsityConfig(num_heads=2, block=32)
    _parity(cfg.make_layout(256), 32, 256, (64, 128), kpm_mode=mode)


def test_bf16_parity():
    cfg = BSLongformerSparsityConfig(num_heads=2, block=64,
                                     num_sliding_window_blocks=5)
    _parity(cfg.make_layout(512), 64, 512, (128, 128),
            dtype=jnp.bfloat16)


def test_banded_matches_generic_v2():
    """The fast path and the generic row-run kernels must agree on the
    same layout (both already match the oracle; this pins them to each
    other directly, incl. the lse/normalization conventions)."""
    cfg = BSLongformerSparsityConfig(num_heads=2, block=32)
    L = cfg.make_layout(256)
    key = jax.random.PRNGKey(3)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (1, 2, 256, 16), jnp.float32)
               for i in range(3))

    def run():
        def loss(q, k, v):
            return jnp.sum(
                bs.block_sparse_attention(q, k, v, L)
                .astype(jnp.float32) ** 2)
        o = bs.block_sparse_attention(q, k, v, L)
        return (o,) + jax.grad(loss, (0, 1, 2))(q, k, v)

    a = run()
    old = bs.USE_BANDED
    try:
        bs.USE_BANDED = False
        bs._FN_CACHE.clear()
        b = run()
    finally:
        bs.USE_BANDED = old
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=2e-5, rtol=2e-5)


def test_fixed_config_band_detection_consistency():
    """FixedSparsityConfig layouts are block-local, not banded — the
    dispatcher must keep them on the generic path and still match the
    oracle (guards against over-eager detection)."""
    cfg = FixedSparsityConfig(num_heads=2, block=32, num_local_blocks=4)
    L = cfg.make_layout(512)
    kind = bs.planned_kernel(L, 32, interpret=True)
    p = banded.detect_banded(L)
    if p is not None:
        # if it ever matches, the predicate must reproduce the bits
        L2 = make_banded_layout(L.shape[0], L.shape[1], p.g_r, p.g_c,
                                p.w, p.causal)
        assert (L2 == L).all()
    else:
        assert kind != "banded"


def test_bench_geometry_flop_accounting():
    """Structural perf evidence at the scored bench geometry
    (BSLongformer win=3, block=128, S=8192): the banded walk's static
    MXU work must stay near the exact-sparse bound — the property whose
    absence made the generic kernels lose their ~10x density edge
    (VERDICT r3 weak #1). Pure arithmetic (walk_stats), no hardware."""
    cfg = BSLongformerSparsityConfig(num_heads=16, block=128,
                                     num_sliding_window_blocks=3)
    L = cfg.make_layout(8192)
    p = banded.detect_banded(L)
    assert p is not None
    nnz = int(np.count_nonzero(L[0]))
    # the fine-tile walk is essentially exact sparse
    fine = banded.walk_stats(8192, 128, p, 128, 128, n_active_blocks=nnz)
    assert fine["waste"] <= 1.1, fine
    # every candidate tile the autotuner may pick stays within 4.5x of
    # the bound — i.e. never regresses to dense-causal work (which is
    # 9 * (nb^2/2) cell-dots ~ 6.5x the sparse bound here)
    dense = 9 * (64 * 64 // 2 + 32) * 128 * 128
    for blocks in [(128, 128), (256, 256), (256, 512), (512, 512)]:
        st = banded.walk_stats(8192, 128, p, *blocks, n_active_blocks=nnz)
        assert st["waste"] <= 4.5, (blocks, st)
        assert st["computed_cell_dots"] <= 0.65 * dense, (blocks, st)
    # the TABLE-LESS heuristic pick specifically: <= 2.5x bound, <= 1/3
    # of dense-causal (a hardware-tuned table entry may trade FLOPs for
    # wall-clock; the candidate bound above still covers it)
    from deepspeed_tpu.ops.attention import flash as F
    old = F._BLOCK_ENTRIES
    F._BLOCK_ENTRIES = []
    try:
        db = banded.pick_blocks(8192, 128, p, interpret=False)
    finally:
        F._BLOCK_ENTRIES = old
    st = banded.walk_stats(8192, 128, p, *db, n_active_blocks=nnz)
    assert st["waste"] <= 2.5, (db, st)
    assert st["computed_cell_dots"] <= 0.35 * dense, (db, st)
    # long-context scaling (the reference's 10x-longer-sequences axis):
    # at S=32k the banded work stays O(S) — the dense-causal ratio
    # keeps improving ~linearly with S
    L32 = BSLongformerSparsityConfig(
        num_heads=1, block=128,
        num_sliding_window_blocks=3).make_layout(32768)
    p32 = banded.detect_banded(L32)
    nnz32 = int(np.count_nonzero(L32[0]))
    nb32 = 32768 // 128
    st32 = banded.walk_stats(32768, 128, p32, 256, 256,
                             n_active_blocks=nnz32)
    dense32 = 9 * (nb32 * nb32 // 2 + nb32 // 2) * 128 * 128
    assert st32["waste"] <= 2.5, st32
    assert st32["computed_cell_dots"] <= 0.12 * dense32, (
        st32["computed_cell_dots"] / dense32)


def test_zero_coverage_rows_zero_output():
    """A fully-masked key set (mul-mode kpm dropping every key) must
    yield zero output rows, matching the generic kernels' convention."""
    cfg = BSLongformerSparsityConfig(num_heads=2, block=32)
    L = cfg.make_layout(256)
    banded._FORCE_BLOCKS = (64, 64)
    key = jax.random.PRNGKey(4)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (1, 2, 256, 16), jnp.float32)
               for i in range(3))
    kpm = np.zeros((1, 256), np.float32)        # mul-mode: drop all keys
    o = bs.block_sparse_attention(q, k, v, L, key_padding_mask=kpm,
                                  key_padding_mask_mode="mul")
    assert float(jnp.abs(o).max()) == 0.0
