"""Optimizer numerics vs reference math (mirrors reference
tests/unit/test_adam_acuracy.py and lamb kernel tests — tier-2 numerics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import (
    Adam, Lamb, SGD, build_optimizer)


def numpy_adam(p, g, m, v, step, lr, b1, b2, eps, wd, adamw):
    g = g.copy()
    if wd and not adamw:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    bc1 = 1 - b1 ** step
    bc2 = 1 - b2 ** step
    update = (m / bc1) / (np.sqrt(v / bc2) + eps)
    if wd and adamw:
        update = update + wd * p
    return p - lr * update, m, v


@pytest.mark.parametrize("adamw", [False, True])
@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_adam_matches_numpy(adamw, wd):
    rng = np.random.RandomState(0)
    p = rng.randn(4, 8).astype(np.float32)
    params = {"w": jnp.asarray(p)}
    opt = Adam(lr=1e-2, weight_decay=wd, adamw_mode=adamw)
    state = opt.init(params)

    np_p, np_m, np_v = p.copy(), np.zeros_like(p), np.zeros_like(p)
    for step in range(1, 4):
        g = rng.randn(4, 8).astype(np.float32)
        params, state = jax.jit(opt.update)({"w": jnp.asarray(g)}, state,
                                            params)
        np_p, np_m, np_v = numpy_adam(np_p, g, np_m, np_v, step, 1e-2,
                                      0.9, 0.999, 1e-8, wd, adamw)
    np.testing.assert_allclose(np.asarray(params["w"]), np_p, rtol=1e-5,
                               atol=1e-6)
    assert int(state.step) == 3


def test_sgd_momentum():
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = SGD(lr=0.1, momentum=0.9)
    state = opt.init(params)
    g = {"w": jnp.ones((4,), jnp.float32)}
    params, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.9 * np.ones(4),
                               rtol=1e-6)
    params, state = opt.update(g, state, params)
    # buf = 0.9*1 + 1 = 1.9; p = 0.9 - 0.1*1.9 = 0.71
    np.testing.assert_allclose(np.asarray(params["w"]), 0.71 * np.ones(4),
                               rtol=1e-6)


def test_lamb_trust_ratio_clamped():
    params = {"w": jnp.full((8, 8), 100.0, jnp.float32)}
    opt = Lamb(lr=1e-3, max_coeff=10.0, min_coeff=0.01)
    state = opt.init(params)
    g = {"w": jnp.full((8, 8), 1e-6, jnp.float32)}
    new_params, state = opt.update(g, state, params)
    # trust ratio would be enormous; must be clamped to max_coeff=10
    delta = np.asarray(params["w"] - new_params["w"])
    assert np.all(delta > 0)
    # max step size = lr * max_coeff * update, update ~= g/sqrt(v)≈1 after
    # bias correction; so delta <= lr * max_coeff
    assert np.max(delta) <= 1e-3 * 10.0 * 1.5


def test_lamb_zero_weight_norm_uses_unit_trust():
    params = {"w": jnp.zeros((4,), jnp.float32)}
    opt = Lamb(lr=0.1)
    state = opt.init(params)
    g = {"w": jnp.ones((4,), jnp.float32)}
    new_params, _ = opt.update(g, state, params)
    assert np.all(np.isfinite(np.asarray(new_params["w"])))


def test_build_optimizer_from_config():
    opt = build_optimizer("adam", {"lr": 3e-4, "betas": [0.8, 0.9],
                                   "weight_decay": 0.1})
    assert isinstance(opt, Adam) and opt.lr == 3e-4 and opt.b1 == 0.8
    opt = build_optimizer("lamb", {"lr": 1e-2, "max_coeff": 5.0})
    assert isinstance(opt, Lamb) and opt.max_coeff == 5.0
    opt = build_optimizer("sgd", {"lr": 0.1, "momentum": 0.9})
    assert isinstance(opt, SGD)
    with pytest.raises(ValueError):
        build_optimizer("adagrad", {})


def test_fp16_param_dtype_preserved():
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    opt = Adam(lr=0.1)
    state = opt.init(params)
    new_params, _ = opt.update({"w": jnp.ones((4,), jnp.bfloat16)}, state,
                               params)
    assert new_params["w"].dtype == jnp.bfloat16
    # moments stay fp32 regardless
    assert state.exp_avg["w"].dtype == jnp.float32
