"""Optimizer numerics vs reference math (mirrors reference
tests/unit/test_adam_acuracy.py and lamb kernel tests — tier-2 numerics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import (
    Adam, Lamb, SGD, build_optimizer)


def numpy_adam(p, g, m, v, step, lr, b1, b2, eps, wd, adamw):
    g = g.copy()
    if wd and not adamw:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    bc1 = 1 - b1 ** step
    bc2 = 1 - b2 ** step
    update = (m / bc1) / (np.sqrt(v / bc2) + eps)
    if wd and adamw:
        update = update + wd * p
    return p - lr * update, m, v


@pytest.mark.parametrize("adamw", [False, True])
@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_adam_matches_numpy(adamw, wd):
    rng = np.random.RandomState(0)
    p = rng.randn(4, 8).astype(np.float32)
    params = {"w": jnp.asarray(p)}
    opt = Adam(lr=1e-2, weight_decay=wd, adamw_mode=adamw)
    state = opt.init(params)

    np_p, np_m, np_v = p.copy(), np.zeros_like(p), np.zeros_like(p)
    for step in range(1, 4):
        g = rng.randn(4, 8).astype(np.float32)
        params, state = jax.jit(opt.update)({"w": jnp.asarray(g)}, state,
                                            params)
        np_p, np_m, np_v = numpy_adam(np_p, g, np_m, np_v, step, 1e-2,
                                      0.9, 0.999, 1e-8, wd, adamw)
    np.testing.assert_allclose(np.asarray(params["w"]), np_p, rtol=1e-5,
                               atol=1e-6)
    assert int(state.step) == 3


def test_sgd_momentum():
    params = {"w": jnp.ones((4,), jnp.float32)}
    opt = SGD(lr=0.1, momentum=0.9)
    state = opt.init(params)
    g = {"w": jnp.ones((4,), jnp.float32)}
    params, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.9 * np.ones(4),
                               rtol=1e-6)
    params, state = opt.update(g, state, params)
    # buf = 0.9*1 + 1 = 1.9; p = 0.9 - 0.1*1.9 = 0.71
    np.testing.assert_allclose(np.asarray(params["w"]), 0.71 * np.ones(4),
                               rtol=1e-6)


def test_lamb_trust_ratio_clamped():
    params = {"w": jnp.full((8, 8), 100.0, jnp.float32)}
    opt = Lamb(lr=1e-3, max_coeff=10.0, min_coeff=0.01)
    state = opt.init(params)
    g = {"w": jnp.full((8, 8), 1e-6, jnp.float32)}
    new_params, state = opt.update(g, state, params)
    # trust ratio would be enormous; must be clamped to max_coeff=10
    delta = np.asarray(params["w"] - new_params["w"])
    assert np.all(delta > 0)
    # max step size = lr * max_coeff * update, update ~= g/sqrt(v)≈1 after
    # bias correction; so delta <= lr * max_coeff
    assert np.max(delta) <= 1e-3 * 10.0 * 1.5


def test_lamb_zero_weight_norm_uses_unit_trust():
    params = {"w": jnp.zeros((4,), jnp.float32)}
    opt = Lamb(lr=0.1)
    state = opt.init(params)
    g = {"w": jnp.ones((4,), jnp.float32)}
    new_params, _ = opt.update(g, state, params)
    assert np.all(np.isfinite(np.asarray(new_params["w"])))


def test_build_optimizer_from_config():
    opt = build_optimizer("adam", {"lr": 3e-4, "betas": [0.8, 0.9],
                                   "weight_decay": 0.1})
    assert isinstance(opt, Adam) and opt.lr == 3e-4 and opt.b1 == 0.8
    opt = build_optimizer("lamb", {"lr": 1e-2, "max_coeff": 5.0})
    assert isinstance(opt, Lamb) and opt.max_coeff == 5.0
    opt = build_optimizer("sgd", {"lr": 0.1, "momentum": 0.9})
    assert isinstance(opt, SGD)
    with pytest.raises(ValueError):
        build_optimizer("adagrad", {})


def test_fp16_param_dtype_preserved():
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    opt = Adam(lr=0.1)
    state = opt.init(params)
    new_params, _ = opt.update({"w": jnp.ones((4,), jnp.bfloat16)}, state,
                               params)
    assert new_params["w"].dtype == jnp.bfloat16
    # moments stay fp32 regardless
    assert state.exp_avg["w"].dtype == jnp.float32


# --------------------------------------------------------------------- #
# 8-bit optimizer states (TPU extension beyond the reference)
# --------------------------------------------------------------------- #
class TestAdam8bit:

    def _run(self, opt, params, n_steps, seed=0):
        rng = np.random.RandomState(seed)
        state, p = opt.init(params), params
        upd = jax.jit(opt.update)
        for _ in range(n_steps):
            g = {k: jnp.asarray(rng.randn(*np.shape(v)), jnp.float32)
                 for k, v in params.items()}
            p, state = upd(g, state, p)
        return p, state

    def test_tracks_fp32_adam(self):
        from deepspeed_tpu.ops.optimizers import Adam, Adam8bit
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(1000, 7), jnp.float32),
                  "b": jnp.asarray(rng.randn(3), jnp.float32)}
        p32, _ = self._run(Adam(lr=1e-2, weight_decay=0.01), params, 50)
        p8, _ = self._run(Adam8bit(lr=1e-2, weight_decay=0.01), params, 50)
        for k in params:
            d = np.abs(np.asarray(p32[k]) - np.asarray(p8[k])).max()
            rel = d / (np.abs(np.asarray(p32[k])).max() + 1e-9)
            assert rel < 0.02, (k, float(rel))

    def test_small_v_under_block_outlier_does_not_explode(self):
        """Regression: linear int8 v-quantization zeroed any v below
        absmax/254, and the eps-only denominator turned a surviving
        first moment into a +2.36 one-step parameter jump. sqrt-space
        codes + the code-0 floor keep every update Adam-bounded."""
        from deepspeed_tpu.ops.optimizers import Adam8bit
        opt = Adam8bit(lr=1e-2)
        n = 256
        params = {"w": jnp.zeros((n,), jnp.float32)}
        state, p = opt.init(params), params
        g = np.full((n,), 1e-4, np.float32)
        g[0] = 10.0    # block absmax outlier dominates the shared scale
        g = {"w": jnp.asarray(g)}
        upd = jax.jit(opt.update)
        for _ in range(20):
            p, state = upd(g, state, p)
        # constant gradient: |update| <= lr / (1 - small); far below 1
        assert np.abs(np.asarray(p["w"])).max() < 20 * 1e-2 * 1.5, \
            np.abs(np.asarray(p["w"])).max()

    def test_state_bytes_about_4x_smaller(self):
        from deepspeed_tpu.ops.optimizers import Adam, Adam8bit
        params = {"w": jnp.zeros((4096, 64), jnp.float32)}

        def nbytes(tree):
            return sum(x.size * x.dtype.itemsize
                       for x in jax.tree_util.tree_leaves(tree))
        s32 = Adam().init(params)
        s8 = Adam8bit().init(params)
        ratio = nbytes((s32.exp_avg, s32.exp_avg_sq)) / nbytes(
            (s8.m_codes, s8.m_scales, s8.v_codes, s8.v_scales))
        assert ratio > 3.9, ratio

    def test_build_optimizer_dispatch_and_momentum_override(self):
        from deepspeed_tpu.ops.optimizers import Adam8bit, build_optimizer
        opt = build_optimizer("Adam8bit", {"lr": 2e-3, "block_size": 128})
        assert isinstance(opt, Adam8bit) and opt.block_size == 128
        params = {"w": jnp.ones((64,), jnp.float32)}
        state = opt.init(params)
        g = {"w": jnp.full((64,), 0.1, jnp.float32)}
        # traced beta1 override flows like lr (OneCycle momentum hook)
        p2, s2 = jax.jit(opt.update)(g, state, params,
                                     momentum=jnp.float32(0.5))
        m = np.asarray(s2.m_codes["w"], np.float32) * \
            np.asarray(s2.m_scales["w"])
        np.testing.assert_allclose(m.reshape(-1)[:64], 0.05, rtol=0.02)

    def test_frozen_block_first_real_update_not_suppressed(self):
        """Regression: an all-zero v block must store scale 0, not a
        placeholder — a phantom scale let the code-0 dequant floor
        inject a fake second moment into frozen blocks and shrink their
        first real update ~60x vs fp32 Adam."""
        from deepspeed_tpu.ops.optimizers import Adam, Adam8bit
        params = {"w": jnp.zeros((256,), jnp.float32)}
        zero_g = {"w": jnp.zeros((256,), jnp.float32)}
        real_g = {"w": jnp.full((256,), 1e-3, jnp.float32)}
        results = {}
        for name, opt in (("fp32", Adam(lr=1e-2)),
                          ("q8", Adam8bit(lr=1e-2))):
            st, p = opt.init(params), params
            upd = jax.jit(opt.update)
            for _ in range(5):
                p, st = upd(zero_g, st, p)
            p, st = upd(real_g, st, p)
            results[name] = float(np.abs(np.asarray(p["w"])).max())
        assert results["q8"] > 0.5 * results["fp32"], results
