"""LR schedule tests (mirrors reference tests/unit/test_lr_schedulers.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    WarmupLR, OneCycle, LRRangeTest, build_lr_schedule)


class TestWarmupLR:

    def test_linear_ramp(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                     warmup_num_steps=10, warmup_type="linear")
        assert float(s.lr_at(jnp.asarray(0))) == pytest.approx(0.0)
        assert float(s.lr_at(jnp.asarray(5))) == pytest.approx(0.5)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(1.0)
        assert float(s.lr_at(jnp.asarray(100))) == pytest.approx(1.0)

    def test_log_ramp_monotone(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                     warmup_num_steps=100, warmup_type="log")
        lrs = [float(s.lr_at(jnp.asarray(i))) for i in range(0, 120, 10)]
        assert all(b >= a - 1e-7 for a, b in zip(lrs, lrs[1:]))
        assert lrs[-1] == pytest.approx(1.0)

    def test_step_facade(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                     warmup_num_steps=4, warmup_type="linear")
        for _ in range(4):
            s.step()
        assert s.get_lr()[0] == pytest.approx(0.75)
        sd = s.state_dict()
        s2 = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                      warmup_num_steps=4, warmup_type="linear")
        s2.load_state_dict(sd)
        assert s2.last_batch_iteration == s.last_batch_iteration


class TestLRRangeTest:

    def test_continuous(self):
        s = LRRangeTest(lr_range_test_min_lr=0.1,
                        lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0)
        assert float(s.lr_at(jnp.asarray(0))) == pytest.approx(0.1)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(0.2)
        assert float(s.lr_at(jnp.asarray(20))) == pytest.approx(0.3)

    def test_staircase(self):
        s = LRRangeTest(lr_range_test_min_lr=0.1,
                        lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0,
                        lr_range_test_staircase=True)
        assert float(s.lr_at(jnp.asarray(9))) == pytest.approx(0.1)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(0.2)
        assert float(s.lr_at(jnp.asarray(19))) == pytest.approx(0.2)


class TestOneCycle:

    def test_triangle(self):
        s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                     cycle_first_step_size=10, cycle_second_step_size=10)
        assert float(s.lr_at(jnp.asarray(0))) == pytest.approx(0.1)
        assert float(s.lr_at(jnp.asarray(5))) == pytest.approx(0.55)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(1.0)
        assert float(s.lr_at(jnp.asarray(15))) == pytest.approx(0.55)
        assert float(s.lr_at(jnp.asarray(20))) == pytest.approx(0.1)

    def test_decay_phase(self):
        s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                     cycle_first_step_size=5, cycle_second_step_size=5,
                     decay_step_size=5, decay_lr_rate=1.0)
        after = float(s.lr_at(jnp.asarray(15)))  # 5 steps past cycle end
        assert after == pytest.approx(0.1 / 2.0)

    def test_momentum_counter_cycles(self):
        s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                     cycle_first_step_size=10, cycle_second_step_size=10,
                     cycle_min_mom=0.85, cycle_max_mom=0.99)
        assert float(s.mom_at(jnp.asarray(0))) == pytest.approx(0.99)
        assert float(s.mom_at(jnp.asarray(10))) == pytest.approx(0.85)
        assert float(s.mom_at(jnp.asarray(20))) == pytest.approx(0.99)


def test_onecycle_momentum_applied_to_adam():
    """VERDICT r2 #4: mom_at must actually reach the optimizer — the
    engine threads it into the compiled Adam update as the per-step
    beta1 (reference lr_schedules.py:518-540 mutates param_groups betas).
    With a constant unit gradient, exp_avg follows the recursion
    m_k = mu_k * m_{k-1} + (1 - mu_k) exactly."""
    import jax
    import deepspeed_tpu as ds

    params = {"w": jnp.ones((4,), jnp.float32)}

    def loss_fn(p, batch, rng=None):
        return jnp.sum(p["w"])          # d/dw == 1 everywhere

    eng, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10**9,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-2, "betas": [0.9, 0.999]}},
            "scheduler": {"type": "OneCycle",
                          "params": {"cycle_min_lr": 1e-3,
                                     "cycle_max_lr": 1e-2,
                                     "cycle_first_step_size": 3,
                                     "cycle_second_step_size": 3,
                                     "cycle_min_mom": 0.5,
                                     "cycle_max_mom": 0.9}},
        })
    sched = eng.lr_scheduler
    assert sched.cycle_momentum

    batch = {"x": np.zeros((8, 1), np.float32)}
    m_ref, steps = 0.0, 6
    for k in range(steps):
        eng.train_batch(iter([batch]))
        mu = float(sched.mom_at(jnp.asarray(k)))
        m_ref = mu * m_ref + (1.0 - mu)
    m_eng = np.asarray(eng.state.opt_state.exp_avg["w"])
    np.testing.assert_allclose(m_eng, np.full((4,), m_ref), rtol=1e-5)
    # and the cycle really varied beta1 (not a constant-0.9 run)
    m_const = 0.0
    for _ in range(steps):
        m_const = 0.9 * m_const + 0.1
    assert abs(m_ref - m_const) > 1e-3


def test_build_from_config():
    s = build_lr_schedule("WarmupLR", {"warmup_max_lr": 0.5})
    assert isinstance(s, WarmupLR)
    s = build_lr_schedule("OneCycle", {"cycle_min_lr": 0.01,
                                       "cycle_max_lr": 0.1})
    assert isinstance(s, OneCycle)
    s = build_lr_schedule("LRRangeTest", {})
    assert isinstance(s, LRRangeTest)
    assert build_lr_schedule(None, None) is None
    with pytest.raises(ValueError):
        build_lr_schedule("CosineNope", {})


def test_tuning_args_to_config_roundtrip():
    """CLI tuning args -> scheduler config (reference lr_schedules.py
    add_tuning_arguments/get_config_from_args/get_lr_from_config)."""
    import argparse
    from deepspeed_tpu.runtime.lr_schedules import (
        add_tuning_arguments, get_config_from_args, get_lr_from_config)
    p = argparse.ArgumentParser()
    add_tuning_arguments(p)
    args, _ = p.parse_known_args(
        ["--lr_schedule", "OneCycle", "--cycle_min_lr", "0.02",
         "--cycle_max_lr", "0.2", "--cycle_momentum"])
    cfg, err = get_config_from_args(args)
    assert err is None
    assert cfg["type"] == "OneCycle"
    assert cfg["params"]["cycle_min_lr"] == 0.02
    assert cfg["params"]["cycle_momentum"] is True
    lr, err = get_lr_from_config(cfg)
    assert err == "" and lr == 0.2
    # the generated config constructs a working schedule
    s = build_lr_schedule(cfg["type"], cfg["params"])
    assert isinstance(s, OneCycle) and s.cycle_momentum

    args2, _ = p.parse_known_args([])
    cfg2, err2 = get_config_from_args(args2)
    assert cfg2 is None and "not specified" in err2
