"""LR schedule tests (mirrors reference tests/unit/test_lr_schedulers.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    WarmupLR, OneCycle, LRRangeTest, build_lr_schedule)


class TestWarmupLR:

    def test_linear_ramp(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                     warmup_num_steps=10, warmup_type="linear")
        assert float(s.lr_at(jnp.asarray(0))) == pytest.approx(0.0)
        assert float(s.lr_at(jnp.asarray(5))) == pytest.approx(0.5)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(1.0)
        assert float(s.lr_at(jnp.asarray(100))) == pytest.approx(1.0)

    def test_log_ramp_monotone(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                     warmup_num_steps=100, warmup_type="log")
        lrs = [float(s.lr_at(jnp.asarray(i))) for i in range(0, 120, 10)]
        assert all(b >= a - 1e-7 for a, b in zip(lrs, lrs[1:]))
        assert lrs[-1] == pytest.approx(1.0)

    def test_step_facade(self):
        s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                     warmup_num_steps=4, warmup_type="linear")
        for _ in range(4):
            s.step()
        assert s.get_lr()[0] == pytest.approx(0.75)
        sd = s.state_dict()
        s2 = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=1.0,
                      warmup_num_steps=4, warmup_type="linear")
        s2.load_state_dict(sd)
        assert s2.last_batch_iteration == s.last_batch_iteration


class TestLRRangeTest:

    def test_continuous(self):
        s = LRRangeTest(lr_range_test_min_lr=0.1,
                        lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0)
        assert float(s.lr_at(jnp.asarray(0))) == pytest.approx(0.1)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(0.2)
        assert float(s.lr_at(jnp.asarray(20))) == pytest.approx(0.3)

    def test_staircase(self):
        s = LRRangeTest(lr_range_test_min_lr=0.1,
                        lr_range_test_step_size=10,
                        lr_range_test_step_rate=1.0,
                        lr_range_test_staircase=True)
        assert float(s.lr_at(jnp.asarray(9))) == pytest.approx(0.1)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(0.2)
        assert float(s.lr_at(jnp.asarray(19))) == pytest.approx(0.2)


class TestOneCycle:

    def test_triangle(self):
        s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                     cycle_first_step_size=10, cycle_second_step_size=10)
        assert float(s.lr_at(jnp.asarray(0))) == pytest.approx(0.1)
        assert float(s.lr_at(jnp.asarray(5))) == pytest.approx(0.55)
        assert float(s.lr_at(jnp.asarray(10))) == pytest.approx(1.0)
        assert float(s.lr_at(jnp.asarray(15))) == pytest.approx(0.55)
        assert float(s.lr_at(jnp.asarray(20))) == pytest.approx(0.1)

    def test_decay_phase(self):
        s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                     cycle_first_step_size=5, cycle_second_step_size=5,
                     decay_step_size=5, decay_lr_rate=1.0)
        after = float(s.lr_at(jnp.asarray(15)))  # 5 steps past cycle end
        assert after == pytest.approx(0.1 / 2.0)

    def test_momentum_counter_cycles(self):
        s = OneCycle(cycle_min_lr=0.1, cycle_max_lr=1.0,
                     cycle_first_step_size=10, cycle_second_step_size=10,
                     cycle_min_mom=0.85, cycle_max_mom=0.99)
        assert float(s.mom_at(jnp.asarray(0))) == pytest.approx(0.99)
        assert float(s.mom_at(jnp.asarray(10))) == pytest.approx(0.85)
        assert float(s.mom_at(jnp.asarray(20))) == pytest.approx(0.99)


def test_build_from_config():
    s = build_lr_schedule("WarmupLR", {"warmup_max_lr": 0.5})
    assert isinstance(s, WarmupLR)
    s = build_lr_schedule("OneCycle", {"cycle_min_lr": 0.01,
                                       "cycle_max_lr": 0.1})
    assert isinstance(s, OneCycle)
    s = build_lr_schedule("LRRangeTest", {})
    assert isinstance(s, LRRangeTest)
    assert build_lr_schedule(None, None) is None
    with pytest.raises(ValueError):
        build_lr_schedule("CosineNope", {})
