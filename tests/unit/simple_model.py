"""Shared tiny-model fixtures (mirrors reference tests/unit/simple_model.py:
SimpleModel :9, random_dataloader :104, config helpers :115-134) — rebuilt
as pure-JAX loss functions per the engine's model contract."""

import jax
import jax.numpy as jnp
import numpy as np


def init_simple_params(key, hidden_dim: int, n_layers: int = 2):
    """Linear stack params: n_layers of hidden->hidden + bias."""
    params = {}
    for i in range(n_layers):
        key, k1 = jax.random.split(key)
        params[f"layer_{i}"] = {
            "w": jax.random.normal(k1, (hidden_dim, hidden_dim),
                                   jnp.float32) / np.sqrt(hidden_dim),
            "b": jnp.zeros((hidden_dim,), jnp.float32),
        }
    return params


def simple_loss_fn(params, batch):
    """Linear stack + mean-squared-error regression loss."""
    x = batch["x"]
    for i in range(len(params)):
        layer = params[f"layer_{i}"]
        x = x @ layer["w"].astype(x.dtype) + layer["b"].astype(x.dtype)
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return jnp.mean((x - batch["y"].astype(x.dtype)) ** 2)


def random_dataset(n_samples: int, hidden_dim: int, seed: int = 0):
    """In-memory dataset of (x, y) dicts."""
    rng = np.random.RandomState(seed)
    xs = rng.randn(n_samples, hidden_dim).astype(np.float32)
    ys = rng.randn(n_samples, hidden_dim).astype(np.float32)
    return [{"x": xs[i], "y": ys[i]} for i in range(n_samples)]


def random_batches(n_batches: int, batch_size: int, hidden_dim: int,
                   seed: int = 0):
    """Learnable task: y = x @ W_true, so loss can approach 0."""
    rng = np.random.RandomState(seed)
    w_true = (np.random.RandomState(1234).randn(hidden_dim, hidden_dim)
              .astype(np.float32) / np.sqrt(hidden_dim))
    out = []
    for _ in range(n_batches):
        x = rng.randn(batch_size, hidden_dim).astype(np.float32)
        out.append({"x": x, "y": x @ w_true})
    return out


def base_config(**overrides):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(overrides)
    return cfg
