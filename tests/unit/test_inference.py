"""Inference serving engine (deepspeed_tpu/inference/): bucketed
prefill/decode with KV cache, continuous batching, checkpoint bridge,
serving telemetry.

Tier-1 acceptance pins (ISSUE 5):
- greedy ``generate()`` exactly matches a one-shot full-sequence
  forward argmax loop on CPU for BOTH model families;
- steady-state decode performs ZERO recompiles after bucket warmup
  (CompileTracker-counted);
- scheduler admission/eviction/slot-reuse semantics and deterministic
  per-request sampling with fixed keys.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    cfg = GPT2Config(vocab_size=61, max_position_embeddings=32,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))


def tiny_llama():
    from deepspeed_tpu.models.llama import LlamaConfig, init_llama_params
    cfg = LlamaConfig(vocab_size=61, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2,
                      max_position_embeddings=32)
    return cfg, init_llama_params(cfg, jax.random.PRNGKey(4))


TINY_INF = {"max_batch_size": 3, "prompt_buckets": [4, 8],
            "batch_buckets": [1, 2], "max_seq_len": 32,
            "max_new_tokens": 4}


def greedy_reference(forward, params, cfg, prompt, n):
    """No-cache argmax loop: one full forward per generated token."""
    ids = jnp.asarray([prompt], jnp.int32)
    for _ in range(n):
        logits = forward(params, cfg, ids, dtype=jnp.float32)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return np.asarray(ids)[0].tolist()


# --------------------------------------------------------------------- #
# buckets
# --------------------------------------------------------------------- #
class TestBuckets:
    def test_pick_bucket(self):
        from deepspeed_tpu.inference.buckets import pick_bucket
        assert pick_bucket(1, (4, 8)) == 4
        assert pick_bucket(4, (4, 8)) == 4
        assert pick_bucket(5, (4, 8)) == 8
        with pytest.raises(ValueError, match="exceeds the largest"):
            pick_bucket(9, (4, 8))

    def test_validate_buckets(self):
        from deepspeed_tpu.inference.buckets import validate_buckets
        assert validate_buckets([4, 8], "b") == (4, 8)
        for bad in ([], [0, 4], [8, 4], [4, 4]):
            with pytest.raises(ValueError):
                validate_buckets(bad, "b")

    def test_pad_prompts(self):
        from deepspeed_tpu.inference.buckets import pad_prompts
        ids, lengths = pad_prompts([[1, 2], [3, 4, 5]], 4, 3)
        assert ids.shape == (3, 4)
        np.testing.assert_array_equal(lengths, [2, 3, 1])  # pad row len 1
        np.testing.assert_array_equal(ids[0], [1, 2, 0, 0])
        np.testing.assert_array_equal(ids[2], [0, 0, 0, 0])
        with pytest.raises(ValueError):
            pad_prompts([[1] * 5], 4, 1)          # prompt > bucket
        with pytest.raises(ValueError):
            pad_prompts([[1], [2]], 4, 1)         # batch > bucket


# --------------------------------------------------------------------- #
# scheduler (pure host-side: no jax)
# --------------------------------------------------------------------- #
class TestScheduler:
    def _sched(self, slots=3, clock=None):
        from deepspeed_tpu.inference.scheduler import Scheduler
        kw = {"clock": clock} if clock else {}
        return Scheduler(slots, (4, 8), (1, 2), 32, **kw)

    def test_submit_validation(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched()
        with pytest.raises(ValueError, match="largest prompt bucket"):
            s.submit(Request(prompt=list(range(1, 10))))
        with pytest.raises(ValueError, match="max_len"):
            s.submit(Request(prompt=[1, 2, 3], max_new_tokens=30))
        with pytest.raises(ValueError, match="empty"):
            Request(prompt=[])

    def test_admission_groups_by_bucket_fifo(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(slots=3)
        r1 = Request(prompt=[1, 2, 3], max_new_tokens=4)        # bucket 4
        r2 = Request(prompt=[1] * 7, max_new_tokens=4)          # bucket 8
        r3 = Request(prompt=[4, 5], max_new_tokens=4)           # bucket 4
        for r in (r1, r2, r3):
            s.submit(r)
        batches = s.admit()
        # head (r1) fixes bucket 4; r3 rides along; r2 admits second
        assert len(batches) == 2
        assert batches[0].prompt_bucket == 4
        assert [r.uid for r in batches[0].requests] == [r1.uid, r3.uid]
        assert batches[0].batch_bucket == 2
        assert batches[1].prompt_bucket == 8
        assert [r.uid for r in batches[1].requests] == [r2.uid]
        assert batches[1].batch_bucket == 1
        assert s.queue_depth == 0 and s.occupancy == 1.0

    def test_eviction_and_slot_reuse(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(slots=1)
        a = Request(prompt=[1, 2], max_new_tokens=2)
        b = Request(prompt=[3], max_new_tokens=1, eos_id=9)
        s.submit(a)
        s.submit(b)
        (batch,) = s.admit()
        assert [r.uid for r in batch.requests] == [a.uid]
        sid = batch.slot_ids[0]
        assert s.record_tokens({sid: 5}) == []        # 1/2 tokens
        assert s.admit() == []                        # slot still busy
        done = s.record_tokens({sid: 6})
        assert [f.uid for f in done] == [a.uid]
        assert done[0].tokens == [5, 6]
        assert done[0].finish_reason == "length"
        # slot freed -> b admitted into the SAME slot
        (batch2,) = s.admit()
        assert batch2.slot_ids == [sid]
        done = s.record_tokens({sid: 9})              # eos on first token
        assert done[0].finish_reason == "eos"
        assert s.idle()

    def test_decode_state_bookkeeping(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(slots=2)
        s.submit(Request(prompt=[1, 2, 3], max_new_tokens=3,
                         temperature=0.7, seed=42))
        (batch,) = s.admit()
        sid = batch.slot_ids[0]
        assert s.decode_state()[0] == []        # first token still pending
        s.record_tokens({sid: 7})               # prefill's first token
        sids, toks, poss, temps, seeds = s.decode_state()
        assert sids == [sid] and toks == [7]
        assert poss == [3]                      # prompt tokens in cache
        assert temps == [0.7] and seeds == [42]
        s.record_tokens({sid: 8})               # decode wrote tok 7 at 3
        assert s.decode_state()[2] == [4]

    def test_ttft_drain(self):
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        s = self._sched(slots=1, clock=lambda: t[0])
        s.submit(Request(prompt=[1], max_new_tokens=2))
        (batch,) = s.admit()
        t[0] = 0.25
        s.record_tokens({batch.slot_ids[0]: 1})
        assert s.drain_ttfts() == [250.0]
        assert s.drain_ttfts() == []


# --------------------------------------------------------------------- #
# model-level cached forward (satellite: training signature unchanged)
# --------------------------------------------------------------------- #
class TestCachedForward:
    def test_causal_cache_mask(self):
        from deepspeed_tpu.models.gpt2 import causal_cache_mask
        m = np.asarray(causal_cache_mask(jnp.asarray([0, 2]), 2, 5))
        assert m.shape == (2, 1, 2, 5)
        # row 0 at offset 0: query j attends k <= j
        np.testing.assert_array_equal(m[0, 0, 0], [1, 0, 0, 0, 0])
        np.testing.assert_array_equal(m[0, 0, 1], [1, 1, 0, 0, 0])
        # row 1 at offset 2: query 0 sits at absolute position 2
        np.testing.assert_array_equal(m[1, 0, 0], [1, 1, 1, 0, 0])
        np.testing.assert_array_equal(m[1, 0, 1], [1, 1, 1, 1, 0])

    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_chunked_cached_forward_matches_oneshot(self, family):
        if family == "gpt2":
            from deepspeed_tpu.models.gpt2 import gpt2_forward as fwd
            cfg, params = tiny_gpt2()
            heads = cfg.num_heads
        else:
            from deepspeed_tpu.models.llama import llama_forward as fwd
            cfg, params = tiny_llama()
            heads = cfg.kv_heads      # GQA cache stays kv_heads-sized
        hd = cfg.hidden_size // cfg.num_heads
        B, S, max_len = 2, 7, 16
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 61, (B, S)),
                          jnp.int32)
        ref = fwd(params, cfg, ids, dtype=jnp.float32)
        cache = tuple(jnp.zeros((cfg.num_layers, B, heads, max_len, hd),
                                jnp.float32) for _ in range(2))
        # prefill 4 tokens into the cache, then decode 3 one by one
        lg, cache = fwd(params, cfg, ids[:, :4], dtype=jnp.float32,
                        kv_cache=cache)
        outs = [lg]
        for t in range(4, S):
            lg, cache = fwd(params, cfg, ids[:, t:t + 1],
                            dtype=jnp.float32, kv_cache=cache,
                            cache_position=jnp.full((B,), t, jnp.int32))
            outs.append(lg)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4)


# --------------------------------------------------------------------- #
# the serving engine
# --------------------------------------------------------------------- #
class TestInferenceEngine:
    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_greedy_generate_parity(self, family):
        """ISSUE 5 acceptance: token-by-token greedy parity with the
        one-shot full-forward argmax loop, under continuous batching
        (6 mixed-length requests over 3 slots -> slot reuse on the
        real path)."""
        from deepspeed_tpu.inference import InferenceEngine
        if family == "gpt2":
            from deepspeed_tpu.models.gpt2 import gpt2_forward as fwd
            cfg, params = tiny_gpt2()
        else:
            from deepspeed_tpu.models.llama import llama_forward as fwd
            cfg, params = tiny_llama()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        rng = np.random.RandomState(1)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (3, 5, 7, 2, 8, 4)]
        outs = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
        for prompt, out in zip(prompts, outs):
            assert out == greedy_reference(fwd, params, cfg, prompt, 4)

    def test_zero_steady_state_recompiles_after_warmup(self):
        """ISSUE 5 acceptance: warmup compiles exactly
        len(batch_buckets) x len(prompt_buckets) prefill programs + 1
        decode program; serving traffic that stays inside the bucket
        table compiles NOTHING more (CompileTracker-exact)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        assert engine.steady_state_recompiles == -1   # before warmup
        programs = engine.warmup()
        assert programs == 2 * 2 + 1
        assert engine.compile_tracker.counts == {"prefill": 4,
                                                 "decode": 1}
        rng = np.random.RandomState(2)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (1, 4, 5, 8, 3, 6, 2, 7)]
        engine.generate(prompts, max_new_tokens=3)
        engine.generate(prompts[:2], max_new_tokens=5, temperature=0.5)
        assert engine.steady_state_recompiles == 0
        assert engine.compile_tracker.total_compiles == programs

    def test_sampling_deterministic_per_request_keys(self):
        """Same seeds -> identical streams regardless of runs; seeds are
        per-request, so a request's stream does not depend on what else
        shares the batch."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompts = [[1, 2, 3], [4, 5]]
        a = engine.generate(prompts, max_new_tokens=6, temperature=0.8,
                            seeds=[7, 8])
        b = engine.generate(prompts, max_new_tokens=6, temperature=0.8,
                            seeds=[7, 8])
        assert a == b
        c = engine.generate(prompts, max_new_tokens=6, temperature=0.8,
                            seeds=[70, 80])
        assert a != c
        # request 0 alone samples the same stream as batched with 1
        solo = engine.generate([prompts[0]], max_new_tokens=6,
                               temperature=0.8, seeds=[7])
        assert solo[0] == a[0]
        assert all(0 <= t < 61 for out in a for t in out)

    def test_eos_stops_generation(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompt = [1, 2, 3]
        full = engine.generate([prompt], max_new_tokens=6,
                               temperature=0.0)[0]
        gen = full[len(prompt):]
        # declare a token greedy decoding is known to emit as EOS: the
        # rerun must stop at its FIRST occurrence, inclusive
        eos = gen[1]
        stop = gen.index(eos)
        stopped = engine.generate([prompt], max_new_tokens=6,
                                  temperature=0.0, eos_id=eos)[0]
        assert stopped == full[:len(prompt) + stop + 1]

    def test_serving_telemetry_and_report(self, tmp_path):
        """Serve/* scalars + serve events land in events.jsonl; the
        obs_report serving section renders them (function AND CLI —
        the tier-1 serving-report smoke)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        icfg = dict(TINY_INF, events_dir=str(tmp_path))
        engine = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        engine.warmup()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]
        engine.generate(prompts, max_new_tokens=4)
        engine.close()

        rows = [json.loads(line)
                for line in open(tmp_path / "events.jsonl")]
        tags = {r["tag"] for r in rows if "tag" in r}
        # tag schema pinned (utils/monitor.write_serving_metrics)
        assert {"Serve/ttft_ms", "Serve/token_latency_ms",
                "Serve/tokens_per_sec", "Serve/queue_depth",
                "Serve/batch_occupancy"} <= tags
        events = {r["event"] for r in rows if "event" in r}
        assert {"serve_warmup", "serve_finish", "compile"} <= events
        assert sum(1 for r in rows
                   if r.get("tag") == "Serve/ttft_ms") == len(prompts)

        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(tmp_path))
        sv = s["serving"]
        assert sv["requests"] == len(prompts)
        assert sv["decode_steps"] >= 1
        assert sv["ttft_ms"]["p50"] is not None
        assert sv["ttft_ms"]["p95"] >= sv["ttft_ms"]["p50"]
        assert sv["token_latency_ms"]["p95"] is not None
        assert sv["tokens_per_sec"]["last"] > 0
        assert 0 < sv["batch_occupancy_mean"] <= 1
        text = obs_report.render(s)
        assert "serving" in text and "ttft_ms" in text
        assert obs_report.main([str(tmp_path)]) == 0
        assert obs_report.main([str(tmp_path), "--json"]) == 0

    def test_serve_tag_registry_in_sync(self):
        """One tag, three homes: the monitor (canonical writer), the
        profiling registry (re-export), and stdlib-only obs_report
        (mirrored strings) must agree."""
        from deepspeed_tpu import profiling as prof
        from deepspeed_tpu.utils import monitor as m
        obs_report = _load_tool("obs_report")
        assert m.TAG_SERVE_TTFT == prof.TAG_SERVE_TTFT == \
            obs_report.T_TTFT
        assert m.TAG_SERVE_TOKEN_LATENCY == \
            prof.TAG_SERVE_TOKEN_LATENCY == obs_report.T_TOK_LAT
        assert m.TAG_SERVE_TPS == prof.TAG_SERVE_TPS == obs_report.T_TPS
        assert m.TAG_SERVE_QUEUE_DEPTH == prof.TAG_SERVE_QUEUE_DEPTH == \
            obs_report.T_QDEPTH
        assert m.TAG_SERVE_OCCUPANCY == prof.TAG_SERVE_OCCUPANCY == \
            obs_report.T_OCC

    def test_rejects_unservable_config(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        with pytest.raises(ValueError, match="prompt_buckets"):
            # buckets exceed the model's position table after clamping
            InferenceEngine(cfg, params,
                            dict(TINY_INF, prompt_buckets=[4, 64],
                                 max_seq_len=1024))


# --------------------------------------------------------------------- #
# checkpoint -> serving bridge
# --------------------------------------------------------------------- #
class TestFromCheckpoint:
    def _save_training_checkpoint(self, tmp_path, cfg, params):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(cfg, dtype=jnp.float32,
                               deterministic=True),
            model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 10**9,
                    "optimizer": {"type": "Adam",
                                  "params": {"lr": 1e-3}}})
        return engine.save_checkpoint(str(tmp_path))

    def test_params_only_load_and_parity(self, tmp_path):
        """A committed PR-1 training checkpoint serves: params-only load
        (no optimizer state touched), greedy outputs identical to an
        engine built from the in-memory params."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.runtime import checkpoint as ckpt
        cfg, params = tiny_gpt2()
        self._save_training_checkpoint(tmp_path, cfg, params)

        groups = ckpt.state_groups(
            os.path.join(str(tmp_path), ckpt.read_latest(str(tmp_path))))
        assert groups["model_states"] == "sharded"
        assert groups["optim_states"] == "sharded"
        assert groups["meta"]

        served = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=TINY_INF,
            dtype=jnp.float32)
        direct = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompts = [[1, 2, 3], [4, 5, 6, 7]]
        assert served.generate(prompts, max_new_tokens=4) == \
            direct.generate(prompts, max_new_tokens=4)

    def test_params_only_checkpoint_is_servable(self, tmp_path):
        """A tag carrying ONLY model_states (no optimizer group at all)
        loads — proof the bridge never requires training state."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.runtime import checkpoint as ckpt
        cfg, params = tiny_gpt2()
        tag_dir = tmp_path / "weights_only"
        tag_dir.mkdir()
        ckpt.save_tree_sharded(str(tag_dir), "model_states", params)
        ckpt.write_meta(str(tag_dir), {"global_step": 0})
        ckpt.write_commit_marker(str(tag_dir))
        ckpt.write_latest(str(tmp_path), "weights_only")
        groups = ckpt.state_groups(str(tag_dir))
        assert groups["model_states"] == "sharded"
        assert groups["optim_states"] is None
        engine = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=TINY_INF,
            dtype=jnp.float32)
        out = engine.generate([[1, 2, 3]], max_new_tokens=2)[0]
        assert len(out) == 5

    def test_qwz_quantized_weight_path(self, tmp_path):
        """quantize_weights=True ships params through the qwZ int8
        block format: the engine still serves, and greedy outputs stay
        close to the fp32 weights' (identical at this size — int8
        block quantization error is far below the logit gaps)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        self._save_training_checkpoint(tmp_path, cfg, params)
        q = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=TINY_INF,
            dtype=jnp.float32, quantize_weights=True)
        # weights really were roundtripped through int8 blocks
        assert not np.allclose(np.asarray(q.params["wte"]),
                               np.asarray(params["wte"]))
        out = q.generate([[1, 2, 3]], max_new_tokens=3)[0]
        assert len(out) == 6 and all(0 <= t < 61 for t in out)

    def test_verify_checkpoint_cli_reports_state_groups(self, tmp_path,
                                                        capsys):
        """tools/verify_checkpoint.py names the state groups a committed
        tag contains (the satellite's reporting requirement)."""
        cfg, params = tiny_gpt2()
        self._save_training_checkpoint(tmp_path, cfg, params)
        vc = _load_tool("verify_checkpoint")
        assert vc.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "state groups:" in out
        assert "model_states(sharded)" in out
        assert "optim_states(sharded)" in out

    def test_from_checkpoint_rejects_corrupt(self, tmp_path):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, _ = tiny_gpt2()
        with pytest.raises(FileNotFoundError):
            InferenceEngine.from_checkpoint(
                str(tmp_path), cfg, inference_config=TINY_INF)


# --------------------------------------------------------------------- #
# config section
# --------------------------------------------------------------------- #
class TestInferenceConfigSection:
    def test_defaults_parse(self):
        from deepspeed_tpu.runtime.config import get_inference_config
        cfg = get_inference_config({})
        assert cfg["max_batch_size"] == 8
        assert cfg["prompt_buckets"] == [64, 256]
        assert cfg["batch_buckets"] == [1, 8]
        assert cfg["temperature"] == 0.0 and cfg["top_k"] == 0

    def test_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_inference_config)
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"prompt_buckets": [8, 4]}})
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"batch_buckets": [16],
                               "max_batch_size": 8}})
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"prompt_buckets": [2048],
                               "max_seq_len": 1024}})

    def test_rides_deepspeed_config(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                               "inference": {"max_batch_size": 2,
                                             "prompt_buckets": [16],
                                             "batch_buckets": [2],
                                             "max_seq_len": 64}},
                              world_size=1)
        assert cfg.inference_config["max_batch_size"] == 2
        assert cfg.inference_config["prompt_buckets"] == [16]
