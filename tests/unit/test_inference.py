"""Inference serving engine (deepspeed_tpu/inference/): bucketed
prefill/decode with KV cache, continuous batching, checkpoint bridge,
serving telemetry.

Tier-1 acceptance pins (ISSUE 5):
- greedy ``generate()`` exactly matches a one-shot full-sequence
  forward argmax loop on CPU for BOTH model families;
- steady-state decode performs ZERO recompiles after bucket warmup
  (CompileTracker-counted);
- scheduler admission/eviction/slot-reuse semantics and deterministic
  per-request sampling with fixed keys.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    cfg = GPT2Config(vocab_size=61, max_position_embeddings=32,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))


def tiny_llama():
    from deepspeed_tpu.models.llama import LlamaConfig, init_llama_params
    cfg = LlamaConfig(vocab_size=61, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2,
                      max_position_embeddings=32)
    return cfg, init_llama_params(cfg, jax.random.PRNGKey(4))


TINY_INF = {"max_batch_size": 3, "prompt_buckets": [4, 8],
            "batch_buckets": [1, 2], "max_seq_len": 32,
            "max_new_tokens": 4}


def greedy_reference(forward, params, cfg, prompt, n):
    """No-cache argmax loop: one full forward per generated token."""
    ids = jnp.asarray([prompt], jnp.int32)
    for _ in range(n):
        logits = forward(params, cfg, ids, dtype=jnp.float32)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return np.asarray(ids)[0].tolist()


# --------------------------------------------------------------------- #
# buckets
# --------------------------------------------------------------------- #
class TestBuckets:
    def test_pick_bucket(self):
        from deepspeed_tpu.inference.buckets import pick_bucket
        assert pick_bucket(1, (4, 8)) == 4
        assert pick_bucket(4, (4, 8)) == 4
        assert pick_bucket(5, (4, 8)) == 8
        with pytest.raises(ValueError, match="exceeds the largest"):
            pick_bucket(9, (4, 8))

    def test_validate_buckets(self):
        from deepspeed_tpu.inference.buckets import validate_buckets
        assert validate_buckets([4, 8], "b") == (4, 8)
        for bad in ([], [0, 4], [8, 4], [4, 4]):
            with pytest.raises(ValueError):
                validate_buckets(bad, "b")

    def test_pad_prompts(self):
        from deepspeed_tpu.inference.buckets import pad_prompts
        ids, lengths = pad_prompts([[1, 2], [3, 4, 5]], 4, 3)
        assert ids.shape == (3, 4)
        np.testing.assert_array_equal(lengths, [2, 3, 1])  # pad row len 1
        np.testing.assert_array_equal(ids[0], [1, 2, 0, 0])
        np.testing.assert_array_equal(ids[2], [0, 0, 0, 0])
        with pytest.raises(ValueError):
            pad_prompts([[1] * 5], 4, 1)          # prompt > bucket
        with pytest.raises(ValueError):
            pad_prompts([[1], [2]], 4, 1)         # batch > bucket


# --------------------------------------------------------------------- #
# scheduler (pure host-side: no jax)
# --------------------------------------------------------------------- #
def test_host_side_scheduling_modules_stay_jax_free():
    """scheduler.py advertises "nothing here imports jax, so scheduler
    policy is unit-testable in microseconds" — pin that at the source
    level for the whole host-side chain it pulls in (scheduler ->
    paging, buckets), so a convenience import can't quietly drag jax
    back into admission policy.

    ISSUE 8 extension: the same modules must also stay KERNEL-AGNOSTIC
    — scheduling/paging policy must not know (or care) whether decode
    attention runs the fused Pallas paged kernel or the gather
    fallback, so no import from ops.attention (or any ops/ module) and
    no kernel-path strings may appear. The engine owns the path choice;
    the scheduler only ever produces block tables."""
    import ast
    import pathlib

    import deepspeed_tpu.inference as inf
    root = pathlib.Path(inf.__file__).parent
    for mod in ("scheduler.py", "paging.py", "buckets.py", "tracing.py",
                "draft.py", "disagg.py", "fleet.py", "rpc.py"):
        src = (root / mod).read_text()
        for node in ast.walk(ast.parse(src)):
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for n in names:
                assert n != "jax" and not n.startswith("jax."), \
                    f"{mod} imports {n}"
                assert ".ops" not in n and not n.startswith("ops"), \
                    f"{mod} imports kernel code: {n}"
        assert "pallas" not in src.lower(), \
            f"{mod} mentions a kernel path — scheduling must stay " \
            f"kernel-agnostic"


class TestScheduler:
    def _sched(self, slots=3, clock=None):
        from deepspeed_tpu.inference.scheduler import Scheduler
        kw = {"clock": clock} if clock else {}
        return Scheduler(slots, (4, 8), (1, 2), 32, **kw)

    def test_submit_validation(self):
        # ISSUE 19: unservable shapes are a graceful submit-time
        # rejection (pinned reason "reject_too_long"), never a crash
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched()
        uid = s.submit(Request(prompt=list(range(1, 10))))  # > bucket 8
        uid2 = s.submit(Request(prompt=[1, 2, 3], max_new_tokens=30))
        rejects = s.drain_rejects()
        assert [r.uid for r in rejects] == [uid, uid2]
        for r in rejects:
            assert r.finish_reason == "reject_too_long"
            assert r.tokens == [] and r.ttft_ms is None
        assert s.drain_rejects() == []          # one-shot drain
        assert not s.queue                      # never queued
        with pytest.raises(ValueError, match="empty"):
            Request(prompt=[])

    def test_admission_groups_by_bucket_fifo(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(slots=3)
        r1 = Request(prompt=[1, 2, 3], max_new_tokens=4)        # bucket 4
        r2 = Request(prompt=[1] * 7, max_new_tokens=4)          # bucket 8
        r3 = Request(prompt=[4, 5], max_new_tokens=4)           # bucket 4
        for r in (r1, r2, r3):
            s.submit(r)
        batches = s.admit()
        # head (r1) fixes bucket 4; r3 rides along; r2 admits second
        assert len(batches) == 2
        assert batches[0].prompt_bucket == 4
        assert [r.uid for r in batches[0].requests] == [r1.uid, r3.uid]
        assert batches[0].batch_bucket == 2
        assert batches[1].prompt_bucket == 8
        assert [r.uid for r in batches[1].requests] == [r2.uid]
        assert batches[1].batch_bucket == 1
        assert s.queue_depth == 0 and s.occupancy == 1.0

    def test_eviction_and_slot_reuse(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(slots=1)
        a = Request(prompt=[1, 2], max_new_tokens=2)
        b = Request(prompt=[3], max_new_tokens=1, eos_id=9)
        s.submit(a)
        s.submit(b)
        (batch,) = s.admit()
        assert [r.uid for r in batch.requests] == [a.uid]
        sid = batch.slot_ids[0]
        assert s.record_tokens({sid: 5}) == []        # 1/2 tokens
        assert s.admit() == []                        # slot still busy
        done = s.record_tokens({sid: 6})
        assert [f.uid for f in done] == [a.uid]
        assert done[0].tokens == [5, 6]
        assert done[0].finish_reason == "length"
        # slot freed -> b admitted into the SAME slot
        (batch2,) = s.admit()
        assert batch2.slot_ids == [sid]
        done = s.record_tokens({sid: 9})              # eos on first token
        assert done[0].finish_reason == "eos"
        assert s.idle()

    def test_decode_state_bookkeeping(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(slots=2)
        s.submit(Request(prompt=[1, 2, 3], max_new_tokens=3,
                         temperature=0.7, seed=42))
        (batch,) = s.admit()
        sid = batch.slot_ids[0]
        assert s.decode_state()[0] == []        # first token still pending
        s.record_tokens({sid: 7})               # prefill's first token
        sids, toks, poss, temps, seeds = s.decode_state()
        assert sids == [sid] and toks == [7]
        assert poss == [3]                      # prompt tokens in cache
        assert temps == [0.7] and seeds == [42]
        s.record_tokens({sid: 8})               # decode wrote tok 7 at 3
        assert s.decode_state()[2] == [4]

    def test_ttft_drain(self):
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        s = self._sched(slots=1, clock=lambda: t[0])
        s.submit(Request(prompt=[1], max_new_tokens=2))
        (batch,) = s.admit()
        t[0] = 0.25
        s.record_tokens({batch.slot_ids[0]: 1})
        assert s.drain_ttfts() == [250.0]
        assert s.drain_ttfts() == []


# --------------------------------------------------------------------- #
# model-level cached forward (satellite: training signature unchanged)
# --------------------------------------------------------------------- #
class TestCachedForward:
    def test_causal_cache_mask(self):
        from deepspeed_tpu.models.gpt2 import causal_cache_mask
        m = np.asarray(causal_cache_mask(jnp.asarray([0, 2]), 2, 5))
        assert m.shape == (2, 1, 2, 5)
        # row 0 at offset 0: query j attends k <= j
        np.testing.assert_array_equal(m[0, 0, 0], [1, 0, 0, 0, 0])
        np.testing.assert_array_equal(m[0, 0, 1], [1, 1, 0, 0, 0])
        # row 1 at offset 2: query 0 sits at absolute position 2
        np.testing.assert_array_equal(m[1, 0, 0], [1, 1, 1, 0, 0])
        np.testing.assert_array_equal(m[1, 0, 1], [1, 1, 1, 1, 0])

    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_chunked_cached_forward_matches_oneshot(self, family):
        if family == "gpt2":
            from deepspeed_tpu.models.gpt2 import gpt2_forward as fwd
            cfg, params = tiny_gpt2()
            heads = cfg.num_heads
        else:
            from deepspeed_tpu.models.llama import llama_forward as fwd
            cfg, params = tiny_llama()
            heads = cfg.kv_heads      # GQA cache stays kv_heads-sized
        hd = cfg.hidden_size // cfg.num_heads
        B, S, max_len = 2, 7, 16
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 61, (B, S)),
                          jnp.int32)
        ref = fwd(params, cfg, ids, dtype=jnp.float32)
        cache = tuple(jnp.zeros((cfg.num_layers, B, heads, max_len, hd),
                                jnp.float32) for _ in range(2))
        # prefill 4 tokens into the cache, then decode 3 one by one
        lg, cache = fwd(params, cfg, ids[:, :4], dtype=jnp.float32,
                        kv_cache=cache)
        outs = [lg]
        for t in range(4, S):
            lg, cache = fwd(params, cfg, ids[:, t:t + 1],
                            dtype=jnp.float32, kv_cache=cache,
                            cache_position=jnp.full((B,), t, jnp.int32))
            outs.append(lg)
        got = jnp.concatenate(outs, axis=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4)


# --------------------------------------------------------------------- #
# the serving engine
# --------------------------------------------------------------------- #
class TestInferenceEngine:
    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_greedy_generate_parity(self, family):
        """ISSUE 5 acceptance: token-by-token greedy parity with the
        one-shot full-forward argmax loop, under continuous batching
        (6 mixed-length requests over 3 slots -> slot reuse on the
        real path)."""
        from deepspeed_tpu.inference import InferenceEngine
        if family == "gpt2":
            from deepspeed_tpu.models.gpt2 import gpt2_forward as fwd
            cfg, params = tiny_gpt2()
        else:
            from deepspeed_tpu.models.llama import llama_forward as fwd
            cfg, params = tiny_llama()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        rng = np.random.RandomState(1)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (3, 5, 7, 2, 8, 4)]
        outs = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
        for prompt, out in zip(prompts, outs):
            assert out == greedy_reference(fwd, params, cfg, prompt, 4)

    def test_zero_steady_state_recompiles_after_warmup(self):
        """ISSUE 5 acceptance: warmup compiles exactly
        len(batch_buckets) x len(prompt_buckets) prefill programs + 1
        decode program; serving traffic that stays inside the bucket
        table compiles NOTHING more (CompileTracker-exact)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        assert engine.steady_state_recompiles == -1   # before warmup
        programs = engine.warmup()
        assert programs == 2 * 2 + 1
        assert engine.compile_tracker.counts == {"prefill": 4,
                                                 "decode": 1}
        rng = np.random.RandomState(2)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (1, 4, 5, 8, 3, 6, 2, 7)]
        engine.generate(prompts, max_new_tokens=3)
        engine.generate(prompts[:2], max_new_tokens=5, temperature=0.5)
        assert engine.steady_state_recompiles == 0
        assert engine.compile_tracker.total_compiles == programs

    def test_sampling_deterministic_per_request_keys(self):
        """Same seeds -> identical streams regardless of runs; seeds are
        per-request, so a request's stream does not depend on what else
        shares the batch."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompts = [[1, 2, 3], [4, 5]]
        a = engine.generate(prompts, max_new_tokens=6, temperature=0.8,
                            seeds=[7, 8])
        b = engine.generate(prompts, max_new_tokens=6, temperature=0.8,
                            seeds=[7, 8])
        assert a == b
        c = engine.generate(prompts, max_new_tokens=6, temperature=0.8,
                            seeds=[70, 80])
        assert a != c
        # request 0 alone samples the same stream as batched with 1
        solo = engine.generate([prompts[0]], max_new_tokens=6,
                               temperature=0.8, seeds=[7])
        assert solo[0] == a[0]
        assert all(0 <= t < 61 for out in a for t in out)

    def test_eos_stops_generation(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompt = [1, 2, 3]
        full = engine.generate([prompt], max_new_tokens=6,
                               temperature=0.0)[0]
        gen = full[len(prompt):]
        # declare a token greedy decoding is known to emit as EOS: the
        # rerun must stop at its FIRST occurrence, inclusive
        eos = gen[1]
        stop = gen.index(eos)
        stopped = engine.generate([prompt], max_new_tokens=6,
                                  temperature=0.0, eos_id=eos)[0]
        assert stopped == full[:len(prompt) + stop + 1]

    def test_serving_telemetry_and_report(self, tmp_path):
        """Serve/* scalars + serve events land in events.jsonl; the
        obs_report serving section renders them (function AND CLI —
        the tier-1 serving-report smoke)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        icfg = dict(TINY_INF, events_dir=str(tmp_path))
        engine = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        engine.warmup()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]
        engine.generate(prompts, max_new_tokens=4)
        engine.close()

        rows = [json.loads(line)
                for line in open(tmp_path / "events.jsonl")]
        tags = {r["tag"] for r in rows if "tag" in r}
        # tag schema pinned (utils/monitor.write_serving_metrics)
        assert {"Serve/ttft_ms", "Serve/token_latency_ms",
                "Serve/tokens_per_sec", "Serve/queue_depth",
                "Serve/batch_occupancy"} <= tags
        events = {r["event"] for r in rows if "event" in r}
        assert {"serve_warmup", "serve_finish", "compile"} <= events
        assert sum(1 for r in rows
                   if r.get("tag") == "Serve/ttft_ms") == len(prompts)

        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(tmp_path))
        sv = s["serving"]
        assert sv["requests"] == len(prompts)
        assert sv["decode_steps"] >= 1
        assert sv["ttft_ms"]["p50"] is not None
        assert sv["ttft_ms"]["p95"] >= sv["ttft_ms"]["p50"]
        assert sv["token_latency_ms"]["p95"] is not None
        assert sv["tokens_per_sec"]["last"] > 0
        assert 0 < sv["batch_occupancy_mean"] <= 1
        text = obs_report.render(s)
        assert "serving" in text and "ttft_ms" in text
        assert obs_report.main([str(tmp_path)]) == 0
        assert obs_report.main([str(tmp_path), "--json"]) == 0

    def test_serve_tag_registry_in_sync(self):
        """One tag, three homes: the monitor (canonical writer), the
        profiling registry (re-export), and stdlib-only obs_report
        (mirrored strings) must agree."""
        from deepspeed_tpu import profiling as prof
        from deepspeed_tpu.utils import monitor as m
        obs_report = _load_tool("obs_report")
        assert m.TAG_SERVE_TTFT == prof.TAG_SERVE_TTFT == \
            obs_report.T_TTFT
        assert m.TAG_SERVE_TOKEN_LATENCY == \
            prof.TAG_SERVE_TOKEN_LATENCY == obs_report.T_TOK_LAT
        assert m.TAG_SERVE_TPS == prof.TAG_SERVE_TPS == obs_report.T_TPS
        assert m.TAG_SERVE_QUEUE_DEPTH == prof.TAG_SERVE_QUEUE_DEPTH == \
            obs_report.T_QDEPTH
        assert m.TAG_SERVE_OCCUPANCY == prof.TAG_SERVE_OCCUPANCY == \
            obs_report.T_OCC
        assert m.TAG_SERVE_KV_PAGES == prof.TAG_SERVE_KV_PAGES == \
            obs_report.T_KV_PAGES
        assert m.TAG_SERVE_TOKENS_IN_FLIGHT == \
            prof.TAG_SERVE_TOKENS_IN_FLIGHT == obs_report.T_TOKENS_IN_FLIGHT
        # ISSUE 9: the request-granular plane's tags (queue wait, TBT,
        # SLO attainment, goodput) live in the same three homes
        assert m.TAG_SERVE_QUEUE_WAIT == prof.TAG_SERVE_QUEUE_WAIT == \
            obs_report.T_QUEUE_WAIT
        assert m.TAG_SERVE_TBT == prof.TAG_SERVE_TBT == obs_report.T_TBT
        assert m.TAG_SERVE_SLO == prof.TAG_SERVE_SLO == obs_report.T_SLO
        assert m.TAG_SERVE_GOODPUT == prof.TAG_SERVE_GOODPUT == \
            obs_report.T_GOODPUT
        assert m.TAG_SERVE_PREFIX_HIT == prof.TAG_SERVE_PREFIX_HIT == \
            obs_report.T_PREFIX_HIT
        # ISSUE 13: speculation + disaggregation scalars
        assert m.TAG_SERVE_SPEC_ACCEPT == prof.TAG_SERVE_SPEC_ACCEPT == \
            obs_report.T_SPEC_ACCEPT == "Serve/spec_accept_rate"
        assert m.TAG_SERVE_HANDOFF == prof.TAG_SERVE_HANDOFF == \
            obs_report.T_HANDOFF == "Serve/handoff_ms"
        # ISSUE 17: quantized-serving scalars
        assert m.TAG_SERVE_KV_POOL_BPT == prof.TAG_SERVE_KV_POOL_BPT \
            == obs_report.T_KV_POOL_BPT == "Serve/kv_pool_bytes_per_token"
        assert m.TAG_SERVE_QUANT_LOGIT_ERR == \
            prof.TAG_SERVE_QUANT_LOGIT_ERR == \
            obs_report.T_QUANT_LOGIT_ERR == "Serve/quant_logit_err"
        # ISSUE 19: chunked-prefill scalars
        assert m.TAG_SERVE_CHUNK_DISPATCHES == \
            prof.TAG_SERVE_CHUNK_DISPATCHES == \
            obs_report.T_CHUNK_DISPATCHES == "Serve/chunk_dispatches"
        assert m.TAG_SERVE_TBT_MAX == prof.TAG_SERVE_TBT_MAX == \
            obs_report.T_TBT_MAX == "Serve/tbt_max_ms"

    def test_rejects_unservable_config(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        with pytest.raises(ValueError, match="prompt_buckets"):
            # buckets exceed the model's position table after clamping
            InferenceEngine(cfg, params,
                            dict(TINY_INF, prompt_buckets=[4, 64],
                                 max_seq_len=1024))


# --------------------------------------------------------------------- #
# checkpoint -> serving bridge
# --------------------------------------------------------------------- #
class TestFromCheckpoint:
    def _save_training_checkpoint(self, tmp_path, cfg, params):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(cfg, dtype=jnp.float32,
                               deterministic=True),
            model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 10**9,
                    "optimizer": {"type": "Adam",
                                  "params": {"lr": 1e-3}}})
        return engine.save_checkpoint(str(tmp_path))

    def test_params_only_load_and_parity(self, tmp_path):
        """A committed PR-1 training checkpoint serves: params-only load
        (no optimizer state touched), greedy outputs identical to an
        engine built from the in-memory params."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.runtime import checkpoint as ckpt
        cfg, params = tiny_gpt2()
        self._save_training_checkpoint(tmp_path, cfg, params)

        groups = ckpt.state_groups(
            os.path.join(str(tmp_path), ckpt.read_latest(str(tmp_path))))
        assert groups["model_states"] == "sharded"
        assert groups["optim_states"] == "sharded"
        assert groups["meta"]

        served = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=TINY_INF,
            dtype=jnp.float32)
        direct = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompts = [[1, 2, 3], [4, 5, 6, 7]]
        assert served.generate(prompts, max_new_tokens=4) == \
            direct.generate(prompts, max_new_tokens=4)

    def test_params_only_checkpoint_is_servable(self, tmp_path):
        """A tag carrying ONLY model_states (no optimizer group at all)
        loads — proof the bridge never requires training state."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.runtime import checkpoint as ckpt
        cfg, params = tiny_gpt2()
        tag_dir = tmp_path / "weights_only"
        tag_dir.mkdir()
        ckpt.save_tree_sharded(str(tag_dir), "model_states", params)
        ckpt.write_meta(str(tag_dir), {"global_step": 0})
        ckpt.write_commit_marker(str(tag_dir))
        ckpt.write_latest(str(tmp_path), "weights_only")
        groups = ckpt.state_groups(str(tag_dir))
        assert groups["model_states"] == "sharded"
        assert groups["optim_states"] is None
        engine = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=TINY_INF,
            dtype=jnp.float32)
        out = engine.generate([[1, 2, 3]], max_new_tokens=2)[0]
        assert len(out) == 5

    def test_qwz_quantized_weight_path(self, tmp_path):
        """quantize_weights=True ships params through the qwZ int8
        block format: the engine still serves, and greedy outputs stay
        close to the fp32 weights' (identical at this size — int8
        block quantization error is far below the logit gaps)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        self._save_training_checkpoint(tmp_path, cfg, params)
        q = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=TINY_INF,
            dtype=jnp.float32, quantize_weights=True)
        # weights really were roundtripped through int8 blocks
        assert not np.allclose(np.asarray(q.params["wte"]),
                               np.asarray(params["wte"]))
        out = q.generate([[1, 2, 3]], max_new_tokens=3)[0]
        assert len(out) == 6 and all(0 <= t < 61 for t in out)

    def test_verify_checkpoint_cli_reports_state_groups(self, tmp_path,
                                                        capsys):
        """tools/verify_checkpoint.py names the state groups a committed
        tag contains (the satellite's reporting requirement)."""
        cfg, params = tiny_gpt2()
        self._save_training_checkpoint(tmp_path, cfg, params)
        vc = _load_tool("verify_checkpoint")
        assert vc.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "state groups:" in out
        assert "model_states(sharded)" in out
        assert "optim_states(sharded)" in out

    def test_from_checkpoint_rejects_corrupt(self, tmp_path):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, _ = tiny_gpt2()
        with pytest.raises(FileNotFoundError):
            InferenceEngine.from_checkpoint(
                str(tmp_path), cfg, inference_config=TINY_INF)


# --------------------------------------------------------------------- #
# config section
# --------------------------------------------------------------------- #
class TestInferenceConfigSection:
    def test_defaults_parse(self):
        from deepspeed_tpu.runtime.config import get_inference_config
        cfg = get_inference_config({})
        assert cfg["max_batch_size"] == 8
        assert cfg["prompt_buckets"] == [64, 256]
        assert cfg["batch_buckets"] == [1, 8]
        assert cfg["temperature"] == 0.0 and cfg["top_k"] == 0

    def test_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_inference_config)
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"prompt_buckets": [8, 4]}})
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"batch_buckets": [16],
                               "max_batch_size": 8}})
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"prompt_buckets": [2048],
                               "max_seq_len": 1024}})

    def test_rides_deepspeed_config(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                               "inference": {"max_batch_size": 2,
                                             "prompt_buckets": [16],
                                             "batch_buckets": [2],
                                             "max_seq_len": 64}},
                              world_size=1)
        assert cfg.inference_config["max_batch_size"] == 2
        assert cfg.inference_config["prompt_buckets"] == [16]


# --------------------------------------------------------------------- #
# paged KV cache (ISSUE 7 tentpole): page pool + block tables + prefix
# caching; occupancy bounded by tokens in flight, not slots x max_len
# --------------------------------------------------------------------- #
class TestPageAllocator:
    def _alloc(self, pages=9, ps=4, prefix=True):
        from deepspeed_tpu.inference.kv_cache import PageAllocator
        return PageAllocator(pages, ps, prefix_cache=prefix)

    def test_alloc_free_refcount(self):
        al = self._alloc()
        assert al.free_pages == 8 and al.pages_in_use == 0
        a = al.alloc(3)
        assert len(a) == 3 and al.pages_in_use == 3
        assert all(al.refcount(p) == 1 for p in a)
        assert al.alloc(6) is None          # partial grabs never happen
        assert al.free_pages == 5
        al.free(a)
        assert al.free_pages == 8 and al.pages_in_use == 0
        with pytest.raises(ValueError, match="unowned"):
            al.free(a[:1])

    def test_prefix_survives_until_last_reader_evicts(self):
        al = self._alloc()
        prompt = list(range(10))            # 2 full pages of 4 + tail
        owner = al.alloc(3)
        al.register_prefix(prompt, owner)
        shared, reused = al.match_prefix(prompt)
        assert shared == owner[:2] and reused == 8
        # a reader takes references on the shared pages
        al.incref(shared)
        assert al.refcount(owner[0]) == 2
        # owner evicts: shared pages SURVIVE (reader still holds them)
        al.free(owner)
        assert al.refcount(owner[0]) == 1
        assert al.match_prefix(prompt)[0] == owner[:2]
        # last reader evicts: pages return AND the prefix entry drops
        al.free(shared)
        assert al.free_pages == 8
        assert al.match_prefix(prompt) == ([], 0)

    def test_prefix_disabled(self):
        al = self._alloc(prefix=False)
        pages = al.alloc(2)
        al.register_prefix(list(range(8)), pages)
        assert al.match_prefix(list(range(8))) == ([], 0)

    def test_prefix_hit_verifies_content_not_just_hash(self):
        """A chain-hash collision (builtin tuple hashing is predictable,
        so craftable) must NOT hand one request another prompt's KV
        pages: hits verify the stored page's tokens."""
        al = self._alloc()
        prompt = list(range(8))
        owner = al.alloc(2)
        al.register_prefix(prompt, owner)
        other = [99] * 8
        # simulate the collision: point other's chain hash at owner's page
        h_other = next(al._chain_hashes(other))
        al._prefix[h_other] = owner[0]
        assert al.match_prefix(other) == ([], 0)     # content rejects
        assert al.match_prefix(prompt)[1] == 8       # genuine hit holds

    def test_prefix_hit_verifies_parent_chain_not_just_chunk(self):
        """Deep-layer K/V of page i depends on the WHOLE prefix before
        it, not just page i's own tokens — so a colliding entry whose
        chunk MATCHES but whose registered context differs must still be
        rejected. The parent-link check pins this: a hit at page i
        requires the candidate's registered predecessor to be the exact
        physical page matched at i-1."""
        al = self._alloc(pages=9, ps=4)
        attacker = [7, 7, 7, 7] + [1, 2, 3, 4]   # context A + chunk C
        ap = al.alloc(2)
        al.register_prefix(attacker, ap)
        victim = [0, 1, 2, 3] + [1, 2, 3, 4]     # context V + same chunk C
        vp = al.alloc(1)
        al.register_prefix(victim[:4], vp)        # page 0 registered honestly
        # simulate a chain-hash collision at the victim's page 1: the
        # index hands back the attacker's page, whose own chunk equals
        # the victim's — the old content-only check would accept it
        h_victim = list(al._chain_hashes(victim))[1]
        al._prefix[h_victim] = ap[1]
        got, n = al.match_prefix(victim)
        assert got == vp and n == 4      # page 1 rejected: wrong parent
        assert al.match_prefix(attacker)[0] == ap    # honest chain holds

    def test_divergent_prompts_share_only_common_pages(self):
        al = self._alloc(pages=17)
        a = list(range(12))
        b = list(range(8)) + [99, 98, 97, 96]    # diverges at page 2
        pa = al.alloc(3)
        al.register_prefix(a, pa)
        shared, reused = al.match_prefix(b)
        assert shared == pa[:2] and reused == 8

    def test_shared_duplicate_tokens(self):
        """Per-reader context sums count shared prefix pages once per
        reader; the allocator reports the exact overcount so
        ``tokens_in_flight`` can deduplicate."""
        al = self._alloc()
        owner = al.alloc(2)                      # 2 full shared pages
        al.register_prefix(list(range(8)), owner)
        assert al.shared_duplicate_tokens == 0   # one owner, no dupes
        al.incref(owner)                         # reader 1
        al.incref(owner)                         # reader 2
        assert al.shared_duplicate_tokens == 2 * 2 * 4
        al.free(owner)                           # one reference drops
        assert al.shared_duplicate_tokens == 2 * 4
        al.free(owner)
        al.free(owner)
        assert al.shared_duplicate_tokens == 0


class TestPagedServing:
    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_paged_vs_dense_generate_parity_small_pool(self, family):
        """ISSUE 7 acceptance: a mixed-length workload whose DENSE
        footprint exceeds the page pool (6 live requests x max_len 32 =
        192 token-slots dense; the pool holds 44) serves with greedy
        outputs EXACTLY matching the dense path, for both families,
        under continuous batching."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2() if family == "gpt2" else tiny_llama()
        rng = np.random.RandomState(7)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (3, 5, 7, 2, 8, 4, 6, 1)]
        dense = InferenceEngine(
            cfg, params, dict(TINY_INF, paged_kv={"enabled": False}),
            dtype=jnp.float32)
        ref = dense.generate(prompts, max_new_tokens=4, temperature=0.0)
        paged = InferenceEngine(
            cfg, params,
            dict(TINY_INF, paged_kv={"page_size": 4, "num_pages": 12}),
            dtype=jnp.float32)
        assert paged.paged and paged.scheduler.allocator is not None
        got = paged.generate(prompts, max_new_tokens=4, temperature=0.0)
        assert got == ref
        # every page returned once the workload drained
        al = paged.scheduler.allocator
        assert al.pages_in_use == 0 and al.free_pages == 11
        assert paged.scheduler.peak_tokens_in_flight > 0

    def test_paged_sampling_parity_with_dense(self):
        """Temperature sampling keys are position-based: the paged path
        must reproduce the dense stream exactly (same fold_in schedule
        even when a prefix offset splits prefill)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
        dense = InferenceEngine(
            cfg, params, dict(TINY_INF, paged_kv={"enabled": False}),
            dtype=jnp.float32)
        paged = InferenceEngine(
            cfg, params,
            dict(TINY_INF, paged_kv={"page_size": 4, "num_pages": 16}),
            dtype=jnp.float32)
        kw = dict(max_new_tokens=5, temperature=0.8, seeds=[7, 8, 9])
        assert paged.generate(prompts, **kw) == dense.generate(prompts,
                                                               **kw)

    def test_prefix_cache_shares_pages_with_parity(self):
        """Repeated system prompts prefill once: later requests reuse
        the registered pages (hit tokens > 0), outputs stay exactly the
        dense path's, and the shared pages free only after the last
        reader evicts."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        sys_prompt = list(range(1, 9))          # 2 full pages of 4
        prompts = [sys_prompt + [10], sys_prompt + [20, 21],
                   sys_prompt[:]]
        icfg = dict(TINY_INF, prompt_buckets=[4, 16], max_seq_len=32,
                    paged_kv={"page_size": 4, "num_pages": 20})
        dense = InferenceEngine(
            cfg, params, dict(icfg, paged_kv={"enabled": False}),
            dtype=jnp.float32)
        ref = dense.generate(prompts, max_new_tokens=3, temperature=0.0)
        paged = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        got = paged.generate(prompts, max_new_tokens=3, temperature=0.0)
        assert got == ref
        al = paged.scheduler.allocator
        assert al.prefix_hit_tokens >= 8        # later prompts reused
        assert al.pages_in_use == 0             # all returned at drain

    def test_prefix_cache_off_still_serves(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        paged = InferenceEngine(
            cfg, params,
            dict(TINY_INF, paged_kv={"page_size": 4, "num_pages": 20,
                                     "prefix_cache": False}),
            dtype=jnp.float32)
        outs = paged.generate([[1, 2, 3], [1, 2, 3]], max_new_tokens=3)
        assert outs[0] == outs[1]
        assert paged.scheduler.allocator.prefix_hit_tokens == 0

    def test_warmup_program_count_and_zero_recompiles_under_churn(self):
        """ISSUE 7 CI satellite: with paging enabled, warmup compiles
        EXACTLY len(batch_buckets) x len(prompt_buckets) prefill
        programs + the one paged decode program; a mixed-length churn
        workload (page alloc/free + prefix reuse + slot turnover) then
        compiles NOTHING more (CompileTracker-exact)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(
            cfg, params,
            dict(TINY_INF, paged_kv={"page_size": 4, "num_pages": 14}),
            dtype=jnp.float32)
        programs = engine.warmup()
        assert programs == 2 * 2 + 1
        assert engine.compile_tracker.counts == {"prefill": 4,
                                                 "decode": 1}
        rng = np.random.RandomState(5)
        sys_prompt = rng.randint(1, 61, (4,)).tolist()
        churn = [rng.randint(1, 61, (n,)).tolist()
                 for n in (1, 4, 5, 8, 3, 6, 2, 7)]
        churn += [sys_prompt + [int(t)] for t in rng.randint(1, 61, (4,))]
        engine.generate(churn, max_new_tokens=3)
        engine.generate(churn[:3], max_new_tokens=5, temperature=0.5)
        assert engine.steady_state_recompiles == 0
        assert engine.compile_tracker.total_compiles == programs

    def test_paged_telemetry_lands_in_events(self, tmp_path):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        icfg = dict(TINY_INF, events_dir=str(tmp_path),
                    paged_kv={"page_size": 4, "num_pages": 20})
        engine = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        engine.warmup()
        engine.generate([[1, 2, 3], [1, 2, 3], [4, 5]],
                        max_new_tokens=4)
        engine.close()
        rows = [json.loads(line)
                for line in open(tmp_path / "events.jsonl")]
        tags = {r["tag"] for r in rows if "tag" in r}
        assert {"Serve/kv_pages_in_use", "Serve/tokens_in_flight",
                "Serve/prefix_hit_rate"} <= tags
        pages = [r["value"] for r in rows
                 if r.get("tag") == "Serve/kv_pages_in_use"]
        assert max(pages) > 0
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(tmp_path))
        pk = s["serving"]["paged_kv"]
        assert pk["pages_in_use_peak"] > 0
        assert pk["tokens_in_flight_peak"] > 0
        assert "paged_kv" in obs_report.render(s)

    def test_submit_rejects_request_larger_than_pool(self):
        # ISSUE 19: graceful rejection — the caller sees an ordinary
        # FinishedRequest with the pinned reason from the next step
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.inference.scheduler import Request
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(
            cfg, params,
            dict(TINY_INF, paged_kv={"page_size": 4, "num_pages": 3}),
            dtype=jnp.float32)
        uid = engine.submit(Request(prompt=[1, 2, 3], max_new_tokens=8))
        fins = engine.step()
        assert [f.uid for f in fins] == [uid]
        assert fins[0].finish_reason == "reject_too_long"


class TestLookaheadAdmission:
    """ISSUE 7 satellite: bounded-lookahead admission — a head request
    that doesn't fit the free pages must not stall the whole queue."""

    def _sched(self, lookahead, pages=10, ps=4, occupy=True):
        """Scheduler with 9 usable pages; ``occupy`` admits a resident
        8-page request so only ONE page stays free — a later 8-page head
        fits the pool in principle (submit accepts it) but not the
        current free pages (admission must look past it)."""
        from deepspeed_tpu.inference.kv_cache import PageAllocator
        from deepspeed_tpu.inference.scheduler import Request, Scheduler
        s = Scheduler(3, (4, 16), (1, 2), 32,
                      allocator=PageAllocator(pages, ps),
                      lookahead=lookahead)
        if occupy:
            resident = Request(prompt=[9] * 16, max_new_tokens=16)
            s.submit(resident)
            (batch,) = s.admit()
            s.record_tokens({batch.slot_ids[0]: 1})   # mid-decode
            assert s.allocator.free_pages == pages - 1 - 8
        return s

    def test_small_request_behind_big_head_lands(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(lookahead=4)
        big = Request(prompt=[1] * 16, max_new_tokens=16)   # 8 pages
        small = Request(prompt=[2, 3], max_new_tokens=2)    # 1 page
        s.submit(big)
        s.submit(small)
        (batch,) = s.admit()
        assert [r.uid for r in batch.requests] == [small.uid]
        assert s.queue_depth == 1                # big still waiting
        # small finishes -> its page frees -> big still blocked (needs
        # 8, 2 free): the queue drains only when capacity appears
        sid = batch.slot_ids[0]
        s.record_tokens({sid: 1})
        s.record_tokens({sid: 2})
        assert s.admit() == []

    def test_strict_fifo_blocks_without_lookahead(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(lookahead=0)
        s.submit(Request(prompt=[1] * 16, max_new_tokens=16))
        s.submit(Request(prompt=[2, 3], max_new_tokens=2))
        assert s.admit() == []                   # head-of-line blocked

    def test_lookahead_window_is_bounded(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(lookahead=1)
        s.submit(Request(prompt=[1] * 16, max_new_tokens=16))
        s.submit(Request(prompt=[3] * 16, max_new_tokens=16))
        fits = Request(prompt=[2, 3], max_new_tokens=2)
        s.submit(fits)                           # position 2 > window
        assert s.admit() == []
        s2 = self._sched(lookahead=2)
        s2.submit(Request(prompt=[1] * 16, max_new_tokens=16))
        s2.submit(Request(prompt=[3] * 16, max_new_tokens=16))
        fits2 = Request(prompt=[2, 3], max_new_tokens=2)
        s2.submit(fits2)
        (batch,) = s2.admit()
        assert [r.uid for r in batch.requests] == [fits2.uid]

    def test_fifo_order_restored_when_head_fits(self):
        from deepspeed_tpu.inference.scheduler import Request
        s = self._sched(lookahead=4, pages=20)
        a = Request(prompt=[1, 2], max_new_tokens=2)
        b = Request(prompt=[3, 4], max_new_tokens=2)
        s.submit(a)
        s.submit(b)
        (batch,) = s.admit()
        assert [r.uid for r in batch.requests] == [a.uid, b.uid]


class TestTokensInFlight:
    def test_shared_prefix_counted_once(self):
        """``tokens_in_flight`` reports physical pool occupancy: a
        prefix shared by N readers lands once, not N times."""
        from deepspeed_tpu.inference.kv_cache import PageAllocator
        from deepspeed_tpu.inference.scheduler import Request, Scheduler
        s = Scheduler(3, (4, 16), (1, 2), 32,
                      allocator=PageAllocator(20, 4))
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        s.submit(Request(prompt=prompt, max_new_tokens=4))
        s.submit(Request(prompt=prompt, max_new_tokens=4))
        s.admit()
        # reuse caps one token short of the prompt -> the second reader
        # shares exactly the first page (4 of its 8 context tokens)
        assert s.allocator.shared_duplicate_tokens == 4
        assert s.tokens_in_flight == 8 + 8 - 4
        assert s.peak_tokens_in_flight == 12
class TestServingMesh:
    MESH_INF = dict(TINY_INF, mesh={"axes": {"model": 2}})

    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_sharded_decode_parity(self, family):
        """Tensor-parallel serving over a 2-way CPU mesh: greedy outputs
        exactly match the unsharded engine for both families (llama
        exercises the GQA kv_heads split)."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2() if family == "gpt2" else tiny_llama()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (3, 5, 7, 2, 8)]
        base = InferenceEngine(cfg, params, TINY_INF, dtype=jnp.float32)
        ref = base.generate(prompts, max_new_tokens=4, temperature=0.0)
        sharded = InferenceEngine(cfg, params, self.MESH_INF,
                                  dtype=jnp.float32)
        assert sharded.mesh is not None
        assert dict(sharded.mesh.shape) == {"model": 2}
        got = sharded.generate(prompts, max_new_tokens=4,
                               temperature=0.0)
        assert got == ref
        # params really live sharded: a column-parallel leaf is split
        from jax.sharding import PartitionSpec as P
        leaf = sharded.params["h_0"]["attn"][
            "qkvw" if family == "gpt2" else "wq"]
        assert leaf.sharding.spec == P(None, "model")

    def test_sharded_zero_steady_state_recompiles(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, self.MESH_INF,
                                 dtype=jnp.float32)
        programs = engine.warmup()
        assert programs == 2 * 2 + 1
        rng = np.random.RandomState(2)
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (1, 4, 5, 8, 3)]
        engine.generate(prompts, max_new_tokens=3)
        assert engine.steady_state_recompiles == 0

    def test_from_checkpoint_reshards_onto_serving_mesh(self, tmp_path):
        """Train on the default (unsharded) layout, serve on a model=2
        mesh: from_checkpoint materializes the params straight into the
        serving NamedShardings and outputs match the in-memory
        engine."""
        import deepspeed_tpu
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
        cfg, params = tiny_gpt2()
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2_loss_fn(cfg, dtype=jnp.float32,
                               deterministic=True),
            model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "steps_per_print": 10**9,
                    "optimizer": {"type": "Adam",
                                  "params": {"lr": 1e-3}}})
        engine.save_checkpoint(str(tmp_path))
        served = InferenceEngine.from_checkpoint(
            str(tmp_path), cfg, inference_config=self.MESH_INF,
            dtype=jnp.float32)
        assert served.mesh is not None
        from jax.sharding import PartitionSpec as P
        assert served.params["h_0"]["mlp"]["fc_w"].sharding.spec == \
            P(None, "model")
        direct = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        prompts = [[1, 2, 3], [4, 5, 6, 7]]
        assert served.generate(prompts, max_new_tokens=4) == \
            direct.generate(prompts, max_new_tokens=4)

    def test_mesh_rejects_indivisible_heads(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()          # 4 heads
        with pytest.raises(ValueError, match="divide"):
            InferenceEngine(cfg, params,
                            dict(TINY_INF, mesh={"axes": {"model": 3}}),
                            dtype=jnp.float32)


# --------------------------------------------------------------------- #
# new config keys
# --------------------------------------------------------------------- #
class TestPagedConfigSection:
    def test_defaults(self):
        from deepspeed_tpu.runtime.config import get_inference_config
        cfg = get_inference_config({})
        assert cfg["paged_kv"] == {"enabled": True, "page_size": 16,
                                   "num_pages": 0, "prefix_cache": True,
                                   "attn_kernel": "pallas",
                                   "decode_page_buckets": [],
                                   "kv_dtype": None, "kv_quant_block": 0}
        assert cfg["mesh"] == {"axes": {}}
        assert cfg["admit_lookahead"] == 4

    def test_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_inference_config)
        with pytest.raises(DeepSpeedConfigError, match="page_size"):
            get_inference_config(
                {"inference": {"paged_kv": {"page_size": 0}}})
        with pytest.raises(DeepSpeedConfigError, match="num_pages"):
            get_inference_config(
                {"inference": {"paged_kv": {"num_pages": 1}}})
        with pytest.raises(DeepSpeedConfigError, match="admit_lookahead"):
            get_inference_config({"inference": {"admit_lookahead": -1}})
        with pytest.raises(DeepSpeedConfigError, match="mesh.axes"):
            get_inference_config(
                {"inference": {"mesh": {"axes": {"model": 0}}}})
        # unknown axis names fail HERE with a curated message, not as
        # an opaque jax resource error deep in engine init
        with pytest.raises(DeepSpeedConfigError, match="'model'"):
            get_inference_config(
                {"inference": {"mesh": {"axes": {"tp": 2}}}})

    def test_auto_pool_matches_dense_worst_case(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        # max_batch_size 3, max_len 32, page_size 16 -> 3*2 + null
        assert engine.paged_spec.num_pages == 7
        assert engine.paged_spec.pages_per_seq == 2


# --------------------------------------------------------------------- #
# quantized serving (ISSUE 17)
# --------------------------------------------------------------------- #
class TestQuantizedServing:
    """int8-resident weights + int8 KV page pool: the serving bytes
    halve on both levers while greedy decode stays within the pinned
    error budget — and the zero-recompile/continuous-batching pins
    hold with quantization on."""

    # max |logits_fp - logits_quant| budget at the tiny geometry: the
    # measured error is ~0.02; 0.05 leaves slack without ever letting a
    # real regression (e.g. a dropped scale) through
    LOGIT_BUDGET = 0.05

    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    @pytest.mark.parametrize("mode", ["weights", "kv", "both"])
    def test_quant_matrix_greedy_and_zero_recompiles(self, family,
                                                     mode):
        """The quantized-serving matrix: each quantization lever (and
        both together) serves the mixed-length prefix-sharing workload
        under continuous batching with greedy outputs matching the fp
        engine (the quantization error at this scale sits far below
        the logit gaps — the budget itself is pinned by the logit-err
        probe test) and zero steady-state recompiles."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.runtime.quantized_params import \
            is_quantized_tree
        cfg, params = tiny_gpt2() if family == "gpt2" else tiny_llama()
        rng = np.random.RandomState(11)
        # 2 full pages of shared system prompt + staggered readers (the
        # admission batches split 2+1, so the later reader reuses the
        # registered prefix pages)
        sys_prompt = rng.randint(1, 61, (8,)).tolist()
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (3, 6, 2, 7)]
        prompts += [sys_prompt + [10], sys_prompt + [20, 21],
                    sys_prompt[:]]
        base_inf = dict(TINY_INF, prompt_buckets=[4, 16])

        extra = {}
        if mode in ("weights", "both"):
            extra["quantize_weights"] = "int8"
        pk = {"page_size": 4, "num_pages": 20}
        if mode in ("kv", "both"):
            pk["kv_dtype"] = "int8"
            if mode == "both":
                pk["kv_quant_block"] = 4
        ref_eng = InferenceEngine(
            cfg, params, dict(base_inf, paged_kv=dict(
                page_size=4, num_pages=20)), dtype=jnp.float32)
        ref = ref_eng.generate(prompts, max_new_tokens=4,
                               temperature=0.0)
        q_eng = InferenceEngine(
            cfg, params, dict(base_inf, paged_kv=pk, **extra),
            dtype=jnp.float32)
        q_eng.warmup()
        got = q_eng.generate(prompts, max_new_tokens=4,
                             temperature=0.0)
        assert got == ref
        assert q_eng.steady_state_recompiles == 0
        assert is_quantized_tree(q_eng.params) == \
            (mode in ("weights", "both"))
        assert len(q_eng._cache) == (4 if mode in ("kv", "both")
                                     else 2)
        dq = q_eng.debug_state()["quantization"]
        assert dq["weights_resident"] == (
            "int8" if mode in ("weights", "both") else "off")
        assert dq["kv_dtype"] == ("int8" if mode in ("kv", "both")
                                  else "float32")
        if mode in ("weights", "both"):
            assert dq["weight_bytes"] < dq["weight_bytes_dense"]
        # prefix reuse really happened under quantization
        assert q_eng.scheduler.allocator.prefix_hit_tokens >= 4

    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_quant_logit_err_budget_and_probe(self, family, tmp_path):
        """The pinned error budget (NOT bitwise): max logit delta of
        the int8-resident forward vs the fp forward stays under
        LOGIT_BUDGET, and recording it on the engine lands the
        Serve/quant_logit_err scalar + debug_state field + obs_report
        quantization block."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.runtime.quantized_params import \
            quantize_param_tree
        if family == "gpt2":
            from deepspeed_tpu.models.gpt2 import gpt2_forward as fwd
            cfg, params = tiny_gpt2()
        else:
            from deepspeed_tpu.models.llama import llama_forward as fwd
            cfg, params = tiny_llama()
        rng = np.random.RandomState(12)
        ids = jnp.asarray(rng.randint(1, 61, (2, 8)), jnp.int32)
        logits_fp = fwd(params, cfg, ids, dtype=jnp.float32)
        logits_q = fwd(quantize_param_tree(params), cfg, ids,
                       dtype=jnp.float32)
        err = float(jnp.max(jnp.abs(logits_fp - logits_q)))
        assert 0.0 < err < self.LOGIT_BUDGET

        icfg = dict(TINY_INF, events_dir=str(tmp_path),
                    quantize_weights="int8",
                    paged_kv={"page_size": 4, "num_pages": 20,
                              "kv_dtype": "int8"})
        eng = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        eng.record_quant_logit_err(err)
        eng.generate([[1, 2, 3], [4, 5]], max_new_tokens=3)
        state = eng.debug_state()
        assert state["quantization"]["quant_logit_err"] == err
        assert state["quantization"]["kv_pool_bytes_per_token"] > 0
        eng.close()
        rows = [json.loads(line)
                for line in open(tmp_path / "events.jsonl")]
        tags = {r["tag"] for r in rows if "tag" in r}
        assert {"Serve/quant_logit_err",
                "Serve/kv_pool_bytes_per_token"} <= tags
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(tmp_path))
        qz = s["serving"]["quantization"]
        assert qz["quant_logit_err"] == pytest.approx(err)
        assert qz["kv_pool_bytes_per_token"] > 0

    def test_all_levers_plus_spec_decode_zero_recompiles(self):
        """ISSUE 17 acceptance: quant-weights + quant-KV + spec-decode
        all ON — greedy outputs bitwise match the same quantized
        engine without speculation, steady_state_recompiles == 0, and
        every submitted request finishes exactly once."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        quant = {"quantize_weights": "int8",
                 "paged_kv": {"page_size": 4, "num_pages": 20,
                              "kv_dtype": "int8"}}
        # repetitive prompts so the n-gram drafter actually proposes
        prompts = [[1, 2, 3, 1, 2, 3, 1, 2], [4, 5, 4, 5, 4, 5],
                   [7, 8, 9, 7, 8, 9, 7]]
        base = InferenceEngine(cfg, params, dict(TINY_INF, **quant),
                               dtype=jnp.float32)
        base.warmup()
        ref = base.generate(prompts, max_new_tokens=8,
                            temperature=0.0)
        spec = InferenceEngine(
            cfg, params,
            dict(TINY_INF, spec_decode={"enabled": True, "k": 4},
                 **quant), dtype=jnp.float32)
        spec.warmup()
        got = spec.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert got == ref
        assert spec.steady_state_recompiles == 0
        assert base.steady_state_recompiles == 0
        st = spec.debug_state()
        assert st["quantization"]["weights_resident"] == "int8"
        assert st["quantization"]["kv_dtype"] == "int8"

    def test_quant_config_normalization_and_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_inference_config)
        c = get_inference_config({})
        assert c["quantize_weights"] is False
        assert c["paged_kv"]["kv_dtype"] is None
        assert c["paged_kv"]["kv_quant_block"] == 0
        # legacy boolean means wire-quantize, dequantize to bf16
        c = get_inference_config(
            {"inference": {"quantize_weights": True}})
        assert c["quantize_weights"] == "bf16"
        c = get_inference_config(
            {"inference": {"quantize_weights": "int8",
                           "paged_kv": {"kv_dtype": "int8",
                                        "kv_quant_block": 8}}})
        assert c["quantize_weights"] == "int8"
        assert c["paged_kv"]["kv_dtype"] == "int8"
        assert c["paged_kv"]["kv_quant_block"] == 8
        with pytest.raises(DeepSpeedConfigError,
                           match="quantize_weights"):
            get_inference_config(
                {"inference": {"quantize_weights": "fp8"}})
        with pytest.raises(DeepSpeedConfigError, match="kv_dtype"):
            get_inference_config(
                {"inference": {"paged_kv": {"kv_dtype": "fp4"}}})
        with pytest.raises(DeepSpeedConfigError,
                           match="kv_quant_block"):
            get_inference_config(
                {"inference": {"paged_kv": {"kv_quant_block": 4}}})
