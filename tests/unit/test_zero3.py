"""ZeRO stage 3 (TPU-native extension; the reference caps at stage 2,
zero/constants.py:28-40): persistent state sharded like stage 2, and NO
replicated full-parameter transient — the engine skips the up-front
compute-dtype cast so weights are gathered + cast at use sites."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_loss_fn,
                                       init_gpt2_params)

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)


def _cfg(stage, **over):
    c = {
        "train_micro_batch_size_per_gpu": 1,
        "steps_per_print": 1000,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": stage},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    c.update(over)
    return c


MODEL = GPT2Config(vocab_size=2048, max_position_embeddings=64,
                   hidden_size=128, num_layers=4, num_heads=4,
                   embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)


def _engine(stage, seed=0):
    params = init_gpt2_params(MODEL, jax.random.PRNGKey(seed))
    loss_fn = gpt2_loss_fn(MODEL, deterministic=True, remat=True)
    engine, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                               config=_cfg(stage))
    return engine


def _batches(n, bs=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{"input_ids": rng.randint(0, MODEL.vocab_size,
                                      (bs, 33)).astype(np.int32)}
            for _ in range(n)]


def test_stage3_accepted_and_state_sharded():
    e = _engine(3)
    assert e.zero_stage == 3
    # persistent master params sharded 1/dp over 'data' (like stage 2)
    wte = e.state.params["wte"]
    local = wte.addressable_shards[0].data.shape
    assert np.prod(local) == np.prod(wte.shape) // 8


def test_stage3_matches_stage2_trajectory():
    """Only the cast LOCATION differs: stage 3 computes e.g. layernorm
    stats from fp32 weights where stage 2 pre-rounded to bf16 — same
    update math, sub-1e-4 numeric drift."""
    e3, e2 = _engine(3, seed=1), _engine(2, seed=1)
    for b in _batches(3, seed=2):
        l3 = float(e3.train_batch(iter([b])))
        l2 = float(e2.train_batch(iter([b])))
        np.testing.assert_allclose(l3, l2, rtol=1e-4)
    # Adam normalizes grads, so cast-order rounding walks individual
    # params apart at ~lr scale per step; the trajectory-level invariant
    # is the per-step loss match above plus a small relative RMS drift
    num = den = 0.0
    for a, b in zip(jax.tree_util.tree_leaves(e3.state.params),
                    jax.tree_util.tree_leaves(e2.state.params)):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        num += float(np.sum((a - b) ** 2))
        den += float(np.sum(b ** 2))
    assert np.sqrt(num / den) < 1e-2, np.sqrt(num / den)


def test_stage3_lower_temp_memory_than_stage2():
    """The stage-3 step must compile to strictly less XLA temp memory than
    stage 2 (no full bf16 param copy). Uses the compiler's own memory
    analysis — the honest 8-device-mesh proxy for peak HBM."""
    e3, e2 = _engine(3), _engine(2)
    b = _batches(1)[0]
    sizes = {}
    for name, e in (("s3", e3), ("s2", e2)):
        from jax.sharding import NamedSharding, PartitionSpec as P
        batch = jax.device_put(
            b, NamedSharding(e.mesh, P("data")))
        step = e._get_compiled_micro_step()
        ma = step.lower(e.state, batch).compile().memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend provides no memory analysis")
        sizes[name] = ma.temp_size_in_bytes
    assert sizes["s3"] < sizes["s2"], sizes


def test_stage3_rejected_with_pipeline():
    from deepspeed_tpu.models.gpt2 import gpt2_pipeline_spec
    spec = gpt2_pipeline_spec(MODEL, num_stages=2)
    with pytest.raises(ValueError, match="stage 3"):
        ds.initialize(model=spec, config=_cfg(
            3, mesh={"axes": {"pipe": 2, "data": 4, "model": 1}}))
