"""Checkpoint save/load round-trips (mirrors reference
tests/unit/test_checkpointing.py: ZeRO stages, fp16 state, lr scheduler,
elastic world-size changes)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import (
    base_config, init_simple_params, random_batches, simple_loss_fn)

HIDDEN = 16


def make_engine(config, seed=0):
    params = init_simple_params(jax.random.PRNGKey(seed), HIDDEN)
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_parameters=params, config=config)
    return engine


def train_steps(engine, n, seed=0):
    batches = iter(random_batches(
        n * engine.gradient_accumulation_steps, 16, HIDDEN, seed=seed))
    losses = [float(engine.train_batch(batches)) for _ in range(n)]
    return losses


def params_equal(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    return all(np.allclose(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb))


@pytest.mark.parametrize("stage", [0, 2])
def test_roundtrip_preserves_training(tmp_path, stage):
    cfg = base_config(zero_optimization={"stage": stage})
    e1 = make_engine(cfg, seed=1)
    train_steps(e1, 5, seed=2)
    e1.save_checkpoint(str(tmp_path), client_state={"note": "hi"})

    e2 = make_engine(cfg, seed=99)  # different init
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client == {"note": "hi"}
    assert e2.global_steps == 5
    assert params_equal(e1.state.params, e2.state.params)
    assert params_equal(e1.state.opt_state.exp_avg, e2.state.opt_state.exp_avg)

    # resumed training must follow the same trajectory
    l1 = train_steps(e1, 3, seed=5)
    l2 = train_steps(e2, 3, seed=5)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_latest_tag_and_explicit_tag(tmp_path):
    e = make_engine(base_config())
    train_steps(e, 2)
    e.save_checkpoint(str(tmp_path))
    train_steps(e, 2)
    e.save_checkpoint(str(tmp_path))
    # "latest" points to step 4
    e2 = make_engine(base_config())
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 4
    # explicit older tag still loadable
    e3 = make_engine(base_config())
    e3.load_checkpoint(str(tmp_path), tag="global_step2")
    assert e3.global_steps == 2


def test_elastic_zero_resharding(tmp_path):
    """Save under ZeRO-2, reload under stage 0 (different 'partitioning') —
    the reference needed merge-then-repartition (stage2.py:1713); here it is
    free because checkpoints are global arrays."""
    e1 = make_engine(base_config(zero_optimization={"stage": 2}), seed=1)
    train_steps(e1, 3)
    e1.save_checkpoint(str(tmp_path))

    e2 = make_engine(base_config(), seed=2)  # stage 0
    e2.load_checkpoint(str(tmp_path))
    assert params_equal(e1.state.params, e2.state.params)
    l1 = train_steps(e1, 2, seed=9)
    l2 = train_steps(e2, 2, seed=9)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)


def test_sharded_checkpoint_layout(tmp_path):
    """Saving writes per-process shard files + manifests, not a monolith
    (reference per-dp-rank zero files, engine.py:1153-1164)."""
    e = make_engine(base_config(zero_optimization={"stage": 2}))
    train_steps(e, 1)
    d = e.save_checkpoint(str(tmp_path))
    import os
    files = os.listdir(d)
    assert "model_states.shard_0.npz" in files
    assert "model_states.shard_0.json" in files
    assert "optim_states.shard_0.npz" in files
    assert "model_states.npz" not in files


def test_sharded_save_writes_no_duplicate_replicas(tmp_path):
    """A ZeRO-2 sharded optimizer leaf is written once across all shard
    entries (replica-0 only): total saved elements == global elements."""
    import json as _json
    import os
    e = make_engine(base_config(zero_optimization={"stage": 2}))
    train_steps(e, 1)
    d = e.save_checkpoint(str(tmp_path))
    with open(os.path.join(d, "optim_states.shard_0.json")) as f:
        man = _json.load(f)
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            {"opt_state": e.state.opt_state,
             "loss_scale": e.state.loss_scale})[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx",
                                                     getattr(p, "name", p))))
                       for p in path)
        flat[key] = leaf
    for key, entry in man.items():
        saved = sum(
            int(np.prod([e2 - b for b, e2 in zip(c["start"], c["stop"])]))
            if c["start"] else 1
            for c in entry["chunks"])
        want = int(np.prod(flat[key].shape)) if hasattr(flat[key], "shape") else 1
        assert saved == want, f"{key}: saved {saved} != global {want}"


def test_elastic_dp8_to_dp4_roundtrip(tmp_path):
    """Save under dp=8 ZeRO-2 sharding, resume under a dp=4 mesh — the
    sharded loader repartitions chunk-by-chunk (reference elastic ckpt,
    stage2.py:1713-1779 merge-then-repartition)."""
    cfg8 = base_config(zero_optimization={"stage": 2},
                       mesh={"axes": {"data": 8}})
    e1 = make_engine(cfg8, seed=1)
    train_steps(e1, 3, seed=2)
    e1.save_checkpoint(str(tmp_path))

    cfg4 = base_config(zero_optimization={"stage": 2},
                       mesh={"axes": {"data": 4}})
    e2 = make_engine(cfg4, seed=77)
    assert e2.dp_world_size == 4
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert params_equal(e1.state.params, e2.state.params)
    assert params_equal(e1.state.opt_state.exp_avg,
                        e2.state.opt_state.exp_avg)
    # resumed training on the smaller world still converges identically
    # per-step given identical global batches
    l2 = train_steps(e2, 2, seed=5)
    assert all(np.isfinite(l2))


def test_legacy_single_file_checkpoint_loads(tmp_path):
    """Old-format (pre-sharded) checkpoints still load."""
    import os
    from deepspeed_tpu.runtime import checkpoint as ckpt
    e1 = make_engine(base_config(), seed=1)
    train_steps(e1, 2)
    d = os.path.join(str(tmp_path), "global_step2")
    os.makedirs(d)
    ckpt.save_tree(os.path.join(d, "model_states.npz"), e1.state.params)
    ckpt.save_tree(os.path.join(d, "optim_states.npz"),
                   {"opt_state": e1.state.opt_state,
                    "loss_scale": e1.state.loss_scale})
    ckpt.write_meta(d, {"global_step": 2, "micro_step": 0,
                        "skipped_steps": 0,
                        "rng": np.asarray(e1.state.rng).tolist(),
                        "lr_scheduler": None, "dp_world_size": 8,
                        "zero_stage": 0, "client_state": {}})
    ckpt.write_latest(str(tmp_path), "global_step2")
    e2 = make_engine(base_config(), seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert params_equal(e1.state.params, e2.state.params)


def test_missing_checkpoint_warns(tmp_path):
    e = make_engine(base_config())
    path, client = e.load_checkpoint(str(tmp_path))
    assert path is None and client == {}


def test_fp16_scaler_state_restored(tmp_path):
    cfg = base_config(fp16={"enabled": True, "initial_scale_power": 10})
    e1 = make_engine(cfg)
    train_steps(e1, 3)
    e1.save_checkpoint(str(tmp_path))
    e2 = make_engine(cfg)
    e2.load_checkpoint(str(tmp_path))
    assert e2.loss_scale() == e1.loss_scale()


def test_lr_scheduler_state_restored(tmp_path):
    cfg = base_config(scheduler={
        "type": "WarmupLR",
        "params": {"warmup_max_lr": 1e-2, "warmup_num_steps": 100}})
    e1 = make_engine(cfg)
    train_steps(e1, 4)
    e1.save_checkpoint(str(tmp_path))
    e2 = make_engine(cfg)
    e2.load_checkpoint(str(tmp_path))
    assert e2.get_lr() == e1.get_lr()


def test_truncated_shard_falls_back_to_previous_tag(tmp_path):
    """Torn write (file cut short mid-flush): size check against the
    COMMITTED marker catches it; resume falls back one tag."""
    from deepspeed_tpu.runtime import fault
    e = make_engine(base_config(), seed=1)
    train_steps(e, 2, seed=2)
    e.save_checkpoint(str(tmp_path))
    # live params at step 2 are the ground truth the fallback must match
    params_at_step2 = jax.tree_util.tree_map(np.asarray, e.state.params)
    train_steps(e, 2, seed=3)
    e.save_checkpoint(str(tmp_path))
    fault.truncate_file(
        str(tmp_path / "global_step4" / "model_states.shard_0.npz"))
    e2 = make_engine(base_config(), seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("global_step2")
    assert e2.global_steps == 2
    assert params_equal(params_at_step2, e2.state.params)


def test_save_retries_transient_oserror(tmp_path):
    """Two injected write flakes, then success: the exponential-backoff
    retry makes the save commit without caller involvement."""
    from deepspeed_tpu.runtime import checkpoint as ckpt
    from deepspeed_tpu.runtime import fault
    fault.reset()
    e = make_engine(base_config(), seed=1)
    train_steps(e, 2)
    fault.arm("io_write", exc=OSError("flake"), times=2)
    try:
        d = e.save_checkpoint(str(tmp_path))
    finally:
        fault.reset()
    import os
    assert os.path.isfile(os.path.join(d, ckpt.COMMIT_MARKER))
    e2 = make_engine(base_config(), seed=5)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and e2.global_steps == 2


def test_loss_scale_state_roundtrips(tmp_path):
    """Dynamic loss scale + skipped-step counters survive save/load."""
    cfg = base_config(fp16={"enabled": True, "initial_scale_power": 8,
                            "loss_scale_window": 100})
    e1 = make_engine(cfg, seed=1)
    train_steps(e1, 3, seed=2)
    scale_before = e1.loss_scale()
    skipped_before = e1.skipped_steps
    e1.save_checkpoint(str(tmp_path))
    e2 = make_engine(cfg, seed=42)
    e2.load_checkpoint(str(tmp_path))
    assert e2.loss_scale() == scale_before
    assert e2.skipped_steps == skipped_before
    # and keeps evolving identically from there
    l1 = train_steps(e1, 2, seed=7)
    l2 = train_steps(e2, 2, seed=7)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    assert e1.loss_scale() == e2.loss_scale()


def test_keep_n_retention_gc(tmp_path):
    """checkpoint.keep_n garbage-collects only committed older tags."""
    import os
    from deepspeed_tpu.runtime import checkpoint as ckpt
    cfg = base_config(checkpoint={"keep_n": 2})
    e = make_engine(cfg)
    for _ in range(3):
        train_steps(e, 1)
        e.save_checkpoint(str(tmp_path))
    tags = ckpt.list_tags(str(tmp_path))
    assert tags == ["global_step3", "global_step2"]
    assert not os.path.isdir(str(tmp_path / "global_step1"))
    # an uncommitted (legacy) dir is never GC'd
    legacy = tmp_path / "global_step0"
    legacy.mkdir()
    ckpt.write_meta(str(legacy), {"global_step": 0})
    train_steps(e, 1)
    e.save_checkpoint(str(tmp_path))
    assert os.path.isdir(str(legacy))
    assert not os.path.isdir(str(tmp_path / "global_step2"))


def test_keep_n_never_deletes_named_tag_or_latest(tmp_path):
    """Retention manages only automatic step-suffixed tags: a custom
    name ('best') — including when it was saved last and `latest` points
    at it — is user-owned and survives GC."""
    import os
    from deepspeed_tpu.runtime import checkpoint as ckpt
    cfg = base_config(checkpoint={"keep_n": 2})
    e = make_engine(cfg)
    for _ in range(3):
        train_steps(e, 1)
        e.save_checkpoint(str(tmp_path))
    train_steps(e, 1)
    d = e.save_checkpoint(str(tmp_path), tag="best")
    assert os.path.isdir(d), "retention deleted the tag it just saved"
    assert ckpt.read_latest(str(tmp_path)) == "best"
    tags = ckpt.list_tags(str(tmp_path))
    assert "best" in tags
    assert "global_step1" not in tags  # step tags still pruned to keep_n
    e2 = make_engine(cfg)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("best")
    assert e2.global_steps == 4


def test_write_latest_atomic_and_empty_is_none(tmp_path):
    """Satellite: `latest` is written via temp + os.replace (no torn
    droppings) and an empty/whitespace pointer reads as None, not ''."""
    import os
    from deepspeed_tpu.runtime import checkpoint as ckpt
    ckpt.write_latest(str(tmp_path), "global_step7")
    assert ckpt.read_latest(str(tmp_path)) == "global_step7"
    assert not os.path.exists(str(tmp_path / "latest.tmp"))
    with open(str(tmp_path / "latest"), "w") as f:
        f.write("   \n")
    assert ckpt.read_latest(str(tmp_path)) is None
    assert ckpt.read_latest(str(tmp_path / "nonexistent")) is None


def test_sharded_exists_requires_complete_save(tmp_path):
    """Satellite: shard_0.json alone no longer vouches for a
    multi-process save — the commit marker (or every fragment) must be
    present."""
    import os
    from deepspeed_tpu.runtime import checkpoint as ckpt
    d = str(tmp_path)
    tree = {"w": np.arange(8, dtype=np.float32)}
    ckpt.save_tree_sharded(d, "model_states", tree)
    # legacy (no marker): complete fragment set -> True
    assert ckpt.sharded_exists(d, "model_states")
    # fake a second process's manifest with no npz: partial save -> False
    with open(os.path.join(d, "model_states.shard_1.json"), "w") as f:
        f.write("{}")
    assert not ckpt.sharded_exists(d, "model_states")
    os.remove(os.path.join(d, "model_states.shard_1.json"))
    # committed: marker is authoritative, listed files must exist
    ckpt.write_commit_marker(d, process_count=1)
    assert ckpt.sharded_exists(d, "model_states")
    os.remove(os.path.join(d, "model_states.shard_0.npz"))
    assert not ckpt.sharded_exists(d, "model_states")


def test_meta_topology_mismatch_warns_not_crashes(tmp_path, caplog):
    """Satellite: resuming under a different dp world / ZeRO stage logs
    a warning but restores fine (elastic resume is supported)."""
    import logging
    from deepspeed_tpu.utils.logging import logger as ds_logger
    e1 = make_engine(base_config(zero_optimization={"stage": 2}), seed=1)
    train_steps(e1, 2)
    e1.save_checkpoint(str(tmp_path))
    e2 = make_engine(base_config(), seed=5)  # stage 0
    old_propagate = ds_logger.propagate
    ds_logger.propagate = True  # the project logger is propagate=False
    try:
        with caplog.at_level(logging.WARNING, logger=ds_logger.name):
            path, _ = e2.load_checkpoint(str(tmp_path))
    finally:
        ds_logger.propagate = old_propagate
    assert path is not None
    assert any("zero_stage" in r.message for r in caplog.records)
    assert params_equal(e1.state.params, e2.state.params)


def test_sharded_tree_cross_sharding_reload():
    """Direct module-level check of the chunk-manifest loader: save under
    one sharding (model-axis split), reload under a different one
    (data-axis split) and replicated — exact reassembly either way."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import build_mesh
    from deepspeed_tpu.runtime import checkpoint as ckpt
    import tempfile

    mesh = build_mesh({"data": 2, "model": 4})
    rng = np.random.RandomState(0)
    tree = {
        "a": jax.device_put(rng.randn(8, 12).astype(np.float32),
                            NamedSharding(mesh, P("model", None))),
        "b": jax.device_put(rng.randn(16).astype(np.float32),
                            NamedSharding(mesh, P("data"))),
        "c": jax.device_put(np.float32(3.5), NamedSharding(mesh, P())),
    }
    with tempfile.TemporaryDirectory() as d:
        ckpt.save_tree_sharded(d, "t", tree)
        out = ckpt.load_tree_sharded(
            d, "t", tree,
            shardings={"a": NamedSharding(mesh, P(None, "data")),
                       "b": NamedSharding(mesh, P()),
                       "c": NamedSharding(mesh, P())})
        for k in tree:
            np.testing.assert_array_equal(np.asarray(out[k]),
                                          np.asarray(tree[k]))
        # and the new shardings took effect
        assert out["a"].sharding.spec == P(None, "data")
