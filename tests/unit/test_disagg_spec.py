"""Disaggregated prefill/decode serving + speculative decoding (ISSUE 13).

Tier-1 pins:
- host-side drafting (inference/draft.py): n-gram prompt-lookup
  semantics — longest suffix first, most recent occurrence wins — and
  the callable escape hatch; jax-free by construction;
- handoff bookkeeping (inference/disagg.py): FIFO queue with
  requeue-at-front (pool pressure backpressures the handoff, never the
  prefill loop), eviction-voided records, the dispatch-ordering trace
  ("no decode dispatch waits behind a prefill dispatch" as pure
  ordering), and LinkModel-priced wire cost;
- scheduler run semantics: a verify dispatch's (accepted + 1)-token run
  advances position per token, a mid-run stop DISCARDS the remainder,
  and rejected drafts exist only in the draft ledger — never in
  total_tokens/goodput;
- engine end-to-end: greedy outputs with speculation ON are bitwise
  identical to the plain engine (gpt2 AND llama, continuous batching +
  prefix reuse), the verify program set is fixed at warmup
  (steady_state_recompiles == 0), disaggregated serving (shared pool
  and separate pools) preserves outputs and drains both pools exactly,
  TTFT decomposes as queue + prefill + handoff in the trail, and
  eviction mid-flight with speculation keeps pool accounting exact.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    cfg = GPT2Config(vocab_size=61, max_position_embeddings=64,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))


def tiny_llama():
    from deepspeed_tpu.models.llama import LlamaConfig, init_llama_params
    cfg = LlamaConfig(vocab_size=61, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2,
                      max_position_embeddings=64)
    return cfg, init_llama_params(cfg, jax.random.PRNGKey(4))


TINY_INF = {"max_batch_size": 3, "prompt_buckets": [4, 8, 16, 24],
            "batch_buckets": [1, 2], "max_seq_len": 48,
            "max_new_tokens": 8}

# continuous batching + prefix reuse + draftable repetition: two
# requests share a full-page prefix (prefix-cache reuse under spec),
# two are periodic (the n-gram drafter's best case), the rest are
# arbitrary mixed lengths (draft stalls ride along)
SHARED = list(range(1, 17))                  # one full 16-token page
WORKLOAD = [SHARED + [20, 21], SHARED + [30, 31, 32],
            [5, 6, 7] * 4, [9, 10] * 5,
            [40, 41, 42], [50, 51, 52, 53, 54]]


def serve_all(eng, prompts, max_new=8):
    """submit/step driver returning (outputs in submit order, finished
    records by uid) — generate() hides the FinishedRequests."""
    from deepspeed_tpu.inference import Request
    uids = [eng.submit(Request(prompt=list(p), max_new_tokens=max_new,
                               temperature=0.0, seed=0))
            for p in prompts]
    fins = {f.uid: f for f in eng.run()}
    outs = [fins[u].prompt + fins[u].tokens for u in uids]
    return outs, [fins[u] for u in uids]


def read_trail(events_dir):
    obs_report = _load_tool("obs_report")
    rows = []
    for seg in obs_report.segment_files(
            os.path.join(str(events_dir), "events.jsonl")):
        if os.path.exists(seg):
            rows += [json.loads(line) for line in open(seg)]
    return rows


# --------------------------------------------------------------------- #
# drafting (inference/draft.py — jax-free, pure host)
# --------------------------------------------------------------------- #
class TestNGramDrafter:
    def _d(self, k=4, lo=1, hi=3):
        from deepspeed_tpu.inference.draft import NGramDrafter
        return NGramDrafter(k=k, ngram_min=lo, ngram_max=hi)

    def test_proposes_pattern_continuation(self):
        d = self._d()
        # history ends in [5, 6, 7]; the most recent earlier trigram
        # occurrence is one period back — its continuation (the rest of
        # the history after it) predicts the cycle
        h = [5, 6, 7] * 4
        assert d.propose(h, 4) == [5, 6, 7]

    def test_longest_suffix_wins(self):
        d = self._d()
        # suffix [2, 3] matches at one site, suffix [3] at two; the
        # bigram site's continuation (9) must win over the unigram's
        h = [1, 2, 3, 9, 8, 3, 7, 2, 3]
        assert d.propose(h, 1) == [9]

    def test_most_recent_occurrence_wins(self):
        d = self._d(lo=1, hi=1)
        # token 3 occurs twice; the LATER occurrence's continuation (7)
        # is the prediction, not the earlier one's (9)
        h = [3, 9, 8, 3, 7, 2, 3]
        assert d.propose(h, 1) == [7]

    def test_no_match_is_a_stall_not_an_error(self):
        d = self._d()
        assert d.propose([1, 2, 3, 4, 5], 4) == []
        assert d.propose([], 4) == []
        assert d.propose([1], 4) == []

    def test_k_caps_the_run(self):
        d = self._d(k=8)
        h = [5, 6, 7] * 4
        assert len(d.propose(h, 2)) <= 2
        assert d.propose(h, 2) == [5, 6]

    def test_make_drafter(self):
        from deepspeed_tpu.inference.draft import (CallableDrafter,
                                                   NGramDrafter,
                                                   make_drafter)
        base = {"enabled": True, "k": 4, "method": "ngram",
                "ngram_min": 1, "ngram_max": 3}
        assert isinstance(make_drafter(base, None), NGramDrafter)
        assert make_drafter(dict(base, enabled=False), None) is None
        fn = lambda hist, k: list(hist[-k:])
        d = make_drafter(dict(base, method="callable"), fn)
        assert isinstance(d, CallableDrafter)
        assert d.propose([1, 2, 3], 2) == [2, 3]
        with pytest.raises(ValueError, match="draft_fn"):
            make_drafter(dict(base, method="callable"), None)


# --------------------------------------------------------------------- #
# handoff bookkeeping (inference/disagg.py — jax-free, pure host)
# --------------------------------------------------------------------- #
def _rec(uid, t=0.0):
    from deepspeed_tpu.inference.disagg import HandoffRecord
    return HandoffRecord(uid=uid, slot=uid, first_token=1, live_pages=2,
                         prompt_tokens=20, t_ready=t)


class TestHandoffQueue:
    def _q(self, now):
        from deepspeed_tpu.inference.disagg import HandoffQueue
        return HandoffQueue(clock=lambda: now[0])

    def test_fifo_drain_and_claim_wait(self):
        now = [10.0]
        q = self._q(now)
        q.push(_rec(1, t=9.0))
        q.push(_rec(2, t=9.5))
        recs = q.drain()
        assert [r.uid for r in recs] == [1, 2]
        assert len(q) == 0
        assert q.claimed(recs[0]) == pytest.approx(1000.0)  # 1 s wait
        assert q.claimed(recs[1]) == pytest.approx(500.0)
        assert q.total_handoffs == 2

    def test_requeue_keeps_arrival_order(self):
        now = [0.0]
        q = self._q(now)
        a, b = _rec(1), _rec(2)
        q.push(a)
        q.push(b)
        recs = q.drain()
        q.requeue(recs[0])          # claim for uid 1 bounced
        q.push(_rec(3))             # newer handoff arrives
        assert [r.uid for r in q.drain()] == [1, 3]
        assert recs[0].attempts == 1
        assert q.total_requeues == 1

    def test_dropped_voids_evicted_records(self):
        now = [0.0]
        q = self._q(now)
        q.push(_rec(1))
        rec = q.drain()[0]
        q.dropped(rec)
        st = q.debug_state()
        assert st["dropped"] == 1 and st["handoffs"] == 0
        assert st["peak_depth"] == 1 and st["depth"] == 0


class TestDispatchTrace:
    def test_decode_first_holds(self):
        from deepspeed_tpu.inference.disagg import DispatchTrace
        t = DispatchTrace()
        for step in range(3):           # claims -> decode -> prefill
            t.record(step, "handoff")
            t.record(step, "verify")
            t.record(step, "prefill")
        assert t.decode_first_fraction() == 1.0

    def test_interleaved_step_is_a_violation(self):
        from deepspeed_tpu.inference.disagg import DispatchTrace
        t = DispatchTrace()
        t.record(0, "decode")
        t.record(0, "prefill")          # ok
        t.record(1, "prefill")
        t.record(1, "decode")           # decode waited behind prefill
        assert t.decode_first_fraction() == 0.5

    def test_unmixed_trace_measures_nothing(self):
        from deepspeed_tpu.inference.disagg import DispatchTrace
        t = DispatchTrace()
        t.record(0, "decode")
        t.record(1, "decode")
        assert t.decode_first_fraction() is None

    def test_ring_bound(self):
        from deepspeed_tpu.inference.disagg import DispatchTrace
        t = DispatchTrace(cap=8)
        for i in range(100):
            t.record(i, "decode")
        assert len(t.rows()) == 8 and t.total == 100


class TestPriceHandoff:
    class _Link:
        def bytes_per_us(self, axis):
            return 100.0 if axis == "intra" else 10.0

        def latency_us(self, axis):
            return 1.0 if axis == "intra" else 10.0

    def test_priced_per_hop_and_axis(self):
        from deepspeed_tpu.inference.disagg import price_handoff
        link = self._Link()
        # 2 pages x 1000 B over inter: 10 us latency + 2000/10 us
        assert price_handoff(2, 1000, link, axis="inter") == \
            pytest.approx(0.210)
        assert price_handoff(2, 1000, link, axis="intra") == \
            pytest.approx(0.021)
        assert price_handoff(2, 1000, link, axis="inter", hops=2) == \
            pytest.approx(0.420)

    def test_nothing_moved_costs_nothing(self):
        from deepspeed_tpu.inference.disagg import price_handoff
        assert price_handoff(0, 1000, self._Link()) == 0.0
        assert price_handoff(2, 1000, self._Link(), hops=0) == 0.0


# --------------------------------------------------------------------- #
# scheduler run semantics (jax-free)
# --------------------------------------------------------------------- #
class TestRecordTokenRuns:
    def _serve_one(self, max_new=8, eos=None):
        from deepspeed_tpu.inference.scheduler import Request, Scheduler
        t = [0.0]
        s = Scheduler(1, (4, 8), (1, 2), 32, clock=lambda: t[0])
        s.submit(Request(prompt=[1, 2, 3], max_new_tokens=max_new,
                         eos_id=eos))
        batches = s.admit()
        sid = batches[0].slot_ids[0]
        s.record_tokens({sid: 10})      # prefill's first token
        return s, sid, t

    def test_run_advances_position_per_token(self):
        s, sid, _ = self._serve_one()
        slot = s.slots[sid]
        p0 = slot.position
        done = s.record_token_runs({sid: [11, 12, 13]})
        assert done == []
        slot = s.slots[sid]
        assert slot.position == p0 + 3
        assert slot.tokens[-4:] == [10, 11, 12, 13]
        assert slot.pending_tok == 13   # last kept token is pending
        assert s.total_tokens == 4

    def test_mid_run_stop_discards_remainder(self):
        s, sid, _ = self._serve_one(max_new=8, eos=12)
        done = s.record_token_runs({sid: [11, 12, 13, 14]})
        assert len(done) == 1
        # tokens past the stop are never emitted or counted
        assert done[0].tokens == [10, 11, 12]
        assert done[0].finish_reason == "eos"
        assert s.total_tokens == 3
        assert s.slots[sid] is None     # slot freed for the next admit

    def test_max_new_mid_run(self):
        s, sid, _ = self._serve_one(max_new=3)
        done = s.record_token_runs({sid: [11, 12, 13, 14]})
        assert len(done) == 1
        assert done[0].tokens == [10, 11, 12]
        assert done[0].finish_reason == "length"

    def test_draft_ledger_and_tokens_per_s(self):
        s, sid, t = self._serve_one()
        t[0] += 0.5
        s.record_token_runs({sid: [11, 12, 13]}, {sid: (4, 2)})
        t[0] += 0.5
        done = s.record_token_runs({sid: [14, 15, 16, 17]},
                                   {sid: (3, 3)})
        assert len(done) == 1
        fin = done[0]
        # rejected drafts live ONLY in the ledger, never in the run
        assert fin.draft_proposed == 7 and fin.draft_accepted == 5
        assert fin.tokens_per_s is not None and fin.tokens_per_s > 0
        assert fin.tokens_per_s == pytest.approx(
            len(fin.tokens) / (fin.latency_ms / 1e3))

    def test_draft_proposals_respect_caps(self):
        from deepspeed_tpu.inference.draft import NGramDrafter
        from deepspeed_tpu.inference.scheduler import Request, Scheduler
        s = Scheduler(1, (4, 8), (1, 2), 32,
                      drafter=NGramDrafter(k=4, ngram_min=1,
                                           ngram_max=3), spec_k=4)
        s.submit(Request(prompt=[5, 6, 7, 5, 6, 7], max_new_tokens=3))
        sid = s.admit()[0].slot_ids[0]
        s.record_tokens({sid: 5})
        props = s.draft_proposals()
        # max_new 3, one token kept -> at most (3 - 1 - 1) = 1 proposal
        # even though the drafter could continue the cycle for 4
        assert 0 < len(props[sid]) <= 1
        assert s.draft_proposals(cap=0) == {}


# --------------------------------------------------------------------- #
# config surface (runtime/config.py)
# --------------------------------------------------------------------- #
class TestConfigValidation:
    def _cfg(self, **inf):
        from deepspeed_tpu.runtime.config import get_inference_config
        return get_inference_config({"inference": inf})

    @pytest.fixture(autouse=True)
    def _err(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfigError
        self.Err = DeepSpeedConfigError

    def test_defaults_off(self):
        cfg = self._cfg()
        assert cfg["spec_decode"]["enabled"] is False
        assert cfg["disagg"]["enabled"] is False
        assert cfg["spec_decode"]["k"] == 4
        assert cfg["disagg"]["separate_pools"] is None

    def test_spec_requires_paged(self):
        with pytest.raises(self.Err, match="paged_kv"):
            self._cfg(paged_kv={"enabled": False},
                      spec_decode={"enabled": True})

    def test_spec_k_bounds(self):
        with pytest.raises(self.Err, match="spec_decode.k"):
            self._cfg(spec_decode={"enabled": True, "k": 0})

    def test_spec_method_vocabulary(self):
        with pytest.raises(self.Err, match="method"):
            self._cfg(spec_decode={"enabled": True, "method": "oracle"})

    def test_ngram_ordering(self):
        with pytest.raises(self.Err, match="ngram"):
            self._cfg(spec_decode={"enabled": True, "ngram_min": 3,
                                   "ngram_max": 2})

    def test_verify_widths_floor(self):
        with pytest.raises(self.Err, match="verify_widths"):
            self._cfg(spec_decode={"enabled": True,
                                   "verify_widths": [1]})

    def test_disagg_prefill_pages(self):
        with pytest.raises(self.Err, match="prefill_pages"):
            self._cfg(disagg={"enabled": True, "prefill_pages": 1})

    def test_decode_mesh_needs_disagg(self):
        with pytest.raises(self.Err, match="disagg.enabled"):
            self._cfg(disagg={"enabled": False,
                              "decode_mesh": {"axes": {"model": 1}}})


# --------------------------------------------------------------------- #
# engine end-to-end (CPU backend; interpret-mode kernels)
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    """One baseline run + spec/disagg variants over the SAME workload —
    built once; every parity/telemetry test below reads this."""
    from deepspeed_tpu.inference import InferenceEngine
    cfg, params = tiny_gpt2()
    out = {}

    def build(name, extra, obs=False):
        ic = dict(TINY_INF, **extra)
        kw = {}
        if obs:
            tmp = tmp_path_factory.mktemp(name)
            ic["events_dir"] = str(tmp)
            # window row every 4 tokens so the short workload still
            # crosses the spec-window emission stride
            kw["observability_config"] = {
                "serve": {"enabled": True, "sample_rate": 0.25}}
            out[name + "_dir"] = tmp
        eng = InferenceEngine(cfg, params, ic, dtype=jnp.float32, **kw)
        warm = eng.warmup()
        outs, fins = serve_all(eng, WORKLOAD)
        out[name] = {"outs": outs, "fins": fins, "warm": warm,
                     "rc": eng.steady_state_recompiles,
                     "state": eng.debug_state(),
                     "total_tokens": eng.scheduler.total_tokens}
        eng.close()

    build("base", {})
    build("spec", {"spec_decode": {"enabled": True, "k": 4}}, obs=True)
    build("disagg", {"disagg": {"enabled": True}}, obs=True)
    build("sep", {"disagg": {"enabled": True, "separate_pools": True}})
    build("both", {"spec_decode": {"enabled": True, "k": 4},
                   "disagg": {"enabled": True, "separate_pools": True}})
    return out


class TestSpecEngine:
    def test_greedy_parity_gpt2(self, runs):
        assert runs["spec"]["outs"] == runs["base"]["outs"]

    def test_zero_recompiles_under_churn(self, runs):
        assert runs["base"]["rc"] == 0
        assert runs["spec"]["rc"] == 0

    def test_warmup_program_set_pinned(self, runs):
        # speculation adds exactly one verify program per verify width
        # (tables ride at full pps — never widths x page buckets)
        widths = runs["spec"]["state"]["spec_decode"]["verify_widths"]
        assert runs["spec"]["warm"] == runs["base"]["warm"] + len(widths)
        progs = runs["spec"]["state"]["programs"]
        assert "verify" in progs and progs["verify"]["dispatches"] > 0

    def test_speculation_actually_accepts(self, runs):
        spec = runs["spec"]["state"]["slo"]["spec"]
        assert spec["proposed"] > 0
        assert 0 < spec["accepted"] <= spec["proposed"]

    def test_goodput_counts_only_kept_tokens(self, runs):
        # rejected drafts must not inflate token accounting: the
        # scheduler's counter equals the tokens the requests got
        kept = sum(len(o) - len(p)
                   for o, p in zip(runs["spec"]["outs"], WORKLOAD))
        assert runs["spec"]["total_tokens"] == kept
        assert runs["spec"]["total_tokens"] == \
            runs["base"]["total_tokens"]

    def test_finished_requests_carry_the_ledger(self, runs):
        fins = runs["spec"]["fins"]
        assert all(f.tokens_per_s is not None and f.tokens_per_s > 0
                   for f in fins)
        assert all(f.draft_accepted <= f.draft_proposed for f in fins)
        assert sum(f.draft_accepted for f in fins) > 0
        # the baseline engine's requests carry an empty ledger
        assert all(f.draft_proposed == 0 for f in runs["base"]["fins"])

    def test_spec_trail_rows(self, runs):
        rows = read_trail(runs["spec_dir"])
        windows = [r for r in rows
                   if r.get("event") == "serve_spec_window"]
        assert windows, "no serve_spec_window rows in the trail"
        for r in windows:
            assert {"proposed", "accepted", "dispatches",
                    "accept_rate"} <= set(r)
        reasons = {r["reason"] for r in rows
                   if r.get("event") == "serve_defer"}
        from deepspeed_tpu.inference.tracing import DEFER_REASONS
        assert reasons <= set(DEFER_REASONS)

    def test_llama_greedy_parity(self):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_llama()
        prompts = [WORKLOAD[0], WORKLOAD[2], WORKLOAD[4]]

        def go(extra):
            eng = InferenceEngine(cfg, params, dict(TINY_INF, **extra),
                                  dtype=jnp.float32)
            eng.warmup()
            outs, _ = serve_all(eng, prompts)
            rc = eng.steady_state_recompiles
            eng.close()
            return outs, rc

        base, rc_b = go({})
        spec, rc_s = go({"spec_decode": {"enabled": True, "k": 3}})
        assert spec == base
        assert rc_b == 0 and rc_s == 0

    def test_eviction_mid_flight_keeps_pool_exact(self):
        from deepspeed_tpu.inference import InferenceEngine, Request
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(
            cfg, params,
            dict(TINY_INF, spec_decode={"enabled": True, "k": 4}),
            dtype=jnp.float32)
        eng.warmup()
        uids = [eng.submit(Request(prompt=list(p), max_new_tokens=8,
                                   temperature=0.0, seed=0))
                for p in WORKLOAD[:3]]
        eng.step()                  # prefill + first verify in flight
        fin = eng.cancel(uids[1])   # evict between steps, mid-decode
        assert fin is not None
        eng.run()
        alloc = eng.scheduler.allocator
        # exact accounting: every page came back, no double free, the
        # eviction freed the victim's pages despite pending speculation
        assert alloc.pages_in_use == 0
        assert alloc.free_pages == alloc.num_pages - 1
        assert eng.steady_state_recompiles == 0
        eng.close()


class TestDisaggEngine:
    def test_shared_pool_parity(self, runs):
        assert runs["disagg"]["outs"] == runs["base"]["outs"]
        assert runs["disagg"]["rc"] == 0

    def test_separate_pools_parity(self, runs):
        assert runs["sep"]["outs"] == runs["base"]["outs"]
        assert runs["sep"]["rc"] == 0

    def test_spec_plus_disagg_parity(self, runs):
        assert runs["both"]["outs"] == runs["base"]["outs"]
        assert runs["both"]["rc"] == 0

    def test_every_handoff_claimed(self, runs):
        for name in ("disagg", "sep", "both"):
            dg = runs[name]["state"]["disagg"]
            assert dg["queue"]["depth"] == 0
            assert dg["queue"]["handoffs"] == len(WORKLOAD)
            assert dg["queue"]["dropped"] == 0

    def test_pools_drain_exactly(self, runs):
        # decode pool empty after the run...
        pool = runs["sep"]["state"]["page_pool"]
        assert pool["pages_in_use"] == 0
        # ...and the prefill pool too (handoff claims re-homed every
        # slot; admission-side pages all came back)
        ppool = runs["sep"]["state"]["disagg"]["prefill_pool"]
        assert ppool["pages_in_use"] == 0

    def test_separate_pools_move_only_live_pages(self, runs):
        h = runs["sep"]["state"]["disagg"]["handoff"]
        from deepspeed_tpu.inference import pages_for
        live = sum(pages_for(len(p), 16) for p in WORKLOAD)
        assert h["pages_moved"] == live
        assert h["bytes_moved"] > 0

    def test_decode_never_waits_behind_prefill(self, runs):
        # the structural pin: in every traced step that ran both
        # phases, all decode-phase dispatches preceded all prefills
        for name in ("disagg", "sep", "both"):
            frac = runs[name]["state"]["disagg"]["decode_first_fraction"]
            assert frac is None or frac == 1.0
        assert any(
            runs[n]["state"]["disagg"]["decode_first_fraction"] == 1.0
            for n in ("disagg", "sep", "both")), \
            "no traced step ever mixed decode and prefill phases"

    def test_ttft_decomposes_with_handoff(self, runs):
        rows = read_trail(runs["disagg_dir"])
        handoffs = [r for r in rows if r.get("event") == "serve_handoff"]
        assert len(handoffs) == len(WORKLOAD)
        for r in handoffs:
            assert {"uid", "mode", "queue_ms", "transfer_ms",
                    "handoff_ms", "pages"} <= set(r)
            assert r["mode"] == "shared_pool"
            assert r["handoff_ms"] >= 0.0
        finishes = [r for r in rows if r.get("event") == "serve_finish"]
        assert finishes
        for r in finishes:
            # the PR 9 identity grows a handoff term: TTFT = queue wait
            # + prefill + handoff, per request, in the trail itself
            assert r["ttft_ms"] == pytest.approx(
                r["queue_wait_ms"] + r["prefill_ms"] + r["handoff_ms"],
                abs=0.05)
        # handoff must precede the first token's release in file order
        first_h = min(i for i, r in enumerate(rows)
                      if r.get("event") == "serve_handoff")
        first_t = min(i for i, r in enumerate(rows)
                      if r.get("event") == "serve_first_token")
        assert first_h < first_t

    def test_obs_report_serve_sections(self, runs):
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(runs["spec_dir"]))
        spec = s["serving"]["speculation"]
        assert spec["dispatches"] > 0 and spec["accepted"] > 0
        assert spec["accepted_per_dispatch"] > 0
        rendered = obs_report.render_serve(s)
        assert "speculation" in rendered
        s2 = obs_report.summarize(str(runs["disagg_dir"]))
        dg = s2["serving"]["disagg"]
        assert dg["handoffs"] == len(WORKLOAD)
        assert "disagg_handoff" in obs_report.render_serve(s2)
