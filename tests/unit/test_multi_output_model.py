"""Multi-output model tests (mirror reference
tests/unit/test_multi_output_model.py + multi_output_model.py: a model with
several outputs/losses trained through the engine).

In the functional contract the client's loss_fn combines the outputs —
here: weighted sum of two cross-entropies plus an aux dict, exercising the
(loss, aux) tuple return the engine must accept."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as ds


def _init(key, hidden=8, classes=4):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "trunk": jax.random.normal(k1, (hidden, hidden)) * 0.3,
        "head1": jax.random.normal(k2, (hidden, classes)) * 0.3,
        "head2": jax.random.normal(k3, (hidden, classes)) * 0.3,
    }


def _multi_output_loss(weights):
    w1, w2 = weights

    def loss_fn(params, batch, rng):
        h = jnp.tanh(batch["x"] @ params["trunk"])
        losses = []
        for head, tgt in (("head1", "y1"), ("head2", "y2")):
            logp = jax.nn.log_softmax(h @ params[head])
            nll = -jnp.mean(jnp.take_along_axis(
                logp, batch[tgt][:, None], axis=1))
            losses.append(nll)
        total = w1 * losses[0] + w2 * losses[1]
        return total, {"loss1": losses[0], "loss2": losses[1]}
    return loss_fn


def _batches(n, bs=8, hidden=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append({"x": rng.randn(bs, hidden).astype(np.float32),
                    "y1": rng.randint(0, classes, bs).astype(np.int32),
                    "y2": rng.randint(0, classes, bs).astype(np.int32)})
    return out


def test_two_output_model_trains():
    """(reference test_multi_output_model.py two-output case)"""
    params = _init(jax.random.PRNGKey(0))
    engine, *_ = ds.initialize(
        model=_multi_output_loss((1.0, 0.5)),
        model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}})
    batches = _batches(1)
    losses = [float(engine.train_batch(iter([batches[0]])))
              for _ in range(12)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_weighted_sum_matches_manual():
    """Engine loss == w1*l1 + w2*l2 computed by hand on the same params."""
    params = _init(jax.random.PRNGKey(0))
    loss_fn = _multi_output_loss((0.3, 0.7))
    batch = _batches(1)[0]
    total, aux = loss_fn(params, batch, None)
    np.testing.assert_allclose(
        float(total),
        0.3 * float(aux["loss1"]) + 0.7 * float(aux["loss2"]), rtol=1e-6)

    engine, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 0.0}}})
    got = float(engine.eval_batch(batch)[0]
                if isinstance(engine.eval_batch(batch), tuple)
                else engine.eval_batch(batch))
    np.testing.assert_allclose(got, float(total), rtol=1e-5)
