"""Unified profiling & telemetry layer (deepspeed_tpu/profiling/).

Covers the ISSUE-3 acceptance bar: on CPU, a 3-step ``train_batch`` run
with ``observability.enabled`` produces a cost-analysis FLOPs/MFU
record, exactly the expected compile count (an injected shape change
bumps it by one), memory watermark scalars, and an ``obs_report``
summary with step-time percentiles, MFU, comm bytes, and recompile
count. Plus standalone-probe unit tests (flops registry, compile
tracker, memory snapshot, trace spans) and the run-report CLI smoke.
"""

import importlib.util
import json

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _events(path):
    rows = [json.loads(l) for l in open(path)]
    tags = {}
    for r in rows:
        if "tag" in r:
            tags.setdefault(r["tag"], []).append((r["step"], r["value"]))
    return rows, tags


# ------------------------------------------------------------ acceptance


def test_three_step_run_produces_full_observability_record(tmp_path):
    """The acceptance scenario, asserted end to end on the 8-device CPU
    mesh — tensorboard stays OFF so this also pins the event-log-only
    path (monitor mirror with no tensorboard writer)."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 4,
            # per-step flush: this test reads the per-step records
            # mid-run; the async pipeline otherwise defers device-
            # valued scalars to steps_per_print boundaries
            "steps_per_print": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "observability": {
                "enabled": True, "events_dir": str(tmp_path),
                "chrome_trace_path": str(tmp_path / "trace.json")},
        })
    assert engine.observability.enabled
    for b in random_batches(3, 4, 8):
        engine.train_batch(iter([b]))

    rows, tags = _events(tmp_path / "events.jsonl")

    # (1) cost-analysis FLOPs/MFU record
    assert tags["Observability/flops_per_step"][0][1] > 0
    assert tags["Observability/bytes_accessed"][0][1] > 0
    mfus = [v for _, v in tags["Observability/mfu"]]
    assert len(mfus) == 3 and all(v > 0 for v in mfus)
    profs = [r for r in rows if r.get("event") == "flops_profile"]
    assert len(profs) == 1 and profs[0]["fn"] == "micro_step"
    assert profs[0]["num_devices"] == 8

    # (2) exactly the expected compile count: ONE micro_step compile
    # across all three same-shape steps
    assert tags["Observability/recompiles"][-1][1] == 1.0
    compiles = [r for r in rows if r.get("event") == "compile"]
    assert len(compiles) == 1 and compiles[0]["fn"] == "micro_step"
    assert compiles[0]["wall_ms"] > 0

    # ... and an injected shape change bumps it by exactly one
    bigger = random_batches(1, 8, 8)[0]
    engine.train_batch(iter([bigger]))
    rows, tags = _events(tmp_path / "events.jsonl")
    assert tags["Observability/recompiles"][-1][1] == 2.0

    # (3) memory watermark scalars, one per step, monotone peak
    peaks = [v for _, v in tags["Memory/peak_bytes_in_use"]]
    assert len(peaks) == 4 and all(v > 0 for v in peaks)
    assert peaks == sorted(peaks)
    assert len(tags["Memory/bytes_in_use"]) == 4
    assert len(tags["Memory/step_delta_bytes"]) == 4

    # per-step training scalars ride along without tensorboard
    assert len(tags["Train/Samples/step_time_ms"]) == 4
    assert all(v > 0 for _, v in tags["Train/Samples/samples_per_sec"])
    assert all(v > 0 for _, v in tags["Train/Samples/comm_bytes_per_step"])

    # chrome trace: spans on disk mid-run, no close() needed
    trace = json.load(open(tmp_path / "trace.json"))
    names = {e["name"] for e in trace["traceEvents"]}
    assert "train_batch" in names

    # (4) obs_report renders the summary from the same log
    obs_report = _load_obs_report()
    s = obs_report.summarize(str(tmp_path))
    assert s["steps"] == 4
    assert s["step_time_ms"]["p50"] > 0
    assert s["step_time_ms"]["p95"] >= s["step_time_ms"]["p50"]
    assert s["samples_per_sec"]["last"] > 0
    assert s["mfu"]["best"] > 0
    assert s["flops_per_step"] > 0
    assert s["comm"]["bytes_per_step"] > 0
    assert s["recompiles"]["count"] == 2
    assert s["recompiles"]["per_fn"]["micro_step"]["count"] == 2
    assert s["memory"]["peak_bytes_in_use"] > 0
    text = obs_report.render(s)
    for needle in ("step_time_ms", "mfu", "recompiles", "memory",
                   "samples_per_sec"):
        assert needle in text

    engine.observability.close()
    # close() is idempotent and seals a compile summary event
    engine.observability.close()
    rows, _ = _events(tmp_path / "events.jsonl")
    summaries = [r for r in rows if r.get("event") == "compile_summary"]
    assert len(summaries) == 1 and summaries[0]["total_compiles"] == 2


def test_observability_disabled_is_transparent(tmp_path):
    """Default-off: raw jit functions (the HLO audits call .lower() on
    them), no event files, no monitor coupling."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert not engine.observability.enabled
    step = engine._get_compiled_micro_step()
    from deepspeed_tpu.profiling import TrackedFunction
    assert not isinstance(step, TrackedFunction)
    assert hasattr(step, "lower")
    for b in random_batches(2, 4, 8):
        engine.train_batch(iter([b]))
    assert not os.path.exists(tmp_path / "events.jsonl")


def test_legacy_profiler_section_aliases_into_observability():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "profiler": {"enabled": True, "output_path": "/tmp/x",
                     "start_step": 5},
    }, world_size=1)
    tr = cfg.observability_config["trace"]
    assert tr["enabled"] and tr["output_path"] == "/tmp/x"
    assert tr["start_step"] == 5 and tr["num_steps"] == 3
    # legacy attribute still points at the same dict
    assert cfg.profiler_config is tr
    # explicit observability.trace keys win over the legacy block
    cfg2 = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 1,
        "profiler": {"enabled": True, "start_step": 5},
        "observability": {"trace": {"start_step": 9}},
    }, world_size=1)
    assert cfg2.observability_config["trace"]["start_step"] == 9
    assert cfg2.observability_config["trace"]["enabled"] is True


def test_observability_config_validation():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "observability": {"recompile_warn_after": -1}},
                        world_size=1)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "observability": {"enabled": True,
                                           "events_dir": 7}},
                        world_size=1)


# ------------------------------------------------------------ probes


def test_flops_profiler_counts_matmul_flops():
    """cost_analysis of a pure matmul ≈ 2*m*k*n FLOPs — pins that the
    normalization reads the right keys."""
    from deepspeed_tpu.profiling.flops import profile_jit_fn
    m = k = n = 128
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((m, k), jnp.float32)
    b = jnp.ones((k, n), jnp.float32)
    prof = profile_jit_fn(f, (a, b), name="matmul")
    assert prof.flops == pytest.approx(2 * m * k * n, rel=0.01)
    assert prof.bytes_accessed >= 3 * m * n * 4
    assert prof.compile_ms > 0
    assert prof.arithmetic_intensity > 0


def test_peak_flops_registry():
    from deepspeed_tpu.profiling.flops import (CPU_FALLBACK_PEAK_FLOPS,
                                               peak_flops_per_device)

    class FakeDev:
        def __init__(self, kind):
            self.device_kind = kind

    assert peak_flops_per_device(FakeDev("TPU v4"))[0] == 275e12
    assert peak_flops_per_device(FakeDev("TPU v5 lite"))[0] == 197e12
    assert peak_flops_per_device(FakeDev("TPU v5p"))[0] == 459e12
    peak, label = peak_flops_per_device(FakeDev("cpu"))
    assert peak == CPU_FALLBACK_PEAK_FLOPS
    assert "nominal-peak" in label  # unknown devices can't fake real MFU


def test_compute_mfu():
    from deepspeed_tpu.profiling.flops import compute_mfu
    assert compute_mfu(1e12, 1.0, 2e12) == pytest.approx(0.5)
    assert compute_mfu(1e12, 0.0, 2e12) == 0.0
    assert compute_mfu(1e12, 1.0, 0.0) == 0.0


def test_compile_tracker_counts_and_warns(monkeypatch):
    import deepspeed_tpu.profiling.recompile as rc
    warnings = []
    monkeypatch.setattr(rc.logger, "warning",
                        lambda msg, *a, **k: warnings.append(str(msg)))
    step = [0]
    tracker = rc.CompileTracker(step_provider=lambda: step[0], warn_after=1)
    f = tracker.wrap(jax.jit(lambda x: x * 2), "f")
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))                     # cache hit: no new compile
    assert tracker.counts == {"f": 1}
    step[0] = 5
    f(jnp.ones((8,)))                     # steady-state recompile
    assert tracker.counts == {"f": 2}
    assert tracker.total_compiles == 2
    assert any("steady-state recompile" in w for w in warnings)
    assert tracker.total_compile_ms > 0
    assert [e.count for e in tracker.events] == [1, 2]
    assert tracker.events[1].step == 5
    s = tracker.summary()
    assert s["total_compiles"] == 2 and s["per_fn"]["f"]["count"] == 2


def test_compile_tracker_signature_fallback():
    """Without _cache_size (non-jit callables, exotic jax builds) the
    shape/dtype-signature detector still counts compiles exactly."""
    from deepspeed_tpu.profiling.recompile import CompileTracker
    tracker = CompileTracker()
    calls = []
    f = tracker.wrap(lambda x: calls.append(x.shape) or x, "g")
    f._has_cache_size = False
    x4, x8 = np.ones((4,)), np.ones((8,))
    f(x4); f(x4); f(x8); f(x4)
    assert tracker.counts == {"g": 2}


def test_tracked_function_passes_lower_through():
    from deepspeed_tpu.profiling.recompile import CompileTracker
    f = CompileTracker().wrap(jax.jit(lambda x: x + 1), "h")
    txt = f.lower(jnp.ones((4,))).compile().as_text()
    assert "HloModule" in txt or "ENTRY" in txt


def test_memory_snapshot_cpu_host_fallback():
    from deepspeed_tpu.profiling.memory import MemoryWatermark, memory_snapshot
    snap = memory_snapshot()
    assert snap is not None and snap["source"] in ("device", "host")
    assert snap["bytes_in_use"] > 0 and snap["peak_bytes_in_use"] > 0
    wm = MemoryWatermark()
    s1 = wm.sample("forward")
    s2 = wm.sample("step")
    assert s1["delta_bytes"] == 0 and isinstance(s2["delta_bytes"], int)
    assert wm.peak_bytes >= max(s1["bytes_in_use"], s2["bytes_in_use"])
    assert wm.samples_by_phase["forward"] is s1


def test_trace_span_records_chrome_events(tmp_path):
    import time
    from deepspeed_tpu.profiling.spans import (ChromeTraceRecorder,
                                               trace_span)
    rec = ChromeTraceRecorder()
    with trace_span("forward", recorder=rec):
        time.sleep(0.002)
    with trace_span("backward", recorder=rec, micro=3):
        pass
    assert [e["name"] for e in rec.events] == ["forward", "backward"]
    assert rec.events[0]["ph"] == "X"
    assert rec.events[0]["dur"] >= 1000          # µs
    assert rec.events[1]["args"] == {"micro": 3}
    out = rec.dump(str(tmp_path / "t" / "trace.json"))
    data = json.load(open(out))
    assert len(data["traceEvents"]) == 2


def test_trace_span_default_recorder_roundtrip():
    from deepspeed_tpu.profiling.spans import (ChromeTraceRecorder,
                                               get_default_recorder,
                                               set_default_recorder,
                                               trace_span)
    rec = ChromeTraceRecorder()
    set_default_recorder(rec)
    try:
        with trace_span("x"):
            pass
        assert get_default_recorder() is rec
        assert rec.events and rec.events[0]["name"] == "x"
    finally:
        set_default_recorder(None)
    with trace_span("y"):                        # no recorder: still fine
        pass
    assert len(rec.events) == 1


# ------------------------------------------------------- run-report CLI


def _synthetic_log(tmp_path):
    """events.jsonl with every record family the report consumes."""
    rows = []
    for i, ms in enumerate([120.0, 100.0, 105.0, 98.0, 300.0]):
        step = (i + 1) * 32
        rows += [
            {"tag": "Train/Samples/step_time_ms", "value": ms, "step": step},
            {"tag": "Train/Samples/samples_per_sec",
             "value": 32 / (ms / 1e3), "step": step},
            {"tag": "Train/Samples/train_loss", "value": 5.0 - i,
             "step": step},
            {"tag": "Observability/mfu", "value": 0.30 + 0.01 * i,
             "step": step},
            {"tag": "Observability/recompiles", "value": 1.0, "step": step},
            {"tag": "Memory/peak_bytes_in_use", "value": 1e9 + i,
             "step": step},
            {"tag": "Memory/bytes_in_use", "value": 9e8, "step": step},
            {"tag": "Train/Samples/comm_bytes_per_step", "value": 123456.0,
             "step": step},
            {"tag": "Train/Samples/comm_compression_ratio", "value": 3.4,
             "step": step},
        ]
    rows.append({"tag": "Observability/flops_per_step", "value": 2.5e12,
                 "step": 32})
    rows.append({"tag": "Train/Samples/checkpoint_save_ms", "value": 42.0,
                 "step": 160})
    rows.append({"tag": "Train/Samples/checkpoint_save_ok", "value": 1.0,
                 "step": 160})
    rows.append({"event": "compile", "fn": "micro_step", "count": 1,
                 "wall_ms": 1234.5, "step": 0})
    path = tmp_path / "events.jsonl"
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        f.write("{torn line, never parsed\n")   # crash-torn tail tolerated
    return path


def test_obs_report_summarize_fields(tmp_path):
    _synthetic_log(tmp_path)
    obs_report = _load_obs_report()
    s = obs_report.summarize(str(tmp_path))     # dir resolution
    assert s["steps"] == 5
    assert s["step_time_ms"]["p50"] == pytest.approx(105.0)
    assert s["step_time_ms"]["p95"] == pytest.approx(264.0)
    assert s["samples_per_sec"]["best"] == pytest.approx(32 / 0.098, rel=1e-3)
    assert s["mfu"]["last"] == pytest.approx(0.34)
    assert s["flops_per_step"] == pytest.approx(2.5e12)
    assert s["comm"]["bytes_per_step"] == pytest.approx(123456.0)
    assert s["comm"]["compression_ratio"] == pytest.approx(3.4)
    assert s["recompiles"]["count"] == 1
    assert s["recompiles"]["per_fn"]["micro_step"]["wall_ms"] == \
        pytest.approx(1234.5)
    assert s["memory"]["peak_bytes_in_use"] == pytest.approx(1e9 + 4)
    assert s["checkpoints"]["saves"] == 1
    assert s["checkpoints"]["save_ms_mean"] == pytest.approx(42.0)
    assert s["loss"]["first"] == 5.0 and s["loss"]["last"] == 1.0


def test_obs_report_cli_smoke(tmp_path):
    """Tier-1 CI smoke: the CLI subprocess renders the summary (and the
    --json mode round-trips) against a synthetic log — stdlib only, no
    jax init in the child."""
    _synthetic_log(tmp_path)
    script = os.path.join(REPO, "tools", "obs_report.py")
    r = subprocess.run([sys.executable, script, str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    for needle in ("run report:", "step_time_ms", "p50=105.00",
                   "p95=264.00", "mfu", "recompiles        : 1",
                   "samples_per_sec"):
        assert needle in r.stdout, (needle, r.stdout)
    rj = subprocess.run([sys.executable, script, str(tmp_path), "--json"],
                        capture_output=True, text=True, timeout=60)
    assert rj.returncode == 0
    s = json.loads(rj.stdout)
    assert s["steps"] == 5 and s["recompiles"]["count"] == 1
    # missing log: explicit error, exit 2
    rerr = subprocess.run([sys.executable, script, str(tmp_path / "nope")],
                          capture_output=True, text=True, timeout=60)
    assert rerr.returncode == 2 and "error" in rerr.stderr


@pytest.mark.slow
def test_bench_mfu_cost_model_row():
    """The hardware-free bench row lands a real JSON row from a fresh
    child (same invocation the ladder parent uses)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--metric", "mfu_cost_model"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    rows = [json.loads(l) for l in r.stdout.splitlines()
            if l.strip().startswith("{")]
    assert rows, (r.stdout[-2000:], r.stderr[-2000:])
    row = rows[-1]
    assert row["metric"] == "mfu_cost_model"
    assert row["unit"] == "flops_per_token_cost_model"
    assert row["value"] > 0
    # cost model vs analytic 6N+12LSH: same order of magnitude (the
    # compiled program includes the optimizer + loss, analytic doesn't)
    assert 0.2 < row["vs_baseline"] < 5.0
    assert row["detail"]["flops_per_step_per_device"] > 0
