"""Transformer layer + flash attention numerics (mirrors reference
tests/unit/test_cuda_forward.py / test_cuda_backward.py: fused layer vs
reference implementation across a shape/precision/pre-LN grid)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention.flash import (
    attention_reference, flash_attention)
from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer,
    init_transformer_params, transformer_layer_forward)


class TestFlashAttention:

    @pytest.mark.parametrize("S", [64, 128, 256])
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_reference(self, S, causal):
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(2, 4, S, 64), jnp.float32)
                   for _ in range(3))
        o_ref = attention_reference(q, k, v, causal=causal)
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=2e-5, rtol=2e-5)

    def test_fwd_with_padding_mask(self):
        rng = np.random.RandomState(1)
        q, k, v = (jnp.asarray(rng.randn(2, 2, 128, 32), jnp.float32)
                   for _ in range(3))
        mask = jnp.asarray(
            np.where(rng.rand(2, 1, 1, 128) > 0.3, 0.0, -1e9), jnp.float32)
        o_ref = attention_reference(q, k, v, mask=mask)
        o = flash_attention(q, k, v, mask=mask, interpret=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        rng = np.random.RandomState(2)
        q, k, v = (jnp.asarray(rng.randn(1, 2, 128, 32), jnp.float32)
                   for _ in range(3))

        def f_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        def f_fl(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=True) ** 2)

        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(f_fl, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=1e-3)

    def test_masked_grads_match_reference(self):
        rng = np.random.RandomState(3)
        q, k, v = (jnp.asarray(rng.randn(2, 2, 64, 32), jnp.float32)
                   for _ in range(3))
        mask = jnp.asarray(
            np.where(rng.rand(2, 1, 1, 64) > 0.3, 0.0, -1e9), jnp.float32)

        def f_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, mask=mask) ** 2)

        def f_fl(q, k, v):
            return jnp.sum(flash_attention(q, k, v, mask=mask,
                                           interpret=True) ** 2)

        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(f_fl, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=1e-3)

    def test_irregular_seq_falls_back(self):
        rng = np.random.RandomState(4)
        q, k, v = (jnp.asarray(rng.randn(1, 1, 50, 16), jnp.float32)
                   for _ in range(3))
        o = flash_attention(q, k, v)  # 50 % 16 != 0 -> reference path
        o_ref = attention_reference(q, k, v)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=1e-6)


class TestFlashDropout:
    """In-kernel attention dropout (reference: fused softmax-dropout CUDA
    kernels, csrc/transformer/dropout_kernels.cu). The counter-based hash
    mask must (a) hit the configured rate, (b) regenerate identically in
    the forward and both backward kernels, (c) be seed-deterministic."""

    def _qkv(self, B=2, H=3, S=128, D=32, seed=0):
        rng = np.random.RandomState(seed)
        return tuple(jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
                     for _ in range(3))

    @pytest.mark.slow
    def test_mask_rate_and_scaling(self):
        from deepspeed_tpu.ops.attention.flash import dropout_mask_reference
        for rate in (0.1, 0.3, 0.5):
            keep = dropout_mask_reference(7, 4, 4, 256, 256, rate)
            frac = float(np.asarray(keep).mean())
            # 4*4*256*256 = 1M samples: binomial std ~ 5e-4
            assert abs(frac - (1.0 - rate)) < 5e-3, (rate, frac)
        # inverted-dropout scaling preserves the mean
        q, k, v = self._qkv()
        rng = jax.random.PRNGKey(3)
        outs = [flash_attention(q, k, v, dropout_rate=0.3,
                                dropout_rng=jax.random.fold_in(rng, i),
                                interpret=True) for i in range(16)]
        mean = jnp.mean(jnp.stack(outs), axis=0)
        o_nodrop = flash_attention(q, k, v, interpret=True)
        # E[dropout(P)] = P, so the seed-averaged output approaches the
        # dropout-free output
        err = float(jnp.abs(mean - o_nodrop).max())
        scale = float(jnp.abs(o_nodrop).max())
        assert err < 0.35 * scale, (err, scale)

    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_oracle_same_mask(self, causal):
        from deepspeed_tpu.ops.attention.flash import dropout_seed_from_rng
        q, k, v = self._qkv()
        rng = jax.random.PRNGKey(11)
        seed = dropout_seed_from_rng(rng).reshape(())
        o = flash_attention(q, k, v, causal=causal, dropout_rate=0.2,
                            dropout_rng=rng, interpret=True)
        o_ref = attention_reference(q, k, v, causal=causal,
                                    dropout_rate=0.2, dropout_seed=seed)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=3e-5, rtol=3e-5)

    @pytest.mark.parametrize("masked", [False, True])
    @pytest.mark.slow
    def test_grads_match_oracle_same_mask(self, masked):
        """fwd/bwd mask consistency: dq/dk/dv against the dense oracle
        that applies the identical hash mask — if the backward kernels
        regenerated different bits this fails loudly."""
        from deepspeed_tpu.ops.attention.flash import dropout_seed_from_rng
        q, k, v = self._qkv(S=64)
        mask = None
        if masked:
            mrng = np.random.RandomState(5)
            mask = jnp.asarray(
                np.where(mrng.rand(2, 1, 1, 64) > 0.3, 0.0, -1e9),
                jnp.float32)
        rng = jax.random.PRNGKey(13)
        seed = dropout_seed_from_rng(rng).reshape(())

        def f_fl(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, mask=mask, causal=not masked, dropout_rate=0.25,
                dropout_rng=rng, interpret=True) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(attention_reference(
                q, k, v, mask=mask, causal=not masked, dropout_rate=0.25,
                dropout_seed=seed) ** 2)

        gf = jax.grad(f_fl, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=1e-3)

    def test_seed_determinism(self):
        q, k, v = self._qkv()
        r1, r2 = jax.random.PRNGKey(0), jax.random.PRNGKey(1)
        o1a = flash_attention(q, k, v, dropout_rate=0.2, dropout_rng=r1,
                              interpret=True)
        o1b = flash_attention(q, k, v, dropout_rate=0.2, dropout_rng=r1,
                              interpret=True)
        o2 = flash_attention(q, k, v, dropout_rate=0.2, dropout_rng=r2,
                             interpret=True)
        np.testing.assert_array_equal(np.asarray(o1a), np.asarray(o1b))
        assert float(jnp.abs(o1a - o2).max()) > 1e-3

    @pytest.mark.slow
    def test_gpt2_trains_through_flash_dropout(self):
        """attn_dropout=0.1 training path must run the flash kernel (no
        dense (S,S) fallback) and produce a finite decreasing loss."""
        from deepspeed_tpu.models.gpt2 import (
            GPT2Config, gpt2_loss_fn, init_gpt2_params)
        cfg = GPT2Config(vocab_size=128, max_position_embeddings=64,
                         hidden_size=64, num_layers=2, num_heads=4,
                         embd_dropout=0.1, attn_dropout=0.1,
                         resid_dropout=0.1)
        params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
        loss_fn = gpt2_loss_fn(cfg, deterministic=False)
        # (B, 33) ids -> 32-token inputs after the label shift: a multiple
        # of 16, so this exercises the flash kernel, not the dense fallback
        ids = jnp.asarray(np.random.RandomState(0).randint(
            0, 128, size=(2, 33)), jnp.int32)
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, {"input_ids": ids}, jax.random.PRNGKey(1))
        )(params)
        assert np.isfinite(float(loss))
        gnorm = jax.tree_util.tree_reduce(
            lambda a, g: a + float(jnp.sum(jnp.abs(g))), grads, 0.0)
        assert np.isfinite(gnorm) and gnorm > 0.0


def torch_free_reference_layer(params, config, x, mask=None):
    """Unfused jnp encoder layer — the analog of the reference's
    tests/unit/modeling.py BERT layer used as ground truth."""
    return transformer_layer_forward(params, config, x, attention_mask=mask,
                                     rng=None, deterministic=True,
                                     use_flash=False)


class TestTransformerLayer:

    def _mk(self, batch=2, seq=64, hidden=64, heads=4, pre_ln=True,
            fp32=True):
        cfg = DeepSpeedTransformerConfig(
            batch_size=batch, max_seq_length=seq, hidden_size=hidden,
            intermediate_size=4 * hidden, heads=heads,
            attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
            num_hidden_layers=2, initializer_range=0.02,
            pre_layer_norm=pre_ln, bf16=not fp32, training=False)
        params = init_transformer_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(batch, seq, hidden), jnp.float32)
        return cfg, params, x

    @pytest.mark.parametrize("pre_ln", [True, False])
    @pytest.mark.parametrize("seq", [64, 128])
    def test_flash_vs_unfused(self, pre_ln, seq):
        cfg, params, x = self._mk(seq=seq, pre_ln=pre_ln)
        out_ref = torch_free_reference_layer(params, cfg, x)
        out = transformer_layer_forward(params, cfg, x, deterministic=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                                   atol=2e-5, rtol=2e-5)

    def test_with_padding_mask(self):
        cfg, params, x = self._mk(seq=64)
        rng = np.random.RandomState(1)
        mask = jnp.asarray(
            np.where(rng.rand(2, 1, 1, 64) > 0.3, 0.0, -1e9), jnp.float32)
        out_ref = torch_free_reference_layer(params, cfg, x, mask=mask)
        out = transformer_layer_forward(params, cfg, x, attention_mask=mask,
                                        deterministic=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.slow
    def test_backward_matches(self):
        cfg, params, x = self._mk(seq=64)

        def loss_flash(p):
            return jnp.sum(transformer_layer_forward(
                p, cfg, x, deterministic=True) ** 2)

        def loss_ref(p):
            return jnp.sum(torch_free_reference_layer(p, cfg, x) ** 2)

        gf = jax.grad(loss_flash)(params)
        gr = jax.grad(loss_ref)(params)
        for kname in params:
            np.testing.assert_allclose(
                np.asarray(gf[kname]), np.asarray(gr[kname]),
                atol=5e-3, rtol=5e-3, err_msg=kname)

    def test_dropout_changes_output_and_is_seeded(self):
        cfg, params, x = self._mk()
        cfg.training = True
        cfg.hidden_dropout_ratio = 0.5
        r = jax.random.PRNGKey(7)
        o1 = transformer_layer_forward(params, cfg, x, rng=r,
                                       deterministic=False)
        o2 = transformer_layer_forward(params, cfg, x, rng=r,
                                       deterministic=False)
        o3 = transformer_layer_forward(params, cfg, x,
                                       rng=jax.random.PRNGKey(8),
                                       deterministic=False)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2))
        assert not np.allclose(np.asarray(o1), np.asarray(o3))

    def test_layer_object_facade(self):
        cfg, params, x = self._mk()
        layer = DeepSpeedTransformerLayer(cfg, initial_params=params)
        out = layer(x, deterministic=True)
        assert out.shape == x.shape


def test_flash_block_policy_scales_with_seq():
    """Below the stream threshold K/V are VMEM-resident (512-wide blocks
    overflowed scoped VMEM at S>=8192 on v5e, capped 256); at/over the
    threshold the kernels stream K/V by DMA and big blocks stay legal at
    any S."""
    from deepspeed_tpu.ops.attention.flash import _pick_blocks, _use_stream
    assert _pick_blocks(1024, 1024) == (512, 512)
    assert not _use_stream(4096, 4096)
    assert _use_stream(8192, 8192)
    # streamed tiles put the block width in the DMA lane dim (must be a
    # 128-multiple): irregular long seqs stay resident
    assert not _use_stream(8192 + 16, 8192 + 16)
    assert _pick_blocks(8192, 8192) == (512, 512)
    assert _pick_blocks(32768, 32768) == (512, 512)


def _grads_match_streamed(loss, args, thresh=128, tol=1e-5):
    """Grad parity harness: run `loss` grads on the resident path, then
    with streaming forced via STREAM_THRESHOLD, and compare (few-ulp
    fp32 reassociation tolerance — the streamed dots contract transposed
    tiles in a different order)."""
    from deepspeed_tpu.ops.attention import flash as F
    g_res = jax.grad(loss, argnums=tuple(range(len(args))))(*args)
    old = F.STREAM_THRESHOLD
    try:
        F.STREAM_THRESHOLD = thresh   # force streaming
        g_str = jax.grad(loss, argnums=tuple(range(len(args))))(*args)
    finally:
        F.STREAM_THRESHOLD = old
    for a, b in zip(g_res, g_str):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=tol, atol=tol)


@pytest.mark.parametrize("S,causal",
                         [(128, True), (384, True), (384, False)])
@pytest.mark.slow
def test_flash_streaming_matches_resident(S, causal):
    """Force streaming at a small S: outputs and grads must match the
    resident path. S=384 uses 128-blocks -> 3-deep DMA loops incl. the
    causal ragged bounds (streaming requires 128-multiple seqs: the block
    width is the DMA lane dim). Streamed tiles are stored transposed (D, block)
    — Mosaic requires DMA lane dims to be 128-aligned, which head_dim 64
    never is — so the dots contract in a different order than the
    resident path: allow a few-ulp fp32 reassociation tolerance (a real
    indexing bug shows up as O(1) diffs, not 1e-6)."""
    from deepspeed_tpu.ops.attention import flash as F
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (1, 2, S, 16), jnp.float32)
               for i in range(3))

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, causal=causal)
                       .astype(jnp.float32) ** 2)

    _grads_match_streamed(loss, (q, k, v))


@pytest.mark.slow
def test_flash_streaming_dropout_matches_resident():
    """Streamed + in-kernel dropout: the counter-hash mask must
    regenerate identically whether K/V are resident or DMA-streamed
    (the tile walk order differs; the hash is coordinate-keyed)."""
    from deepspeed_tpu.ops.attention import flash as F
    key = jax.random.PRNGKey(2)
    S = 256
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (1, 2, S, 16), jnp.float32)
               for i in range(3))
    rng = jax.random.PRNGKey(5)

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(
            q, k, v, causal=True, dropout_rate=0.2, dropout_rng=rng)
            .astype(jnp.float32) ** 2)

    _grads_match_streamed(loss, (q, k, v))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.slow
def test_flash_irregular_long_seq_pads_to_stream(causal):
    """ADVICE r2: a long sequence that is 16- but not 128-divisible must
    be internally padded (NEG_INF-masked tail keys, sliced outputs) so
    streaming always engages, instead of warn-then-maybe-crash on the
    resident path. Output and grads must match the dense reference."""
    from deepspeed_tpu.ops.attention import flash as F
    key = jax.random.PRNGKey(4)
    S = 208                      # %16 == 0, %128 != 0
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (1, 2, S, 16), jnp.float32)
               for i in range(3))

    old = F.STREAM_THRESHOLD
    try:
        F.STREAM_THRESHOLD = 128   # make S=208 a "long" sequence
        o = F.flash_attention(q, k, v, causal=causal)
        g = jax.grad(lambda q, k, v: jnp.sum(
            F.flash_attention(q, k, v, causal=causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
    finally:
        F.STREAM_THRESHOLD = old
    o_ref = F.attention_reference(q, k, v, causal=causal,
                                  sm_scale=1.0 / np.sqrt(16))
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        F.attention_reference(q, k, v, causal=causal,
                              sm_scale=1.0 / np.sqrt(16)) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               rtol=2e-5, atol=2e-5)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name}")


@pytest.mark.slow
def test_flash_streaming_masked_matches_resident():
    """Streamed + key-padding-mask path: the mask rides as a
    VMEM-resident ref sliced at dynamic 128-aligned offsets while K/V
    stream by DMA — exercise the combination (BERT long-seq shape)."""
    from deepspeed_tpu.ops.attention import flash as F
    key = jax.random.PRNGKey(1)
    S = 384
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (2, 2, S, 16), jnp.float32)
               for i in range(3))
    mrng = np.random.RandomState(7)
    mask = jnp.asarray(
        np.where(mrng.rand(2, 1, 1, S) > 0.25, 0.0, -1e9), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(F.flash_attention(q, k, v, mask=mask)
                       .astype(jnp.float32) ** 2)

    _grads_match_streamed(loss, (q, k, v))


class TestTransformerLayerGrid:
    """Shape / precision / variant grid vs the unfused oracle — the
    reference ran DeepSpeedTransformerLayer across a (batch, seq, hidden,
    heads) x fp16 x pre-LN grid (tests/unit/test_cuda_forward.py /
    test_cuda_backward.py); this is the TPU analog."""

    def _mk(self, batch, seq, hidden, heads, pre_ln, fp32):
        from deepspeed_tpu.ops.transformer.transformer import (
            DeepSpeedTransformerConfig, init_transformer_params)
        cfg = DeepSpeedTransformerConfig(
            batch_size=batch, max_seq_length=seq, hidden_size=hidden,
            intermediate_size=4 * hidden, heads=heads,
            attn_dropout_ratio=0.0, hidden_dropout_ratio=0.0,
            num_hidden_layers=2, initializer_range=0.02,
            pre_layer_norm=pre_ln, bf16=not fp32, training=False)
        params = init_transformer_params(cfg, jax.random.PRNGKey(2))
        rng = np.random.RandomState(batch + seq)
        x = jnp.asarray(rng.randn(batch, seq, hidden) * 0.5, jnp.float32)
        return cfg, params, x

    @pytest.mark.parametrize("batch,seq,hidden,heads", [
        (1, 16, 32, 2),      # irregular small seq -> reference fallback
        (3, 64, 96, 3),      # odd batch/heads
        (2, 128, 64, 4),
        (8, 32, 128, 8),
        (1, 256, 64, 2),
    ])
    @pytest.mark.parametrize("pre_ln", [True, False])
    @pytest.mark.slow
    def test_forward_grid(self, batch, seq, hidden, heads, pre_ln):
        from deepspeed_tpu.ops.transformer.transformer import (
            transformer_layer_forward)
        cfg, params, x = self._mk(batch, seq, hidden, heads, pre_ln, True)
        ref = transformer_layer_forward(params, cfg, x, rng=None,
                                        deterministic=True, use_flash=False)
        out = transformer_layer_forward(params, cfg, x, deterministic=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, rtol=3e-5)

    @pytest.mark.parametrize("batch,seq,hidden,heads", [
        (2, 64, 64, 4), (1, 128, 96, 3),
    ])
    @pytest.mark.parametrize("pre_ln", [True, False])
    @pytest.mark.slow
    def test_backward_grid(self, batch, seq, hidden, heads, pre_ln):
        from deepspeed_tpu.ops.transformer.transformer import (
            transformer_layer_forward)
        cfg, params, x = self._mk(batch, seq, hidden, heads, pre_ln, True)

        def loss(p, flash):
            return jnp.sum(transformer_layer_forward(
                p, cfg, x, deterministic=True, use_flash=flash) ** 2)

        gf = jax.grad(lambda p: loss(p, True))(params)
        gr = jax.grad(lambda p: loss(p, False))(params)
        for (pa, a), (_, b) in zip(
                jax.tree_util.tree_flatten_with_path(gf)[0],
                jax.tree_util.tree_flatten_with_path(gr)[0]):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-3, rtol=5e-3,
                                       err_msg=str(pa))

    def test_bf16_config_close_to_fp32(self):
        from deepspeed_tpu.ops.transformer.transformer import (
            transformer_layer_forward)
        cfg16, params, x = self._mk(2, 64, 64, 4, True, False)
        cfg32, _, _ = self._mk(2, 64, 64, 4, True, True)
        o16 = transformer_layer_forward(params, cfg16, x,
                                        deterministic=True)
        o32 = transformer_layer_forward(params, cfg32, x,
                                        deterministic=True)
        np.testing.assert_allclose(np.asarray(o16, np.float32),
                                   np.asarray(o32), atol=5e-2, rtol=5e-2)


def test_shipped_block_table_resolves(monkeypatch):
    """Every entry in the checked-in block_table.json must resolve
    through the REAL loader path (entries list + device_kind matching),
    not just the _BLOCK_TABLE test hook — guards loader rewrites against
    silently orphaning the hardware-measured winners (r4 loader added
    device_kind/gqa/kind fields).

    The lookup is pinned per entry by monkeypatching flash._device_kind
    to the entry's own recorded device: stamped entries only match on
    the chip that measured them, so resolving them against THIS host's
    device kind would fail deterministically on CPU dev boxes the
    moment a hardware sweep stamps the table (ADVICE r4)."""
    import json
    import os
    from deepspeed_tpu.ops.attention import flash as F
    path = os.path.join(os.path.dirname(F.__file__), "block_table.json")
    entries = json.load(open(path))
    assert entries, "shipped block table is empty?"
    kinds_seen = set()
    for e in entries:
        kind = e.get("kind", "flash")
        kinds_seen.add(kind)
        monkeypatch.setattr(F, "_device_kind",
                            lambda dk=e.get("device_kind"): dk)
        if kind == "flash":
            got = F._pick_blocks(e["seq_q"], e["seq_k"], e["d"],
                                 gqa=e.get("gqa", 1))
            assert got == (e["bq"], e["bk"]), (e, got)
        elif kind == "masked":
            got = F.lookup_masked_blocks(e["seq_q"], e["seq_k"], e["d"],
                                         bool(e["stream"]))
            assert got == e["b"], (e, got)
            assert F.pick_masked_block(e["seq_q"], e["seq_k"], e["d"],
                                       stream=bool(e["stream"])) == e["b"]
    # the unified-kernel entries must ship alongside the flash ones
    assert "masked" in kinds_seen, sorted(kinds_seen)


def test_block_table_lookup_and_fallback():
    """Autotuned block table (tools/autotune_blocks.py): exact shape hits
    override the heuristic; unknown shapes keep it; the sweep override
    wins over both."""
    from deepspeed_tpu.ops.attention import flash as F
    old_table, old_force = F._BLOCK_TABLE, F._FORCE_BLOCKS
    try:
        F._BLOCK_TABLE = {(128, 128, 64, False): (64, 64)}
        assert F._pick_blocks(128, 128, 64) == (64, 64)
        # unknown shape -> heuristic (largest divisor under cap)
        assert F._pick_blocks(256, 256, 64) == (256, 256)
        # no head-dim given (legacy callers) -> heuristic
        assert F._pick_blocks(128, 128) == (128, 128)
        F._FORCE_BLOCKS = (32, 32)
        assert F._pick_blocks(128, 128, 64) == (32, 32)
    finally:
        F._BLOCK_TABLE, F._FORCE_BLOCKS = old_table, old_force


@pytest.mark.slow
@pytest.mark.parametrize("pre_ln", [True, False])
def test_recompute_knobs_preserve_numerics(pre_ln):
    """The recompute knobs (reference compile-time variants:
    attn_dropout_checkpoint / gelu_checkpoint / normalize_invertible)
    must change MEMORY behavior only: loss and grads identical, and the
    compiled program actually contains remat regions."""
    cfg_kw = dict(batch_size=2, max_seq_length=32, hidden_size=32,
                  intermediate_size=64, heads=2, attn_dropout_ratio=0.0,
                  hidden_dropout_ratio=0.0, num_hidden_layers=1,
                  initializer_range=0.02, pre_layer_norm=pre_ln,
                  training=True)
    base = DeepSpeedTransformerConfig(**cfg_kw)
    knobs = DeepSpeedTransformerConfig(**cfg_kw,
                                       attn_dropout_checkpoint=True,
                                       gelu_checkpoint=True,
                                       normalize_invertible=True)
    params = init_transformer_params(base, jax.random.PRNGKey(0), 0)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32), jnp.float32)

    def loss(cfg):
        def f(p, x):
            out = transformer_layer_forward(p, cfg, x, deterministic=True)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return f

    l0, g0 = jax.value_and_grad(loss(base))(params, x)
    l1, g1 = jax.value_and_grad(loss(knobs))(params, x)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g0, g1)
    # remat really present with knobs on, absent off
    jx_on = str(jax.make_jaxpr(loss(knobs))(params, x))
    jx_off = str(jax.make_jaxpr(loss(base))(params, x))
    assert "remat" in jx_on
    assert "remat" not in jx_off


class TestFlashGQA:
    """Grouped-query attention: kv_heads < heads served natively by the
    kernels (shared K/V rows via index map / DMA row select)."""

    @pytest.mark.parametrize("hkv", [1, 2, 4])
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_matches_repeated_kv(self, hkv, causal):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 4, 128, 64), jnp.float32)
        k, v = (jnp.asarray(rng.randn(2, hkv, 128, 64), jnp.float32)
                for _ in range(2))
        rep = 4 // hkv
        o_ref = flash_attention(q, jnp.repeat(k, rep, axis=1),
                                jnp.repeat(v, rep, axis=1),
                                causal=causal, interpret=True)
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_repeated_kv(self, causal):
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(2, 4, 64, 64), jnp.float32)
        k, v = (jnp.asarray(rng.randn(2, 2, 64, 64), jnp.float32)
                for _ in range(2))

        def f_gqa(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           interpret=True) ** 2)

        def f_rep(q, k, v):
            return jnp.sum(flash_attention(
                q, jnp.repeat(k, 2, axis=1), jnp.repeat(v, 2, axis=1),
                causal=causal, interpret=True) ** 2)

        gq, gk, gv = jax.grad(f_gqa, argnums=(0, 1, 2))(q, k, v)
        # jnp.repeat's vjp already sums the group's grads back onto the
        # shared kv head, so f_rep's grads are directly comparable
        rq, rk, rv = jax.grad(f_rep, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(np.asarray(gq), np.asarray(rq),
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(rk),
                                   atol=2e-4, rtol=2e-4)
        np.testing.assert_allclose(np.asarray(gv), np.asarray(rv),
                                   atol=2e-4, rtol=2e-4)

    def test_gqa_with_padding_mask_and_reference_path(self):
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(2, 4, 64, 64), jnp.float32)
        k, v = (jnp.asarray(rng.randn(2, 2, 64, 64), jnp.float32)
                for _ in range(2))
        keep = (rng.rand(2, 64) > 0.3).astype(np.float32)
        mask = jnp.asarray((1.0 - keep)[:, None, None, :] * -1e9)
        o = flash_attention(q, k, v, mask=mask, interpret=True)
        o_ref = attention_reference(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                                   atol=2e-5, rtol=2e-5)
        # irregular seq -> reference fallback handles GQA too
        o2 = flash_attention(q[:, :, :50], k[:, :, :50], v[:, :, :50],
                             causal=True)
        assert o2.shape == (2, 4, 50, 64)

    def test_bad_head_ratio_rejected(self):
        q = jnp.zeros((1, 4, 32, 64))
        kv = jnp.zeros((1, 3, 32, 64))
        with pytest.raises(AssertionError):
            flash_attention(q, kv, kv)

    def test_gqa_streamed_matches_resident(self):
        """The DMA row select must follow the kv group under streaming."""
        from deepspeed_tpu.ops.attention import flash as F
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 4, 256, 64), jnp.float32)
        k, v = (jnp.asarray(rng.randn(1, 2, 256, 64), jnp.float32)
                for _ in range(2))

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True) ** 2)

        resident = (f(q, k, v), *jax.grad(f, argnums=(1, 2))(q, k, v))
        old = F.STREAM_THRESHOLD
        try:
            F.STREAM_THRESHOLD = 128   # force the streamed kernels
            streamed = (f(q, k, v), *jax.grad(f, argnums=(1, 2))(q, k, v))
        finally:
            F.STREAM_THRESHOLD = old
        for a, b in zip(resident, streamed):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)
