"""Engine end-to-end tests (mirrors reference tests/unit/test_fp16.py's
init+train-loop pattern, on the 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from tests.unit.simple_model import (
    base_config, init_simple_params, random_batches, simple_loss_fn)

HIDDEN = 16


def make_engine(config, n_layers=2, seed=0):
    params = init_simple_params(jax.random.PRNGKey(seed), HIDDEN, n_layers)
    engine, optimizer, loader, sched = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_parameters=params, config=config)
    return engine


def train(engine, n_steps=10, batch_size=None, seed=0):
    if batch_size is None:
        batch_size = (engine.train_micro_batch_size_per_gpu() *
                      engine.dp_world_size)
    batches = random_batches(
        n_steps * engine.gradient_accumulation_steps, batch_size, HIDDEN,
        seed=seed)
    it = iter(batches)
    losses = []
    for _ in range(n_steps):
        losses.append(float(engine.train_batch(it)))
    return losses


class TestEngineBasics:

    def test_initialize_returns_tuple(self):
        params = init_simple_params(jax.random.PRNGKey(0), HIDDEN)
        engine, optimizer, loader, sched = deepspeed_tpu.initialize(
            model=simple_loss_fn, model_parameters=params,
            config=base_config())
        assert engine is not None and optimizer is not None
        assert engine.dp_world_size == 8  # conftest mesh
        assert engine.train_batch_size() == 16  # 2 per chip * 8

    def test_loss_decreases(self):
        engine = make_engine(base_config())
        losses = train(engine, n_steps=30)
        assert losses[-1] < losses[0] * 0.7, losses
        assert engine.global_steps == 30

    def test_forward_backward_step_facade(self):
        engine = make_engine(base_config())
        batch = random_batches(1, 16, HIDDEN)[0]
        loss1 = engine(batch)
        engine.backward(loss1)
        engine.step()
        assert engine.global_steps == 1
        loss2 = engine(batch)
        engine.backward(loss2)
        engine.step()
        assert float(loss2) < float(loss1)

    def test_gradient_accumulation(self):
        cfg = base_config(gradient_accumulation_steps=4)
        engine = make_engine(cfg)
        assert engine.train_batch_size() == 2 * 4 * 8
        losses = train(engine, n_steps=10)
        assert engine.global_steps == 10
        assert losses[-1] < losses[0]

    def test_facade_accumulation_boundary(self):
        cfg = base_config(gradient_accumulation_steps=2)
        engine = make_engine(cfg)
        batch = random_batches(1, 16, HIDDEN)[0]
        engine.backward(engine(batch))
        engine.step()  # not a boundary yet
        assert engine.global_steps == 0
        engine.backward(engine(batch))
        engine.step()  # boundary
        assert engine.global_steps == 1

    def test_eval_batch_no_update(self):
        engine = make_engine(base_config())
        batch = random_batches(1, 16, HIDDEN)[0]
        loss_a = float(engine.eval_batch(batch))
        loss_b = float(engine.eval_batch(batch))
        assert loss_a == pytest.approx(loss_b)
        assert engine.global_steps == 0


class TestPrecision:

    def test_bf16(self):
        engine = make_engine(base_config(bf16={"enabled": True}))
        losses = train(engine, n_steps=20)
        assert losses[-1] < losses[0]

    def test_fp16_dynamic_scale(self):
        engine = make_engine(base_config(
            fp16={"enabled": True, "initial_scale_power": 8}))
        losses = train(engine, n_steps=20)
        assert losses[-1] < losses[0]
        assert engine.loss_scale() > 0

    def test_fp16_static_scale(self):
        engine = make_engine(base_config(
            fp16={"enabled": True, "loss_scale": 128.0}))
        train(engine, n_steps=5)
        assert engine.loss_scale() == 128.0


class TestZeroStages:

    @pytest.mark.parametrize("stage", [0, 1, 2])
    def test_zero_stage_trains(self, stage):
        engine = make_engine(base_config(
            zero_optimization={"stage": stage}))
        losses = train(engine, n_steps=15)
        assert losses[-1] < losses[0], f"stage {stage}: {losses}"

    def test_zero_matches_ddp(self):
        """ZeRO sharding must not change the math (reference test_fp16
        parity pattern)."""
        cfg0 = base_config()
        cfg2 = base_config(zero_optimization={"stage": 2})
        e0 = make_engine(cfg0, seed=3)
        e2 = make_engine(cfg2, seed=3)
        l0 = train(e0, n_steps=5, seed=7)
        l2 = train(e2, n_steps=5, seed=7)
        np.testing.assert_allclose(l0, l2, rtol=1e-5)

    def test_zero_opt_state_is_sharded(self):
        engine = make_engine(base_config(zero_optimization={"stage": 1}))
        # moment buffers for (16,16) weights should be sharded over data(8)
        m = engine.state.opt_state.exp_avg["layer_0"]["w"]
        shard_shape = m.sharding.shard_shape(m.shape)
        assert shard_shape != m.shape, "opt state unexpectedly replicated"


class TestSchedulers:

    def test_warmup_lr_applied(self):
        cfg = base_config(scheduler={
            "type": "WarmupLR",
            "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2,
                       "warmup_num_steps": 10, "warmup_type": "linear"}})
        engine = make_engine(cfg)
        assert engine.get_lr()[0] == pytest.approx(0.0)
        train(engine, n_steps=5)
        assert engine.get_lr()[0] == pytest.approx(5e-3, rel=1e-3)
        train(engine, n_steps=10)
        assert engine.get_lr()[0] == pytest.approx(1e-2, rel=1e-3)


class TestGradClip:

    def test_gradient_clipping_runs(self):
        engine = make_engine(base_config(gradient_clipping=0.1))
        losses = train(engine, n_steps=10)
        assert np.isfinite(losses).all()


def test_config_accessor_facade():
    """Reference engine accessor-method surface (engine.py:255-370) —
    scripts calling these must port unchanged."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    eng, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "Adam",
                              "params": {"lr": 1e-3, "betas": [0.9, 0.99]}},
                "scheduler": {"type": "WarmupLR",
                              "params": {"warmup_min_lr": 1e-4,
                                         "warmup_max_lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10**9})
    assert eng.optimizer_name() == "adam"
    assert eng.optimizer_params()["lr"] == 1e-3
    assert eng.scheduler_name() == "WarmupLR"
    assert eng.zero_optimization_stage() == 2
    assert eng.zero_optimization_partition_gradients()
    assert not eng.amp_enabled() and eng.amp_params() is None
    assert not eng.dynamic_loss_scale()        # fp16 off
    assert eng.get_mom() == [0.9]
    assert isinstance(eng.wall_clock_breakdown(), bool)
    assert eng.train() is eng and eng.eval() is eng

    # module_state_dict round-trip through load_module_state_dict
    sd = eng.module_state_dict()
    rng = np.random.RandomState(0)
    eng.train_batch(iter([{"x": rng.randn(8, 8).astype(np.float32),
                           "y": rng.randn(8, 1).astype(np.float32)}]))
    changed = eng.module_state_dict()
    assert any(not np.allclose(a, b)
               for a, b in zip(jax.tree_util.tree_leaves(sd),
                               jax.tree_util.tree_leaves(changed)))
    eng.load_module_state_dict(sd)
    restored = eng.module_state_dict()
    for a, b in zip(jax.tree_util.tree_leaves(sd),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(a, b)

    eng.zero_grad()                       # accum buffer cleared, no error
    eng.allreduce_gradients()             # documented no-op
    assert isinstance(eng.dump_state(), list)
