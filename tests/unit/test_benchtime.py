"""The shared scan-amortized measurement protocol (utils/benchtime.py).

The invariant under test once failed silently in production: a window
smaller than the tunnel's RTT jitter "measured" 0.00 ms and poisoned the
autotune block table.  The protocol must rescale until a window clears
the noise floor and RAISE (NoiseFloorError) when it cannot — a noise
reading must never come back as a measurement.

Reference analog: the GemmTest autotuner's repeated-timing loop
(csrc/includes/gemm_test.h:27).
"""

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.utils.benchtime import (NoiseFloorError, measure_rtt,
                                           scan_grad_seconds)


def _args():
    key = jax.random.PRNGKey(0)
    return tuple(jax.random.normal(jax.random.fold_in(key, i),
                                   (2, 64, 64), jnp.bfloat16)
                 for i in range(3))


def _grad_fn():
    def loss(q, k, v):
        return jnp.sum((q @ k @ v).astype(jnp.float32))
    return jax.grad(loss, argnums=(0, 1, 2))


def test_measures_positive_time_and_beats():
    rtt = measure_rtt()
    beats = []
    sec, n = scan_grad_seconds(_grad_fn(), _args(), rtt, start_len=2,
                               min_floor=0.05, beat=lambda: beats.append(1))
    assert sec > 0.0
    assert n >= 2
    # at least compile+settle and one measured window per growth round
    assert len(beats) >= 2


def test_scan_length_grows_to_clear_floor():
    # tiny per-eval work against a fat floor forces rescaling
    _, n = scan_grad_seconds(_grad_fn(), _args(), rtt=0.0, start_len=1,
                             min_floor=0.05, max_len=4096)
    assert n > 1


def test_raises_noise_floor_error_not_zero():
    # an absurd rtt makes the floor unreachable: the protocol must raise,
    # never return a ~0 "measurement"
    with pytest.raises(NoiseFloorError):
        scan_grad_seconds(_grad_fn(), _args(), rtt=100.0, start_len=1,
                          max_len=2, grow_rounds=2)


def test_noise_floor_error_is_not_a_generic_fallback_trigger():
    # bench.py's sparse row falls back to the v1 kernel on Exception but
    # must re-raise NoiseFloorError; the type distinction is the contract
    assert issubclass(NoiseFloorError, RuntimeError)
    try:
        scan_grad_seconds(_grad_fn(), _args(), rtt=100.0, start_len=1,
                          max_len=2, grow_rounds=2)
    except NoiseFloorError as e:
        # the message must name the scan length actually measured
        assert "scan length 2" in str(e)
