"""Serving fleet (deepspeed_tpu/inference/fleet.py): multi-replica
router with SLO-driven load shedding, replica drain, and live weight
swap — serve through a preemption.

Tier-1 acceptance pins (ISSUE 14):
- a fixed mixed-length workload over 3 replicas reproduces the
  single-engine greedy outputs BITWISE — with a mid-run weight swap
  (same weights) AND with a replica drained mid-run (its queue
  redistributes to survivors);
- zero dropped responses in every scenario (exactly one
  FinishedRequest per submitted uid; a shed is a synthesized zero-token
  answer, never a missing one);
- ``steady_state_recompiles == 0`` on every replica across routing,
  drain, and swap;
- an injected mid-swap load failure (``serve.swap_load``) rolls the
  replica back to its old weights without killing it;
- the ``Serve/{shed_rate,fleet_queue_depth,weight_version}`` tags and
  the shed vocabulary stay in sync across their three homes.

The shed-ladder / routing-policy tests run on duck-typed fake engines:
fleet.py is jax-free (pinned by test_inference.py), so pure routing
policy is unit-testable in microseconds.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime import fault

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    cfg = GPT2Config(vocab_size=61, max_position_embeddings=64,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))


INF = {"max_batch_size": 3, "prompt_buckets": [4, 8, 16, 24],
       "batch_buckets": [1, 2], "max_seq_len": 48,
       "max_new_tokens": 8}
NEW_TOKENS = 8

# the pinned mixed-length workload: enough requests that a drained
# replica still holds a non-empty queue (redistribution is exercised,
# not vacuously skipped)
_rng = np.random.RandomState(5)
WORKLOAD = [_rng.randint(1, 61, (l,)).tolist()
            for l in (5, 9, 3, 12, 4, 7, 15, 6, 8, 10, 5, 13)]


def _requests():
    from deepspeed_tpu.inference import Request
    return [Request(prompt=list(p), max_new_tokens=NEW_TOKENS,
                    temperature=0.0, seed=0) for p in WORKLOAD]


def _submit_all(target):
    reqs = _requests()
    return [target.submit(r) for r in reqs]


def _serve_single(cfg, params, events_dir=None):
    from deepspeed_tpu.inference import InferenceEngine
    ic = dict(INF)
    if events_dir is not None:
        ic["events_dir"] = events_dir
    eng = InferenceEngine(cfg, params, ic, dtype=jnp.float32)
    eng.warmup()
    uids = _submit_all(eng)
    by_uid = {f.uid: f.tokens for f in eng.run()}
    outs = [by_uid[u] for u in uids]
    rc = eng.steady_state_recompiles
    eng.close()
    return outs, rc


def _save_tag(ckptlib, root, tag, params, step):
    d = os.path.join(root, tag)
    os.makedirs(d, exist_ok=True)
    ckptlib.save_tree_sharded(d, "model_states", params)
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"global_step": step}, f)
    ckptlib.write_commit_marker(d)
    ckptlib.write_latest(root, tag)
    return d


@pytest.fixture(scope="module")
def fleet_runs(tmp_path_factory):
    """All the expensive real-engine serving, once per module."""
    from deepspeed_tpu.inference import FleetRouter, InferenceEngine
    from deepspeed_tpu.runtime import checkpoint as ckptlib

    cfg, p1 = tiny_gpt2()
    from deepspeed_tpu.models.gpt2 import init_gpt2_params
    p2 = init_gpt2_params(cfg, jax.random.PRNGKey(7))

    ckroot = str(tmp_path_factory.mktemp("fleet_ckpt"))
    _save_tag(ckptlib, ckroot, "global_step1", p1, 1)
    _save_tag(ckptlib, ckroot, "global_step2", p2, 2)

    out = {"ckroot": ckroot}
    out["base"], out["base_rc"] = _serve_single(cfg, p1)
    out["p2_ref"], _ = _serve_single(cfg, p2)

    evdir = str(tmp_path_factory.mktemp("fleet_events"))

    def build_fleet(events=False):
        engines = []
        for i in range(3):
            ic = dict(INF)
            if events and i == 0:
                ic["events_dir"] = evdir
            eng = InferenceEngine(cfg, p1, ic, dtype=jnp.float32)
            eng.warmup()
            engines.append(eng)
        return engines, FleetRouter(engines, {"replicas": 3})

    try:
        # ---- fleet 1: routing parity + mid-run swap + push + rollback
        engines, router = build_fleet(events=True)
        uids = _submit_all(router)
        fins = []
        while len(fins) < 4:           # some answers land pre-swap...
            fins.extend(router.step())
        swap1 = router.swap_weights(ckroot, tag="global_step1")
        fins.extend(router.run())      # ...the rest after (same weights)
        by_uid = {f.uid: f for f in fins}
        out["swap_outs"] = [by_uid[u].tokens for u in uids]
        out["swap_fins"] = len(fins)
        out["swap_versions"] = {f.weight_version for f in fins}
        out["swap1"] = swap1

        # push genuinely NEW weights (auto-resolves newest committed)
        out["swap2"] = router.swap_weights(ckroot)
        uids2 = _submit_all(router)
        by_uid2 = {f.uid: f for f in router.run()}
        out["push_outs"] = [by_uid2[u].tokens for u in uids2]
        out["push_versions"] = {f.weight_version
                                for f in by_uid2.values()}

        # injected mid-swap failure on every replica: atomic-or-rollback
        fault.arm_from_env(
            env={fault.ENV_ARM: "serve.swap_load:oserror:3"})
        out["swap3"] = router.swap_weights(ckroot, tag="global_step1")
        fault.reset()
        uids3 = _submit_all(router)
        by_uid3 = {f.uid: f for f in router.run()}
        out["rollback_outs"] = [by_uid3[u].tokens for u in uids3]
        out["rollback_versions"] = {f.weight_version
                                    for f in by_uid3.values()}
        out["fleet1_rc"] = [e.steady_state_recompiles for e in engines]
        out["fleet1_state"] = router.debug_state()
        router.close()
        out["events_dir"] = evdir

        # ---- fleet 2: dispatch-fault reroute + preemption drain
        engines2, router2 = build_fleet()
        fault.arm("serve.dispatch", exc=OSError("injected flake"),
                  times=1)
        uids_d = _submit_all(router2)
        assert fault.get_injector().fired("serve.dispatch") == 1
        out["reroutes"] = router2.total_reroutes
        fins2 = router2.step()         # replicas get some work in flight
        fault.arm("serve.replica_preempt",
                  exc=fault.InjectedCrash("preempted"), times=1,
                  filter=lambda **ctx: ctx.get("replica") == 0)
        fins2.extend(router2.run())
        fault.reset()
        by_uid_d = {f.uid: f for f in fins2}
        out["drain_outs"] = [by_uid_d[u].tokens for u in uids_d]
        out["drain_fins"] = len(fins2)
        out["drain_reasons"] = {f.finish_reason for f in fins2}
        out["drain_state"] = router2.debug_state()
        out["redistributed"] = router2.total_redistributed
        out["fleet2_rc"] = [e.steady_state_recompiles for e in engines2]
        router2.close()
    finally:
        fault.reset()
    return out


class TestFleetContract:
    def test_baseline_sane(self, fleet_runs):
        assert len(fleet_runs["base"]) == len(WORKLOAD)
        assert all(len(t) == NEW_TOKENS for t in fleet_runs["base"])
        assert fleet_runs["base_rc"] == 0
        # the two weight sets genuinely disagree (else the swap pins
        # below would be vacuous)
        assert fleet_runs["p2_ref"] != fleet_runs["base"]

    def test_swap_parity_bitwise(self, fleet_runs):
        """Mid-run swap to the SAME weights: every request's greedy
        output bitwise equals the single-engine baseline."""
        assert fleet_runs["swap_outs"] == fleet_runs["base"]

    def test_swap_zero_dropped_and_versioned(self, fleet_runs):
        assert fleet_runs["swap_fins"] == len(WORKLOAD)
        # answers finished before the swap are stamped "initial",
        # after it the tag — the swap is attributable per response
        assert fleet_runs["swap_versions"] == {"initial",
                                               "global_step1"}
        assert fleet_runs["swap1"] == {0: "global_step1",
                                       1: "global_step1",
                                       2: "global_step1"}

    def test_push_new_weights_changes_outputs(self, fleet_runs):
        """Auto-resolved push of different weights: the fleet now
        reproduces a fresh engine built with those weights."""
        assert fleet_runs["swap2"] == {0: "global_step2",
                                       1: "global_step2",
                                       2: "global_step2"}
        assert fleet_runs["push_outs"] == fleet_runs["p2_ref"]
        assert fleet_runs["push_versions"] == {"global_step2"}

    def test_mid_swap_fault_rolls_back(self, fleet_runs):
        """serve.swap_load injection on every replica: each rolls back
        to (and keeps serving) its OLD weights — no replica dies, no
        output changes, no recompile."""
        assert fleet_runs["swap3"] == {0: None, 1: None, 2: None}
        assert fleet_runs["rollback_outs"] == fleet_runs["p2_ref"]
        assert fleet_runs["rollback_versions"] == {"global_step2"}

    def test_zero_steady_state_recompiles(self, fleet_runs):
        assert fleet_runs["fleet1_rc"] == [0, 0, 0]
        assert fleet_runs["fleet2_rc"] == [0, 0, 0]

    def test_dispatch_fault_reroutes(self, fleet_runs):
        """A transient serve.dispatch failure reroutes to the next-best
        replica — the request is never dropped."""
        assert fleet_runs["reroutes"] == 1
        st = fleet_runs["drain_state"]
        assert sum(r["dispatch_faults"] for r in st["replicas"]) == 1

    def test_drain_parity_bitwise(self, fleet_runs):
        """Replica 0 preempted mid-run (injected serve.replica_preempt):
        queued requests redistribute, in-flight finish in place, and
        every greedy output still bitwise equals the baseline."""
        assert fleet_runs["drain_outs"] == fleet_runs["base"]

    def test_drain_zero_dropped(self, fleet_runs):
        assert fleet_runs["drain_fins"] == len(WORKLOAD)
        assert fleet_runs["drain_reasons"] <= {"length", "eos"}

    def test_drain_redistributes_and_retires(self, fleet_runs):
        assert fleet_runs["redistributed"] >= 1
        st = fleet_runs["drain_state"]
        r0 = st["replicas"][0]
        assert r0["status"] == "retired"
        assert str(r0["drain_reason"]).startswith("fault:")
        assert {r["status"] for r in st["replicas"][1:]} == {"live"}

    def test_fleet_debug_state_shape(self, fleet_runs):
        st = fleet_runs["fleet1_state"]
        assert st["routing"] == "least_loaded"
        assert st["submitted"] == 3 * len(WORKLOAD)
        assert st["shed"]["total"] == 0 and st["shed"]["rate"] == 0.0
        assert st["fleet_queue_depth"] == 0
        assert {r["weight_version"] for r in st["replicas"]} == \
            {"global_step2"}
        assert all(r["weight_ordinal"] == 2 for r in st["replicas"])


class TestFleetObservability:
    def test_event_trail_and_obs_report(self, fleet_runs):
        ev = os.path.join(fleet_runs["events_dir"], "events.jsonl")
        rows = [json.loads(l) for l in open(ev) if l.strip()]
        kinds = {r.get("event") for r in rows if "event" in r}
        assert {"fleet_swap", "fleet_swap_push", "fleet_state"} <= kinds
        # replica 0 owns the event writer: its 2 applied swaps and 1
        # rolled-back swap land, each stamped with the serving version
        swaps = [r for r in rows if r.get("event") == "fleet_swap"]
        assert sum(1 for r in swaps if r["ok"]) == 2
        assert sum(1 for r in swaps if not r["ok"]) == 1
        assert all(not r["ok"] or r["weight_version"] for r in swaps)

        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(fleet_runs["events_dir"])
        fl = s["serving"]["fleet"]
        assert fl is not None
        assert len(fl["replicas"]) == 3
        assert fl["routing"] == "least_loaded"
        assert fl["shed"]["total"] == 0
        assert [t for t in fl["timeline"] if t["kind"] == "swap"]
        text = obs_report.render_serve(s)
        assert "fleet" in text and "replica 0" in text
        assert obs_report.main([fleet_runs["events_dir"],
                                "--serve"]) == 0
        assert obs_report.main([fleet_runs["events_dir"],
                                "--json"]) == 0

    def test_serve_ready_preflight(self, fleet_runs, capsys):
        """tools/verify_checkpoint.py --serve-ready: the fleet swap
        preflight — the tag must verify AND carry model_states."""
        vc = _load_tool("verify_checkpoint")
        tag_dir = os.path.join(fleet_runs["ckroot"], "global_step2")
        assert vc.main([tag_dir, "--serve-ready"]) == 0
        assert "serve-ready OK" in capsys.readouterr().out
        assert vc.main([fleet_runs["ckroot"], "--serve-ready",
                        "--all"]) == 0
        # a tag with no model_states group can never be a swap target
        bad = os.path.join(fleet_runs["ckroot"], "optim_only")
        os.makedirs(bad, exist_ok=True)
        with open(os.path.join(bad, "meta.json"), "w") as f:
            json.dump({"global_step": 3}, f)
        from deepspeed_tpu.runtime import checkpoint as ckptlib
        ckptlib.write_commit_marker(bad)
        assert vc.main([bad, "--serve-ready"]) != 0


class TestCancelMidHandoff:
    @pytest.mark.parametrize("extra", [
        {"disagg": {"enabled": True}},
        {"disagg": {"enabled": True, "separate_pools": True}},
    ], ids=["shared_pool", "separate_pools"])
    def test_cancel_pops_handoff_record(self, extra):
        """A request cancelled while its completed prefill waits in the
        handoff queue must take its HandoffRecord with it — a phantom
        record would sit in the queue forever once the scheduler goes
        idle (or resurrect a freed slot at the next claim drain)."""
        from deepspeed_tpu.inference import InferenceEngine, Request
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(cfg, params, dict(INF, **extra),
                              dtype=jnp.float32)
        eng.warmup()
        uids = [eng.submit(Request(prompt=list(p),
                                   max_new_tokens=NEW_TOKENS,
                                   temperature=0.0, seed=0))
                for p in WORKLOAD[:3]]
        eng.step()                      # prefill wave -> records queued
        q = eng._handoff_q
        assert len(q) > 0
        victim = q._q[0].uid
        depth = len(q)
        fin = eng.cancel(victim)
        assert fin is not None and fin.uid == victim
        assert len(q) == depth - 1      # record went with the request
        assert q.pop(victim) is None
        assert q.total_dropped == 1
        # the survivors still finish and the queue fully drains — no
        # phantom claim, no stuck reservation
        done = {}
        while not (eng.scheduler.idle() and len(q) == 0):
            for f in eng.step():
                done[f.uid] = f
        survivors = [u for u in uids if u != victim]
        assert set(done) == set(survivors)
        assert all(len(done[u].tokens) == NEW_TOKENS
                   for u in survivors)
        assert eng.debug_state()["disagg"]["queue"]["depth"] == 0
        assert eng.steady_state_recompiles == 0
        eng.close()


# --------------------------------------------------------------------- #
# shed ladder / routing policy on duck-typed fakes (fleet.py is
# jax-free: policy tests run in microseconds, no device state)
# --------------------------------------------------------------------- #
class _FakeSched:
    def __init__(self):
        self.queue = []
        self.total_tokens = 0
        self.occupancy = 0.0
        self.weight_version = "initial"

    @property
    def queue_depth(self):
        return len(self.queue)

    def active_slots(self):
        return []

    def idle(self):
        return not self.queue


class _FakeEngine:
    """The engine's host-side surface, minus the device."""

    def __init__(self, ttft_samples=(), prefix_hits=0):
        from deepspeed_tpu.utils.monitor import Histogram
        self.scheduler = _FakeSched()
        self.received = []
        self.spec_on = True
        self.monitor = None
        self._log = None
        self.steady_state_recompiles = 0
        tracer = type("T", (), {})()
        tracer.slo_ttft_ms = 100.0
        tracer.hist = {"ttft_ms": Histogram()}
        for v in ttft_samples:
            tracer.hist["ttft_ms"].record(v)
        self._tracer = tracer
        if prefix_hits:
            alloc = type("A", (), {})()
            alloc.match_prefix = lambda p, n=prefix_hits: ([], n)
            self.scheduler.admit_allocator = alloc

    def submit(self, req):
        self.scheduler.queue.append(req)
        self.received.append(req)
        return req.uid

    def step(self):
        from deepspeed_tpu.inference import FinishedRequest
        fins = [FinishedRequest(
            uid=r.uid, prompt=list(r.prompt),
            tokens=[1] * r.max_new_tokens, finish_reason="length",
            ttft_ms=1.0, latency_ms=1.0)
            for r in self.scheduler.queue]
        self.scheduler.queue = []
        self.scheduler.total_tokens += sum(len(f.tokens) for f in fins)
        return fins

    def cancel(self, uid, reason="evicted"):
        from deepspeed_tpu.inference import FinishedRequest
        for i, r in enumerate(self.scheduler.queue):
            if r.uid == uid:
                del self.scheduler.queue[i]
                return FinishedRequest(
                    uid=uid, prompt=list(r.prompt), tokens=[],
                    finish_reason=reason, ttft_ms=None, latency_ms=0.0)
        return None

    def set_speculation(self, on):
        self.spec_on = bool(on)
        return True


def _router(fakes, **slo):
    from deepspeed_tpu.inference import FleetRouter
    cfg = {"replicas": len(fakes)}
    if slo:
        cfg["slo_shed"] = slo
    return FleetRouter(fakes, cfg)


def _req(prompt=(1, 2, 3), priority=0, max_new=8):
    from deepspeed_tpu.inference import Request
    return Request(prompt=list(prompt), max_new_tokens=max_new,
                   temperature=0.0, priority=priority)


class TestShedLadder:
    def test_healthy_fleet_sheds_nothing(self):
        r = _router([_FakeEngine([1.0, 2.0]), _FakeEngine([1.0])],
                    enabled=True, ttft_budget_ms=1000.0, min_samples=1)
        assert r.shed_level() == 0
        uid = r.submit(_req(priority=0))
        fins = r.run()
        assert [f.uid for f in fins] == [uid]
        assert fins[0].finish_reason == "length"
        assert r.total_shed == 0 and r.shed_rate == 0.0

    def test_rung1_rejects_low_tier_only(self):
        fakes = [_FakeEngine([50.0, 60.0]), _FakeEngine([55.0])]
        r = _router(fakes, enabled=True, ttft_budget_ms=10.0,
                    min_samples=1, shed_below_priority=1,
                    degrade_factor=100.0)
        assert r.shed_level() == 1
        lo = r.submit(_req(priority=0))
        hi = r.submit(_req(priority=1))
        fins = {f.uid: f for f in r.run()}
        assert fins[lo].finish_reason == "shed_slo"
        assert fins[lo].tokens == []          # a zero-token ANSWER
        assert fins[hi].finish_reason == "length"
        assert r.shed_by_reason == {"shed_slo": 1}
        assert r.shed_by_priority == {0: 1}
        assert r.shed_rate == 0.5

    def test_rung2_caps_budget_and_disables_spec(self):
        fakes = [_FakeEngine([50.0, 60.0]), _FakeEngine([55.0])]
        r = _router(fakes, enabled=True, ttft_budget_ms=10.0,
                    min_samples=1, shed_below_priority=1,
                    degrade_factor=1.5, degrade_max_new=4)
        assert r.shed_level() == 2
        uid = r.submit(_req(priority=1, max_new=40))
        assert not any(f.spec_on for f in fakes)   # fleet-wide off
        got = [q for f in fakes for q in f.received]
        assert len(got) == 1 and got[0].uid == uid
        assert got[0].max_new_tokens == 4          # capped, same uid
        assert r.total_degraded == 1
        # recovery: budget satisfied again -> ladder disengages and
        # speculation comes back (the plain/spec programs are both
        # warm, so neither transition recompiles)
        r._budget_ms = 1e9
        r.submit(_req(priority=0))
        assert r.shed_level() == 0
        assert all(f.spec_on for f in fakes)
        r.run()

    def test_capacity_shed_when_no_live_replica(self):
        fakes = [_FakeEngine(), _FakeEngine()]
        r = _router(fakes)
        r.drain(0, reason="test")
        r.drain(1, reason="test")
        r.step()                       # both idle -> both retire
        st = r.debug_state()
        assert {x["status"] for x in st["replicas"]} == {"retired"}
        uid = r.submit(_req())
        fins = {f.uid: f for f in r.run()}
        assert fins[uid].finish_reason == "shed_capacity"
        assert fins[uid].tokens == []

    def test_least_loaded_routing(self):
        busy, idle = _FakeEngine(), _FakeEngine()
        busy.scheduler.queue = [_req(), _req()]
        r = _router([busy, idle])
        r.submit(_req())
        assert len(idle.received) == 1 and not busy.received

    def test_prefix_affinity_routing(self):
        from deepspeed_tpu.inference import FleetRouter
        cold, warm = _FakeEngine(), _FakeEngine(prefix_hits=16)
        r = FleetRouter([cold, warm],
                        {"replicas": 2, "routing": "prefix_affinity"})
        r.submit(_req(prompt=list(range(1, 20))))
        assert len(warm.received) == 1 and not cold.received

    def test_drain_redistributes_queued_fakes(self):
        a, b = _FakeEngine(), _FakeEngine()
        r = _router([a, b])
        # pin both requests onto a, then drain it
        b.scheduler.queue = [_req(), _req(), _req()]
        u1 = r.submit(_req())
        u2 = r.submit(_req())
        assert len(a.received) == 2
        b.scheduler.queue = []
        r.drain(0, reason="manual")
        fins = {f.uid: f for f in r.run()}
        assert r.total_redistributed == 2
        assert set(fins) >= {u1, u2}
        assert all(fins[u].finish_reason == "length" for u in (u1, u2))
        st = r.debug_state()
        assert st["replicas"][0]["status"] == "retired"
        assert st["replicas"][0]["drain_reason"] == "manual"


class TestFleetConfig:
    def _cfg(self, **fleet):
        from deepspeed_tpu.runtime.config import get_inference_config
        return get_inference_config({"inference": {"fleet": fleet}})

    def test_defaults(self):
        fl = self._cfg()["fleet"]
        assert fl["replicas"] == 1
        assert fl["routing"] == "least_loaded"
        assert fl["slo_shed"]["enabled"] is False
        assert fl["slo_shed"]["ttft_budget_ms"] is None
        assert fl["slo_shed"]["min_samples"] == 8
        assert fl["slo_shed"]["shed_below_priority"] == 1
        assert fl["slo_shed"]["degrade_factor"] == 2.0
        assert fl["slo_shed"]["degrade_max_new"] == 32
        assert fl["swap"]["verify_integrity"] is True

    def test_rejects_bad_values(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfigError
        with pytest.raises(DeepSpeedConfigError, match="replicas"):
            self._cfg(replicas=0)
        with pytest.raises(DeepSpeedConfigError, match="routing"):
            self._cfg(routing="round_robin")
        with pytest.raises(DeepSpeedConfigError,
                           match="ttft_budget_ms"):
            self._cfg(slo_shed={"ttft_budget_ms": -1})
        with pytest.raises(DeepSpeedConfigError,
                           match="degrade_factor"):
            self._cfg(slo_shed={"degrade_factor": 0.5})

    def test_router_rejects_empty_fleet(self):
        from deepspeed_tpu.inference import FleetRouter
        with pytest.raises(ValueError, match="at least one"):
            FleetRouter([])


class TestRegistrySync:
    def test_fleet_tags_three_homes(self):
        """One tag, three homes (extends the PR 9 pin to the fleet
        scalars): monitor (canonical), profiling (re-export),
        obs_report (stdlib mirror)."""
        from deepspeed_tpu import profiling as prof
        from deepspeed_tpu.utils import monitor as m
        obs_report = _load_tool("obs_report")
        assert m.TAG_SERVE_SHED_RATE == prof.TAG_SERVE_SHED_RATE == \
            obs_report.T_SHED_RATE == "Serve/shed_rate"
        assert m.TAG_SERVE_FLEET_QDEPTH == \
            prof.TAG_SERVE_FLEET_QDEPTH == \
            obs_report.T_FLEET_QDEPTH == "Serve/fleet_queue_depth"
        assert m.TAG_SERVE_WEIGHT_VERSION == \
            prof.TAG_SERVE_WEIGHT_VERSION == \
            obs_report.T_WEIGHT_VERSION == "Serve/weight_version"
        # ISSUE 16 process-fleet scalars ride the same registry
        assert m.TAG_SERVE_MIGRATIONS == \
            prof.TAG_SERVE_MIGRATIONS == \
            obs_report.T_MIGRATIONS == "Serve/migrations"
        assert m.TAG_SERVE_REPLICA_RESTARTS == \
            prof.TAG_SERVE_REPLICA_RESTARTS == \
            obs_report.T_REPLICA_RESTARTS == "Serve/replica_restarts"

    def test_shed_vocabulary_pinned(self):
        """Every shed decision lands in the trail with a reason from
        this exact vocabulary — dashboards group by these strings."""
        from deepspeed_tpu.inference.tracing import (DEFER_REASONS,
                                                     SHED_REASONS)
        assert SHED_REASONS == ("shed_slo", "shed_capacity",
                                "degrade_max_new", "degrade_spec_off",
                                "drain", "reject_too_long")
        # the serve-trail defer vocabulary is unchanged by the fleet
        assert isinstance(DEFER_REASONS, tuple) and DEFER_REASONS
        assert not set(SHED_REASONS) & set(DEFER_REASONS)


class TestQuantizedSwap:
    """ISSUE 17 satellite: a mid-run weight swap onto an int8-RESIDENT
    replica loads the full-precision checkpoint, re-quantizes, and
    re-places the tree with the warmup programs' exact avals — the
    fleet's zero-recompile live-swap guarantee survives quantized
    serving."""

    def test_swap_onto_int8_resident_replicas(self, tmp_path):
        from deepspeed_tpu.inference import FleetRouter, InferenceEngine
        from deepspeed_tpu.runtime import checkpoint as ckptlib
        from deepspeed_tpu.runtime.quantized_params import \
            is_quantized_tree

        cfg, p1 = tiny_gpt2()
        from deepspeed_tpu.models.gpt2 import init_gpt2_params
        p2 = init_gpt2_params(cfg, jax.random.PRNGKey(7))
        ckroot = str(tmp_path)
        _save_tag(ckptlib, ckroot, "global_step1", p1, 1)
        _save_tag(ckptlib, ckroot, "global_step2", p2, 2)

        qinf = dict(INF, quantize_weights="int8",
                    paged_kv={"kv_dtype": "int8"})

        def serve_once(params):
            eng = InferenceEngine(cfg, params, dict(qinf),
                                  dtype=jnp.float32)
            eng.warmup()
            uids = _submit_all(eng)
            by_uid = {f.uid: f.tokens for f in eng.run()}
            outs = [by_uid[u] for u in uids]
            rc = eng.steady_state_recompiles
            eng.close()
            return outs, rc

        base_q, base_rc = serve_once(p1)
        p2_q, _ = serve_once(p2)
        assert base_rc == 0 and base_q != p2_q

        engines = []
        for _ in range(2):
            eng = InferenceEngine(cfg, p1, dict(qinf),
                                  dtype=jnp.float32)
            eng.warmup()
            assert is_quantized_tree(eng.params)
            engines.append(eng)
        router = FleetRouter(engines, {"replicas": 2})
        try:
            uids = _submit_all(router)
            fins = router.step()
            while len(fins) < 4:            # some answers land pre-swap
                fins.extend(router.step())
            # same weights back: the swap itself must not perturb
            # outputs, and the tree must come back int8-resident
            swap = router.swap_weights(ckroot, tag="global_step1")
            assert swap == {0: "global_step1", 1: "global_step1"}
            fins.extend(router.run())
            by_uid = {f.uid: f.tokens for f in fins}
            assert [by_uid[u] for u in uids] == base_q
            assert len(fins) == len(WORKLOAD)
            for eng in engines:
                assert is_quantized_tree(eng.params)
                assert eng.steady_state_recompiles == 0

            # push genuinely new weights: outputs become the p2
            # quantized reference, still zero recompiles
            uids = _submit_all(router)
            assert router.swap_weights(ckroot) == \
                {0: "global_step2", 1: "global_step2"}
            by_uid = {f.uid: f.tokens for f in router.run()}
            assert [by_uid[u] for u in uids] == p2_q
            for eng in engines:
                assert is_quantized_tree(eng.params)
                assert eng.steady_state_recompiles == 0
        finally:
            router.close()
