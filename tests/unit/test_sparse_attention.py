"""Sparse attention tests — mirrors the reference's
tests/unit/test_sparse_attention.py (sparse ops vs dense masked torch)
with our Pallas kernel checked against the dense-masked jnp oracle, plus
layout-structure assertions for every sparsity config."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BertSparseSelfAttention, BigBirdSparsityConfig,
    BSLongformerSparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
    SparseAttentionUtils, SparseSelfAttention, SparsityConfig,
    VariableSparsityConfig, block_sparse_attention,
    block_sparse_attention_reference, build_col_luts, build_row_luts,
    layout_additive_mask, sparsity_config_from_dict)


# --------------------------------------------------------------------- #
# layout structure
# --------------------------------------------------------------------- #
def test_dense_layout():
    layout = DenseSparsityConfig(num_heads=2, block=16).make_layout(64)
    assert layout.shape == (2, 4, 4)
    assert (layout == 1).all()


def test_seq_len_divisibility():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=2, block=16).make_layout(65)


def test_fixed_layout_local_windows():
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    layout = cfg.make_layout(128)   # 8 blocks
    # local: 2x2 diagonal windows all present
    for w in range(4):
        assert (layout[0, 2 * w:2 * w + 2, 2 * w:2 * w + 2] == 1).all()
    # global: last block of each window (indices 1,3,5,7) fully attended
    for g in (1, 3, 5, 7):
        assert (layout[0, :, g] == 1).all()
    # heads share the layout by default
    assert (layout[0] == layout[1]).all()


def test_fixed_layout_unidirectional():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(128)
    nb = layout.shape[1]
    upper = np.triu(np.ones((nb, nb), dtype=bool), k=1)
    assert (layout[0][upper] == 0).all()


def test_fixed_different_patterns_per_head():
    cfg = FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=4,
                              num_global_blocks=1,
                              different_layout_per_head=True,
                              num_different_global_patterns=4)
    layout = cfg.make_layout(128)
    # head h uses global column slot (num_local - 1 - h) within each window
    for h in range(4):
        g = 3 - h
        assert (layout[h, :, g] == 1).all()


def test_fixed_validation_errors():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=2, num_local_blocks=4,
                            num_global_blocks=3)
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=2, attention="unidirectional",
                            horizontal_global_attention=True)
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=2, num_different_global_patterns=2)


def test_variable_layout():
    cfg = VariableSparsityConfig(num_heads=2, block=16, num_random_blocks=1,
                                 local_window_blocks=[1, 2],
                                 global_block_indices=[0])
    layout = cfg.make_layout(128)
    assert (layout[0, :, 0] == 1).all()          # global column 0
    assert layout[0, 0, 0] == 1                  # first local window
    # each row has at least its random block
    assert (layout[0].sum(axis=-1) >= 1).all()
    # deterministic under the seed
    layout2 = cfg.make_layout(128)
    assert (layout == layout2).all()


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=2, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    layout = cfg.make_layout(128)
    nb = layout.shape[1]
    assert (layout[0, 0, :] == 1).all()          # global row
    assert (layout[0, :, 0] == 1).all()          # global column
    for r in range(1, nb - 1):                   # sliding window
        assert layout[0, r, r - 1] and layout[0, r, r] and layout[0, r, r + 1]


def test_bslongformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=2, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0, 2])
    layout = cfg.make_layout(128)
    for g in (0, 2):
        assert (layout[0, g, :] == 1).all()
        assert (layout[0, :, g] == 1).all()


def test_luts_roundtrip():
    cfg = BigBirdSparsityConfig(num_heads=2, block=16)
    layout = cfg.make_layout(128)
    lut, cnt = build_row_luts(layout)
    H, nq, _ = layout.shape
    rebuilt = np.zeros_like(layout)
    for h in range(H):
        for r in range(nq):
            rebuilt[h, r, lut[h, r, :cnt[h, r]]] = 1
    assert (rebuilt == layout).all()
    clut, ccnt = build_col_luts(layout)
    assert (ccnt == layout.sum(axis=1)).all()


# --------------------------------------------------------------------- #
# kernel numerics vs dense oracle
# --------------------------------------------------------------------- #
def _dense_guarded_attention(q, k, v, add_mask, sm_scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale + add_mask
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _rand_qkv(B, H, S, D, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, H, S, D), dtype) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("cfg_factory", [
    lambda H: FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=2,
                                  num_global_blocks=1),
    lambda H: BigBirdSparsityConfig(num_heads=H, block=16,
                                    num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1),
    lambda H: BSLongformerSparsityConfig(num_heads=H, block=16,
                                         num_sliding_window_blocks=3),
    lambda H: DenseSparsityConfig(num_heads=H, block=16),
])
def test_kernel_matches_dense_oracle(cfg_factory):
    B, H, S, D = 2, 2, 128, 32
    cfg = cfg_factory(H)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D)
    sm_scale = D ** -0.5
    out = block_sparse_attention(q, k, v, layout, sm_scale=sm_scale)
    expected = _dense_guarded_attention(
        q, k, v, jnp.asarray(layout_additive_mask(layout, cfg.block))[None],
        sm_scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_kernel_matches_reference_impl():
    B, H, S, D = 1, 2, 64, 16
    cfg = FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=2)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=3)
    out = block_sparse_attention(q, k, v, layout)
    ref = block_sparse_attention_reference(q, k, v, layout)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_key_padding_mask_add():
    B, H, S, D = 2, 2, 64, 16
    cfg = FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=2)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=1)
    kpm = np.zeros((B, S), np.float32)
    kpm[:, 40:] = -1e9                              # additive padding mask
    out = block_sparse_attention(q, k, v, layout,
                                 key_padding_mask=jnp.asarray(kpm),
                                 key_padding_mask_mode="add")
    ref = block_sparse_attention_reference(
        q, k, v, layout, key_padding_mask=jnp.asarray(kpm),
        key_padding_mask_mode="add")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kernel_attn_mask_mul():
    B, H, S, D = 1, 2, 64, 16
    cfg = BigBirdSparsityConfig(num_heads=H, block=16)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=2)
    am = np.tril(np.ones((S, S), np.float32))       # causal keep-mask
    out = block_sparse_attention(q, k, v, layout,
                                 attn_mask=jnp.asarray(am),
                                 attn_mask_mode="mul")
    ref = block_sparse_attention_reference(
        q, k, v, layout, attn_mask=jnp.asarray(am), attn_mask_mode="mul")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_kernel_gradients_match_oracle():
    B, H, S, D = 1, 2, 64, 16
    cfg = FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=2)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=4)
    mask = jnp.asarray(layout_additive_mask(layout, cfg.block))[None]
    sm_scale = D ** -0.5

    def loss_kernel(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, layout,
                                              sm_scale=sm_scale) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_guarded_attention(q, k, v, mask,
                                                sm_scale) ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5,
                                   rtol=5e-4, err_msg=f"d{name}")


def test_kernel_gradients_with_masks():
    B, H, S, D = 1, 2, 64, 16
    cfg = BSLongformerSparsityConfig(num_heads=H, block=16)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=5)
    kpm = np.zeros((B, S), np.float32)
    kpm[:, 48:] = -1e9
    kpm = jnp.asarray(kpm)

    def loss_kernel(q, k, v):
        out = block_sparse_attention(q, k, v, layout, key_padding_mask=kpm,
                                     key_padding_mask_mode="add")
        return jnp.sum(out ** 2)

    def loss_ref(q, k, v):
        out = block_sparse_attention_reference(
            q, k, v, layout, key_padding_mask=kpm,
            key_padding_mask_mode="add")
        return jnp.sum(out ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5,
                                   rtol=5e-4, err_msg=f"d{name}")


@pytest.mark.slow
def test_masked_path_v2_matches_v1():
    """VERDICT r2 #3: the blocked attn-mask variant now runs on the
    row-run (splash v2) kernels — outputs and grads must match the v1
    per-triple kernels bit-for-bit-ish on the same masked layout."""
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs

    B, H, S, D = 1, 2, 64, 16
    cfg = BSLongformerSparsityConfig(num_heads=H, block=16)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=7)
    am = (np.random.RandomState(3).rand(S, S) > 0.2).astype(np.float32)

    def run(use_v2):
        old = bs.USE_SPLASH_V2
        bs.USE_SPLASH_V2 = use_v2
        bs._FN_CACHE.clear()
        try:
            def loss(q, k, v):
                out = block_sparse_attention(
                    q, k, v, layout, attn_mask=jnp.asarray(am),
                    attn_mask_mode="mul")
                return jnp.sum(out ** 2)
            o = block_sparse_attention(q, k, v, layout,
                                       attn_mask=jnp.asarray(am),
                                       attn_mask_mode="mul")
            g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return o, g
        finally:
            bs.USE_SPLASH_V2 = old
            bs._FN_CACHE.clear()

    o2, g2 = run(True)
    o1, g1 = run(False)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                               atol=1e-5, rtol=1e-5)
    for a, b, name in zip(g2, g1, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_kernel_bf16():
    B, H, S, D = 1, 2, 64, 16
    cfg = FixedSparsityConfig(num_heads=H, block=16, num_local_blocks=2)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=6, dtype=jnp.bfloat16)
    out = block_sparse_attention(q, k, v, layout)
    ref = block_sparse_attention_reference(q, k, v, layout)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


# --------------------------------------------------------------------- #
# modules + utils
# --------------------------------------------------------------------- #
def test_sparse_self_attention_module():
    B, H, S, D = 2, 4, 64, 16
    attn = SparseSelfAttention(FixedSparsityConfig(num_heads=H, block=16,
                                                   num_local_blocks=2))
    q, k, v = _rand_qkv(B, H, S, D, seed=7)
    out = attn(q, k, v)
    assert out.shape == (B, H, S, D)
    ref = block_sparse_attention_reference(q, k, v, attn.get_layout(S),
                                           sm_scale=D ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    # layout cache hit
    assert attn.get_layout(S) is attn.get_layout(S)


def test_bert_sparse_self_attention():
    from deepspeed_tpu.models.bert import BertConfig
    cfg = BertConfig(hidden_size=64, num_heads=4)
    layer = BertSparseSelfAttention(
        cfg, FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2))
    params = layer.init_params(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 64, 64), jnp.float32)
    mask = jnp.ones((2, 64), jnp.float32).at[:, 50:].set(0.0)
    # mul-mode key padding via 'add' of -inf needs additive form; the module
    # defaults to 'add' mode, so feed additive values
    out = layer(params, x, attention_mask=(mask - 1.0) * 1e9)
    assert out.shape == (2, 64, 64)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_pad_unpad_roundtrip():
    ids = jnp.asarray(np.arange(2 * 50).reshape(2, 50), jnp.int32)
    mask = jnp.ones((2, 50), jnp.int32)
    labels = jnp.zeros((2, 50), jnp.int32)
    pad_len, pids, pmask, ptt, ppos, plab = \
        SparseAttentionUtils.pad_to_block_size(
            16, ids, pad_token_id=0, attention_mask=mask, labels=labels)
    assert pad_len == 14 and pids.shape == (2, 64)
    assert int(pmask[0, 50:].sum()) == 0
    assert (np.asarray(plab[:, 50:]) == -100).all()
    out = SparseAttentionUtils.unpad_sequence_output(
        pad_len, jnp.zeros((2, 64, 8)))
    assert out.shape == (2, 50, 8)
    # no-op when already aligned
    pad_len, pids, *_ = SparseAttentionUtils.pad_to_block_size(
        16, jnp.zeros((1, 32), jnp.int32), 0)
    assert pad_len == 0 and pids.shape == (1, 32)


def test_extend_position_embedding():
    params = {"pos_emb": jnp.asarray(
        np.random.RandomState(0).randn(128, 8), jnp.float32)}
    out = SparseAttentionUtils.extend_position_embedding(params, 300)
    assert out["pos_emb"].shape == (300, 8)
    np.testing.assert_allclose(np.asarray(out["pos_emb"][:128]),
                               np.asarray(params["pos_emb"]))
    np.testing.assert_allclose(np.asarray(out["pos_emb"][128:256]),
                               np.asarray(params["pos_emb"]))


@pytest.mark.slow
def test_replace_model_self_attention_surgery():
    """Model surgery (reference sparse_attention_utils.py:85): swap the BERT
    encoder's core attention for block-sparse, reusing dense weights; with a
    dense sparsity layout the output must match the dense encoder."""
    from deepspeed_tpu.models.bert import BertConfig, init_bert_params
    from deepspeed_tpu.ops.sparse_attention import DenseSparsityConfig

    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=64,
                     hidden_dropout=0.0, attn_dropout=0.0)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 64)),
                      jnp.int32)

    from deepspeed_tpu.models.bert import bert_encoder
    dense_out = bert_encoder(params, cfg, ids, dtype=jnp.float32)

    sp, scfg, encoder_fn = \
        SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
            params, cfg,
            sparsity_config=DenseSparsityConfig(num_heads=2, block=16))
    sparse_out = encoder_fn(sp, input_ids=ids, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(sparse_out, np.float32),
                               np.asarray(dense_out, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_surgery_extends_positions_and_runs_sparse():
    from deepspeed_tpu.models.bert import BertConfig, init_bert_params
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=64,
                     hidden_dropout=0.0, attn_dropout=0.0)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    sp, scfg, encoder_fn = \
        SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
            params, cfg, max_position=256,
            sparsity_config=FixedSparsityConfig(num_heads=2, block=16,
                                                num_local_blocks=4))
    assert scfg.max_position_embeddings == 256
    assert sp["pos_emb"].shape[0] == 256
    # 4x the original max length now runs (the 10x-longer-sequences claim
    # mechanism, BASELINE.md sparse attention row)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (1, 256)),
                      jnp.int32)
    out = encoder_fn(sp, input_ids=ids, dtype=jnp.float32)
    assert out.shape == (1, 256, 32)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_update_tokenizer_model_max_length():
    class Tok:
        model_max_length = 512
        init_kwargs = {}
    tok = SparseAttentionUtils.update_tokenizer_model_max_length(Tok(), 2048)
    assert tok.model_max_length == 2048
    assert tok.init_kwargs["model_max_length"] == 2048


def test_surgery_respects_key_padding():
    """Padding tokens must not leak into sparse attention (mul-mode mask):
    output at kept positions matches dense masked encoder."""
    from deepspeed_tpu.models.bert import (BertConfig, bert_encoder,
                                           init_bert_params)
    from deepspeed_tpu.ops.sparse_attention import DenseSparsityConfig

    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=1,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=64,
                     hidden_dropout=0.0, attn_dropout=0.0)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 64)),
                      jnp.int32)
    mask = jnp.ones((2, 64), jnp.int32).at[:, 40:].set(0)

    dense = bert_encoder(params, cfg, ids, attention_mask=mask,
                         dtype=jnp.float32)
    sparse = bert_encoder(params, cfg, ids, attention_mask=mask,
                          dtype=jnp.float32,
                          sparsity_config=DenseSparsityConfig(num_heads=2,
                                                              block=16))
    np.testing.assert_allclose(np.asarray(sparse[:, :40], np.float32),
                               np.asarray(dense[:, :40], np.float32),
                               rtol=2e-2, atol=2e-2)



# --------------------------------------------------------------------- #
# composable MatMul / Softmax ops (reference matmul.py:595, softmax.py:207)
# --------------------------------------------------------------------- #
class TestComposableSparseOps:

    def _setup(self, B=2, H=2, S=64, D=16, blk=16, seed=0):
        from deepspeed_tpu.ops.sparse_attention import MatMul, Softmax
        cfg = BSLongformerSparsityConfig(num_heads=H, block=blk,
                                         num_sliding_window_blocks=3)
        layout = cfg.make_layout(S)
        q, k, v = _rand_qkv(B, H, S, D, seed=seed)
        return MatMul, Softmax, layout, q, k, v, blk

    def test_sdd_softmax_dsd_pipeline_matches_reference(self):
        """The reference's own composition (sparse_self_attention.py:125:
        sdd_nt -> sparse softmax -> dsd_nn) must reproduce the fused
        oracle."""
        MatMul, Softmax, layout, q, k, v, blk = self._setup()
        D = q.shape[-1]
        sdd = MatMul(layout, blk, "sdd", trans_a=False, trans_b=True)
        dsd = MatMul(layout, blk, "dsd")
        sm = Softmax(layout, blk)
        scores = sdd(q, k)                       # (B, nnz, blk, blk)
        assert scores.shape[1] == int(layout.sum())
        probs = sm(scores, scale=float(D) ** -0.5)
        out = dsd(probs, v)
        ref = block_sparse_attention_reference(q, k, v, layout)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_softmax_masks_match_reference(self):
        MatMul, Softmax, layout, q, k, v, blk = self._setup(seed=3)
        B, H, S, D = q.shape
        sdd = MatMul(layout, blk, "sdd", trans_b=True)
        dsd = MatMul(layout, blk, "dsd")
        sm = Softmax(layout, blk)
        mrng = np.random.RandomState(5)
        kpm = (mrng.rand(B, S) > 0.25).astype(np.float32)   # mul-mode
        am = (mrng.rand(S, S) > 0.2).astype(np.float32)
        probs = sm(sdd(q, k), scale=float(D) ** -0.5,
                   key_padding_mask=jnp.asarray(kpm),
                   key_padding_mask_mode="mul",
                   attn_mask=jnp.asarray(am), attn_mask_mode="mul")
        out = dsd(probs, v)
        ref = block_sparse_attention_reference(
            q, k, v, layout, key_padding_mask=jnp.asarray(kpm),
            key_padding_mask_mode="mul", attn_mask=jnp.asarray(am),
            attn_mask_mode="mul")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_dds_matches_dense_masked(self):
        """dense x sparse: out == a @ (dense-masked b)."""
        from deepspeed_tpu.ops.sparse_attention import MatMul
        B, H, S, blk = 1, 2, 64, 16
        cfg = FixedSparsityConfig(num_heads=H, block=blk,
                                  num_local_blocks=2)
        layout = cfg.make_layout(S)
        rng = np.random.RandomState(7)
        a = jnp.asarray(rng.randn(B, H, 24, S), jnp.float32)
        dense_b = jnp.asarray(rng.randn(B, H, S, S), jnp.float32)
        # compress dense_b to the layout's nonzero blocks
        sdd_id = MatMul(layout, blk, "sdd")   # identity trick not needed:
        hs, rs, cs = np.nonzero(layout)
        bb = np.asarray(dense_b).reshape(B, H, S // blk, blk,
                                         S // blk, blk)
        b_sparse = jnp.asarray(
            bb.transpose(0, 1, 2, 4, 3, 5)[:, hs, rs, cs])
        out = MatMul(layout, blk, "dds")(a, b_sparse)
        mask = np.kron(np.asarray(layout, np.float32),
                       np.ones((blk, blk), np.float32))  # (H, S, S)
        ref = jnp.einsum("bhqk,bhkn->bhqn", a,
                         dense_b * jnp.asarray(mask)[None])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_sparse_ops_differentiable(self):
        MatMul, Softmax, layout, q, k, v, blk = self._setup(seed=9)
        D = q.shape[-1]
        sdd = MatMul(layout, blk, "sdd", trans_b=True)
        dsd = MatMul(layout, blk, "dsd")
        sm = Softmax(layout, blk)

        def loss(q, k, v):
            return jnp.sum(dsd(sm(sdd(q, k), scale=float(D) ** -0.5), v)
                           ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(block_sparse_attention_reference(
                q, k, v, layout) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a_, b_, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a_), np.asarray(b_),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"d{name}")


# --------------------------------------------------------------------- #
# coarse walk (layout coarsening through the streamed-mask channel)
# --------------------------------------------------------------------- #
def _run_coarse_case(S, fine_block, coarse, with_am, with_kpm, seed=11):
    """Run block_sparse_attention with _FORCE_COARSE_BLOCK=coarse (0 =
    off) and return (o, (dq, dk, dv)). Pins the LEGACY dispatch:
    _FORCE_COARSE_BLOCK only exists on the v2 coarse walk, which the
    unified masked kernel (the PR 11 default) would otherwise
    short-circuit — these tests guard the oracle path the legacy bench
    row still measures."""
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    B, H, D = 1, 2, 16
    cfg = BSLongformerSparsityConfig(num_heads=H, block=fine_block)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=seed)
    kw = {}
    if with_am:
        kw["attn_mask"] = jnp.asarray(
            (np.random.RandomState(5).rand(S, S) > 0.15).astype(np.float32))
        kw["attn_mask_mode"] = "mul"
    if with_kpm:
        kpm = np.zeros((B, S), np.float32)
        kpm[:, -fine_block:] = -1e9
        kw["key_padding_mask"] = jnp.asarray(kpm)
        kw["key_padding_mask_mode"] = "add"

    old = bs._FORCE_COARSE_BLOCK
    old_masked = bs.USE_MASKED_FLASH
    bs._FORCE_COARSE_BLOCK = coarse
    bs.USE_MASKED_FLASH = False
    bs._FN_CACHE.clear()
    try:
        def loss(q, k, v):
            return jnp.sum(block_sparse_attention(q, k, v, layout, **kw)
                           .astype(jnp.float32) ** 2)
        o = block_sparse_attention(q, k, v, layout, **kw)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return o, g
    finally:
        bs._FORCE_COARSE_BLOCK = old
        bs.USE_MASKED_FLASH = old_masked
        bs._FN_CACHE.clear()


@pytest.mark.slow
@pytest.mark.parametrize("fine_block,coarse", [(128, 256), (64, 256),
                                               (128, 512)])
@pytest.mark.parametrize("with_am", [False, True])
def test_coarse_walk_matches_fine(fine_block, coarse, with_am):
    """The coarsened walk (fine structure as streamed NEG_INF mask
    tiles) must reproduce the fine walk exactly: outputs and grads,
    with and without a user attention mask, including fine blocks < 128
    that previously had no streaming path at all."""
    S = 512
    o_c, g_c = _run_coarse_case(S, fine_block, coarse, with_am, True)
    o_f, g_f = _run_coarse_case(S, fine_block, 0, with_am, True)
    np.testing.assert_allclose(np.asarray(o_c), np.asarray(o_f),
                               atol=1e-5, rtol=1e-5)
    for a, b, name in zip(g_c, g_f, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name}")


@pytest.mark.slow
def test_coarse_walk_matches_dense_oracle():
    """Coarse walk vs the dense-masked oracle (not just the fine
    kernel), so an error shared by both kernel paths would still show."""
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    B, H, S, D = 1, 2, 512, 16
    cfg = BSLongformerSparsityConfig(num_heads=H, block=128)
    layout = cfg.make_layout(S)
    q, k, v = _rand_qkv(B, H, S, D, seed=3)
    old = bs._FORCE_COARSE_BLOCK
    old_masked = bs.USE_MASKED_FLASH
    bs._FORCE_COARSE_BLOCK = 256
    bs.USE_MASKED_FLASH = False          # the legacy coarse walk under test
    bs._FN_CACHE.clear()
    try:
        o = block_sparse_attention(q, k, v, layout)
    finally:
        bs._FORCE_COARSE_BLOCK = old
        bs.USE_MASKED_FLASH = old_masked
        bs._FN_CACHE.clear()
    ref = block_sparse_attention_reference(q, k, v, layout)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_coarse_index_structure():
    """build_coarse_index: content dedup collapses a banded layout to a
    handful of unique tiles; count_only matches the full build; per_coord
    keys split identical patterns at different coordinates."""
    from deepspeed_tpu.ops.sparse_attention.blocksparse_v2 import (
        build_coarse_index)
    H, fine_block, S = 2, 128, 4096
    cfg = BSLongformerSparsityConfig(num_heads=H, block=fine_block)
    layout = cfg.make_layout(S)

    coarse, tiles, csr, csc, qrows, kcols = build_coarse_index(
        layout, fine_block, 512, per_coord=False)
    nnz_c, n_unique = build_coarse_index(layout, fine_block, 512,
                                         per_coord=False, count_only=True)
    assert coarse.shape == (H, 8, 8)
    assert len(csr) == len(csc) == nnz_c == int(coarse.sum())
    assert tiles.shape[0] == n_unique
    # banded layout: content dedup far below one-tile-per-pair
    assert n_unique < nnz_c / 2
    # every fine nonzero is representable: expanding each unique tile's
    # valid (non-NEG_INF) positions reproduces exactly the fine layout
    f = 512 // fine_block
    item = 0
    for h in range(H):
        for R in range(coarse.shape[1]):
            for C in np.nonzero(coarse[h, R])[0]:
                bits = tiles[csr[item]][::fine_block, ::fine_block] == 0.0
                np.testing.assert_array_equal(
                    bits, layout[h, R * f:(R + 1) * f,
                                 C * f:(C + 1) * f].astype(bool))
                item += 1
    assert item == nnz_c
    _, _, csr_pc, _, _, _ = build_coarse_index(layout, fine_block, 512,
                                               per_coord=True)
    n_unique_pc = len(np.unique(csr_pc))
    assert n_unique_pc >= n_unique


def test_pick_coarse_block_model():
    """_pick_coarse_block: picks a coarse tile for a banded long-seq
    layout, honors the force flag and the tile-memory budget, and
    declines when the sequence does not divide."""
    from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
    cfg = BSLongformerSparsityConfig(num_heads=2, block=128)
    layout = cfg.make_layout(4096)
    picked = bs._pick_coarse_block(layout, 128, has_am=False)
    assert picked in (256, 512)

    old = bs._FORCE_COARSE_BLOCK
    try:
        bs._FORCE_COARSE_BLOCK = 0
        assert bs._pick_coarse_block(layout, 128, False) is None
        bs._FORCE_COARSE_BLOCK = 512
        assert bs._pick_coarse_block(layout, 128, False) == 512
    finally:
        bs._FORCE_COARSE_BLOCK = old

    # S=192 divides by neither 256 nor 512 -> no candidate
    small = cfg.make_layout(384)[:, :3, :3]   # (H, 3, 3) blocks, S=384
    assert bs._pick_coarse_block(small, 128, False) is None

    # budget: per-coord uniques at a huge budgetless layout would pass,
    # but a zero budget must refuse
    old_budget = bs._COARSE_TILE_BUDGET
    try:
        bs._COARSE_TILE_BUDGET = 0
        assert bs._pick_coarse_block(layout, 128, False) is None
    finally:
        bs._COARSE_TILE_BUDGET = old_budget


# --------------------------------------------------------------------- #
# JSON sub-config -> SparsityConfig (runtime/config.py get_sparse_attention
# produces the dict; the reference left this glue to its examples repo)
# --------------------------------------------------------------------- #
class TestSparsityConfigFromDict:

    def test_every_mode_builds_and_roundtrips_layout(self):
        from deepspeed_tpu.runtime.config import get_sparse_attention
        configs = [
            ({"mode": "dense"}, DenseSparsityConfig),
            ({"mode": "fixed", "block": 16, "num_local_blocks": 4,
              "num_global_blocks": 1,
              "different_layout_per_head": True,
              "num_different_global_patterns": 4},
             FixedSparsityConfig),
            ({"mode": "variable", "block": 16,
              "local_window_blocks": [2, 2],
              "global_block_indices": [0]}, VariableSparsityConfig),
            ({"mode": "bigbird", "block": 16, "num_random_blocks": 1,
              "num_sliding_window_blocks": 3}, BigBirdSparsityConfig),
            ({"mode": "bslongformer", "block": 16,
              "num_sliding_window_blocks": 3}, BSLongformerSparsityConfig),
        ]
        for raw, klass in configs:
            parsed = get_sparse_attention({"sparse_attention": raw})
            sc = sparsity_config_from_dict(parsed, num_heads=4)
            assert isinstance(sc, klass), (raw, type(sc))
            layout = sc.make_layout(256)
            assert layout.shape == (4, 256 // sc.block, 256 // sc.block)
            assert layout.sum() > 0

    def test_parsed_defaults_match_class_defaults(self):
        # a bare {"mode": "fixed"} through the config parser must build
        # the same layout as FixedSparsityConfig() defaults (block 16 is
        # the JSON schema default, reference constants.py:32)
        from deepspeed_tpu.runtime.config import get_sparse_attention
        parsed = get_sparse_attention({"sparse_attention": {"mode": "fixed"}})
        sc = sparsity_config_from_dict(parsed, num_heads=2)
        ref = FixedSparsityConfig(num_heads=2, block=16)
        np.testing.assert_array_equal(sc.make_layout(128), ref.make_layout(128))

    def test_none_passthrough_and_bad_mode(self):
        assert sparsity_config_from_dict(None, num_heads=2) is None
        with pytest.raises(ValueError, match="not in"):
            sparsity_config_from_dict({"mode": "nope"}, num_heads=2)
