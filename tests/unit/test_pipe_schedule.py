"""Pipeline schedule tests (mirrors reference tests/unit/test_pipe_schedule.py
— pure-CPU instruction-sequence assertions)."""

import pytest

from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass, DataParallelSchedule, ForwardPass, InferenceSchedule,
    LoadMicroBatch, OptimizerStep, RecvActivation, RecvGrad, ReduceGrads,
    ReduceTiedGrads, SendActivation, SendGrad, TrainSchedule)


def _cmds_of(sched, cls):
    out = []
    for tick, cmds in enumerate(sched):
        for c in cmds:
            if isinstance(c, cls):
                out.append((tick, c))
    return out


@pytest.mark.parametrize("micro_batches,stages", [(1, 1), (4, 2), (2, 4),
                                                  (8, 4), (3, 3)])
def test_train_schedule_complete(micro_batches, stages):
    """Every stage forwards and backwards every micro-batch exactly once,
    forward strictly before backward."""
    for stage in range(stages):
        sched = TrainSchedule(micro_batches, stages, stage)
        fwd = _cmds_of(sched, ForwardPass)
        bwd = _cmds_of(sched, BackwardPass)
        assert sorted(c.micro_batch_id for _, c in fwd) == \
            list(range(micro_batches))
        assert sorted(c.micro_batch_id for _, c in bwd) == \
            list(range(micro_batches))
        fwd_tick = {c.micro_batch_id: t for t, c in fwd}
        bwd_tick = {c.micro_batch_id: t for t, c in bwd}
        for m in range(micro_batches):
            assert fwd_tick[m] < bwd_tick[m]


@pytest.mark.parametrize("micro_batches,stages", [(4, 2), (2, 4), (8, 4)])
def test_train_schedule_dataflow(micro_batches, stages):
    """Cross-stage dependencies: stage s+1 forwards m only after stage s;
    stage s backwards m only after stage s+1."""
    fwd_tick = {}
    bwd_tick = {}
    for stage in range(stages):
        sched = TrainSchedule(micro_batches, stages, stage)
        for t, c in _cmds_of(sched, ForwardPass):
            fwd_tick[(stage, c.micro_batch_id)] = t
        for t, c in _cmds_of(sched, BackwardPass):
            bwd_tick[(stage, c.micro_batch_id)] = t
    for m in range(micro_batches):
        for s in range(stages - 1):
            assert fwd_tick[(s, m)] < fwd_tick[(s + 1, m)]
            assert bwd_tick[(s + 1, m)] < bwd_tick[(s, m)]
        # backward starts only after the last stage forwarded it
        assert fwd_tick[(stages - 1, m)] <= bwd_tick[(stages - 1, m)]


def test_train_schedule_tick_count():
    """Total ticks = 2*(M + S - 1) (reference schedule.py:192)."""
    for m, s in [(4, 2), (1, 4), (8, 8)]:
        sched = TrainSchedule(m, s, 0)
        assert len(list(sched.steps())) == 2 * (m + s - 1)


def test_train_schedule_sends_match_recvs():
    """SendActivation at stage s pairs with RecvActivation of the same
    micro-batch at stage s+1 (and SendGrad/RecvGrad mirrored)."""
    M, S = 4, 3
    scheds = [TrainSchedule(M, S, s) for s in range(S)]
    for s in range(S - 1):
        sends = {c.micro_batch_id for _, c in
                 _cmds_of(scheds[s], SendActivation)}
        recvs = {c.micro_batch_id for _, c in
                 _cmds_of(scheds[s + 1], RecvActivation)}
        assert sends == recvs == set(range(M))
        gsends = {c.micro_batch_id for _, c in
                  _cmds_of(scheds[s + 1], SendGrad)}
        grecvs = {c.micro_batch_id for _, c in
                  _cmds_of(scheds[s], RecvGrad)}
        assert gsends == grecvs == set(range(M))
    # boundary stages have no external comm
    assert not _cmds_of(scheds[0], RecvActivation)
    assert not _cmds_of(scheds[0], SendGrad)
    assert not _cmds_of(scheds[S - 1], SendActivation)
    assert not _cmds_of(scheds[S - 1], RecvGrad)


def test_train_schedule_no_slot_collision():
    """At most one ForwardPass and one BackwardPass per tick per stage."""
    for stage in range(4):
        sched = TrainSchedule(8, 4, stage)
        for cmds in sched:
            assert sum(isinstance(c, ForwardPass) for c in cmds) <= 1
            assert sum(isinstance(c, BackwardPass) for c in cmds) <= 1


def test_train_schedule_buffer_bound():
    """In-flight (forwarded, not yet backwarded) micro-batches never exceed
    num_pipe_buffers (reference schedule.py:243)."""
    M, S = 8, 4
    for stage in range(S):
        sched = TrainSchedule(M, S, stage)
        outstanding = 0
        peak = 0
        for cmds in sched:
            for c in cmds:
                if isinstance(c, ForwardPass):
                    outstanding += 1
                elif isinstance(c, BackwardPass):
                    outstanding -= 1
            peak = max(peak, outstanding)
        assert peak <= sched.num_pipe_buffers()
        # buffer ids stay in range
        for cmds in sched.steps():
            for c in cmds:
                if hasattr(c, "buffer_id"):
                    assert 0 <= c.buffer_id < sched.num_pipe_buffers()


def test_train_schedule_batch_boundary():
    """Last tick carries ReduceTiedGrads -> ReduceGrads -> OptimizerStep
    (reference schedule.py:230-236)."""
    sched = TrainSchedule(4, 2, 0)
    ticks = list(sched.steps())
    names = [type(c) for c in ticks[-1]]
    assert names[-3:] == [ReduceTiedGrads, ReduceGrads, OptimizerStep]
    for cmds in ticks[:-1]:
        assert not any(isinstance(c, OptimizerStep) for c in cmds)


def test_load_micro_batch_first_last_only():
    """Only first/last stages load data (reference pipe/engine.py:613-649)."""
    M, S = 4, 4
    for stage in range(S):
        sched = TrainSchedule(M, S, stage)
        loads = _cmds_of(sched, LoadMicroBatch)
        if stage in (0, S - 1):
            assert len(loads) == M
        else:
            assert not loads


@pytest.mark.parametrize("micro_batches,stages", [(4, 2), (2, 4)])
def test_inference_schedule(micro_batches, stages):
    """Forward-only wavefront, m at tick m+s, double-buffered
    (reference schedule.py:129-173)."""
    for stage in range(stages):
        sched = InferenceSchedule(micro_batches, stages, stage)
        assert sched.num_pipe_buffers() == 2
        ticks = list(sched.steps())
        assert len(ticks) == micro_batches + stages - 1
        fwd = _cmds_of(sched, ForwardPass)
        assert [c.micro_batch_id for _, c in fwd] == list(range(micro_batches))
        for t, c in fwd:
            assert t == c.micro_batch_id + stage
        assert not _cmds_of(sched, BackwardPass)


def test_data_parallel_schedule():
    sched = DataParallelSchedule(micro_batches=3, stages=1, stage_id=0)
    ticks = list(sched.steps())
    assert len(ticks) == 3
    assert sched.num_pipe_buffers() == 1
    last = [type(c) for c in ticks[-1]]
    assert ReduceGrads in last and OptimizerStep in last
    for cmds in ticks[:-1]:
        assert OptimizerStep not in [type(c) for c in cmds]


def test_instruction_repr_and_eq():
    a = ForwardPass(1, micro_batch_id=3)
    b = ForwardPass(1, micro_batch_id=3)
    c = ForwardPass(0, micro_batch_id=2)
    assert a == b and a != c
    assert "ForwardPass" in repr(a) and "micro_batch_id=3" in repr(a)
