"""CSR tensor tests (mirror reference tests/unit/test_csr.py: round-trip,
add; plus the TPU fixed-capacity in-jit path and sharded csr_allreduce)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.runtime import csr_tensor as csr


def _row_sparse(rows=16, dim=4, hot=(1, 5, 9), seed=0):
    rng = np.random.RandomState(seed)
    d = np.zeros((rows, dim), np.float32)
    for h in hot:
        d[h] = rng.randn(dim)
    return jnp.asarray(d)


def test_csr_tensor_roundtrip():
    dense = _row_sparse()
    t = csr.CSRTensor(dense)
    assert list(np.asarray(t.indices)) == [1, 5, 9]
    np.testing.assert_array_equal(np.asarray(t.to_dense()),
                                  np.asarray(dense))
    sparse_size, dense_size = t.sparse_size()
    assert dense_size == 64 and sparse_size == 3 + 12
    assert "reduction_factor" in str(t)


def test_csr_tensor_add_merges_duplicates():
    a = csr.CSRTensor(_row_sparse(hot=(1, 5)))
    b = csr.CSRTensor(_row_sparse(hot=(5, 9), seed=1))
    expected = np.asarray(a.to_dense()) + np.asarray(b.to_dense())
    a.add(b)
    np.testing.assert_allclose(np.asarray(a.to_dense()), expected, rtol=1e-6)


def test_dense_to_csr_fixed_capacity_jit():
    dense = _row_sparse()

    @jax.jit
    def roundtrip(d):
        idx, vals = csr.dense_to_csr(d, capacity=8)
        return csr.csr_to_dense(idx, vals, rows=d.shape[0])

    np.testing.assert_array_equal(np.asarray(roundtrip(dense)),
                                  np.asarray(dense))


def test_dense_to_csr_capacity_padding():
    dense = _row_sparse(hot=(0, 2))
    idx, vals = csr.dense_to_csr(dense, capacity=5)
    idx = np.asarray(idx)
    assert list(idx[:2]) == [0, 2]
    assert all(idx[2:] == 16)  # pad slots point one past the end
    np.testing.assert_array_equal(np.asarray(vals[2:]), 0.0)


def test_csr_allreduce_matches_dense_psum():
    """Each of 4 ranks contributes a different embedding grad; the CSR
    exchange must equal the dense sum."""
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    rows, dim, cap = 32, 8, 6
    rng = np.random.RandomState(0)
    dense = np.zeros((n, rows, dim), np.float32)
    for r in range(n):
        for h in rng.choice(rows, size=3, replace=False):
            dense[r, h] = rng.randn(dim)
    expected = dense.sum(axis=0)

    @jax.jit
    def run(d):
        def inner(d_local):
            idx, vals = csr.dense_to_csr(d_local[0], capacity=cap)
            out = csr.csr_allreduce(idx, vals, rows=rows, axis_name="data")
            return out[None]
        return shard_map(inner, mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"), check_vma=False)(d)

    out = np.asarray(run(jnp.asarray(dense)))
    for r in range(n):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-6)


def test_wire_volume_reduction():
    rows, dim, cap = 50000, 128, 512  # bert-ish vocab, batch-bounded rows
    dense_elems = rows * dim
    csr_elems = cap * (dim + 1)
    assert dense_elems / csr_elems > 90  # ~97x for this shape


def test_engine_accessor():
    import deepspeed_tpu as ds
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "sparse_gradients": True,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert engine.sparse_gradients_enabled()


# --------------------------------------------------------------------- #
# engine integration: sparse_gradients routes embedding grads through the
# CSR exchange inside the compiled step (reference engine.py:181-187,
# :1088-1139)
# --------------------------------------------------------------------- #

VOCAB, DIM, SEQ = 512, 8, 4


def _init_embed_params(key, vocab=VOCAB, dim=DIM):
    k1, k2 = jax.random.split(key)
    return {
        "embedding": jax.random.normal(k1, (vocab, dim), jnp.float32) * 0.1,
        "proj": {"w": jax.random.normal(k2, (dim, 1), jnp.float32)},
    }


def _embed_loss_fn(params, batch):
    x = params["embedding"][batch["ids"]]          # (B, T, D) gather
    x = jnp.mean(x, axis=1) @ params["proj"]["w"]  # (B, 1)
    return jnp.mean((x - batch["y"]) ** 2)


def _embed_batches(n, global_bs, seed=0):
    rng = np.random.RandomState(seed)
    return [{"ids": rng.randint(0, VOCAB, (global_bs, SEQ)).astype(np.int32),
             "y": rng.randn(global_bs, 1).astype(np.float32)}
            for _ in range(n)]


def _embed_engine(sparse, ga=1, loss_fn=None, seed=3):
    import deepspeed_tpu as ds
    params = _init_embed_params(jax.random.PRNGKey(seed))
    engine, *_ = ds.initialize(
        model=loss_fn or _embed_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": ga,
                "sparse_gradients": sparse,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    return engine


def test_engine_detects_embedding_leaves():
    e = _embed_engine(sparse=True)
    assert e._sparse_grad_paths == {"embedding"}
    e2 = _embed_engine(sparse=False)
    assert e2._sparse_grad_paths == set()


def test_engine_sparse_params_explicit_opt_in():
    """VERDICT r2 weak #5: sparse_gradients_params pins the CSR leaves
    explicitly, bypassing the name heuristic; unknown entries fail at
    init, not at runtime."""
    import deepspeed_tpu as ds
    params = _init_embed_params(jax.random.PRNGKey(3))
    cfg = {"train_micro_batch_size_per_gpu": 2,
           "sparse_gradients": True,
           "sparse_gradients_params": ["embedding"],
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    engine, *_ = ds.initialize(model=_embed_loss_fn,
                               model_parameters=params, config=dict(cfg))
    assert engine._sparse_grad_paths == {"embedding"}
    # a non-embedding-named leaf can be opted in explicitly too
    params2 = {"table": params["embedding"], "proj": params["proj"]}

    def loss2(p, batch):
        x = p["table"][batch["ids"]]
        x = jnp.mean(x, axis=1) @ p["proj"]["w"]
        return jnp.mean((x - batch["y"]) ** 2)

    cfg2 = dict(cfg)
    cfg2["sparse_gradients_params"] = ["table"]
    e2, *_ = ds.initialize(model=loss2, model_parameters=params2,
                           config=cfg2)
    assert e2._sparse_grad_paths == {"table"}
    # heuristic alone would find nothing for 'table'
    cfg3 = dict(cfg)
    cfg3.pop("sparse_gradients_params")
    e3, *_ = ds.initialize(model=loss2, model_parameters=params2,
                           config=cfg3)
    assert e3._sparse_grad_paths == set()
    # unknown entries fail loudly at init
    cfg4 = dict(cfg)
    cfg4["sparse_gradients_params"] = ["no_such_leaf"]
    with pytest.raises(ValueError, match="no_such_leaf"):
        ds.initialize(model=_embed_loss_fn,
                      model_parameters=_init_embed_params(
                          jax.random.PRNGKey(4)), config=cfg4)


@pytest.mark.parametrize("ga", [1, 2])
def test_sparse_updates_match_dense(ga):
    """CSR-exchanged training must produce numerically identical params to
    the dense GSPMD path (same capacity semantics as the reference's
    lossless variable-length gather)."""
    es = _embed_engine(sparse=True, ga=ga, seed=7)
    ed = _embed_engine(sparse=False, ga=ga, seed=7)
    bs = iter(_embed_batches(3 * ga, 16, seed=1))
    bd = iter(_embed_batches(3 * ga, 16, seed=1))
    for _ in range(3):
        ls = es.train_batch(bs)
        ld = ed.train_batch(bd)
        np.testing.assert_allclose(float(ls), float(ld), rtol=1e-5)
    for ks, kd in zip(jax.tree_util.tree_leaves(es.state.params),
                      jax.tree_util.tree_leaves(ed.state.params)):
        np.testing.assert_allclose(np.asarray(ks), np.asarray(kd),
                                   rtol=1e-5, atol=1e-6)
    assert not bool(es._csr_overflow)


def test_sparse_composes_with_zero2_and_bf16():
    """bf16 + ZeRO-2 + sparse_gradients: the compute-dtype cast runs
    inside the CSR shard_map path, where 'data' is a MANUAL axis — the
    ZeRO cast sharding-constraint must not be emitted there (round-5
    regression, same class as the quantized-path pin in
    test_quantized_allreduce.py)."""
    import deepspeed_tpu as ds
    params = _init_embed_params(jax.random.PRNGKey(5))
    engine, *_ = ds.initialize(
        model=_embed_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "sparse_gradients": True,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    losses = [float(engine.train_batch(iter(_embed_batches(2, 16, seed=0))))
              for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    assert not bool(engine._csr_overflow)


def test_sparse_overflow_flag_on_dense_embedding_grad(caplog):
    """A leaf named 'embedding' that receives DENSE grads (tied-head style
    regularizer touching every row) must trip the in-jit overflow flag and
    the loud boundary log (ADVICE r1: silent truncation)."""
    def tied_loss(params, batch):
        base = _embed_loss_fn(params, batch)
        return base + 1e-4 * jnp.sum(params["embedding"] ** 2)

    e = _embed_engine(sparse=True, loss_fn=tied_loss)
    e.train_batch(iter(_embed_batches(1, 16, seed=2)))
    assert bool(e._csr_overflow)
    assert e._csr_overflow_logged
