"""CSR tensor tests (mirror reference tests/unit/test_csr.py: round-trip,
add; plus the TPU fixed-capacity in-jit path and sharded csr_allreduce)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.runtime import csr_tensor as csr


def _row_sparse(rows=16, dim=4, hot=(1, 5, 9), seed=0):
    rng = np.random.RandomState(seed)
    d = np.zeros((rows, dim), np.float32)
    for h in hot:
        d[h] = rng.randn(dim)
    return jnp.asarray(d)


def test_csr_tensor_roundtrip():
    dense = _row_sparse()
    t = csr.CSRTensor(dense)
    assert list(np.asarray(t.indices)) == [1, 5, 9]
    np.testing.assert_array_equal(np.asarray(t.to_dense()),
                                  np.asarray(dense))
    sparse_size, dense_size = t.sparse_size()
    assert dense_size == 64 and sparse_size == 3 + 12
    assert "reduction_factor" in str(t)


def test_csr_tensor_add_merges_duplicates():
    a = csr.CSRTensor(_row_sparse(hot=(1, 5)))
    b = csr.CSRTensor(_row_sparse(hot=(5, 9), seed=1))
    expected = np.asarray(a.to_dense()) + np.asarray(b.to_dense())
    a.add(b)
    np.testing.assert_allclose(np.asarray(a.to_dense()), expected, rtol=1e-6)


def test_dense_to_csr_fixed_capacity_jit():
    dense = _row_sparse()

    @jax.jit
    def roundtrip(d):
        idx, vals = csr.dense_to_csr(d, capacity=8)
        return csr.csr_to_dense(idx, vals, rows=d.shape[0])

    np.testing.assert_array_equal(np.asarray(roundtrip(dense)),
                                  np.asarray(dense))


def test_dense_to_csr_capacity_padding():
    dense = _row_sparse(hot=(0, 2))
    idx, vals = csr.dense_to_csr(dense, capacity=5)
    idx = np.asarray(idx)
    assert list(idx[:2]) == [0, 2]
    assert all(idx[2:] == 16)  # pad slots point one past the end
    np.testing.assert_array_equal(np.asarray(vals[2:]), 0.0)


def test_csr_allreduce_matches_dense_psum():
    """Each of 4 ranks contributes a different embedding grad; the CSR
    exchange must equal the dense sum."""
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    rows, dim, cap = 32, 8, 6
    rng = np.random.RandomState(0)
    dense = np.zeros((n, rows, dim), np.float32)
    for r in range(n):
        for h in rng.choice(rows, size=3, replace=False):
            dense[r, h] = rng.randn(dim)
    expected = dense.sum(axis=0)

    @jax.jit
    def run(d):
        def inner(d_local):
            idx, vals = csr.dense_to_csr(d_local[0], capacity=cap)
            out = csr.csr_allreduce(idx, vals, rows=rows, axis_name="data")
            return out[None]
        return shard_map(inner, mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"), check_vma=False)(d)

    out = np.asarray(run(jnp.asarray(dense)))
    for r in range(n):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-6)


def test_wire_volume_reduction():
    rows, dim, cap = 50000, 128, 512  # bert-ish vocab, batch-bounded rows
    dense_elems = rows * dim
    csr_elems = cap * (dim + 1)
    assert dense_elems / csr_elems > 90  # ~97x for this shape


def test_engine_accessor():
    import deepspeed_tpu as ds
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "sparse_gradients": True,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert engine.sparse_gradients_enabled()
