"""Config system tests (mirrors reference tests/unit/test_config.py)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError


def base_dict(**kwargs):
    d = {"train_batch_size": 32}
    d.update(kwargs)
    return d


class TestBatchTriangle:

    def test_all_three_consistent(self):
        cfg = DeepSpeedConfig(
            {
                "train_batch_size": 32,
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 2,
            },
            world_size=4)
        assert cfg.train_batch_size == 32
        assert cfg.train_micro_batch_size_per_gpu == 4
        assert cfg.gradient_accumulation_steps == 2

    def test_all_three_inconsistent_raises(self):
        with pytest.raises(AssertionError):
            DeepSpeedConfig(
                {
                    "train_batch_size": 32,
                    "train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 4,
                },
                world_size=4)

    def test_derive_grad_acc(self):
        cfg = DeepSpeedConfig(
            {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4},
            world_size=4)
        assert cfg.gradient_accumulation_steps == 2

    def test_derive_micro_batch(self):
        cfg = DeepSpeedConfig(
            {"train_batch_size": 32, "gradient_accumulation_steps": 2},
            world_size=4)
        assert cfg.train_micro_batch_size_per_gpu == 4

    def test_derive_train_batch(self):
        cfg = DeepSpeedConfig(
            {"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 2},
            world_size=4)
        assert cfg.train_batch_size == 32

    def test_only_train_batch(self):
        cfg = DeepSpeedConfig({"train_batch_size": 32}, world_size=4)
        assert cfg.train_micro_batch_size_per_gpu == 8
        assert cfg.gradient_accumulation_steps == 1

    def test_only_micro_batch(self):
        cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4}, world_size=4)
        assert cfg.train_batch_size == 16
        assert cfg.gradient_accumulation_steps == 1

    def test_none_raises(self):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"steps_per_print": 10}, world_size=4)

    def test_chip_spelling(self):
        cfg = DeepSpeedConfig({"train_micro_batch_size_per_chip": 4}, world_size=2)
        assert cfg.train_batch_size == 8


class TestFeatureConfigs:

    def test_defaults(self):
        cfg = DeepSpeedConfig(base_dict(), world_size=1)
        assert not cfg.fp16_enabled
        assert not cfg.bf16_enabled
        assert cfg.zero_optimization_stage == 0
        assert not cfg.zero_enabled
        assert cfg.gradient_clipping == 0.0
        assert cfg.steps_per_print == 10
        assert cfg.prescale_gradients is False
        assert cfg.optimizer_name is None
        assert cfg.scheduler_name is None

    def test_fp16(self):
        cfg = DeepSpeedConfig(
            base_dict(fp16={
                "enabled": True,
                "loss_scale": 0,
                "initial_scale_power": 16,
                "loss_scale_window": 500,
                "hysteresis": 2,
                "min_loss_scale": 1,
            }),
            world_size=1)
        assert cfg.fp16_enabled
        assert cfg.loss_scale == 0
        assert cfg.initial_dynamic_scale == 2**16
        assert cfg.dynamic_loss_scale_args["scale_window"] == 500

    def test_bf16(self):
        cfg = DeepSpeedConfig(base_dict(bf16={"enabled": True}), world_size=1)
        assert cfg.bf16_enabled

    def test_fp16_and_bf16_conflict(self):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(
                base_dict(fp16={"enabled": True}, bf16={"enabled": True}),
                world_size=1)

    def test_zero_stage2(self):
        cfg = DeepSpeedConfig(
            base_dict(zero_optimization={
                "stage": 2,
                "cpu_offload": True,
                "overlap_comm": True,
            }),
            world_size=1)
        assert cfg.zero_enabled
        assert cfg.zero_optimization_stage == 2
        assert cfg.zero_config.cpu_offload
        assert cfg.zero_config.overlap_comm
        assert cfg.zero_config.reduce_scatter

    def test_zero_legacy_bool(self):
        cfg = DeepSpeedConfig(base_dict(zero_optimization=True), world_size=1)
        assert cfg.zero_optimization_stage == 1

    def test_optimizer_scheduler(self):
        cfg = DeepSpeedConfig(
            base_dict(
                optimizer={"type": "Adam", "params": {"lr": 1e-3}},
                scheduler={"type": "WarmupLR",
                           "params": {"warmup_num_steps": 10}},
            ),
            world_size=1)
        assert cfg.optimizer_name == "adam"
        assert cfg.optimizer_params["lr"] == 1e-3
        assert cfg.scheduler_name == "WarmupLR"
        assert cfg.scheduler_params["warmup_num_steps"] == 10

    def test_sparse_attention_fixed(self):
        cfg = DeepSpeedConfig(
            base_dict(sparse_attention={
                "mode": "fixed",
                "block": 16,
                "num_local_blocks": 4,
                "num_global_blocks": 1,
            }),
            world_size=1)
        sa = cfg.sparse_attention
        assert sa["mode"] == "fixed"
        assert sa["block"] == 16
        assert sa["num_local_blocks"] == 4

    def test_sparse_attention_bigbird(self):
        cfg = DeepSpeedConfig(
            base_dict(sparse_attention={"mode": "bigbird", "num_random_blocks": 2}),
            world_size=1)
        assert cfg.sparse_attention["num_random_blocks"] == 2

    def test_sparse_attention_bad_mode(self):
        with pytest.raises(NotImplementedError):
            DeepSpeedConfig(
                base_dict(sparse_attention={"mode": "nope"}), world_size=1)

    def test_activation_checkpointing(self):
        cfg = DeepSpeedConfig(
            base_dict(activation_checkpointing={
                "partition_activations": True,
                "cpu_checkpointing": True,
                "number_checkpoints": 4,
            }),
            world_size=1)
        acc = cfg.activation_checkpointing_config
        assert acc.partition_activations
        assert acc.cpu_checkpointing
        assert acc.number_checkpoints == 4

    def test_pipeline_config(self):
        cfg = DeepSpeedConfig(
            base_dict(pipeline={"stages": 4, "partition": "parameters"}),
            world_size=1)
        assert cfg.pipeline["stages"] == 4
        assert cfg.pipeline["partition"] == "parameters"
        assert cfg.pipeline["seed_layers"] is False

    def test_mesh_axes(self):
        cfg = DeepSpeedConfig(
            base_dict(mesh={"axes": {"data": 4, "model": 2}}), world_size=1)
        assert cfg.mesh_axes == {"data": 4, "model": 2}

    def test_json_file_and_duplicate_keys(self, tmp_path):
        p = tmp_path / "ds_config.json"
        p.write_text(json.dumps(base_dict()))
        cfg = DeepSpeedConfig(str(p), world_size=1)
        assert cfg.train_batch_size == 32

        bad = tmp_path / "dup.json"
        bad.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
        with pytest.raises(ValueError):
            DeepSpeedConfig(str(bad), world_size=1)


class TestCompileCache:
    def test_defaults_and_override(self):
        cfg = DeepSpeedConfig(base_dict(), world_size=1)
        assert cfg.compile_cache_config["enabled"] is True
        assert cfg.compile_cache_config["dir"].endswith("xla_cache")
        cfg = DeepSpeedConfig(
            base_dict(compile_cache={"enabled": False, "dir": "/tmp/x",
                                     "min_compile_secs": 0.0}),
            world_size=1)
        assert cfg.compile_cache_config == {
            "enabled": False, "dir": "/tmp/x", "min_compile_secs": 0.0}

    def test_enable_populates_cache_dir(self, tmp_path, monkeypatch):
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.utils import platform as P
        monkeypatch.setattr(P, "_CACHE_ENABLED_DIR", None)
        prev = jax.config.jax_compilation_cache_dir
        prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs

        def _reset_jax_cache():
            # jax initializes its cache object once; a dir change after
            # another test compiled (e.g. engine default cache) would be
            # ignored without this
            try:
                from jax._src import compilation_cache
                compilation_cache.reset_cache()
            except (ImportError, AttributeError):
                pass

        _reset_jax_cache()
        try:
            assert P.enable_compile_cache(str(tmp_path),
                                          min_compile_secs=0.0)
            # second call, different dir: refused (global setting)
            assert not P.enable_compile_cache(str(tmp_path / "other"))
            assert P.enable_compile_cache(str(tmp_path))
            jax.jit(lambda x: jnp.sin(x) * 41.2512)(jnp.ones((8, 8)))
            import os
            assert os.listdir(str(tmp_path)), "no cache entry written"
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              prev_secs)
            _reset_jax_cache()
