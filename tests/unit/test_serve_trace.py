"""Request-granular serving observability (ISSUE 9).

Tier-1 pins:
- the full request lifecycle event trail (serve_submit -> serve_defer*
  -> serve_prefix_hit? -> serve_admit -> serve_prefill ->
  serve_first_token -> serve_decode_window* -> serve_finish/serve_evict)
  with PINNED per-event required fields, per-uid ordering, and the
  defer-reason vocabulary, under a mixed-length continuous-batching
  workload;
- ``ttft_ms`` is null — never 0.0 — for requests evicted before their
  first token (engine + scheduler paths);
- SLO/goodput accounting: attainment and goodput are distinct from raw
  throughput and land as ``Serve/*`` scalars;
- events.jsonl size rotation: atomic segment rollover, obs_report reads
  segments back in order;
- ``engine.debug_state()`` live introspection (pool, prefix cache,
  slots, queue-by-bucket, per-program dispatches);
- tracing is free at the dispatch level: warmup program set, dispatch
  counts, and steady-state recompiles are IDENTICAL with tracing on
  (the ``serve_trace_overhead`` bench row's tier-1 shadow);
- obs_report ``--serve`` CLI + the versioned ``--json`` schema.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    cfg = GPT2Config(vocab_size=61, max_position_embeddings=32,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))


TINY_INF = {"max_batch_size": 3, "prompt_buckets": [4, 8],
            "batch_buckets": [1, 2], "max_seq_len": 32,
            "max_new_tokens": 4}

# the pinned event schema: required fields per lifecycle event kind
# (docs/observability.md "Serving tracing & SLOs"); extra fields may be
# added, these may not be dropped or renamed
TRAIL_SCHEMA = {
    "serve_submit": {"uid", "prompt_tokens", "max_new_tokens"},
    "serve_defer": {"uid", "reason"},
    "serve_prefix_hit": {"uid", "tokens", "pages"},
    "serve_admit": {"uid", "slot", "queue_wait_ms", "prefix_tokens",
                    "prompt_bucket", "batch_bucket"},
    "serve_prefill": {"uid", "slot", "wall_ms", "prompt_bucket",
                      "batch_bucket", "rows"},
    "serve_first_token": {"uid", "ttft_ms", "prefill_ms"},
    "serve_handoff": {"uid", "mode", "queue_ms", "transfer_ms",
                      "handoff_ms", "pages", "bytes_moved"},
    "serve_spec_window": {"uid", "proposed", "accepted", "dispatches",
                          "accept_rate"},
    # chunked prefill (ISSUE 19): one row per chunk dispatch — chunk
    # ordinal, tokens scattered, wall and cumulative prefill ms
    "serve_prefill_chunk": {"uid", "slot", "chunk", "tokens",
                            "wall_ms", "cum_ms"},
    "serve_decode_window": {"uid", "tokens", "end_token", "window_ms",
                            "tbt_ms"},
    "serve_finish": {"uid", "reason", "new_tokens", "ttft_ms",
                     "latency_ms", "queue_wait_ms", "prefill_ms",
                     "tbt_ms", "tbt_ms_max", "slo_ok"},
    "serve_evict": {"uid", "reason", "new_tokens", "ttft_ms",
                    "latency_ms"},
    # fleet tracing (ISSUE 18): migration lineage rows — emitted by
    # the source at export and the destination at import, sharing the
    # request's trace id so the merged timeline survives replica death
    "serve_migrate_out": {"uid", "position", "pages", "nbytes",
                          "reason"},
    "serve_migrate_in": {"uid", "position", "pages", "nbytes",
                         "resumed_tokens"},
}
TRAIL_KINDS = set(TRAIL_SCHEMA)


def read_rows(tmp_path):
    rows = []
    obs_report = _load_tool("obs_report")
    for seg in obs_report.segment_files(
            os.path.join(str(tmp_path), "events.jsonl")):
        if os.path.exists(seg):
            rows += [json.loads(line) for line in open(seg)]
    return rows


def trail_of(rows, uid):
    """(index, row) of every lifecycle event for one request, in file
    order."""
    return [(i, r) for i, r in enumerate(rows)
            if r.get("event") in TRAIL_KINDS and r.get("uid") == uid]


# --------------------------------------------------------------------- #
# bounded histogram sink (utils/monitor.py)
# --------------------------------------------------------------------- #
class TestHistogram:
    def test_percentiles_and_exact_extremes(self):
        from deepspeed_tpu.utils.monitor import Histogram
        h = Histogram()
        for v in range(1, 101):
            h.record(float(v))
        assert h.count == 100 and h.min == 1.0 and h.max == 100.0
        assert h.percentile(0.0) == 1.0 and h.percentile(1.0) == 100.0
        # log-bucketed: one bucket width (~7.5%) of relative error
        assert abs(h.percentile(0.50) - 50) / 50 < 0.10
        assert abs(h.percentile(0.95) - 95) / 95 < 0.10
        assert abs(h.mean - 50.5) < 1e-9

    def test_bounded_buckets(self):
        from deepspeed_tpu.utils.monitor import Histogram
        h = Histogram()
        rng = np.random.RandomState(0)
        for v in rng.lognormal(3.0, 2.0, size=20_000):
            h.record(float(v))
        # millions of samples may land, bucket count stays O(range)
        assert len(h._buckets) < 400
        assert h.count == 20_000

    def test_snapshot_and_degenerate(self):
        from deepspeed_tpu.utils.monitor import Histogram
        h = Histogram()
        assert h.percentile(0.5) is None
        snap = h.snapshot()
        assert snap["count"] == 0 and snap["p99"] is None
        h.record(5.0)
        snap = h.snapshot()
        assert snap["count"] == 1
        assert snap["p50"] == snap["p99"] == 5.0
        h.record(float("nan"))           # non-finite samples are dropped
        assert h.count == 1


# --------------------------------------------------------------------- #
# events.jsonl size rotation (utils/monitor._JsonlWriter)
# --------------------------------------------------------------------- #
class TestEventLogRotation:
    def test_rotates_and_reads_back_in_order(self, tmp_path):
        from deepspeed_tpu.utils.monitor import _JsonlWriter
        w = _JsonlWriter(str(tmp_path), max_mb=0.001)       # ~1 KiB cap
        for step in range(200):
            w.add_scalar("T/x", float(step), step)
        w.close()
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("events.jsonl."))
        assert len(segs) >= 2, "cap of ~1 KiB must have rotated"
        for seg in segs:
            assert os.path.getsize(tmp_path / seg) >= 1024
        # obs_report folds segments + live file into ONE ordered stream
        obs_report = _load_tool("obs_report")
        scalars, _ = obs_report.load_events(
            str(tmp_path / "events.jsonl"))
        steps = [s for s, _ in scalars["T/x"]]
        assert steps == list(range(200))

    def test_reopen_resumes_sequence(self, tmp_path):
        from deepspeed_tpu.utils.monitor import _JsonlWriter
        w = _JsonlWriter(str(tmp_path), max_mb=0.001)
        for step in range(100):
            w.add_scalar("T/x", float(step), step)
        w.close()
        n1 = len([p for p in os.listdir(tmp_path)
                  if p.startswith("events.jsonl.")])
        # a restarted process must not overwrite existing segments
        w = _JsonlWriter(str(tmp_path), max_mb=0.001)
        for step in range(100, 200):
            w.add_scalar("T/x", float(step), step)
        w.close()
        n2 = len([p for p in os.listdir(tmp_path)
                  if p.startswith("events.jsonl.")])
        assert n2 > n1
        obs_report = _load_tool("obs_report")
        scalars, _ = obs_report.load_events(
            str(tmp_path / "events.jsonl"))
        assert [s for s, _ in scalars["T/x"]] == list(range(200))

    def test_rotation_off_by_default(self, tmp_path):
        from deepspeed_tpu.utils.monitor import _JsonlWriter
        w = _JsonlWriter(str(tmp_path))
        for step in range(200):
            w.add_scalar("T/x", float(step), step)
        w.close()
        assert [p for p in os.listdir(tmp_path)
                if p.startswith("events.jsonl.")] == []


# --------------------------------------------------------------------- #
# ServeTracer unit (jax-free, fake clock + captured writer)
# --------------------------------------------------------------------- #
class _CapWriter:
    def __init__(self):
        self.rows = []

    def add_event(self, kind, **fields):
        self.rows.append(dict(fields, event=kind))


class TestServeTracerUnit:
    def _tracer(self, **cfg):
        from deepspeed_tpu.inference.tracing import ServeTracer
        t = [0.0]
        base = {"enabled": True, "sample_rate": 0.5,
                "slo": {"ttft_ms": 100.0, "tbt_ms": 50.0}}
        base.update(cfg)
        w = _CapWriter()
        tr = ServeTracer(base, writer=w, clock=lambda: t[0])
        return tr, w, t

    def test_defer_dedupe_and_reset_on_admit(self):
        tr, w, _t = self._tracer()
        tr.on_submit(7, 4, 8)
        for _ in range(5):
            tr.on_defer(7, "pages")
        tr.on_defer(7, "bucket")
        assert [r["reason"] for r in w.rows
                if r["event"] == "serve_defer"] == ["pages", "bucket"]
        tr.on_admit(7, 0, 3.0, 0, 4, 2)
        tr.on_defer(7, "pages")          # a fresh cycle may defer again
        assert sum(1 for r in w.rows
                   if r["event"] == "serve_defer") == 3

    def test_decode_window_stride(self):
        tr, w, t = self._tracer(sample_rate=0.5)      # window = 2 tokens
        tr.on_submit(1, 4, 16)
        tr.on_admit(1, 0, 1.0, 0, 4, 1)
        tr.on_first_token(1, 5.0)
        for i in range(9):
            t[0] += 0.002
            tr.on_token(1)
        wins = [r for r in w.rows if r["event"] == "serve_decode_window"]
        # 10 tokens at stride 2 -> windows close at token 2,4,6,8,10
        assert len(wins) == 5
        assert wins[0]["tokens"] == 2 and wins[-1]["end_token"] == 10
        for r in wins:
            assert r["tbt_ms"] == pytest.approx(2.0, rel=0.25)

    def test_slo_classification_and_goodput(self):
        tr, w, t = self._tracer()
        from deepspeed_tpu.inference.scheduler import FinishedRequest

        def fin(uid, ttft, n=4):
            return FinishedRequest(uid=uid, prompt=[1], tokens=[0] * n,
                                   finish_reason="length", ttft_ms=ttft,
                                   latency_ms=50.0, queue_wait_ms=1.0)
        tr.on_submit(1, 1, 4)
        tr.on_admit(1, 0, 1.0, 0, 4, 1)
        tr.on_finish(fin(1, ttft=10.0))               # within SLO
        tr.on_submit(2, 1, 4)
        tr.on_admit(2, 0, 1.0, 0, 4, 1)
        tr.on_finish(fin(2, ttft=500.0))              # TTFT breach
        tr.on_submit(3, 1, 4)
        tr.on_finish(fin(3, ttft=None, n=0), evicted=True)
        assert tr.finished == 3 and tr.evicted == 1
        assert tr.finished_in_slo == 1
        assert tr.slo_attainment == pytest.approx(1 / 3)
        assert tr.good_tokens == 4 and tr.finished_tokens == 8
        oks = {r["uid"]: r["slo_ok"] for r in w.rows
               if r["event"] == "serve_finish"}
        assert oks == {1: True, 2: False}
        ev = [r for r in w.rows if r["event"] == "serve_evict"]
        assert len(ev) == 1 and ev[0]["ttft_ms"] is None

    def test_disabled_tracer_still_emits_legacy_finish(self):
        from deepspeed_tpu.inference.scheduler import FinishedRequest
        from deepspeed_tpu.inference.tracing import ServeTracer
        w = _CapWriter()
        tr = ServeTracer({"enabled": False}, writer=w)
        tr.on_submit(1, 4, 8)
        tr.on_admit(1, 0, 1.0, 0, 4, 1)
        tr.on_token(1)
        assert w.rows == []               # every non-terminal hook no-ops
        tr.on_finish(FinishedRequest(
            uid=1, prompt=[1], tokens=[], finish_reason="evicted",
            ttft_ms=None, latency_ms=3.0), evicted=True)
        assert len(w.rows) == 1
        row = w.rows[0]
        assert row["event"] == "serve_evict"
        assert row["ttft_ms"] is None            # null, never 0.0

    def test_snapshot_histograms(self):
        tr, _w, t = self._tracer()
        tr.on_submit(1, 4, 8)
        tr.on_admit(1, 0, 2.0, 0, 4, 1)
        tr.on_first_token(1, 6.0)
        t[0] += 0.004
        tr.on_token(1)
        snap = tr.snapshot()
        assert snap["slo"] == {"ttft_ms": 100.0, "tbt_ms": 50.0}
        assert snap["latency"]["queue_wait_ms"]["count"] == 1
        assert snap["latency"]["ttft_ms"]["p50"] == pytest.approx(
            6.0, rel=0.10)
        assert snap["latency"]["tbt_ms"]["count"] == 1
        assert snap["in_flight"] == 1


# --------------------------------------------------------------------- #
# scheduler-side decomposition + eviction
# --------------------------------------------------------------------- #
class TestSchedulerDecomposition:
    def _sched(self, clock, **kw):
        from deepspeed_tpu.inference.scheduler import Scheduler
        return Scheduler(3, (4, 8), (1, 2), 32, clock=clock, **kw)

    def test_queue_wait_measured_and_drained(self):
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        s = self._sched(lambda: t[0])
        s.submit(Request(prompt=[1, 2], max_new_tokens=4))
        t[0] = 0.25                       # 250 ms in queue
        batches = s.admit()
        assert len(batches) == 1
        waits = s.drain_queue_waits()
        assert waits == [pytest.approx(250.0)]
        assert s.drain_queue_waits() == []
        t[0] = 0.30
        fins = s.record_tokens({batches[0].slot_ids[0]: 5})
        t[0] = 0.35
        for _ in range(3):
            fins += s.record_tokens({batches[0].slot_ids[0]: 5})
        assert fins and fins[0].finish_reason == "length"
        assert fins[0].queue_wait_ms == pytest.approx(250.0)
        assert fins[0].ttft_ms == pytest.approx(300.0)

    def test_evict_from_queue_has_null_ttft(self):
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        s = self._sched(lambda: t[0])
        uid = s.submit(Request(prompt=[1, 2]))
        t[0] = 0.1
        fin = s.evict(uid)
        assert fin is not None
        assert fin.ttft_ms is None and fin.queue_wait_ms is None
        assert fin.finish_reason == "evicted" and fin.tokens == []
        assert fin.latency_ms == pytest.approx(100.0)
        assert s.idle()
        assert s.evict(uid) is None       # already gone

    def test_evict_in_flight_frees_slot_and_pages(self):
        from deepspeed_tpu.inference.paging import PageAllocator
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        alloc = PageAllocator(9, 4)
        s = self._sched(lambda: t[0], allocator=alloc)
        uid = s.submit(Request(prompt=[1, 2, 3], max_new_tokens=4))
        batches = s.admit()
        s.record_tokens({batches[0].slot_ids[0]: 5})
        assert alloc.pages_in_use > 0
        fin = s.evict(uid)
        assert fin.finish_reason == "evicted"
        assert fin.ttft_ms is not None and len(fin.tokens) == 1
        assert alloc.pages_in_use == 0
        assert s.free_slots() == [0, 1, 2]

    def test_evict_admitted_before_first_token_is_null(self):
        """The FinishedRequest.ttft_ms-is-None path: admitted (slot
        held, queue_wait known) but evicted before any token."""
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        s = self._sched(lambda: t[0])
        uid = s.submit(Request(prompt=[1, 2]))
        t[0] = 0.05
        s.admit()
        fin = s.evict(uid)
        assert fin.ttft_ms is None
        assert fin.queue_wait_ms == pytest.approx(50.0)

    def test_queue_by_bucket(self):
        from deepspeed_tpu.inference.scheduler import Request
        t = [0.0]
        s = self._sched(lambda: t[0])
        for plen in (2, 3, 7, 8, 4):
            s.submit(Request(prompt=list(range(1, plen + 1))))
        assert s.queue_by_bucket() == {4: 3, 8: 2}


# --------------------------------------------------------------------- #
# the pinned lifecycle trail (engine level, mixed-length workload)
# --------------------------------------------------------------------- #
class TestLifecycleTrail:
    @pytest.fixture(scope="class")
    def trail_run(self, tmp_path_factory):
        """One mixed-length continuous-batching run, paged engine with
        a page pool small enough to starve admission (forcing pages +
        lookahead defers), two prompt buckets (forcing bucket defers),
        prefix reuse, and per-token decode windows."""
        from deepspeed_tpu.inference import InferenceEngine
        tmp = tmp_path_factory.mktemp("trail")
        cfg, params = tiny_gpt2()
        icfg = dict(TINY_INF, events_dir=str(tmp), admit_lookahead=0,
                    max_new_tokens=3,
                    paged_kv={"page_size": 4, "num_pages": 5})
        eng = InferenceEngine(
            cfg, params, icfg, dtype=jnp.float32,
            observability_config={"serve": {"sample_rate": 1.0}})
        eng.warmup()
        # pool = 4 usable pages. First admit pass: head [1,2,3,4,16]
        # (2 pages); [1,2,3,4,17] shares its full first page ->
        # serve_prefix_hit + same-batch admit (1 shared + 1 fresh
        # page, 1-token suffix). Next pass: the len-7 head needs 3
        # pages but only 1 is free -> "pages", and with lookahead=0
        # whatever sits behind it isn't even scanned -> "lookahead";
        # once it does land (bucket 8), the short bucket-4 prompts
        # behind it defer "bucket" before getting their own batches.
        prompts = [[1, 2, 3, 4, 16], [1, 2, 3, 4, 17],
                   [4, 5, 6, 7, 8, 9, 10], [11, 12],
                   [13, 14, 15], [17, 18, 19]]
        uids = [eng.submit(__import__(
            "deepspeed_tpu.inference.scheduler",
            fromlist=["Request"]).Request(
                prompt=p, max_new_tokens=3, seed=i))
            for i, p in enumerate(prompts)]
        eng.run()
        state = eng.debug_state()
        eng.close()
        return read_rows(tmp), uids, prompts, state, str(tmp)

    def test_every_request_has_a_complete_ordered_trail(self, trail_run):
        rows, uids, prompts, _state, _d = trail_run
        for uid, prompt in zip(uids, prompts):
            trail = trail_of(rows, uid)
            kinds = [r["event"] for _, r in trail]
            assert kinds[0] == "serve_submit", kinds
            assert kinds[-1] == "serve_finish", kinds
            # strict per-request phase ordering by file position
            pos = {k: i for i, (_, r) in enumerate(trail)
                   for k in [r["event"]] if k != "serve_defer"}
            for a, b in [("serve_submit", "serve_admit"),
                         ("serve_admit", "serve_prefill"),
                         ("serve_prefill", "serve_first_token"),
                         ("serve_first_token", "serve_finish")]:
                assert pos[a] < pos[b], (uid, kinds)
            # defers (if any) happen strictly between submit and admit
            for i, (_, r) in enumerate(trail):
                if r["event"] == "serve_defer":
                    assert pos["serve_submit"] < i < pos["serve_admit"]
            # decode windows live between first token and finish
            for i, (_, r) in enumerate(trail):
                if r["event"] == "serve_decode_window":
                    assert pos["serve_first_token"] < i \
                        < pos["serve_finish"]

    def test_pinned_event_schema(self, trail_run):
        rows, _uids, _prompts, _state, _d = trail_run
        seen = set()
        for r in rows:
            kind = r.get("event")
            if kind in TRAIL_SCHEMA:
                seen.add(kind)
                missing = TRAIL_SCHEMA[kind] - set(r)
                assert not missing, (kind, missing)
        assert {"serve_submit", "serve_defer", "serve_admit",
                "serve_prefill", "serve_first_token",
                "serve_decode_window", "serve_finish"} <= seen

    def test_no_schema_drift_every_tracer_kind_is_renderable(self):
        """Structural version of the PR 13 ``serve_handoff`` near-miss:
        every event kind the tracer can emit must (a) have a pinned
        TRAIL_SCHEMA entry and (b) have a fold handler in the
        obs_report fleet merger — a new trail row that the merged
        report would silently drop fails here, not in production."""
        from deepspeed_tpu.inference.tracing import ServeTracer
        obs_report = _load_tool("obs_report")
        kinds = set(ServeTracer.EVENT_KINDS)
        assert kinds == set(TRAIL_SCHEMA), (
            "tracer kinds and TRAIL_SCHEMA diverged",
            kinds ^ set(TRAIL_SCHEMA))
        unrendered = kinds - set(obs_report.EVENT_HANDLERS)
        assert not unrendered, (
            "tracer kinds with no obs_report fleet handler",
            unrendered)

    def test_defer_reasons_pinned_and_exercised(self, trail_run):
        from deepspeed_tpu.inference.tracing import DEFER_REASONS
        rows, _uids, _prompts, _state, _d = trail_run
        reasons = {r["reason"] for r in rows
                   if r.get("event") == "serve_defer"}
        assert reasons <= set(DEFER_REASONS)
        # the starved pool forces page defers; lookahead=0 plus a
        # queue behind a stuck head forces lookahead defers
        assert "pages" in reasons
        assert "lookahead" in reasons

    def test_bucket_defer_under_mixed_buckets(self, tmp_path):
        """A ride-along candidate in a different prompt bucket defers
        with reason 'bucket' (and is admitted in the same admit pass
        as its own head)."""
        from deepspeed_tpu.inference import InferenceEngine, Request
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(
            cfg, params, dict(TINY_INF, events_dir=str(tmp_path),
                              max_new_tokens=2),
            dtype=jnp.float32)
        eng.warmup()
        eng.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
        eng.submit(Request(prompt=[4, 5, 6, 7, 8, 9], max_new_tokens=2))
        eng.submit(Request(prompt=[7, 8], max_new_tokens=2))
        eng.run()
        eng.close()
        rows = read_rows(tmp_path)
        defers = [r for r in rows if r.get("event") == "serve_defer"]
        assert any(r["reason"] == "bucket" for r in defers)
        # ...and everything still finished
        assert sum(1 for r in rows
                   if r.get("event") == "serve_finish") == 3

    def test_prefix_hit_in_trail(self, trail_run):
        rows, uids, _prompts, _state, _d = trail_run
        hits = [r for r in rows if r.get("event") == "serve_prefix_hit"]
        assert hits, "page-aligned shared prefix must produce a hit row"
        assert all(r["tokens"] >= 1 and r["pages"] >= 1 for r in hits)
        assert any(r["uid"] == uids[1] for r in hits)

    def test_finish_decomposition_adds_up(self, trail_run):
        rows, _uids, _prompts, _state, _d = trail_run
        for r in rows:
            if r.get("event") != "serve_finish":
                continue
            assert r["ttft_ms"] is not None
            assert r["queue_wait_ms"] is not None
            # ttft = queue_wait + prefill (same clock, exact by
            # construction up to rounding)
            assert r["ttft_ms"] == pytest.approx(
                r["queue_wait_ms"] + r["prefill_ms"], abs=0.01)
            assert r["latency_ms"] >= r["ttft_ms"] - 0.01

    def test_debug_state_snapshot(self, trail_run):
        _rows, _uids, _prompts, state, _d = trail_run
        assert state["steady_state_recompiles"] == 0
        assert state["queue_depth"] == 0 and state["slots"] == []
        assert state["programs"]["prefill"]["dispatches"] >= 1
        assert state["programs"]["decode"]["dispatches"] >= 1
        pool = state["page_pool"]
        assert pool["pages_in_use"] == 0
        assert pool["pages_free"] == pool["num_pages"] - 1
        pc = pool["prefix_cache"]
        assert pc["hit_requests"] >= 1
        assert pc["evictions"] >= 1       # drained pool dropped entries
        slo = state["slo"]
        assert slo["finished"] == 6 and slo["evicted"] == 0
        assert slo["latency"]["ttft_ms"]["count"] == 6
        assert slo["attainment"] == 1.0   # default SLO is generous

    def test_serve_state_event_sealed_on_close(self, trail_run):
        rows, _uids, _prompts, _state, _d = trail_run
        states = [r for r in rows if r.get("event") == "serve_state"]
        assert states
        last = states[-1]
        assert last["page_pool"]["pages_in_use"] == 0
        assert last["slo"]["finished"] == 6


# --------------------------------------------------------------------- #
# eviction through the engine: null ttft in the JSON, pool reuse
# --------------------------------------------------------------------- #
class TestEngineEviction:
    def test_cancel_queued_and_inflight(self, tmp_path):
        from deepspeed_tpu.inference import InferenceEngine, Request
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(
            cfg, params, dict(TINY_INF, events_dir=str(tmp_path),
                              max_new_tokens=6),
            dtype=jnp.float32)
        eng.warmup()
        uids = [eng.submit(Request(prompt=[i + 1, i + 2],
                                   max_new_tokens=6))
                for i in range(5)]
        eng.step()                         # admits up to 3, first tokens
        # in-flight cancel (has a first token) + queued cancel (none)
        fin_live = eng.cancel(uids[0])
        fin_queued = eng.cancel(uids[4])
        assert fin_live.ttft_ms is not None
        assert fin_queued.ttft_ms is None
        assert eng.cancel(99999) is None
        rest = eng.run()
        eng.close()
        assert {f.uid for f in rest} == {uids[1], uids[2], uids[3]}
        rows = read_rows(tmp_path)
        evicts = {r["uid"]: r for r in rows
                  if r.get("event") == "serve_evict"}
        assert set(evicts) == {uids[0], uids[4]}
        # the satellite fix: evicted-before-first-token is JSON null,
        # not 0.0
        assert evicts[uids[4]]["ttft_ms"] is None
        assert evicts[uids[0]]["ttft_ms"] is not None
        assert all(r.get("ttft_ms") != 0.0 for r in evicts.values())
        # evictions count in the SLO denominator, not the numerator
        assert rows[-1].get("event") == "serve_state" or True
        state = [r for r in rows if r.get("event") == "serve_state"][-1]
        assert state["slo"]["evicted"] == 2
        assert state["slo"]["finished"] == 5


# --------------------------------------------------------------------- #
# SLO / goodput scalars
# --------------------------------------------------------------------- #
class TestSLOGoodput:
    def _run(self, tmp_path, slo):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(
            cfg, params, dict(TINY_INF, events_dir=str(tmp_path)),
            dtype=jnp.float32,
            observability_config={"serve": {"slo": slo}})
        eng.warmup()
        eng.generate([[1, 2, 3], [4, 5], [6, 7, 8]], max_new_tokens=4)
        state = eng.debug_state()
        eng.close()
        scalars = {}
        for r in read_rows(tmp_path):
            if "tag" in r:
                scalars.setdefault(r["tag"], []).append(r["value"])
        return scalars, state

    def test_goodput_equals_throughput_when_slo_met(self, tmp_path):
        scalars, state = self._run(
            tmp_path, {"ttft_ms": 1e9, "tbt_ms": 1e9})
        assert scalars["Serve/slo_attainment"][-1] == 1.0
        assert state["slo"]["attainment"] == 1.0
        assert scalars["Serve/goodput_tokens_per_s"][-1] == \
            pytest.approx(scalars["Serve/tokens_per_sec"][-1], rel=0.2)

    def test_goodput_zero_when_slo_impossible(self, tmp_path):
        scalars, state = self._run(
            tmp_path, {"ttft_ms": 1e-6, "tbt_ms": 1e-6})
        assert scalars["Serve/slo_attainment"][-1] == 0.0
        assert scalars["Serve/goodput_tokens_per_s"][-1] == 0.0
        assert scalars["Serve/tokens_per_sec"][-1] > 0
        assert state["slo"]["good_tokens"] == 0
        # throughput vs goodput are genuinely distinct numbers
        assert scalars["Serve/queue_wait_ms"], "queue waits must land"
        assert scalars["Serve/tbt_ms"], "per-dispatch TBT must land"


# --------------------------------------------------------------------- #
# tracing must not touch the compiled plane (ISSUE 9 acceptance)
# --------------------------------------------------------------------- #
class TestTracingDispatchInvariants:
    def test_program_set_dispatches_and_outputs_unchanged(self,
                                                          tmp_path):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9, 10], [11],
                   [1, 2, 3], [12, 13]]

        def run(traced, events):
            icfg = dict(TINY_INF)
            if events:
                icfg["events_dir"] = os.path.join(
                    str(tmp_path), "on" if traced else "off")
            eng = InferenceEngine(
                cfg, params, icfg, dtype=jnp.float32,
                observability_config={
                    "serve": {"enabled": traced, "sample_rate": 1.0}})
            warm = eng.warmup()
            outs = eng.generate(prompts, max_new_tokens=4)
            stats = (warm, eng.compile_tracker.total_dispatches,
                     eng.steady_state_recompiles)
            eng.close()
            return outs, stats

        outs_off, (warm_off, disp_off, rc_off) = run(False, False)
        outs_on, (warm_on, disp_on, rc_on) = run(True, True)
        # tracing on: same warmup program set, same dispatch count,
        # zero steady-state recompiles, bitwise-equal greedy outputs
        assert warm_on == warm_off
        assert disp_on == disp_off
        assert rc_on == rc_off == 0
        assert outs_on == outs_off

    def test_bench_row_registered(self):
        import bench
        assert "serve_trace_overhead" in bench.METRICS
        assert "serve_trace_overhead" in bench.HW_FREE
        assert callable(bench.bench_serve_trace_overhead)


# --------------------------------------------------------------------- #
# Chrome-trace request lanes
# --------------------------------------------------------------------- #
class TestChromeLanes:
    def test_recorder_add_lane(self):
        from deepspeed_tpu.profiling.spans import ChromeTraceRecorder
        rec = ChromeTraceRecorder()
        rec.add_lane(7, "req 7", "queue_wait", 0.0, 0.5)
        rec.add_lane(7, "req 7", "decode", 0.5, 1.0, tokens=3)
        metas = [e for e in rec.events if e.get("ph") == "M"]
        assert len(metas) == 1            # one thread_name per lane
        assert metas[0]["args"]["name"] == "req 7"
        xs = [e for e in rec.events if e.get("ph") == "X"]
        assert all(e["tid"] == 7 for e in xs)
        assert xs[1]["args"] == {"tokens": 3}

    def test_engine_emits_request_lanes(self, tmp_path):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        trace_path = str(tmp_path / "trace.json")
        eng = InferenceEngine(
            cfg, params, dict(TINY_INF), dtype=jnp.float32,
            observability_config={"chrome_trace_path": trace_path})
        eng.warmup()
        eng.generate([[1, 2, 3], [4, 5]], max_new_tokens=3)
        eng.close()
        trace = json.load(open(trace_path))
        names = {e["name"] for e in trace["traceEvents"]}
        # engine phase spans AND per-request lane phases in one trace
        assert {"serve/prefill", "serve/decode", "queue_wait",
                "prefill", "decode", "thread_name"} <= names
        lanes = {e["tid"] for e in trace["traceEvents"]
                 if e.get("ph") == "M"}
        assert len(lanes) == 2            # one lane per request


# --------------------------------------------------------------------- #
# obs_report: --serve, versioned schema, engine-driven rotation
# --------------------------------------------------------------------- #
class TestServeReport:
    @pytest.fixture(scope="class")
    def report_run(self, tmp_path_factory):
        from deepspeed_tpu.inference import InferenceEngine
        tmp = tmp_path_factory.mktemp("serve_report")
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(
            cfg, params, dict(TINY_INF, events_dir=str(tmp)),
            dtype=jnp.float32,
            # a tiny rotation cap: the report must survive segments
            observability_config={"events_max_mb": 0.002})
        eng.warmup()
        eng.generate([[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]],
                     max_new_tokens=4)
        eng.close()
        return str(tmp)

    def test_rotation_happened_and_summary_is_whole(self, report_run):
        segs = [p for p in os.listdir(report_run)
                if p.startswith("events.jsonl.")]
        assert segs, "0.002 MiB cap must rotate on this run"
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(report_run)
        assert s["schema"] == 3     # v3 (ISSUE 15) keeps every v2 key
        sv = s["serving"]
        # early rows (warmup, first admits) live in rotated segments;
        # losing them would undercount requests
        assert sv["requests"] == 4
        assert sv["queue_wait_ms"]["p99"] is not None
        assert sv["ttft_ms"]["p99"] >= sv["ttft_ms"]["p50"]
        assert sv["tbt_ms"]["p50"] is not None
        assert sv["slo"]["attainment"] == 1.0
        assert sv["slo"]["goodput_tokens_per_s"] > 0
        assert sv["pool"] is not None
        assert sv["pool"]["prefix_cache"]["entries"] == 0

    def test_render_serve_text(self, report_run):
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(report_run)
        text = obs_report.render_serve(s)
        for needle in ("queue_wait", "ttft", "tbt", "p50", "p95", "p99",
                       "slo_attainment", "goodput", "page_pool",
                       "prefix_cache"):
            assert needle in text, needle
        # the full report also carries the SLO line
        full = obs_report.render(s)
        assert "slo" in full and "goodput" in full

    def test_cli_serve_and_json_schema(self, report_run, capsys):
        obs_report = _load_tool("obs_report")
        assert obs_report.main([report_run, "--serve"]) == 0
        out = capsys.readouterr().out
        assert "serving report" in out and "goodput" in out
        assert obs_report.main([report_run, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["schema"] == 3
        assert payload["serving"]["slo"]["attainment"] == 1.0


# --------------------------------------------------------------------- #
# observability.serve config section
# --------------------------------------------------------------------- #
class TestServeObsConfigSection:
    def test_defaults(self):
        from deepspeed_tpu.runtime.config import get_observability_config
        obs = get_observability_config({})
        assert obs["events_max_mb"] == 0
        srv = obs["serve"]
        assert srv["enabled"] is True
        assert srv["slo"] == {"ttft_ms": 2000.0, "tbt_ms": 200.0}
        assert srv["sample_rate"] == pytest.approx(0.0625)
        assert srv["events_max_mb"] == 0

    def test_serve_inherits_and_overrides_rotation_cap(self):
        from deepspeed_tpu.runtime.config import get_observability_config
        obs = get_observability_config(
            {"observability": {"events_max_mb": 64}})
        assert obs["serve"]["events_max_mb"] == 64
        obs = get_observability_config(
            {"observability": {"events_max_mb": 64,
                               "serve": {"events_max_mb": 8}}})
        assert obs["serve"]["events_max_mb"] == 8

    def test_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_observability_config)
        with pytest.raises(DeepSpeedConfigError, match="sample_rate"):
            get_observability_config(
                {"observability": {"serve": {"sample_rate": 2.0}}})
        with pytest.raises(DeepSpeedConfigError, match="slo"):
            get_observability_config(
                {"observability": {"serve": {"slo": {"ttft_ms": -1}}}})
        with pytest.raises(DeepSpeedConfigError, match="events_max_mb"):
            get_observability_config(
                {"observability": {"events_max_mb": -1}})

    def test_rides_deepspeed_config(self):
        from deepspeed_tpu.runtime.config import DeepSpeedConfig
        cfg = DeepSpeedConfig({
            "train_batch_size": 1,
            "observability": {"serve": {"slo": {"ttft_ms": 500}}}})
        assert cfg.observability_config["serve"]["slo"]["ttft_ms"] == 500.0
