"""1-bit Adam tests (mirror reference tests/onebitadam/: compressed
allreduce vs dense ground truth, error-feedback state; plus optimizer-level
phase semantics and engine integration)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.runtime import custom_collectives as cc
from deepspeed_tpu.runtime.fp16.onebit_adam import OnebitAdam
from deepspeed_tpu.ops.optimizers import Adam, build_optimizer


def test_pack_unpack_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (64,))
    packed = cc.pack_signs(x)
    assert packed.dtype == jnp.uint8 and packed.shape == (8,)
    signs = cc.unpack_signs(packed)
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_padded_numel():
    assert cc.padded_numel(10, 4) == 32   # multiple of 4*8
    assert cc.padded_numel(64, 4) == 64
    assert cc.server_chunk_size(10, 4) == 8


def _numpy_compressed_allreduce(buffers, worker_errors, server_errors):
    """Literal numpy model of the reference Compressed_Allreduce (2-phase
    sign+scale with error feedback) for N ranks — the ground truth."""
    n = len(buffers)
    padded = worker_errors[0].shape[0]
    chunk = padded // n
    new_we, packed_chunks, scales = [], [], []
    for b, we in zip(buffers, worker_errors):
        flat = np.zeros(padded, np.float32)
        flat[:b.size] = b
        comp = flat + we
        scale = np.linalg.norm(comp) / np.sqrt(padded)
        signs = np.where(comp >= 0, 1.0, -1.0).astype(np.float32)
        new_we.append(comp - scale * signs)
        packed_chunks.append(signs.reshape(n, chunk))
        scales.append(scale)
    outs, new_se = [], []
    server_chunks = []
    for r in range(n):  # rank r owns chunk r
        contrib = np.stack([packed_chunks[w][r] * scales[w]
                            for w in range(n)])
        server_m = contrib.mean(axis=0) + server_errors[r]
        s_scale = np.linalg.norm(server_m) / np.sqrt(chunk)
        s_signs = np.where(server_m >= 0, 1.0, -1.0).astype(np.float32)
        new_se.append(server_m - s_scale * s_signs)
        server_chunks.append(s_signs * s_scale)
    full = np.concatenate(server_chunks)
    return full, new_we, new_se


def test_compressed_allreduce_matches_numpy_model():
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))
    numel = 37
    padded = cc.padded_numel(numel, n)
    chunk = padded // n
    rng = np.random.RandomState(0)
    bufs = [rng.randn(numel).astype(np.float32) for _ in range(n)]
    wes = [rng.randn(padded).astype(np.float32) * 0.1 for _ in range(n)]
    ses = [rng.randn(chunk).astype(np.float32) * 0.1 for _ in range(n)]

    expected, exp_we, exp_se = _numpy_compressed_allreduce(bufs, wes, ses)

    @jax.jit
    def run(b, we, se):
        def inner(b, we, se):
            res = cc.compressed_allreduce(b[0], we[0], se[0],
                                          axis_name="data", world_size=n)
            return res.tensor[None], res.worker_error[None], \
                res.server_error[None]
        return shard_map(inner, mesh=mesh,
                         in_specs=(P("data"), P("data"), P("data")),
                         out_specs=(P("data"), P("data"), P("data")),
                         check_vma=False)(b, we, se)

    b = np.stack(bufs)
    we = np.stack(wes)
    se = np.stack(ses)
    out, new_we, new_se = run(b, we, se)
    # every rank must hold the same averaged tensor
    for r in range(n):
        np.testing.assert_allclose(np.asarray(out[r]), expected[:numel],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(new_we[r]), exp_we[r],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(new_se[r]), exp_se[r],
                                   rtol=1e-5, atol=1e-6)


def test_error_feedback_identity():
    """compressed + worker_error' == compensated input, exactly."""
    x = jax.random.normal(jax.random.PRNGKey(1), (64,))
    we = jnp.zeros((64,))
    se = jnp.zeros((64,))
    res = cc.compressed_allreduce(x, we, se, world_size=1)
    scale = jnp.linalg.norm(x) / np.sqrt(64)
    signs = jnp.where(x >= 0, 1.0, -1.0)
    np.testing.assert_allclose(np.asarray(res.worker_error),
                               np.asarray(x - scale * signs), rtol=1e-6)


def test_error_feedback_reduces_bias_over_steps():
    """With error feedback, repeated compression of a constant signal
    converges in mean; without, bias persists. (the EF-SGD property)"""
    rng = np.random.RandomState(0)
    target = rng.randn(128).astype(np.float32)
    we = jnp.zeros((128,))
    se = jnp.zeros((128,))
    acc = np.zeros(128, np.float32)
    steps = 50
    for _ in range(steps):
        res = cc.compressed_allreduce(jnp.asarray(target), we, se,
                                      world_size=1)
        we, se = res.worker_error, res.server_error
        acc += np.asarray(res.tensor)
    mean_err = np.abs(acc / steps - target).mean()
    # plain sign-sgd single-shot error for comparison
    scale = np.linalg.norm(target) / np.sqrt(128)
    oneshot_err = np.abs(scale * np.sign(target) - target).mean()
    assert mean_err < 0.25 * oneshot_err


def test_onebit_adam_warmup_matches_adam():
    params = {"w": jnp.array([1.0, -2.0, 3.0])}
    grads = {"w": jnp.array([0.1, 0.2, -0.3])}
    ob = OnebitAdam(lr=1e-2, freeze_step=10)
    ad = Adam(lr=1e-2, bias_correction=False, adamw_mode=False)
    s_ob = ob.init(params)
    s_ad = ad.init(params)
    p_ob, s_ob = ob.update(grads, s_ob, params, compression=False)
    p_ad, s_ad = ad.update(grads, s_ad, params)
    np.testing.assert_allclose(np.asarray(p_ob["w"]), np.asarray(p_ad["w"]),
                               rtol=1e-6)


def test_onebit_adam_compression_freezes_variance():
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (32,))}
    grads = {"w": jax.random.normal(jax.random.PRNGKey(1), (32,))}
    ob = OnebitAdam(lr=1e-2, freeze_step=1)
    state = ob.init(params)
    params, state = ob.update(grads, state, params, compression=False)
    v_before = np.asarray(state.exp_avg_sq["w"]).copy()
    params, state = ob.update(grads, state, params, compression=True)
    np.testing.assert_array_equal(np.asarray(state.exp_avg_sq["w"]),
                                  v_before)
    # error feedback engaged
    assert np.abs(np.asarray(state.worker_error["w"])).sum() > 0


def test_onebit_adam_converges_on_quadratic():
    """Full 2-phase run drives a quadratic toward its minimum."""
    target = jnp.asarray(np.random.RandomState(3).randn(16).astype(np.float32))
    params = {"w": jnp.zeros((16,))}
    ob = OnebitAdam(lr=0.05, freeze_step=20)
    state = ob.init(params)

    def loss_and_grad(p):
        d = p["w"] - target
        return jnp.sum(d * d), {"w": 2 * d}

    for i in range(120):
        loss, g = loss_and_grad(params)
        params, state = ob.update(g, state, params,
                                  compression=(i >= 20))
    final, _ = loss_and_grad(params)
    # 1-bit compression leaves a noise ball ∝ lr around the optimum; 120
    # steps from loss=‖t‖² must land well inside 15% of it
    assert float(final) < 0.15 * float(jnp.sum(target * target))


def test_build_optimizer_onebit():
    ob = build_optimizer("OneBitAdam".lower(),
                         {"lr": 1e-3, "freeze_step": 5})
    assert isinstance(ob, OnebitAdam) and ob.freeze_step == 5


def test_engine_onebit_phase_switch():
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": 2}},
    }
    engine, *_ = ds.initialize(model=simple_loss_fn,
                               model_parameters=params, config=cfg)
    assert engine._onebit and not engine._onebit_compression
    assert engine._onebit_dist  # dp=8 mesh: distributed compression path
    # global batch = micro_bs * dp so shard_map can slice over 'data'
    batches = random_batches(6, 4 * 8, 8)
    for b in batches:
        engine.train_batch(iter([b]))
    assert engine._onebit_compression  # switched after freeze_step
    assert engine.global_steps == 6


def test_engine_onebit_rejects_zero():
    import deepspeed_tpu as ds
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
    }
    with pytest.raises(AssertionError, match="ZeRO"):
        ds.initialize(model=simple_loss_fn, model_parameters=params,
                      config=cfg)


@pytest.mark.parametrize("extra", [
    {"fp16": {"enabled": True, "initial_scale_power": 8}},
    {"gradient_accumulation_steps": 2},
    {"fp16": {"enabled": True, "initial_scale_power": 8},
     "gradient_accumulation_steps": 2},
], ids=["fp16", "ga2", "fp16_ga2"])
def test_engine_onebit_fp16_and_accumulation(extra):
    """ADVICE r1: the compressed allreduce sits inside a lax.cond branch
    under fp16 (overflow skip) and/or ga>1 (boundary) — these configs must
    compile and converge on the 8-device mesh, both phases."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-3, "freeze_step": 2}},
        **extra,
    }
    engine, *_ = ds.initialize(model=simple_loss_fn,
                               model_parameters=params, config=cfg)
    ga = engine.gradient_accumulation_steps
    losses = []
    for i in range(5):
        batch_group = random_batches(ga, 4 * 8, 8, seed=i)
        losses.append(float(engine.train_batch(iter(batch_group))))
    assert engine._onebit_compression  # past freeze_step in both phases
    assert all(np.isfinite(l) for l in losses)
    # training still learns: loss goes down across the run
    assert losses[-1] < losses[0]
