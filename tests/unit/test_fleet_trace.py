"""Fleet-wide distributed tracing (ISSUE 18), jax-free units:

- trace context (trace id + hop ordinal) rides the RPC request frames
  and the migration record headers, with old-wire fallbacks;
- the router mints trace ids (monotonic, RNG-free) and writes the
  ``fleet_dispatch`` spine rows;
- clock alignment: the midpoint-method offset estimate (best-RTT
  sample wins, uncertainty = RTT/2) and the ``clock_sync`` trail rows;
- the ``obs_report --fleet`` merger: rotation segments interleaved
  across replicas, out-of-order timestamps beyond the clock-sync
  uncertainty are FLAGGED (never silently re-ordered), a missing
  replica log degrades to a router-spine-only (salvaged) timeline;
- ``obs_report --diff`` covers the quantized-serving tags.

The end-to-end lineage pin (kill mid-decode -> one merged timeline)
lives in tests/unit/test_fleet_process.py::TestFleetTracing.
"""

import json
import os
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Writer:
    """Captures add_event rows like monitor._JsonlWriter would write
    them (plus the auto 't' stamp the real writer adds)."""

    def __init__(self):
        self.rows = []

    def add_event(self, kind, **fields):
        row = {"event": str(kind)}
        row.update(fields)
        row.setdefault("t", time.time())
        self.rows.append(row)


# ===================================================================== #
# trace context over the wire
# ===================================================================== #

class TestTraceContextWire:
    def test_request_wire_roundtrip_preserves_trace(self):
        from deepspeed_tpu.inference import rpc
        from deepspeed_tpu.inference.scheduler import Request
        req = Request(prompt=[1, 2, 3], max_new_tokens=4,
                      temperature=0.0, seed=7, uid=42,
                      trace_id="f1a-000003", hop=2)
        back = rpc.request_from_wire(rpc.request_to_wire(req))
        assert back.trace_id == "f1a-000003" and back.hop == 2
        assert back.uid == 42

    def test_old_wire_dict_defaults_unstamped(self):
        # a frame from a pre-tracing router: no trace keys at all
        from deepspeed_tpu.inference import rpc
        back = rpc.request_from_wire(
            {"prompt": [1, 2], "uid": 5, "max_new_tokens": 4})
        assert back.trace_id is None and back.hop == 0

    def test_migration_record_carries_trace_over_wire(self):
        from deepspeed_tpu.inference import rpc
        from deepspeed_tpu.inference.disagg import MigrationRecord
        k = np.arange(2 * 2 * 2 * 4 * 4, dtype=np.float32
                      ).reshape(2, 2, 2, 4, 4)
        rec = MigrationRecord(
            uid=7, prompt=[1, 2, 3], max_new_tokens=8, temperature=0.0,
            seed=11, eos_id=None, priority=0, position=5,
            pending_tok=42, tokens=[42], live_pages=2, page_bytes=64,
            ttft_ms=1.5, queue_wait_ms=0.25, elapsed_ms=3.0,
            trace_id="fbeef-00002a", hop=1, kslab=k, vslab=k + 1.0)
        head, payload = rpc.migration_to_wire(rec)
        assert head["trace_id"] == "fbeef-00002a" and head["hop"] == 1
        back = rpc.migration_from_wire(head, payload)
        assert back.trace_id == "fbeef-00002a" and back.hop == 1
        # durations-not-absolute-times doctrine: the header ships no
        # wall-clock field, only elapsed durations
        assert "t" not in head
        assert back.elapsed_ms == 3.0

    def test_old_migration_header_defaults(self):
        from deepspeed_tpu.inference.disagg import MigrationRecord
        rec = MigrationRecord(
            uid=1, prompt=[1], max_new_tokens=2, temperature=0.0,
            seed=0, eos_id=None, priority=0, position=1,
            pending_tok=3, tokens=[3], live_pages=1, page_bytes=16,
            ttft_ms=None, queue_wait_ms=None, elapsed_ms=0.0)
        assert rec.trace_id is None and rec.hop == 0


# ===================================================================== #
# tracer-side context: replica_id stamping, migration lineage rows
# ===================================================================== #

class TestTracerContext:
    def _tracer(self, w, replica_id=1):
        from deepspeed_tpu.inference.tracing import ServeTracer
        return ServeTracer({"enabled": True, "replica_id": replica_id},
                           writer=w)

    def test_rows_carry_replica_and_trace_context(self):
        w = _Writer()
        tr = self._tracer(w)
        tr.on_submit(5, prompt_tokens=3, max_new_tokens=4,
                     trace_id="fa-000001", hop=0)
        row = w.rows[-1]
        assert row["event"] == "serve_submit"
        assert row["replica_id"] == 1
        assert row["trace_id"] == "fa-000001" and row["hop"] == 0

    def test_unstamped_request_rows_stay_schema_stable(self):
        from deepspeed_tpu.inference.tracing import ServeTracer
        w = _Writer()
        tr = ServeTracer({"enabled": True}, writer=w)
        tr.on_submit(5, prompt_tokens=3, max_new_tokens=4)
        row = w.rows[-1]
        assert "trace_id" not in row and "replica_id" not in row

    def test_migrate_out_row_keeps_context_before_evict(self):
        w = _Writer()
        tr = self._tracer(w)
        tr.on_submit(5, prompt_tokens=3, max_new_tokens=4,
                     trace_id="fa-000002", hop=0)
        tr.on_migrate_out(5, position=7, pages=2, nbytes=128)
        row = w.rows[-1]
        assert row["event"] == "serve_migrate_out"
        assert row["trace_id"] == "fa-000002" and row["hop"] == 0
        assert row["pages"] == 2 and row["nbytes"] == 128
        assert row["reason"] == "migrate"

    def test_migrate_in_resumes_trace_for_finish(self):
        """The destination half installs a resumed trace: the finish
        row carries the ORIGINAL trace id with the bumped hop, and the
        carried queue/ttft durations keep the decomposition summing."""
        from deepspeed_tpu.inference.scheduler import FinishedRequest
        w = _Writer()
        tr = self._tracer(w, replica_id=2)
        tr.on_migrate_in(5, trace_id="fa-000002", hop=1, position=7,
                         pages=2, nbytes=128, queue_wait_ms=0.5,
                         ttft_ms=2.5, elapsed_ms=4.0, tokens=3)
        row = w.rows[-1]
        assert row["event"] == "serve_migrate_in"
        assert row["trace_id"] == "fa-000002" and row["hop"] == 1
        assert row["resumed_tokens"] == 3
        tr.on_token(5)
        fin = FinishedRequest(uid=5, prompt=[1, 2, 3], tokens=[9] * 4,
                              finish_reason="length", ttft_ms=2.5,
                              latency_ms=6.0)
        tr.on_finish(fin)
        frow = w.rows[-1]
        assert frow["event"] == "serve_finish"
        assert frow["trace_id"] == "fa-000002" and frow["hop"] == 1
        assert frow["queue_wait_ms"] == 0.5
        # prefill = ttft - queue_wait: the identity the merger re-checks
        assert frow["prefill_ms"] == pytest.approx(2.0)

    def test_event_kinds_pinned(self):
        from deepspeed_tpu.inference.tracing import ServeTracer
        assert "serve_migrate_out" in ServeTracer.EVENT_KINDS
        assert "serve_migrate_in" in ServeTracer.EVENT_KINDS
        assert len(set(ServeTracer.EVENT_KINDS)) == \
            len(ServeTracer.EVENT_KINDS)


# ===================================================================== #
# router: trace minting + dispatch spine + clock sync
# ===================================================================== #

class _FakeSched:
    def __init__(self):
        self.queue = []
        self.total_tokens = 0
        self.occupancy = 0.0

    @property
    def queue_depth(self):
        return len(self.queue)

    def active_slots(self):
        return []

    def idle(self):
        return not self.queue


class _FakeEngine:
    def __init__(self):
        self.scheduler = _FakeSched()
        self.received = []
        self.monitor = None
        self._log = None

    def submit(self, req):
        self.scheduler.queue.append(req)
        self.received.append(req)
        return req.uid

    def step(self):
        from deepspeed_tpu.inference import FinishedRequest
        fins = [FinishedRequest(uid=r.uid, prompt=list(r.prompt),
                                tokens=[1] * r.max_new_tokens,
                                finish_reason="length", ttft_ms=1.0,
                                latency_ms=1.0)
                for r in self.scheduler.queue]
        self.scheduler.queue = []
        return fins


class TestRouterTraceSpine:
    def _run(self, writer=None, engines=None, reqs=2):
        from deepspeed_tpu.inference import FleetRouter, Request
        engines = engines or [_FakeEngine(), _FakeEngine()]
        router = FleetRouter(engines, writer=writer)
        reqs = [Request(prompt=[1, 2, 3], max_new_tokens=2,
                        temperature=0.0) for _ in range(reqs)]
        for r in reqs:
            router.submit(r)
        router.run()
        return router, reqs

    def test_submit_mints_unique_monotonic_trace_ids(self):
        w = _Writer()
        _router, reqs = self._run(writer=w)
        ids = [r.trace_id for r in reqs]
        assert all(ids) and len(set(ids)) == len(ids)
        assert all(r.hop == 0 for r in reqs)
        disp = [r for r in w.rows if r["event"] == "fleet_dispatch"]
        assert {d["trace_id"] for d in disp} == set(ids)
        assert all(d["hop"] == 0 and d["route_ms"] >= 0.0
                   for d in disp)

    def test_prestamped_request_keeps_upstream_trace(self):
        from deepspeed_tpu.inference import FleetRouter, Request
        router = FleetRouter([_FakeEngine()])
        req = Request(prompt=[1], max_new_tokens=1, temperature=0.0,
                      trace_id="upstream-7", hop=3)
        router.submit(req)
        assert req.trace_id == "upstream-7" and req.hop == 3

    def test_sync_clocks_writes_rows_for_pingable_replicas(self):
        w = _Writer()
        eng = _FakeEngine()
        eng.clock_ping = lambda: {"offset_s": 0.002,
                                  "uncertainty_s": 0.0005,
                                  "rtt_s": 0.001}
        # launched alongside an in-process engine with no ping surface:
        # only the process replica gets a clock_sync row
        self._run(writer=w, engines=[eng, _FakeEngine()], reqs=1)
        cs = [r for r in w.rows if r["event"] == "clock_sync"]
        assert len(cs) >= 1
        assert cs[0]["replica"] == 0
        assert cs[0]["offset_ms"] == pytest.approx(2.0)
        assert cs[0]["uncertainty_ms"] == pytest.approx(0.5)
        assert cs[0]["rtt_ms"] == pytest.approx(1.0)

    def test_clock_ping_midpoint_math_best_rtt_wins(self):
        from deepspeed_tpu.inference import fleet as fleet_mod
        rp = fleet_mod.ReplicaProcess.__new__(fleet_mod.ReplicaProcess)
        # three (t0, t1) brackets; the middle sample has the tightest
        # RTT (2 ms) and a child clock 0.5 s ahead of its midpoint
        real = time.time
        clock = [100.0, 100.010, 200.0, 200.002, 300.0, 300.020]
        children = iter([100.105, 200.501, 300.910])

        def fake_call(method, params, payload=b""):
            assert method == "clock_ping"
            return {"t_child": next(children)}, b""

        rp._call = fake_call
        orig = fleet_mod.time.time
        fleet_mod.time.time = lambda: clock.pop(0) if clock else real()
        try:
            est = rp.clock_ping(samples=3)
        finally:
            fleet_mod.time.time = orig
        assert est["rtt_s"] == pytest.approx(0.002)
        assert est["uncertainty_s"] == pytest.approx(0.001)
        assert est["offset_s"] == pytest.approx(0.5)


# ===================================================================== #
# the merged fleet report: edge cases on synthesized logs
# ===================================================================== #

def _write(dirpath, rows, seg=None):
    os.makedirs(dirpath, exist_ok=True)
    name = "events.jsonl" if seg is None else f"events.jsonl.{seg}"
    with open(os.path.join(dirpath, name), "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _router_rows():
    return [
        {"event": "clock_sync", "replica": 0, "offset_ms": 0.0,
         "uncertainty_ms": 0.5, "rtt_ms": 1.0, "t": 99.0},
        # replica 1's clock runs 1 s ahead of the router's
        {"event": "clock_sync", "replica": 1, "offset_ms": 1000.0,
         "uncertainty_ms": 0.5, "rtt_ms": 1.0, "t": 99.0},
        {"event": "fleet_dispatch", "uid": 5, "trace_id": "t-1",
         "hop": 0, "replica": 0, "route_ms": 0.2, "t": 100.0},
        {"event": "serve_migration", "uid": 5, "trace_id": "t-1",
         "hop": 0, "src": 0, "dst": 1, "pages": 2, "nbytes": 256,
         "position": 7, "transfer_ms": 1.5, "priced_ms": 0.8,
         "t": 100.1},
    ]


def _replica0_rows():
    # hop 0 on replica 0: submit -> admit -> first token -> exported
    return [
        {"event": "serve_submit", "uid": 5, "trace_id": "t-1",
         "hop": 0, "replica_id": 0, "prompt_tokens": 3,
         "max_new_tokens": 8, "t": 100.001},
        {"event": "serve_admit", "uid": 5, "trace_id": "t-1",
         "hop": 0, "replica_id": 0, "slot": 0, "queue_wait_ms": 2.0,
         "prefix_tokens": 0, "prompt_bucket": 8, "batch_bucket": 1,
         "t": 100.003},
        {"event": "serve_first_token", "uid": 5, "trace_id": "t-1",
         "hop": 0, "replica_id": 0, "ttft_ms": 5.0, "prefill_ms": 3.0,
         "t": 100.006},
        {"event": "serve_migrate_out", "uid": 5, "trace_id": "t-1",
         "hop": 0, "replica_id": 0, "position": 7, "pages": 2,
         "nbytes": 256, "reason": "migrate", "t": 100.05},
    ]


def _replica1_rows():
    # hop 1 on replica 1, raw t = router time + 1.0 s (its clock skew)
    return [
        {"event": "serve_migrate_in", "uid": 5, "trace_id": "t-1",
         "hop": 1, "replica_id": 1, "position": 7, "pages": 2,
         "nbytes": 256, "resumed_tokens": 1, "t": 101.102},
        {"event": "serve_decode_window", "uid": 5, "trace_id": "t-1",
         "hop": 1, "replica_id": 1, "tokens": 4, "end_token": 5,
         "window_ms": 3.0, "tbt_ms": 0.75, "t": 101.106},
        {"event": "serve_finish", "uid": 5, "trace_id": "t-1",
         "hop": 1, "replica_id": 1, "reason": "length",
         "new_tokens": 8, "ttft_ms": 5.0, "latency_ms": 9.0,
         "queue_wait_ms": 2.0, "prefill_ms": 3.0, "tbt_ms": 0.6,
         "tbt_ms_max": 1.0, "slo_ok": True, "t": 101.109},
    ]


class TestFleetMerge:
    def test_migrated_trace_stitches_across_logs(self, tmp_path):
        obs_report = _load_tool("obs_report")
        _write(tmp_path / "router", _router_rows())
        _write(tmp_path / "r0", _replica0_rows())
        _write(tmp_path / "r1", _replica1_rows())
        s = obs_report.summarize_fleet(
            [str(tmp_path / d) for d in ("router", "r0", "r1")])
        assert len(s["requests"]) == 1
        r = s["requests"][0]
        assert r["trace_id"] == "t-1" and r["uid"] == 5
        assert r["path"] == [0, 1]
        assert "migrate_out" in r["hops"][0]
        assert "migrate_in" in r["hops"][1]
        assert r["route_ms"] == 0.2
        # wire = aligned submit (100.001) - dispatch (100.0) = 1 ms
        assert r["rpc_wire_ms"] == pytest.approx(1.0, abs=1e-6)
        assert r["replica_queue_ms"] == 2.0 and r["prefill_ms"] == 3.0
        assert r["decode_ms"] == pytest.approx(4.0)
        assert r["migration_ms"] == pytest.approx(1.5)
        assert r["migration_priced_ms"] == pytest.approx(0.8)
        assert r["decomp_exact"] is True and r["flags"] == []
        assert s["out_of_order"] == []
        assert s["missing_replica_logs"] == []
        assert s["rollup"]["migrated"] == 1
        assert s["rollup"]["slo_attainment"] == 1.0
        # the clock table made it out for the report
        assert s["clock_offsets"]["1"]["offset_ms"] == 1000.0
        text = obs_report.render_fleet(s)
        assert "t-1" in text and "replica 1" in text

    def test_rotation_segments_interleave_across_replicas(
            self, tmp_path):
        """Each replica's rotated segments read back in sequence order
        ahead of its live file — splitting hop 1's rows across
        events.jsonl.1/.2/live must not lose or reorder lifecycle."""
        obs_report = _load_tool("obs_report")
        _write(tmp_path / "router", _router_rows())
        r0 = _replica0_rows()
        _write(tmp_path / "r0", r0[:2], seg=1)
        _write(tmp_path / "r0", r0[2:])
        r1 = _replica1_rows()
        _write(tmp_path / "r1", r1[:1], seg=1)
        _write(tmp_path / "r1", r1[1:2], seg=2)
        _write(tmp_path / "r1", r1[2:])
        s = obs_report.summarize_fleet(
            [str(tmp_path / d) for d in ("router", "r0", "r1")])
        r = s["requests"][0]
        assert r["path"] == [0, 1]
        assert "finish" in r["hops"][1]
        assert r["decomp_exact"] is True
        assert s["out_of_order"] == []

    def test_out_of_order_beyond_uncertainty_is_flagged(
            self, tmp_path):
        """A row whose aligned timestamp runs BACKWARDS by more than
        the clock-sync uncertainty is a real anomaly: the merger keeps
        lifecycle order and flags it — never silently re-sorts."""
        obs_report = _load_tool("obs_report")
        _write(tmp_path / "router", _router_rows())
        rows = _replica0_rows()
        # the first-token row claims a time 100 ms BEFORE the admit
        rows[2]["t"] = 99.9
        _write(tmp_path / "r0", rows)
        _write(tmp_path / "r1", _replica1_rows())
        s = obs_report.summarize_fleet(
            [str(tmp_path / d) for d in ("router", "r0", "r1")])
        assert len(s["out_of_order"]) == 1
        o = s["out_of_order"][0]
        assert o["trace_id"] == "t-1"
        assert o["event"] == "serve_first_token"
        assert o["after"] == "serve_admit"
        assert o["skew_ms"] > o["bound_ms"]
        # lifecycle kept: the request still assembled in hop order
        r = s["requests"][0]
        assert "finish" in r["hops"][1]
        text = obs_report.render_fleet(s)
        assert "out-of-order" in text

    def test_skew_within_uncertainty_is_not_flagged(self, tmp_path):
        obs_report = _load_tool("obs_report")
        _write(tmp_path / "router", _router_rows())
        rows = _replica0_rows()
        # 1 ms backwards: inside 2*uncertainty (1 ms) + 1 ms slack
        rows[2]["t"] = rows[1]["t"] - 0.001
        _write(tmp_path / "r0", rows)
        _write(tmp_path / "r1", _replica1_rows())
        s = obs_report.summarize_fleet(
            [str(tmp_path / d) for d in ("router", "r0", "r1")])
        assert s["out_of_order"] == []

    def test_missing_replica_log_degrades_to_router_spine(
            self, tmp_path):
        """A replica whose log is gone entirely (child died before
        flushing, disk lost): its hops reconstruct from the router's
        dispatch/migration rows alone, flagged salvaged-only, and the
        report names the missing replica."""
        obs_report = _load_tool("obs_report")
        rows = _router_rows() + [
            {"event": "fleet_dispatch", "uid": 6, "trace_id": "t-2",
             "hop": 0, "replica": 2, "route_ms": 0.1, "t": 102.0},
        ]
        _write(tmp_path / "router", rows)
        _write(tmp_path / "r0", _replica0_rows())
        _write(tmp_path / "r1", _replica1_rows())
        s = obs_report.summarize_fleet(
            [str(tmp_path / d) for d in ("router", "r0", "r1")])
        assert s["missing_replica_logs"] == [2]
        lost = next(r for r in s["requests"]
                    if r["trace_id"] == "t-2")
        assert lost["hops"] == []             # no replica rows at all
        assert "hop0_salvaged_only" in lost["flags"]
        assert lost["route_ms"] == 0.1        # the spine survives
        text = obs_report.render_fleet(s)
        assert "missing replica logs" in text

    def test_no_router_log_is_an_error(self, tmp_path):
        obs_report = _load_tool("obs_report")
        _write(tmp_path / "r0", _replica0_rows())
        with pytest.raises(ValueError, match="router"):
            obs_report.summarize_fleet([str(tmp_path / "r0")])

    def test_chrome_trace_has_one_lane_per_replica(self, tmp_path):
        obs_report = _load_tool("obs_report")
        _write(tmp_path / "router", _router_rows())
        _write(tmp_path / "r0", _replica0_rows())
        _write(tmp_path / "r1", _replica1_rows())
        s = obs_report.summarize_fleet(
            [str(tmp_path / d) for d in ("router", "r0", "r1")])
        out = str(tmp_path / "trace.json")
        obs_report.write_fleet_trace(s, out)
        trace = json.load(open(out))
        meta = {e["args"]["name"]: e["pid"]
                for e in trace["traceEvents"] if e.get("ph") == "M"}
        assert meta["router"] == 0
        assert meta["replica 0"] == 1 and meta["replica 1"] == 2
        xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        assert xs and all(e["dur"] >= 0 for e in xs)


# ===================================================================== #
# --diff covers the quantized-serving tags (ISSUE 18 satellite)
# ===================================================================== #

class TestDiffQuantMetrics:
    def _run_dir(self, tmp_path, name, qerr, kv_bpt):
        d = tmp_path / name
        _write(d, [])
        with open(os.path.join(d, "events.jsonl"), "a") as f:
            f.write(json.dumps({"tag": "Serve/quant_logit_err",
                                "value": qerr, "step": 0}) + "\n")
            f.write(json.dumps({"tag": "Serve/kv_pool_bytes_per_token",
                                "value": kv_bpt, "step": 0}) + "\n")
        return str(d)

    def test_metrics_registered_with_correct_directions(self):
        obs_report = _load_tool("obs_report")
        by_name = {m[0]: m for m in obs_report.DIFF_METRICS}
        assert by_name["quant_logit_err"][2] == "lower"
        assert by_name["kv_pool_bytes_per_token"][2] == "counter"

    def test_quant_regressions_fail_the_diff(self, tmp_path):
        obs_report = _load_tool("obs_report")
        a = self._run_dir(tmp_path, "a", qerr=0.05, kv_bpt=100.0)
        b = self._run_dir(tmp_path, "b", qerr=0.20, kv_bpt=104.0)
        d = obs_report.diff_runs(a, b)
        assert "quant_logit_err" in d["regressed"]
        assert "kv_pool_bytes_per_token" in d["regressed"]
        assert d["verdict"] == "REGRESSED"
        # and the CLI exits nonzero on it
        assert obs_report.main(["--diff", a, b]) == 1

    def test_quant_improvements_pass(self, tmp_path):
        obs_report = _load_tool("obs_report")
        a = self._run_dir(tmp_path, "a", qerr=0.20, kv_bpt=104.0)
        b = self._run_dir(tmp_path, "b", qerr=0.05, kv_bpt=100.0)
        d = obs_report.diff_runs(a, b)
        by_name = {m["metric"]: m for m in d["metrics"]}
        assert by_name["quant_logit_err"]["verdict"] == "IMPROVED"
        assert by_name["kv_pool_bytes_per_token"]["verdict"] == \
            "IMPROVED"
        assert d["verdict"] == "OK"
