"""Monitor/tensorboard tests: scalar writing (torch SummaryWriter or JSONL
fallback), engine integration writing loss/lr/scale per train_batch."""

import glob
import json
import os

import jax
import numpy as np
import pytest

from deepspeed_tpu.utils.monitor import TensorBoardMonitor, _JsonlWriter


def test_jsonl_writer(tmp_path):
    w = _JsonlWriter(str(tmp_path))
    w.add_scalar("Train/Samples/train_loss", 1.5, 10)
    w.add_scalar("Train/Samples/lr", 1e-3, 10)
    w.flush()
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "events.jsonl"))]
    assert lines[0] == {"tag": "Train/Samples/train_loss", "value": 1.5,
                        "step": 10}


def test_jsonl_schema_pinned(tmp_path):
    """tools/obs_report.py parses this log offline: the scalar row is
    exactly {"tag": str, "value": float, "step": int} (values coerced),
    and structured rows carry {"event": str, ...}."""
    w = _JsonlWriter(str(tmp_path))
    w.add_scalar("t", np.float32(1.5), np.int64(7))   # numpy in, json out
    w.add_event("compile", fn="micro_step", wall_ms=12.5)
    w.close()
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "events.jsonl"))]
    scalar, event = lines
    assert set(scalar) == {"tag", "value", "step"}
    assert type(scalar["tag"]) is str
    assert type(scalar["value"]) is float and scalar["value"] == 1.5
    assert type(scalar["step"]) is int and scalar["step"] == 7
    assert event["event"] == "compile" and event["wall_ms"] == 12.5


def test_jsonl_writer_crash_safe_line_buffering(tmp_path):
    """Rows must be on disk WITHOUT flush()/close(): a preempted run
    keeps its telemetry (the writer opens line-buffered)."""
    w = _JsonlWriter(str(tmp_path))
    w.add_scalar("a", 1.0, 1)
    # no flush, no close — read through a separate fd
    lines = open(os.path.join(tmp_path, "events.jsonl")).readlines()
    assert len(lines) == 1 and json.loads(lines[0])["tag"] == "a"
    w.close()


def test_jsonl_writer_context_manager_and_double_close(tmp_path):
    with _JsonlWriter(str(tmp_path)) as w:
        w.add_scalar("a", 1.0, 1)
    assert w._f is None
    w.close()                      # idempotent
    w.add_scalar("b", 2.0, 2)      # post-close writes are dropped, not a crash
    w.flush()
    lines = open(os.path.join(tmp_path, "events.jsonl")).readlines()
    assert len(lines) == 1


def test_jsonl_writer_del_closes_fd(tmp_path):
    w = _JsonlWriter(str(tmp_path))
    f = w._f
    del w
    import gc
    gc.collect()
    assert f.closed


def test_comm_metrics_flushed(tmp_path, monkeypatch):
    """write_comm_metrics was the only write_* method that never
    flushed — comm telemetry died with the process. Now it flushes like
    the rest."""
    import deepspeed_tpu.utils.monitor as mon

    class CountingWriter(_JsonlWriter):
        flushes = 0

        def flush(self):
            CountingWriter.flushes += 1
            super().flush()

    monkeypatch.setattr(mon, "_make_writer",
                        lambda log_dir: CountingWriter(log_dir))
    m = TensorBoardMonitor(enabled=True, output_path=str(tmp_path),
                           job_name="job")
    m.write_comm_metrics(bytes_per_step=1024.0, compression_ratio=2.0,
                         samples=8)
    assert CountingWriter.flushes >= 1
    m.close()
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "job", "events.jsonl"))]
    tags = {l["tag"]: l["value"] for l in lines}
    assert tags["Train/Samples/comm_bytes_per_step"] == 1024.0
    assert tags["Train/Samples/comm_compression_ratio"] == 2.0


def test_timer_values_flushed_and_gated(tmp_path, monkeypatch):
    """write_timer_values had BOTH halves of the write_* contract
    missing: no _writes() early-out (it crashed a disabled monitor on
    the f-string write path) and no trailing flush (timer telemetry
    buffered in the writer died with the process). Regression-pin
    both."""
    import deepspeed_tpu.utils.monitor as mon

    class CountingWriter(_JsonlWriter):
        flushes = 0

        def flush(self):
            CountingWriter.flushes += 1
            super().flush()

    CountingWriter.flushes = 0
    monkeypatch.setattr(mon, "_make_writer",
                        lambda log_dir: CountingWriter(log_dir))
    m = TensorBoardMonitor(enabled=True, output_path=str(tmp_path),
                           job_name="job")
    m.write_timer_values({"forward_microstep": 12.5, "backward": 30.0},
                         samples=64)
    assert CountingWriter.flushes >= 1
    m.close()
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "job", "events.jsonl"))]
    tags = {l["tag"]: (l["value"], l["step"]) for l in lines}
    assert tags["Train/Samples/forward_microstep"] == (12.5, 64)
    assert tags["Train/Samples/backward"] == (30.0, 64)
    # disabled monitor (no mirror): clean no-op, nothing written
    off = TensorBoardMonitor(enabled=False)
    off.write_timer_values({"forward": 1.0}, samples=1)
    off.close()


def test_monitor_mirror_receives_all_scalars(tmp_path):
    """The observability layer attaches a JSONL mirror: every monitor
    scalar (train metrics, checkpoint events, comm bytes) lands there
    even when tensorboard itself is disabled."""
    m = TensorBoardMonitor(enabled=False)
    assert m.writer is None
    mirror = _JsonlWriter(str(tmp_path))
    m.mirror = mirror
    m.write_train_metrics(loss=1.25, lr=1e-3, loss_scale=1.0, samples=4)
    m.write_checkpoint_event(action="save", ok=True, duration_ms=9.0,
                             samples=4)
    m.write_comm_metrics(bytes_per_step=77.0, samples=4)
    m.close()                      # must NOT close the (borrowed) mirror
    assert m.mirror is None and mirror._f is not None
    mirror.close()
    tags = {json.loads(l)["tag"] for l in
            open(os.path.join(tmp_path, "events.jsonl"))}
    assert {"Train/Samples/train_loss", "Train/Samples/lr",
            "Train/Samples/checkpoint_save_ms",
            "Train/Samples/checkpoint_save_ok",
            "Train/Samples/comm_bytes_per_step"} <= tags


def test_monitor_disabled_noops():
    m = TensorBoardMonitor(enabled=False)
    assert m.writer is None
    m.write_train_metrics(loss=1.0, lr=0.1, loss_scale=2.0, samples=1)
    m.flush(); m.close()  # all no-ops


def test_monitor_nonzero_rank_noops(tmp_path):
    m = TensorBoardMonitor(enabled=True, output_path=str(tmp_path), rank=3)
    assert m.writer is None


def test_monitor_checkpoint_events(tmp_path, monkeypatch):
    """Checkpoint durability telemetry: save/load durations and fallback
    events land as scalars (JSONL fallback path for determinism)."""
    import deepspeed_tpu.utils.monitor as mon
    monkeypatch.setattr(mon, "_make_writer",
                        lambda log_dir: _JsonlWriter(log_dir))
    m = TensorBoardMonitor(enabled=True, output_path=str(tmp_path),
                           job_name="job")
    m.write_checkpoint_event(action="save", ok=True, duration_ms=12.5,
                             samples=64)
    m.write_checkpoint_event(action="fallback", ok=False, samples=64)
    m.close()
    lines = [json.loads(l) for l in
             open(os.path.join(tmp_path, "job", "events.jsonl"))]
    tags = {l["tag"]: l["value"] for l in lines}
    assert tags["Train/Samples/checkpoint_save_ms"] == 12.5
    assert tags["Train/Samples/checkpoint_save_ok"] == 1.0
    assert tags["Train/Samples/checkpoint_fallback_ok"] == 0.0


@pytest.mark.slow
def test_monitor_writes_scalars(tmp_path):
    m = TensorBoardMonitor(enabled=True, output_path=str(tmp_path),
                           job_name="job")
    m.write_train_metrics(loss=2.0, lr=1e-4, loss_scale=8.0, samples=32)
    m.write_timer_values({"forward": 1.25, "backward": 2.5}, samples=32)
    m.close()
    files = glob.glob(str(tmp_path / "job" / "*"))
    assert files, "no event files written"


def test_engine_tensorboard_integration(tmp_path):
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "tensorboard": {"enabled": True,
                        "output_path": str(tmp_path),
                        "job_name": "unit_job"},
    }
    engine, *_ = ds.initialize(model=simple_loss_fn,
                               model_parameters=params, config=cfg)
    assert engine.monitor.enabled and engine.summary_writer is not None
    for b in random_batches(3, 4, 8):
        engine.train_batch(iter([b]))
    engine.monitor.close()
    files = glob.glob(str(tmp_path / "unit_job" / "*"))
    assert files, "engine wrote no tensorboard events"


def test_engine_unfused_path_writes(tmp_path):
    """forward/backward/step facade must also emit scalars (reference
    writes at step time, engine.py:922-936)."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "tensorboard": {"enabled": True, "output_path": str(tmp_path),
                        "job_name": "unfused"},
    }
    engine, *_ = ds.initialize(model=simple_loss_fn,
                               model_parameters=params, config=cfg)
    for b in random_batches(2, 4, 8):
        engine.forward(b)
        engine.backward()
        engine.step()
    engine.monitor.close()
    assert glob.glob(str(tmp_path / "unfused" / "*"))


def test_profiler_trace_window(tmp_path):
    """The configured jax.profiler window starts/stops around the given
    steps and leaves a trace on disk."""
    import os
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    out = str(tmp_path / "trace")
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "profiler": {"enabled": True, "output_path": out,
                             "start_step": 1, "num_steps": 2}})
    batches = random_batches(5, 16, 8)
    for b in batches:
        engine.train_batch(iter([b]))
    assert not engine._profiler_active
    assert os.path.isdir(out) and any(os.scandir(out))


def test_step_time_scalar_written(tmp_path):
    import deepspeed_tpu as ds
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "tensorboard": {"enabled": True,
                                "output_path": str(tmp_path)}})
    for b in random_batches(2, 16, 8):
        engine.train_batch(iter([b]))
    assert engine._last_step_time_ms is not None
    assert engine._last_step_time_ms > 0
