"""End-to-end pipeline training tests on the 8-device CPU mesh (mirrors
reference tests/unit/test_pipe.py: pipe-vs-baseline convergence parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.pipe.spmd import (
    PipelineSpec, build_pipeline_loss_fn, interleave_stages,
    pipeline_tick_counts)

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

H = 16
N_LAYERS = 4


class Linear:
    def __init__(self, h):
        self.h = h

    def init(self, key):
        return {"w": jax.random.normal(key, (self.h, self.h),
                                       jnp.float32) / np.sqrt(self.h),
                "b": jnp.zeros((self.h,), jnp.float32)}

    def __call__(self, p, x, rng=None):
        return jax.nn.relu(x @ p["w"] + p["b"])


def _mse(out, batch):
    return jnp.mean((out - batch["y"]) ** 2)


def _make_module(num_stages):
    return ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(N_LAYERS)],
        num_stages=num_stages, loss_fn=_mse, partition_method="uniform")


def _micro_batches(n, global_mb, seed=0):
    rng = np.random.RandomState(seed)
    w_true = (np.random.RandomState(1234).randn(H, H).astype(np.float32)
              / np.sqrt(H))
    out = []
    for _ in range(n):
        x = rng.randn(global_mb, H).astype(np.float32)
        out.append({"x": x, "y": x @ w_true})
    return out


def _pipe_config(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 4,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "mesh": {"axes": {"pipe": 4, "data": 2}},
    }
    cfg.update(over)
    return cfg


def _baseline_losses(module, params, micros, steps, gas, lr=1e-2):
    """Train the SAME model non-pipelined (sequential forward, dp-only
    mesh) and return per-step mean losses."""
    def loss_fn(p, batch):
        return _mse(module.forward(p, batch["x"]), batch)

    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        # same dp=2 as the pipe run; 'model' axis unused => replicated
        "mesh": {"axes": {"data": 2, "model": 4}},
    }
    eng, *_ = ds.initialize(model=loss_fn, model_parameters=params,
                            config=cfg)
    it = iter(micros)
    return [float(eng.train_batch(it)) for _ in range(steps)]


def test_pipeline_matches_nonpipelined_training():
    """The compiled pipeline computes the SAME grads/updates as sequential
    execution: loss trajectories must match (reference test_pipe.py trains
    pipe vs base and compares losses)."""
    steps, gas = 5, 4
    module = _make_module(num_stages=4)
    params = module.init_params(jax.random.PRNGKey(0))
    micros = _micro_batches(steps * gas, global_mb=4)

    base = _baseline_losses(module, params, micros, steps, gas)

    eng, *_ = ds.initialize(model=_make_module(num_stages=4),
                            model_parameters=params,
                            config=_pipe_config())
    it = iter(micros)
    pipe = [float(eng.train_batch(it)) for _ in range(steps)]

    np.testing.assert_allclose(pipe, base, rtol=2e-4, atol=1e-6)
    assert pipe[-1] < pipe[0]  # actually learning


@pytest.mark.parametrize("zero_stage", [0, 1, 2])
def test_pipeline_zero_composition(zero_stage):
    """PP x ZeRO composes (the reference forbids ZeRO-2+PP,
    engine.py:751-754; the compiled step has no such conflict)."""
    module = _make_module(num_stages=4)
    params = module.init_params(jax.random.PRNGKey(0))
    micros = _micro_batches(24, global_mb=4)
    eng, *_ = ds.initialize(
        model=module, model_parameters=params,
        config=_pipe_config(zero_optimization={"stage": zero_stage}))
    it = iter(micros)
    losses = [float(eng.train_batch(it)) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_pipeline_bf16():
    module = _make_module(num_stages=4)
    eng, *_ = ds.initialize(
        model=module,
        model_parameters=module.init_params(jax.random.PRNGKey(0)),
        config=_pipe_config(bf16={"enabled": True}))
    it = iter(_micro_batches(8, global_mb=4))
    l0 = float(eng.train_batch(it))
    l1 = float(eng.train_batch(it))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_pipeline_eval_batch():
    module = _make_module(num_stages=4)
    params = module.init_params(jax.random.PRNGKey(0))
    micros = _micro_batches(4, global_mb=4)
    eng, *_ = ds.initialize(model=module, model_parameters=params,
                            config=_pipe_config())
    ev = float(eng.eval_batch(iter(micros)))
    # must equal the sequential forward's mean loss over the 4 micros
    ref = np.mean([float(_mse(module.forward(params, m["x"]), m))
                   for m in micros])
    np.testing.assert_allclose(ev, ref, rtol=2e-4)


def test_pipeline_eval_batch_accepts_single_batch():
    """Eval API unification: like the base engine, the pipe engine now
    also accepts one batch pytree — repeated across the micro window,
    so the mean loss equals that batch's loss."""
    module = _make_module(num_stages=4)
    params = module.init_params(jax.random.PRNGKey(0))
    batch = _micro_batches(1, global_mb=4)[0]
    eng, *_ = ds.initialize(model=module, model_parameters=params,
                            config=_pipe_config())
    ev = float(eng.eval_batch(batch))
    ref = float(_mse(module.forward(params, batch["x"]), batch))
    np.testing.assert_allclose(ev, ref, rtol=2e-4)


def test_pipeline_train_batch_via_prefetcher():
    """training_data + async prefetch: the stacked (M, ...) window is
    assembled and device_put by the worker thread, and train_batch
    consumes it pre-stacked."""
    steps, gas = 3, 4
    module = _make_module(num_stages=4)
    params = module.init_params(jax.random.PRNGKey(0))
    micros = _micro_batches(steps * gas, global_mb=4)

    dataset = [{k: v[i] for k, v in m.items()}
               for m in micros for i in range(4)]
    eng, *_ = ds.initialize(model=_make_module(num_stages=4),
                            model_parameters=params,
                            config=_pipe_config(
                                async_pipeline={"prefetch_depth": 2}),
                            training_data=dataset)
    # same data, loader-shuffled order differs from the baseline — only
    # assert the plumbing: prefetcher active, stacked layout, training
    losses = [float(eng.train_batch()) for _ in range(steps)]
    assert eng._prefetcher is not None
    assert eng._prefetcher.stacks_micro_batches
    assert eng.training_dataloader.device_put_enabled is False
    assert np.isfinite(losses).all()
    assert eng.global_steps == steps
    eng.close()
    assert eng._prefetcher is None


def test_pipeline_forbids_fwd_bwd_facade():
    module = _make_module(num_stages=4)
    eng, *_ = ds.initialize(
        model=module,
        model_parameters=module.init_params(jax.random.PRNGKey(0)),
        config=_pipe_config())
    with pytest.raises(RuntimeError, match="train_batch"):
        eng.forward({"x": np.zeros((4, H), np.float32)})


def test_pipeline_spec_with_tied_head():
    """Raw PipelineSpec: embedding tied into the loss head (TiedLayerSpec
    semantics, reference module.py:71) — grads flow into pre params from
    both ends."""
    S, M = 4, 4
    V, D = 12, 8

    def init(key):
        k1, k2 = jax.random.split(key)
        stages = {"w": jax.random.normal(k2, (S, D, D), jnp.float32) * 0.2}
        return {"pre": {"emb": jax.random.normal(k1, (V, D), jnp.float32)},
                "stages": stages,
                "post": {}}

    def pre_apply(pre_p, micro, rng):
        return pre_p["emb"][micro["ids"]]

    def stage_apply(st_p, act, rng):
        return jnp.tanh(act @ st_p["w"])

    def post_apply(post_p, pre_p, act, micro):
        logits = act @ pre_p["emb"].T  # tied head
        logp = jax.nn.log_softmax(logits, axis=-1)
        tgt = micro["ids"]
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None],
                                             axis=-1))

    spec = PipelineSpec(init=init, pre_apply=pre_apply,
                        stage_apply=stage_apply, post_apply=post_apply,
                        num_stages=S)
    mesh = ds.build_mesh({"pipe": S, "data": 2})
    loss_fn = build_pipeline_loss_fn(spec, mesh, num_micro=M)
    params = init(jax.random.PRNGKey(0))
    batch = {"ids": np.random.RandomState(0).randint(
        0, V, size=(M, 4)).astype(np.int32)}
    rng = jax.random.PRNGKey(1)

    loss, grads = jax.jit(jax.value_and_grad(
        lambda p: loss_fn(p, batch, rng)))(params)
    assert np.isfinite(float(loss))
    # tied embedding receives gradient from embedding AND head use
    emb_g = np.asarray(grads["pre"]["emb"])
    assert np.abs(emb_g).sum() > 0
    # every stage's weights got a gradient
    st_g = np.asarray(grads["stages"]["w"])
    assert all(np.abs(st_g[s]).sum() > 0 for s in range(S))

    # parity vs sequential execution of the same math
    def seq_loss(p):
        total = 0.0
        for m in range(M):
            micro = {"ids": batch["ids"][m]}
            act = pre_apply(p["pre"], micro, None)
            for s in range(S):
                act = jnp.tanh(act @ p["stages"]["w"][s])
            total = total + post_apply({}, p["pre"], act, micro)
        return total / M

    ref_loss, ref_grads = jax.jit(jax.value_and_grad(seq_loss))(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6),
        grads, ref_grads)


def test_gpt2_pipeline_matches_sequential():
    """gpt2_pipeline_spec through the compiled pipeline == gpt2_forward
    sequential (3D flagship parity)."""
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, gpt2_loss_fn, gpt2_pipeline_spec, init_gpt2_params)

    cfg = GPT2Config(vocab_size=64, max_position_embeddings=32,
                     hidden_size=32, num_layers=4, num_heads=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    S, M = 2, 2
    spec = gpt2_pipeline_spec(cfg, num_stages=S, dtype=jnp.float32)
    mesh = ds.build_mesh({"pipe": S, "data": 2, "model": 2})
    loss_fn = build_pipeline_loss_fn(spec, mesh, num_micro=M)
    params = spec.init(jax.random.PRNGKey(0))
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size,
                                           size=(M, 4, 17)).astype(np.int32)
    rng = jax.random.PRNGKey(1)
    pipe_loss = float(jax.jit(loss_fn)(params, {"input_ids": ids}, rng))

    # rebuild flat params with the same leaves for the sequential reference
    flat = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    seq_fn = gpt2_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
    ref = np.mean([float(seq_fn(flat, {"input_ids": ids[m]}, rng))
                   for m in range(M)])
    np.testing.assert_allclose(pipe_loss, ref, rtol=2e-4)


def test_gpt2_pipeline_ragged_seq_cooperative_head():
    """seq %% S != 0: the cooperative head pads the exit activation to
    S*ceil(seq/S) and weight-masks the pad (VERDICT r2 weak #2 — this
    config used to fall back to the S-x-redundant masked head). Loss and
    training must match the sequential baseline."""
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, gpt2_loss_fn, gpt2_pipeline_spec, init_gpt2_params)

    cfg = GPT2Config(vocab_size=64, max_position_embeddings=32,
                     hidden_size=32, num_layers=4, num_heads=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    S, M, seq = 4, 4, 19                       # 19 % 4 != 0
    spec = gpt2_pipeline_spec(cfg, num_stages=S, dtype=jnp.float32)
    mesh = ds.build_mesh({"pipe": S, "data": 2})
    loss_fn = build_pipeline_loss_fn(spec, mesh, num_micro=M)
    params = spec.init(jax.random.PRNGKey(0))
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(M, 4, seq + 1)).astype(np.int32)
    rng = jax.random.PRNGKey(1)
    pipe_loss = float(jax.jit(loss_fn)(params, {"input_ids": ids}, rng))

    flat = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    seq_fn = gpt2_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
    ref = np.mean([float(seq_fn(flat, {"input_ids": ids[m]}, rng))
                   for m in range(M)])
    np.testing.assert_allclose(pipe_loss, ref, rtol=2e-4)

    # the training (grad) executor through the engine: loss parity after
    # an optimizer step implies the padded head's gradients are right
    eng, *_ = ds.initialize(model=spec, config={
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": M,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 10**9,
        "mesh": {"axes": {"pipe": S, "data": 2, "model": 1}},
    })
    rngs = np.random.RandomState(1)
    micros = [{"input_ids": rngs.randint(
        0, cfg.vocab_size, (4, seq + 1)).astype(np.int32)}
        for _ in range(2 * M)]
    l0 = float(eng.train_batch(iter(micros[:M])))
    l1 = float(eng.train_batch(iter(micros[M:])))
    assert np.isfinite(l0) and np.isfinite(l1)

    base_fn = gpt2_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
    eng_b, *_ = ds.initialize(
        model=base_fn, model_parameters=init_gpt2_params(
            cfg, jax.random.PRNGKey(0)),
        config={"train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": M,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10**9,
                "mesh": {"axes": {"data": 2}}})
    b0 = float(eng_b.train_batch(iter(micros[:M])))
    b1 = float(eng_b.train_batch(iter(micros[M:])))
    np.testing.assert_allclose([l0, l1], [b0, b1], rtol=2e-3, atol=1e-4)


def test_ragged_seq_head_work_stays_1x():
    """VERDICT r3 #8 'done' criterion: at seq %% S != 0 the cooperative
    head must do ~1x the vocab-GEMM work (pad factor S*chunk/seq), not
    the S-x of the masked redundant fallback. Counted structurally:
    scan-weighted executions of dot_generals producing vocab-dim
    outputs, cooperative spec vs the same spec with post_shard_apply
    stripped (which forces the fallback head on every row)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, gpt2_pipeline_spec

    # vocab must collide with no other GEMM width in the block: 3H=96
    # (fused QKV), 4H=128 (MLP), H=32 — 160 is distinct from all
    cfg = GPT2Config(vocab_size=160, max_position_embeddings=32,
                     hidden_size=32, num_layers=4, num_heads=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    S, M, seq = 4, 4, 19                       # 19 % 4 != 0
    mesh = ds.build_mesh({"pipe": S, "data": 2})
    ids = np.zeros((M, 4, seq + 1), np.int32)
    rng = jax.random.PRNGKey(1)

    def head_flops(spec):
        loss_fn = build_pipeline_loss_fn(spec, mesh, num_micro=M)
        params = spec.init(jax.random.PRNGKey(0))
        jaxpr = jax.make_jaxpr(loss_fn)(params, {"input_ids": ids}, rng)
        return _count_vocab_dot_flops(jaxpr.jaxpr, cfg.vocab_size)

    spec = gpt2_pipeline_spec(cfg, num_stages=S, dtype=jnp.float32)
    assert spec.post_shard_apply is not None
    coop = head_flops(spec)
    fallback = head_flops(spec._replace(post_shard_apply=None))
    assert coop > 0 and fallback > 0
    # cooperative: each pipe row computes 1/S of the (padded) head, so
    # total head work ~= 1x (x pad factor 20/19); the fallback runs the
    # full head masked on every head tick. Ideal single pass is derived
    # INDEPENDENTLY of coop (fallback / head-tick count x pad factor)
    # so a coop regression cannot silently rescale its own bound.
    pad_factor = S * -(-seq // S) / seq        # 20/19
    ideal = fallback / S * pad_factor
    assert coop <= fallback / 2.0, (coop, fallback)
    assert coop <= ideal * 1.5, (coop, ideal, fallback)


def _count_vocab_dot_flops(jaxpr, vocab):
    """Scan-weighted count of dot_general output elements whose trailing
    dim is the vocab size — a structural proxy for head-GEMM FLOPs (the
    same trip-count-aware walk as _count_ppermute_execs)."""
    from jax.extend import core as jex_core

    def subjaxprs(v):
        if isinstance(v, jex_core.ClosedJaxpr):
            yield v.jaxpr
        elif hasattr(v, "eqns"):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                yield from subjaxprs(item)

    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            shape = eqn.outvars[0].aval.shape
            if shape and shape[-1] == vocab:
                total += int(np.prod(shape))
        mult = (eqn.params.get("length", 1)
                if eqn.primitive.name == "scan" else 1)
        for v in eqn.params.values():
            for sub in subjaxprs(v):
                total += mult * _count_vocab_dot_flops(sub, vocab)
    return total


def test_uneven_partition_compiled_pipeline():
    """7 layers over 2 stages (4+3): the compiled executor runs the padded
    stage stack with masked no-op slots and matches the sequential-forward
    baseline (reference parameters-balanced partitions, module.py:348)."""
    module = ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(7)],
        num_stages=2, loss_fn=_mse, partition_method="uniform")
    assert module.stage_layer_counts() == [4, 3]
    params = module.init_params(jax.random.PRNGKey(0))

    micros = _micro_batches(12, 4)
    cfg = _pipe_config(mesh={"axes": {"pipe": 2, "data": 2}},
                       gradient_accumulation_steps=2)
    eng, *_ = ds.initialize(model=module, model_parameters=params,
                            config=cfg)
    pipe_losses = [float(eng.train_batch(iter(micros[2*i:2*i+2])))
                   for i in range(3)]
    assert all(np.isfinite(l) for l in pipe_losses)

    base_losses = _baseline_losses(module, params, micros, steps=3, gas=2)
    np.testing.assert_allclose(pipe_losses, base_losses[:3],
                               rtol=5e-3, atol=1e-4)


def test_uneven_gpt2_pipeline_spec():
    """GPT-2 with L=3 layers over 2 stages trains through the compiled
    pipeline (L % S != 0)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, gpt2_pipeline_spec
    cfg_m = GPT2Config(vocab_size=64, max_position_embeddings=32,
                       hidden_size=32, num_layers=3, num_heads=2,
                       embd_dropout=0.0, attn_dropout=0.0,
                       resid_dropout=0.0)
    spec = gpt2_pipeline_spec(cfg_m, num_stages=2)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"axes": {"pipe": 2, "data": 4, "model": 1}},
    }
    eng, *_ = ds.initialize(model=spec, config=config)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(2):
        micros = iter([{"input_ids": rng.randint(
            0, 64, (8, 17)).astype(np.int32)} for _ in range(2)])
        losses.append(float(eng.train_batch(micros)))
    assert all(np.isfinite(l) for l in losses)


def test_pipeline_memory_flat_in_accumulation_depth():
    """1F1B bound (VERDICT r1 #4): compiled-step temp memory must not grow
    with micro-batch count M — the executor keeps a depth-(2S-1) circular
    buffer, not an (M, ...) outbuf (reference TrainSchedule in-flight
    buffers, schedule.py:243)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, gpt2_pipeline_spec
    cfg_m = GPT2Config(vocab_size=256, max_position_embeddings=64,
                       hidden_size=64, num_layers=4, num_heads=4,
                       embd_dropout=0.0, attn_dropout=0.0,
                       resid_dropout=0.0)
    temps = {}
    for M in (2, 16):
        spec = gpt2_pipeline_spec(cfg_m, num_stages=2)
        eng, *_ = ds.initialize(model=spec, config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": M,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "mesh": {"axes": {"pipe": 2, "data": 4, "model": 1}},
        })
        rng = np.random.RandomState(0)
        batch = jax.device_put(
            {"input_ids": np.stack(
                [rng.randint(0, 256, (8, 33)).astype(np.int32)
                 for _ in range(M)])}, eng._batch_sharding)
        step = eng._get_compiled_micro_step()
        ma = step.lower(eng.state, batch).compile().memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend provides no memory analysis")
        temps[M] = ma.temp_size_in_bytes
    # allow small constant slack; forbid O(M) growth
    assert temps[16] <= temps[2] * 1.25, temps


@pytest.mark.parametrize("gas,virtual", [(4, 2), (6, 2), (4, 4)])
def test_interleaved_pipeline_matches_nonpipelined_training(gas, virtual):
    """virtual_stages=2: 8 layers as 8 global stages cyclically assigned
    to 4 devices — the interleaved executor must compute the SAME
    grads/updates as sequential execution (Megatron interleaved-1F1B
    semantics on the SPMD scan). gas=6 exercises the padded-group decode
    (M %% S != 0): the tail micros' chunk-1 items must still run."""
    steps, lr = 3, 1e-3
    pipe_axis = 8 // virtual
    module = ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(8)],
        num_stages=8, loss_fn=_mse, partition_method="uniform")
    params = module.init_params(jax.random.PRNGKey(0))
    micros = _micro_batches(steps * gas, global_mb=4)

    base = _baseline_losses(module, params, micros, steps, gas, lr=lr)

    eng, *_ = ds.initialize(
        model=module, model_parameters=params,
        config=_pipe_config(gradient_accumulation_steps=gas,
                            mesh={"axes": {"pipe": pipe_axis, "data": 2}},
                            pipeline={"virtual_stages": virtual},
                            optimizer={"type": "Adam",
                                       "params": {"lr": lr}}))
    assert eng.num_virtual == virtual
    it = iter(micros)
    pipe = [float(eng.train_batch(it)) for _ in range(steps)]

    # grad/update parity with sequential execution is the correctness
    # claim; the trajectory check guards against all-masked no-op updates
    np.testing.assert_allclose(pipe, base, rtol=2e-4, atol=1e-6)
    assert pipe[-1] != pipe[0]


def test_interleaved_checkpoint_layout_roundtrip(tmp_path):
    """Stage weights are checkpointed in the V-dependent interleaved
    layout; a resume at a different (pipe_axis, virtual_stages) must
    re-permute them (pipe_layout.json) — same model, different mapping,
    identical training trajectory."""
    micros = _micro_batches(12, global_mb=4)
    module_a = ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(8)],
        num_stages=8, loss_fn=_mse, partition_method="uniform")
    params = module_a.init_params(jax.random.PRNGKey(0))
    eng_a, *_ = ds.initialize(
        model=module_a, model_parameters=params,
        config=_pipe_config(pipeline={"virtual_stages": 2}))
    it = iter(micros)
    for _ in range(2):
        eng_a.train_batch(it)
    eng_a.save_checkpoint(str(tmp_path), tag="ck")
    loss_a = float(eng_a.train_batch(it))

    # resume with the SAME 8 global stages laid out 8x1 instead of 4x2
    module_b = ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(8)],
        num_stages=8, loss_fn=_mse, partition_method="uniform")
    eng_b, *_ = ds.initialize(
        model=module_b, model_parameters=module_b.init_params(
            jax.random.PRNGKey(42)),  # different init: load must win
        config=_pipe_config(mesh={"axes": {"pipe": 8, "data": 1}},
                            train_micro_batch_size_per_gpu=4))
    eng_b.load_checkpoint(str(tmp_path), tag="ck")
    loss_b = float(eng_b.train_batch(iter(micros[8:])))
    np.testing.assert_allclose(loss_b, loss_a, rtol=2e-4)


def _count_ppermute_execs(jaxpr):
    """Total ppermute EXECUTIONS in a jaxpr, multiplying scan bodies by
    their trip counts (XLA cost_analysis counts loop bodies once, so it
    cannot see schedule length — this can)."""
    from jax.extend import core as jex_core

    def subjaxprs(v):
        if isinstance(v, jex_core.ClosedJaxpr):
            yield v.jaxpr
        elif hasattr(v, "eqns"):
            yield v
        elif isinstance(v, (tuple, list)):
            for item in v:
                yield from subjaxprs(item)

    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "ppermute":
            total += 1
        mult = (eqn.params.get("length", 1)
                if eqn.primitive.name == "scan" else 1)
        for v in eqn.params.values():
            for sub in subjaxprs(v):
                total += mult * _count_ppermute_execs(sub)
    return total


def test_interleaved_bubble_tick_count():
    """VERDICT r2 #2 'done' criterion: interleaving cuts the normalized
    schedule from M + 2(S-1) toward M + ~1.5(S-1) ticks. Verified two
    ways: the closed-form tick counts, and a structural count of the
    compiled executor's actual scan iterations (each macro-tick executes
    exactly 2 ppermutes — the fwd and bwd rotations)."""
    from deepspeed_tpu.runtime.pipe.spmd import (
        build_pipeline_grad_fn, module_pipeline_spec)

    S, M = 4, 8
    t1, n1 = pipeline_tick_counts(S, M, V=1)
    t2, n2 = pipeline_tick_counts(S, M, V=2)
    assert (t1, n1) == (M + 2 * S - 2, M + 2 * S - 2)
    assert n2 <= M + 1.5 * (S - 1) + 0.6     # ~1.5(S-1) bubble at V=2
    assert n2 < n1

    mesh = ds.build_mesh({"pipe": S, "data": 2})
    batch = {"x": np.zeros((M, 4, H), np.float32),
             "y": np.zeros((M, 4, H), np.float32)}
    rng = jax.random.PRNGKey(0)
    measured = {}
    for v in (1, 2):
        module = ds.PipelineModule(
            [ds.LayerSpec(Linear, H) for _ in range(8)],
            num_stages=S * v, loss_fn=_mse, partition_method="uniform")
        spec = module_pipeline_spec(module, S * v)
        params = spec.init(jax.random.PRNGKey(0))
        if v > 1:
            params = dict(params)
            params["stages"] = interleave_stages(params["stages"], S, v)
        gf = build_pipeline_grad_fn(spec, mesh, num_micro=M, num_virtual=v)
        assert gf.num_ticks == pipeline_tick_counts(S, M, v)[0]
        jaxpr = jax.make_jaxpr(gf)(params, batch, rng, 1.0)
        measured[v] = _count_ppermute_execs(jaxpr.jaxpr) // 2
    # the compiled schedule really is the claimed length ...
    assert measured[1] == t1, measured
    assert measured[2] == t2, measured
    # ... and in normalized units (a V=2 tick is half the work) the
    # interleaved schedule does measurably less total wall-work
    assert measured[2] / 2 < measured[1] * 0.95, measured


def test_interleaved_gpt2_pipeline_matches_sequential():
    """Interleaved executor with the cooperative sequence-sharded head:
    gpt2_pipeline_spec with 4 global stages on a pipe-2 mesh (V=2)
    matches the sequential forward."""
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, gpt2_loss_fn, gpt2_pipeline_spec, init_gpt2_params)

    cfg = GPT2Config(vocab_size=64, max_position_embeddings=32,
                     hidden_size=32, num_layers=4, num_heads=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    S, V, M = 2, 2, 2
    spec = gpt2_pipeline_spec(cfg, num_stages=S * V, dtype=jnp.float32)
    mesh = ds.build_mesh({"pipe": S, "data": 2, "model": 2})
    loss_fn = build_pipeline_loss_fn(spec, mesh, num_micro=M,
                                     num_virtual=V)
    params = spec.init(jax.random.PRNGKey(0))
    params = dict(params)
    params["stages"] = interleave_stages(params["stages"], S, V)
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size,
                                           size=(M, 4, 17)).astype(np.int32)
    rng = jax.random.PRNGKey(1)
    pipe_loss = float(jax.jit(loss_fn)(params, {"input_ids": ids}, rng))

    flat = init_gpt2_params(cfg, jax.random.PRNGKey(0))
    seq_fn = gpt2_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
    ref = np.mean([float(seq_fn(flat, {"input_ids": ids[m]}, rng))
                   for m in range(M)])
    np.testing.assert_allclose(pipe_loss, ref, rtol=2e-4)


def test_pipeline_fp16_loss_scaling():
    """fp16 + pipeline: the 1F1B executor's explicit grads flow through
    the engine's dynamic loss scaling (overflow skip machinery)."""
    module = _make_module(num_stages=4)
    eng, *_ = ds.initialize(
        model=module,
        model_parameters=module.init_params(jax.random.PRNGKey(0)),
        config=_pipe_config(fp16={"enabled": True,
                                  "initial_scale_power": 8}))
    it = iter(_micro_batches(16, global_mb=4))
    losses = [float(eng.train_batch(it)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert eng.loss_scale() > 0
    assert losses[-1] < losses[0]


def test_interleaved_eval_batch():
    """eval_batch (forward-only wavefront) under virtual_stages=2 must
    equal the sequential forward mean."""
    module = ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(8)],
        num_stages=8, loss_fn=_mse, partition_method="uniform")
    params = module.init_params(jax.random.PRNGKey(0))
    micros = _micro_batches(4, global_mb=4)
    eng, *_ = ds.initialize(model=module, model_parameters=params,
                            config=_pipe_config(
                                pipeline={"virtual_stages": 2}))
    ev = float(eng.eval_batch(iter(micros)))
    ref = np.mean([float(_mse(module.forward(params, m["x"]), m))
                   for m in micros])
    np.testing.assert_allclose(ev, ref, rtol=2e-4)


def test_adam8bit_pipeline_same_layout_resume_and_layout_change_guard(
        tmp_path):
    """Quantized optimizer states compose with the pipeline at a FIXED
    layout (train, save, resume, continue); a layout-change resume must
    raise (axis 0 of the int8 code leaves is quantization blocks, not
    the stage axis, so re-permutation would corrupt state silently)."""
    micros = _micro_batches(12, global_mb=4)
    mk = lambda: ds.PipelineModule(
        [ds.LayerSpec(Linear, H) for _ in range(8)],
        num_stages=8, loss_fn=_mse, partition_method="uniform")
    module_a = mk()
    params = module_a.init_params(jax.random.PRNGKey(0))
    cfg = _pipe_config(pipeline={"virtual_stages": 2},
                       optimizer={"type": "Adam8bit",
                                  "params": {"lr": 1e-2}})
    eng_a, *_ = ds.initialize(model=module_a, model_parameters=params,
                              config=cfg)
    it = iter(micros)
    for _ in range(2):
        eng_a.train_batch(it)
    eng_a.save_checkpoint(str(tmp_path), tag="ck")
    loss_a = float(eng_a.train_batch(it))

    # same layout: resume must reproduce the trajectory
    module_b = mk()
    eng_b, *_ = ds.initialize(
        model=module_b,
        model_parameters=module_b.init_params(jax.random.PRNGKey(42)),
        config=cfg)
    eng_b.load_checkpoint(str(tmp_path), tag="ck")
    loss_b = float(eng_b.train_batch(iter(micros[8:])))
    np.testing.assert_allclose(loss_b, loss_a, rtol=2e-4)

    # different layout: explicit refusal, not silent corruption
    module_c = mk()
    eng_c, *_ = ds.initialize(
        model=module_c,
        model_parameters=module_c.init_params(jax.random.PRNGKey(7)),
        config=_pipe_config(mesh={"axes": {"pipe": 8, "data": 1}},
                            train_micro_batch_size_per_gpu=4,
                            optimizer={"type": "Adam8bit",
                                       "params": {"lr": 1e-2}}))
    with pytest.raises(ValueError, match="Adam8bit"):
        eng_c.load_checkpoint(str(tmp_path), tag="ck")
