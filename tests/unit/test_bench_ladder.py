"""Bench ladder hardening (ISSUE 6 satellite; ROADMAP meta item).

r02–r05 produced zero hardware numbers because one dead tunnel zeroed
each revision's perf record. The contracts pinned here, against the
importable ladder helpers in bench.py (no device, no child process
unless marked slow):

- probe-before-run: a dead tunnel yields explicit ``device_unreachable``
  skip rows for every hardware metric — fast — instead of hanging
  per-metric; hardware-free rows still land.
- resume-from-partial: a rerun at the same source digest reuses the
  fsynced partial rows and only runs missing metrics; a different
  digest never resumes them as measurements (only as clearly-labeled
  stale context on error rows).
- row salvage: a child killed by the per-metric timeout AFTER its row
  streamed (teardown hang — the historical failure) keeps the
  measurement instead of discarding it.
"""

import json
import subprocess
import sys
import time

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
import bench  # noqa: E402


def _row(metric, value=1.0, unit="u"):
    return {"metric": metric, "value": value, "unit": unit,
            "vs_baseline": 1.0, "detail": {}}


# ------------------------------------------------------- resume-from-partial


def test_partial_roundtrip_resumes_same_head(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    row = _row("m1")
    fresh = bench._append_partial("src-AAAA", row, True)
    assert fresh is False                    # header written
    fresh = bench._append_partial("src-AAAA", _row("m2"), fresh)
    got = bench._load_partial("src-AAAA")
    assert set(got) == {"m1", "m2"} and got["m1"] == row


def test_partial_never_resumes_across_source_digests(monkeypatch,
                                                     tmp_path):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    bench._append_partial("src-AAAA", _row("m1", 7.0), True)
    assert bench._load_partial("src-BBBB") == {}
    stale = bench._stale_partial("src-BBBB")
    assert stale["rows"]["m1"]["value"] == 7.0
    assert "NOT a current measurement" in stale["note"]
    assert bench._stale_partial("src-AAAA") is None   # same digest: resume


def test_partial_skips_error_rows_and_no_resume_knob(monkeypatch,
                                                     tmp_path):
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    fresh = bench._append_partial("src-AAAA", _row("good"), True)
    bench._append_partial(
        "src-AAAA", {"metric": "bad", "value": 0.0, "unit": "error",
                     "vs_baseline": 0.0, "detail": {"error": "x"}}, fresh)
    got = bench._load_partial("src-AAAA")
    assert "good" in got and "bad" not in got     # errors rerun
    monkeypatch.setenv("BENCH_NO_RESUME", "1")
    assert bench._load_partial("src-AAAA") == {}


# ------------------------------------------------------------- row salvage


def test_last_metric_row_takes_last_match():
    out = "\n".join(["garbage", json.dumps(_row("m", 1.0)),
                     json.dumps(_row("other", 9.0)),
                     json.dumps(_row("m", 2.0))])
    assert bench._last_metric_row(out, "m")["value"] == 2.0
    assert bench._last_metric_row("", "m") is None
    assert bench._last_metric_row("{not json", "m") is None


def test_watchdog_error_row_does_not_clobber_a_streamed_value_row(
        monkeypatch):
    """A child whose in-process stall watchdog fires during TEARDOWN —
    after the measurement row already streamed — appends a
    device_unreachable error row last and os._exit(2)s. The parent must
    keep the completed measurement (flagged), not discard it for the
    trailing error row."""
    value = _row("m", 4.2)
    err = {"metric": "m", "value": 0.0, "unit": "error",
           "vs_baseline": 0.0,
           "detail": {"error": "device_unreachable: no progress"}}
    out = json.dumps(value) + "\n" + json.dumps(err) + "\n"
    assert bench._last_metric_row(out, "m")["value"] == 4.2

    class R:
        stdout, stderr, returncode = out, "", 2

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: R())
    got, errmsg = bench._run_metric_subprocess("m")
    assert errmsg is None and got["value"] == 4.2
    assert "salvaged" in got["detail"]
    # error-only output still reports the error
    class R2:
        stdout, stderr, returncode = json.dumps(err) + "\n", "", 2

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **k: R2())
    got, errmsg = bench._run_metric_subprocess("m")
    assert got is None and "device_unreachable" in errmsg


def test_timed_out_child_with_streamed_row_is_salvaged(monkeypatch):
    row = _row("decode_throughput", 5.0, "tokens_per_s")

    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(
            cmd, kw.get("timeout") or 1,
            output=json.dumps(row) + "\n")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    got, err = bench._run_metric_subprocess("decode_throughput")
    assert err is None and got["value"] == 5.0
    assert "salvaged" in got["detail"]


def test_timed_out_child_without_row_reports_timeout(monkeypatch):
    def fake_run(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout") or 1)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    got, err = bench._run_metric_subprocess("decode_throughput")
    assert got is None and "exceeded" in err
    # a streamed ERROR row is not a measurement either
    err_row = {"metric": "decode_throughput", "value": 0.0,
               "unit": "error", "vs_baseline": 0.0,
               "detail": {"error": "device_unreachable: stalled"}}

    def fake_run2(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get("timeout") or 1,
                                        output=json.dumps(err_row))

    monkeypatch.setattr(bench.subprocess, "run", fake_run2)
    got, err = bench._run_metric_subprocess("decode_throughput")
    assert got is None


# ---------------------------------------------------------- probe-before-run


def test_dead_tunnel_yields_explicit_skip_rows(monkeypatch, capsys,
                                               tmp_path):
    """End-to-end parent path with a dead tunnel: hardware metrics
    become explicit device_unreachable error rows IMMEDIATELY (two
    probes, no per-metric timeout burn), the headline error row is
    last, and nothing hangs."""
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    monkeypatch.setattr(bench, "METRICS",
                        ["hw_a", "gpt2_train_mfu"])
    monkeypatch.setattr(bench, "HW_FREE", set())
    monkeypatch.setattr(bench, "HEADLINE", "gpt2_train_mfu")
    monkeypatch.setattr(bench, "_probe_tunnel", lambda *a, **k: False)
    monkeypatch.setattr(bench, "_T_START", time.monotonic())
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    ran = []
    monkeypatch.setattr(bench, "_run_metric_subprocess",
                        lambda m: ran.append(m) or (None, "should not run"))
    bench.main()
    out = capsys.readouterr().out
    rows = [json.loads(l) for l in out.splitlines()
            if l.strip().startswith("{")]
    assert ran == []                       # no child burned a timeout
    assert rows and all(r["unit"] == "error" for r in rows)
    for r in rows:
        assert "device_unreachable" in r["detail"]["error"]
        assert r["detail"].get("skipped") is True
    assert rows[-1]["metric"] == "gpt2_train_mfu"   # headline last


def test_hw_free_rows_land_even_with_dead_tunnel(monkeypatch, capsys,
                                                 tmp_path):
    """The hardware-free rows run in forced-CPU children and must land
    (and checkpoint) before any tunnel probe happens."""
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    monkeypatch.setattr(bench, "METRICS", ["freebie", "gpt2_train_mfu"])
    monkeypatch.setattr(bench, "HW_FREE", {"freebie"})
    monkeypatch.setattr(bench, "HEADLINE", "gpt2_train_mfu")
    monkeypatch.setattr(bench, "_probe_tunnel", lambda *a, **k: False)
    monkeypatch.setattr(bench, "_T_START", time.monotonic())
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    monkeypatch.setattr(
        bench, "_run_metric_subprocess",
        lambda m: (_row(m, 3.0), None) if m == "freebie"
        else (None, "nope"))
    monkeypatch.setattr(bench, "_git_head", lambda: "src-TEST")
    bench.main()
    out = capsys.readouterr().out
    rows = [json.loads(l) for l in out.splitlines()
            if l.strip().startswith("{")]
    by_metric = {r["metric"]: r for r in rows}     # last occurrence wins
    assert by_metric["freebie"]["value"] == 3.0
    assert by_metric["gpt2_train_mfu"]["unit"] == "error"
    # and the good row was checkpointed for resume
    assert "freebie" in bench._load_partial("src-TEST")


# ------------------------------------------------- stalled-child postmortem


def test_stalled_child_black_box_is_salvaged(monkeypatch, tmp_path):
    """ISSUE 15: a child whose stall watchdog fired dumps its flight
    ring before os._exit(2) and names the stall in its error row; the
    parent folds BOTH into _STALL_POSTMORTEMS keyed by metric."""
    flight = str(tmp_path / "flight.json")
    monkeypatch.setenv("BENCH_FLIGHT_PATH", flight)
    monkeypatch.setattr(bench, "_STALL_POSTMORTEMS", {})
    err = {"metric": "m", "value": 0.0, "unit": "error",
           "vs_baseline": 0.0,
           "detail": {"error": "device_unreachable: no benchmark "
                               "progress for 300s (tunnel down?)",
                      "skipped": True,
                      "stall_detected": {"phase": "bench_metric",
                                         "flight": flight}}}

    def fake_run(cmd, **kw):
        # the "child": dumps its black box, then streams the error row
        with open(flight, "w") as f:
            json.dump({"trigger": "bench_stall",
                       "rows": [{"event": "bench_start", "metric": "m"},
                                {"event": "bench_beat", "t_mono": 1.0}],
                       "stall": {"metric": "m", "phase": "bench_metric",
                                 "timeout_s": 300},
                       "stacks": {"MainThread (1)": ["wedged here"]}}, f)

        class R:
            stdout = json.dumps(err) + "\n"
            stderr, returncode = "", 2
        return R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    got, errmsg = bench._run_metric_subprocess("m")
    assert got is None and "device_unreachable" in errmsg
    post = bench._STALL_POSTMORTEMS["m"]
    assert post["stall_detected"]["phase"] == "bench_metric"
    assert post["flight"]["trigger"] == "bench_stall"
    assert post["flight"]["rows"] == 2       # pre-stall ring survived
    assert post["flight"]["stall"]["phase"] == "bench_metric"
    assert post["flight"]["threads"] == 1
    # a stale flight file is REMOVED before the next launch — it must
    # never masquerade as a fresh dump
    seen = []

    def fake_run2(cmd, **kw):
        seen.append(bench.os.path.exists(flight))

        class R:
            stdout = json.dumps(_row("m", 1.0)) + "\n"
            stderr, returncode = "", 0
        return R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run2)
    got, errmsg = bench._run_metric_subprocess("m")
    assert got is not None and seen == [False]


def test_error_row_carries_stall_postmortem(monkeypatch, capsys,
                                            tmp_path):
    """main()'s explicit error row for a stalled metric includes the
    salvaged postmortem under detail.stalled."""
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    monkeypatch.setattr(bench, "METRICS", ["stuck"])
    monkeypatch.setattr(bench, "HW_FREE", {"stuck"})
    monkeypatch.setattr(bench, "HEADLINE", "stuck")
    monkeypatch.setattr(bench, "_T_START", time.monotonic())
    monkeypatch.setattr(bench.sys, "argv", ["bench.py"])
    monkeypatch.setattr(bench, "_git_head", lambda: "src-TEST")
    post = {"stall_detected": {"phase": "bench_metric", "flight": "/f"},
            "flight": {"path": "/f", "trigger": "bench_stall",
                       "rows": 7, "stall": None, "threads": 3}}
    monkeypatch.setattr(bench, "_STALL_POSTMORTEMS", {"stuck": post})
    monkeypatch.setattr(bench, "_run_metric_subprocess",
                        lambda m: (None, "metric subprocess exceeded "
                                         "300s (killed)"))
    bench.main()
    rows = [json.loads(l) for l in capsys.readouterr().out.splitlines()
            if l.strip().startswith("{")]
    row = rows[-1]
    assert row["metric"] == "stuck" and row["unit"] == "error"
    assert row["detail"]["stalled"] == post
    assert row["detail"]["stalled"]["flight"]["rows"] == 7


def test_health_overhead_is_in_the_ladder():
    assert "health_overhead" in bench.METRICS
    assert "health_overhead" in bench.HW_FREE
    # hardware-free: runs before the tunnel probe, in canonical order
    assert (bench.METRICS.index("health_overhead")
            < bench.METRICS.index("bert_large_samples_per_s"))


# ------------------------------------------------------------- comm row


def test_comm_overlap_structure_is_in_the_ladder():
    assert "comm_overlap_structure" in bench.METRICS
    assert "comm_overlap_structure" in bench.HW_FREE
    # hardware-free rows run before the tunnel probe, in canonical order
    assert (bench.METRICS.index("comm_overlap_structure")
            < bench.METRICS.index("bert_large_samples_per_s"))


@pytest.mark.slow
def test_bench_comm_overlap_structure_row():
    """The hardware-free row lands a real JSON row from a fresh child
    (same invocation the ladder parent uses): overlapped fraction 1.0,
    serial control 0.0, flush collectives outside the loop."""
    import os
    repo = __file__.rsplit("/tests/", 1)[0]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--metric", "comm_overlap_structure"],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo)
    rows = [json.loads(l) for l in r.stdout.splitlines()
            if l.strip().startswith("{")]
    assert rows, (r.stdout[-2000:], r.stderr[-2000:])
    row = rows[-1]
    assert row["metric"] == "comm_overlap_structure"
    assert row["value"] == 1.0
    assert row["detail"]["serial_overlap_fraction"] == 0.0
    assert row["detail"]["flush_outside_loop"] >= 2
    assert 0 < row["vs_baseline"] <= 1.0
