"""Model-family smoke + engine integration tests (replaces the reference's
tests/model/ harnesses, which drove Megatron-GPT2/BingBert by subprocess —
here tiny configs of the same model families train in-process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.bert import (
    BertConfig, bert_mlm_loss_fn, init_bert_params)
from deepspeed_tpu.models.gpt2 import (
    GPT2Config, count_params, gpt2_forward, gpt2_loss_fn, gpt2_param_specs,
    init_gpt2_params)

TINY_GPT2 = GPT2Config(vocab_size=128, max_position_embeddings=64,
                       hidden_size=32, num_layers=2, num_heads=2,
                       embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
TINY_BERT = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                       num_heads=2, intermediate_size=64,
                       max_position_embeddings=64,
                       hidden_dropout=0.0, attn_dropout=0.0)


class TestGPT2:

    def test_param_count_gpt2_small_shape(self):
        # full-size param count sanity: GPT-2 small ≈ 124M
        from deepspeed_tpu.models.gpt2 import GPT2_SMALL
        params = jax.eval_shape(
            lambda k: init_gpt2_params(GPT2_SMALL, k),
            jax.random.PRNGKey(0))
        n = sum(int(np.prod(l.shape))
                for l in jax.tree_util.tree_leaves(params))
        assert 120e6 < n < 130e6, n

    def test_forward_shapes_and_causality(self):
        params = init_gpt2_params(TINY_GPT2, jax.random.PRNGKey(0))
        ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)))
        logits = gpt2_forward(params, TINY_GPT2, ids, dtype=jnp.float32)
        assert logits.shape == (2, 16, 128)
        # causality: changing a late token must not affect earlier logits
        ids2 = ids.at[:, 10].set((ids[:, 10] + 1) % 128)
        logits2 = gpt2_forward(params, TINY_GPT2, ids2, dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(logits[:, :10]),
                                   np.asarray(logits2[:, :10]), atol=1e-5)
        assert not np.allclose(np.asarray(logits[:, 10:]),
                               np.asarray(logits2[:, 10:]))

    def test_trains_with_engine_zero2(self):
        params = init_gpt2_params(TINY_GPT2, jax.random.PRNGKey(0))
        loss_fn = gpt2_loss_fn(TINY_GPT2, dtype=jnp.float32)
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "zero_optimization": {"stage": 2},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        rng = np.random.RandomState(0)
        data = rng.randint(0, 128, (8, 17))
        losses = [float(engine.train_batch(iter([{"input_ids": data}])))
                  for _ in range(15)]
        assert losses[-1] < losses[0], losses

    @pytest.mark.slow
    def test_tp_sharded_train_step(self):
        """TP over 'model' axis + DP: the Megatron-style 3D slice minus
        pipe (covered in pipeline tests)."""
        params = init_gpt2_params(TINY_GPT2, jax.random.PRNGKey(0))
        loss_fn = gpt2_loss_fn(TINY_GPT2, dtype=jnp.float32)
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn, model_parameters=params,
            param_specs=gpt2_param_specs(TINY_GPT2),
            config={"train_micro_batch_size_per_gpu": 1,
                    "zero_optimization": {"stage": 1},
                    "mesh": {"axes": {"data": 4, "model": 2}},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        rng = np.random.RandomState(0)
        data = rng.randint(0, 128, (4, 17))
        l0 = float(engine.train_batch(iter([{"input_ids": data}])))
        l5 = None
        for _ in range(9):
            l5 = float(engine.train_batch(iter([{"input_ids": data}])))
        assert l5 < l0
        # qkvw must actually be sharded over the model axis
        w = engine.state.params["h_0"]["attn"]["qkvw"]
        assert w.sharding.shard_shape(w.shape)[1] == w.shape[1] // 2

    def test_remat_matches(self):
        params = init_gpt2_params(TINY_GPT2, jax.random.PRNGKey(0))
        ids = {"input_ids": jnp.asarray(
            np.random.RandomState(0).randint(0, 128, (2, 17)))}
        l1 = gpt2_loss_fn(TINY_GPT2, dtype=jnp.float32, remat=False,
                          deterministic=True)(params, ids, None)
        l2 = gpt2_loss_fn(TINY_GPT2, dtype=jnp.float32, remat=True,
                          deterministic=True)(params, ids, None)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


class TestBert:

    def test_mlm_trains(self):
        params = init_bert_params(TINY_BERT, jax.random.PRNGKey(0))
        loss_fn = bert_mlm_loss_fn(TINY_BERT, dtype=jnp.float32)
        engine, *_ = deepspeed_tpu.initialize(
            model=loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Lamb", "params": {"lr": 1e-3}}})
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (8, 16))
        labels = np.where(rng.rand(8, 16) < 0.15, ids, -100)
        attn = np.ones((8, 16), np.int32)
        batch = {"input_ids": ids, "labels": labels, "attention_mask": attn}
        losses = [float(engine.train_batch(iter([batch])))
                  for _ in range(15)]
        assert losses[-1] < losses[0], losses

    def test_padding_mask_ignores_padded_positions(self):
        params = init_bert_params(TINY_BERT, jax.random.PRNGKey(0))
        from deepspeed_tpu.models.bert import bert_encoder
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (1, 16))
        mask = np.ones((1, 16), np.int32)
        mask[0, 8:] = 0
        out1 = bert_encoder(params, TINY_BERT, jnp.asarray(ids),
                            attention_mask=jnp.asarray(mask),
                            dtype=jnp.float32)
        ids2 = ids.copy()
        ids2[0, 12] = (ids2[0, 12] + 1) % 128  # change a PADDED position
        out2 = bert_encoder(params, TINY_BERT, jnp.asarray(ids2),
                            attention_mask=jnp.asarray(mask),
                            dtype=jnp.float32)
        # non-padded outputs unchanged
        np.testing.assert_allclose(np.asarray(out1[:, :8]),
                                   np.asarray(out2[:, :8]), atol=1e-5)


@pytest.mark.slow
def test_bert_tensor_parallel_training():
    """BERT + Megatron-style TP specs over the 'model' axis trains under
    GSPMD (dp x tp mesh) and matches the replicated run's loss."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.bert import (BertConfig, bert_mlm_loss_fn,
                                           bert_param_specs,
                                           init_bert_params)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=64,
                     hidden_dropout=0.0, attn_dropout=0.0)
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    loss_fn = bert_mlm_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (8, 32)).astype(np.int32)
    labels = np.where(rng.rand(8, 32) < 0.15, ids, -100).astype(np.int32)
    batch = {"input_ids": ids, "labels": labels}

    losses = {}
    for name, axes, specs in [
        ("tp", {"data": 2, "model": 4}, bert_param_specs(cfg)),
        ("dp", {"data": 8}, None),
    ]:
        e, *_ = ds.initialize(
            model=loss_fn, model_parameters=params, param_specs=specs,
            config={"train_micro_batch_size_per_gpu": 8 // axes["data"],
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "mesh": {"axes": axes}})
        losses[name] = [float(e.train_batch(iter([batch])))
                        for _ in range(3)]
    np.testing.assert_allclose(losses["tp"], losses["dp"], rtol=1e-4)


def test_hash_dropout_statistics():
    """The counter-hash dropout keeps ~keep_prob of elements, scales by
    1/keep, and is deterministic per key."""
    from deepspeed_tpu.ops.functional import dropout
    x = jnp.ones((512, 512), jnp.float32)
    key = jax.random.PRNGKey(3)
    y1 = np.asarray(dropout(x, 0.3, key, False))
    y2 = np.asarray(dropout(x, 0.3, key, False))
    np.testing.assert_array_equal(y1, y2)
    kept = (y1 != 0).mean()
    assert abs(kept - 0.7) < 0.01
    np.testing.assert_allclose(y1[y1 != 0], 1.0 / 0.7, rtol=1e-6)
    # different key -> different mask
    y3 = np.asarray(dropout(x, 0.3, jax.random.PRNGKey(4), False))
    assert (y1 != y3).any()


class TestGPT2Generate:
    """KV-cache sampling (beyond-reference: the snapshot is
    training-only). Greedy decode must exactly reproduce the naive
    full-forward-per-token loop — one shared cache bug (wrong position,
    stale layer, missed LN) breaks equality immediately."""

    def _cfg_params(self):
        from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
        cfg = GPT2Config(vocab_size=97, max_position_embeddings=32,
                         hidden_size=32, num_layers=3, num_heads=4,
                         embd_dropout=0.0, attn_dropout=0.0,
                         resid_dropout=0.0)
        return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))

    @pytest.mark.slow
    def test_greedy_matches_full_forward_loop(self):
        from deepspeed_tpu.models.gpt2 import gpt2_forward, gpt2_generate
        cfg, params = self._cfg_params()
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(0, 97, (2, 5)), jnp.int32)
        out = gpt2_generate(params, cfg, prompt, max_new_tokens=6,
                            rng=None, dtype=jnp.float32)
        assert out.shape == (2, 11)

        ids = prompt
        for _ in range(6):
            logits = gpt2_forward(params, cfg, ids, deterministic=True,
                                  dtype=jnp.float32)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ids))

    def test_sampled_tokens_in_range_and_deterministic_per_seed(self):
        from deepspeed_tpu.models.gpt2 import gpt2_generate
        cfg, params = self._cfg_params()
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        r = jax.random.PRNGKey(7)
        a = gpt2_generate(params, cfg, prompt, 8, rng=r, temperature=0.8,
                          top_k=10, dtype=jnp.float32)
        b = gpt2_generate(params, cfg, prompt, 8, rng=r, temperature=0.8,
                          top_k=10, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(jnp.max(a)) < 97 and int(jnp.min(a)) >= 0

    def test_generate_edge_cases(self):
        from deepspeed_tpu.models.gpt2 import (gpt2_generate,
                                               init_gpt2_moe_params)
        from deepspeed_tpu.ops.moe import MoEConfig
        cfg, params = self._cfg_params()
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        # max_new_tokens=0 -> prompt unchanged
        out = gpt2_generate(params, cfg, prompt, 0, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))
        # top_k beyond the vocab is clamped, not a trace error
        out = gpt2_generate(params, cfg, prompt, 2, rng=jax.random.PRNGKey(0),
                            top_k=10**6, dtype=jnp.float32)
        assert out.shape == (1, 5)
        # MoE params rejected with a clear error
        moe_cfg = MoEConfig(hidden_size=32, intermediate_size=64,
                            num_experts=2, top_k=1)
        moe_params = init_gpt2_moe_params(cfg, moe_cfg,
                                          jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="dense GPT-2 family"):
            gpt2_generate(moe_params, cfg, prompt, 2)


class TestScanLayers:
    """scan_layers=True: stacked layer params + lax.scan trunk —
    numerically equivalent to the unrolled h_{i} layout."""

    def _pair(self):
        cfg_u = TINY_GPT2._replace(num_layers=3)
        cfg_s = cfg_u._replace(scan_layers=True)
        pu = init_gpt2_params(cfg_u, jax.random.PRNGKey(7))
        ps = init_gpt2_params(cfg_s, jax.random.PRNGKey(7))
        return cfg_u, cfg_s, pu, ps

    def test_stacked_init_matches_unrolled(self):
        cfg_u, cfg_s, pu, ps = self._pair()
        assert set(ps) == {"wte", "wpe", "ln_f", "h"}
        assert ps["h"]["attn"]["qkvw"].shape == (3, 32, 96)
        for i in range(3):
            np.testing.assert_array_equal(
                np.asarray(ps["h"]["attn"]["qkvw"][i]),
                np.asarray(pu[f"h_{i}"]["attn"]["qkvw"]))
        assert count_params(ps) == count_params(pu)

    def test_loss_and_grads_match_unrolled(self):
        cfg_u, cfg_s, pu, ps = self._pair()
        ids = np.random.RandomState(0).randint(
            0, 128, (2, 33)).astype(np.int32)
        batch = {"input_ids": ids}
        rng = jax.random.PRNGKey(1)
        for remat in (False, True):
            lu = gpt2_loss_fn(cfg_u, dtype=jnp.float32, remat=remat,
                              deterministic=True)
            ls = gpt2_loss_fn(cfg_s, dtype=jnp.float32, remat=remat,
                              deterministic=True)
            vu, gu = jax.value_and_grad(lu)(pu, batch, rng)
            vs, gs = jax.value_and_grad(ls)(ps, batch, rng)
            np.testing.assert_allclose(float(vu), float(vs), rtol=1e-6)
            for i in range(3):
                np.testing.assert_allclose(
                    np.asarray(gs["h"]["mlp"]["fc_w"][i]),
                    np.asarray(gu[f"h_{i}"]["mlp"]["fc_w"]),
                    rtol=2e-5, atol=1e-6)

    def test_tp_specs_and_engine_step(self):
        import deepspeed_tpu as ds
        cfg = TINY_GPT2._replace(num_layers=2, scan_layers=True)
        params = init_gpt2_params(cfg, jax.random.PRNGKey(0))
        specs = gpt2_param_specs(cfg)
        assert specs["h"]["attn"]["qkvw"] == jax.sharding.PartitionSpec(
            None, None, "model")
        loss_fn = gpt2_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
        ids = np.random.RandomState(0).randint(
            0, 128, (8, 33)).astype(np.int32)
        e, *_ = ds.initialize(
            model=loss_fn, model_parameters=params, param_specs=specs,
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 2},
                    "mesh": {"axes": {"data": 2, "model": 4}}})
        first = float(e.train_batch(iter([{"input_ids": ids}])))
        for _ in range(4):
            last = float(e.train_batch(iter([{"input_ids": ids}])))
        assert last < first

    def test_generate_matches_unrolled(self):
        from deepspeed_tpu.models.gpt2 import gpt2_generate
        cfg_u, cfg_s, pu, ps = self._pair()
        prompt = np.random.RandomState(3).randint(
            0, 128, (2, 5)).astype(np.int32)
        gu = gpt2_generate(pu, cfg_u, prompt, 6, dtype=jnp.float32)
        gs = gpt2_generate(ps, cfg_s, prompt, 6, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(gu), np.asarray(gs))

    def test_heterogeneous_paths_rejected(self):
        from deepspeed_tpu.models.gpt2 import (gpt2_pipeline_spec,
                                               init_gpt2_moe_params)
        cfg = TINY_GPT2._replace(scan_layers=True)
        with pytest.raises(AssertionError):
            gpt2_pipeline_spec(cfg, num_stages=2)
        with pytest.raises(AssertionError):
            init_gpt2_moe_params(cfg, None, jax.random.PRNGKey(0))


class TestBertScanLayers:
    def _pair(self):
        cfg_u = TINY_BERT._replace(num_layers=3, hidden_dropout=0.0,
                                   attn_dropout=0.0)
        cfg_s = cfg_u._replace(scan_layers=True)
        pu = init_bert_params(cfg_u, jax.random.PRNGKey(5))
        ps = init_bert_params(cfg_s, jax.random.PRNGKey(5))
        return cfg_u, cfg_s, pu, ps

    def test_mlm_loss_and_grads_match_unrolled(self):
        cfg_u, cfg_s, pu, ps = self._pair()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 32)).astype(np.int32)
        labels = np.where(rng.rand(2, 32) < 0.2, ids, -100).astype(np.int32)
        am = (rng.rand(2, 32) > 0.1).astype(np.int32)
        batch = {"input_ids": ids, "labels": labels, "attention_mask": am}
        key = jax.random.PRNGKey(2)
        lu = bert_mlm_loss_fn(cfg_u, dtype=jnp.float32, deterministic=True)
        ls = bert_mlm_loss_fn(cfg_s, dtype=jnp.float32, deterministic=True)
        vu, gu = jax.value_and_grad(lu)(pu, batch, key)
        vs, gs = jax.value_and_grad(ls)(ps, batch, key)
        np.testing.assert_allclose(float(vu), float(vs), rtol=1e-6)
        for i in range(3):
            np.testing.assert_allclose(
                np.asarray(gs["layers"]["qkvw"][i]),
                np.asarray(gu[f"layer_{i}"]["qkvw"]),
                rtol=2e-5, atol=1e-6)

    def test_tp_engine_step(self):
        import deepspeed_tpu as ds
        from deepspeed_tpu.models.bert import bert_param_specs
        cfg = TINY_BERT._replace(scan_layers=True, hidden_dropout=0.0,
                                 attn_dropout=0.0)
        params = init_bert_params(cfg, jax.random.PRNGKey(0))
        loss_fn = bert_mlm_loss_fn(cfg, dtype=jnp.float32,
                                   deterministic=True)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (8, 32)).astype(np.int32)
        labels = np.where(rng.rand(8, 32) < 0.15, ids, -100).astype(np.int32)
        e, *_ = ds.initialize(
            model=loss_fn, model_parameters=params,
            param_specs=bert_param_specs(cfg),
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "mesh": {"axes": {"data": 2, "model": 4}}})
        batch = {"input_ids": ids, "labels": labels}
        first = float(e.train_batch(iter([batch])))
        for _ in range(4):
            last = float(e.train_batch(iter([batch])))
        assert last < first

    def test_sparse_attention_composes_with_scan(self):
        """Model surgery (SparsityConfig attention swap) under the
        scanned encoder matches the unrolled encoder."""
        from deepspeed_tpu.models.bert import bert_encoder
        from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
        cfg_u, cfg_s, pu, ps = self._pair()
        sc = FixedSparsityConfig(num_heads=2, block=16,
                                 num_local_blocks=2, num_global_blocks=1,
                                 attention="bidirectional")
        ids = np.random.RandomState(1).randint(
            0, 128, (2, 64)).astype(np.int32)
        ou = bert_encoder(pu, cfg_u, ids, deterministic=True,
                          dtype=jnp.float32, sparsity_config=sc)
        os_ = bert_encoder(ps, cfg_s, ids, deterministic=True,
                           dtype=jnp.float32, sparsity_config=sc)
        np.testing.assert_allclose(np.asarray(ou), np.asarray(os_),
                                   rtol=1e-5, atol=1e-5)


class TestLlama:
    """Llama-style family: RoPE + RMSNorm + SwiGLU + native-GQA flash."""

    def _cfg(self, **kw):
        from deepspeed_tpu.models.llama import LlamaConfig
        base = dict(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, num_kv_heads=2,
                    max_position_embeddings=128)
        base.update(kw)
        return LlamaConfig(**base)

    def test_rope_relative_position_property(self):
        """Post-RoPE q·k depends only on the relative distance."""
        from deepspeed_tpu.models.llama import apply_rope, rope_cos_sin
        rng = np.random.RandomState(0)
        qv = jnp.asarray(rng.randn(1, 1, 1, 32), jnp.float32)
        kv = jnp.asarray(rng.randn(1, 1, 1, 32), jnp.float32)
        S = 16
        cos, sin = rope_cos_sin(S, 32, 10000.0)
        q = apply_rope(jnp.broadcast_to(qv, (1, 1, S, 32)), cos, sin)
        k = apply_rope(jnp.broadcast_to(kv, (1, 1, S, 32)), cos, sin)
        # same relative offset d: q_i . k_{i-d} constant over i
        scores = np.asarray(jnp.einsum("bhqd,bhkd->bhqk", q, k))[0, 0]
        for d in (1, 3, 7):
            diag = np.array([scores[i, i - d] for i in range(d, S)])
            np.testing.assert_allclose(diag, diag[0], rtol=1e-5, atol=1e-5)
        # rotation preserves norms
        np.testing.assert_allclose(
            np.asarray(jnp.linalg.norm(q, axis=-1)),
            float(jnp.linalg.norm(qv)), rtol=1e-5)

    def test_scan_matches_unrolled(self):
        from deepspeed_tpu.models.llama import (init_llama_params,
                                                llama_loss_fn)
        cfg_u = self._cfg()
        cfg_s = self._cfg(scan_layers=True)
        pu = init_llama_params(cfg_u, jax.random.PRNGKey(3))
        ps = init_llama_params(cfg_s, jax.random.PRNGKey(3))
        ids = np.random.RandomState(0).randint(
            0, 256, (2, 33)).astype(np.int32)
        batch = {"input_ids": ids}
        lu = llama_loss_fn(cfg_u, dtype=jnp.float32)
        ls = llama_loss_fn(cfg_s, dtype=jnp.float32)
        vu, gu = jax.value_and_grad(lu)(pu, batch, None)
        vs, gs = jax.value_and_grad(ls)(ps, batch, None)
        np.testing.assert_allclose(float(vu), float(vs), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(gs["h"]["attn"]["wk"][1]),
            np.asarray(gu["h_1"]["attn"]["wk"]), rtol=2e-5, atol=1e-6)

    def test_gqa_tp_zero2_trains(self):
        import deepspeed_tpu as ds
        from deepspeed_tpu.models.llama import (init_llama_params,
                                                llama_loss_fn,
                                                llama_param_specs)
        cfg = self._cfg()
        params = init_llama_params(cfg, jax.random.PRNGKey(0))
        lf = llama_loss_fn(cfg, dtype=jnp.float32)
        ids = np.random.RandomState(0).randint(
            0, 256, (8, 33)).astype(np.int32)
        e, *_ = ds.initialize(
            model=lf, model_parameters=params,
            param_specs=llama_param_specs(cfg),
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                    "zero_optimization": {"stage": 2},
                    "steps_per_print": 10**9,
                    "mesh": {"axes": {"data": 4, "model": 2}}})
        losses = [float(e.train_batch(iter([{"input_ids": ids}])))
                  for _ in range(12)]
        assert losses[-1] < losses[0] - 0.5, losses

    def test_remat_matches(self):
        from deepspeed_tpu.models.llama import (init_llama_params,
                                                llama_loss_fn)
        cfg = self._cfg(scan_layers=True)
        p = init_llama_params(cfg, jax.random.PRNGKey(1))
        ids = np.random.RandomState(2).randint(
            0, 256, (2, 17)).astype(np.int32)
        batch = {"input_ids": ids}
        v0 = float(llama_loss_fn(cfg, dtype=jnp.float32)(p, batch, None))
        v1 = float(llama_loss_fn(cfg, dtype=jnp.float32, remat=True)(
            p, batch, None))
        np.testing.assert_allclose(v0, v1, rtol=1e-6)

    @pytest.mark.parametrize("scan", [False, True])
    def test_generate_greedy_matches_full_forward(self, scan):
        """KV-cache GQA decode == argmax over the full forward at every
        step (the cache stays kv_heads-sized)."""
        from deepspeed_tpu.models.llama import (init_llama_params,
                                                llama_forward,
                                                llama_generate)
        cfg = self._cfg(scan_layers=scan)
        p = init_llama_params(cfg, jax.random.PRNGKey(4))
        prompt = np.random.RandomState(5).randint(
            0, 256, (2, 5)).astype(np.int32)
        out = np.asarray(llama_generate(p, cfg, prompt, 6,
                                        dtype=jnp.float32))
        assert out.shape == (2, 11)
        seq = prompt
        for t in range(6):
            logits = llama_forward(p, cfg, jnp.asarray(seq),
                                   dtype=jnp.float32)
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            np.testing.assert_array_equal(out[:, 5 + t], nxt)
            seq = np.concatenate([seq, nxt[:, None].astype(np.int32)], 1)
