"""Compiled-HLO collective audit (VERDICT r3 #4).

The only multi-chip PERF evidence this rig can produce: compile the
8-device ZeRO-2 data-parallel step and the 2x2x2 3D pipeline step on the
virtual CPU mesh, walk the partitioned HLO, and pin the communication
volume to theory. Reference scaling claims these de-risk:
/root/reference/docs/_tutorials/megatron.md:402-408 (ZeRO-2 superlinear
scaling — which requires grad traffic ~P and optimizer state NEVER on
the wire) and the ZeRO paper's 2P-per-step communication bound.

Counting rule: ELEMENTS, not bytes — the CPU backend upcasts bf16 dots
to f32, so the same program ships 2x the bytes it would on TPU while
element counts are invariant. all-reduce is counted 2x (ring cost =
reduce-scatter + all-gather); all-to-all / all-gather / reduce-scatter /
collective-permute count 1x their output.

What is asserted (robust to GSPMD strategy choice, fatal to real
regressions):
- ZeRO-2 micro step total wire traffic in [P, 2.6 P] elements: the
  theoretical shape is gather(P params) + reduce-scatter(P grads) ~ 2 P;
  an accidental duplicated grad all-reduce, a per-micro optimizer-state
  gather, or m/v (2 P fp32) crossing the wire all blow the bound.
- no single collective moves > 1.1 P elements (no monolithic state
  gather).
- with gradient accumulation, the per-micro (off-boundary) path ships
  gather(P) + grad-reduction(P) — the FSDP-style shape GSPMD derives
  from sharded fp32 masters — while the boundary branch's optimizer
  update is SHARD-LOCAL (<= 0.2 P): optimizer state and masters never
  cross the wire.
- 3D step: collective-permutes exist and each moves exactly one
  activation tile (mb_local x seq x hidden, possibly model-sharded);
  together with test_pipe.py's scan-weighted tick counts (2 ppermutes
  per tick) this bounds pipeline traffic = 2 ticks x tile.

Documented in docs/performance.md ("multi-chip communication audit").
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
# shared HLO collective accounting (also feeds bench.py's hardware-free
# comm_wire_bytes_per_step row and test_hlo_quantized_comm.py)
from deepspeed_tpu.utils.hlo_audit import (
    collect_collectives, wire_elements,
    conditional_branch_comps as _conditional_branch_comps,
    hlo_computation_body as _hlo_computation_body)

pytestmark = pytest.mark.slow      # multi-minute 8-dev compiles


def _mlp_engine(gas=1):
    def loss_fn(params, batch, rngs=None):
        h = jnp.tanh(batch["x"] @ params["w1"])
        p = h @ params["w2"]
        return jnp.mean((p - batch["y"]) ** 2)

    key = jax.random.PRNGKey(0)
    params = {"w1": jax.random.normal(key, (256, 512)) * 0.1,
              "w2": jax.random.normal(key, (512, 128)) * 0.1}
    P = 256 * 512 + 512 * 128
    engine, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": gas,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 10**9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    from jax.sharding import NamedSharding, PartitionSpec
    shd = NamedSharding(engine.mesh, PartitionSpec("data"))
    rs = np.random.RandomState(0)
    batch = {
        "x": jax.device_put(rs.randn(32, 256).astype(np.float32), shd),
        "y": jax.device_put(rs.randn(32, 128).astype(np.float32), shd)}
    return engine, batch, P


def _micro_step_hlo(engine, batch):
    # the engine's OWN jit wrapper (test_zero3.py technique): the audit
    # must measure the production program, not a hand-copied jit config
    return (engine._get_compiled_micro_step()
            .lower(engine.state, batch).compile().as_text())


def test_zero2_step_wire_traffic_matches_theory():
    engine, batch, P = _mlp_engine()
    colls = collect_collectives(_micro_step_hlo(engine, batch))
    assert colls, "partitioned ZeRO-2 step has no collectives at all?"
    total = wire_elements(colls)
    # theory: all-gather(P params) + reduce-scatter(P grads) = 2 P (+
    # small activation-strategy and scalar terms). 2.6 P headroom covers
    # GSPMD picking activation-gather strategies for small dims; any
    # optimizer-state traffic (+2 P at minimum) or duplicated grad
    # all-reduce (+2 P) blows it.
    assert P <= total <= 2.6 * P, (total, P, [c[:2] for c in colls])
    # no monolithic gather: nothing bigger than one full param set
    biggest = max(c[1] for c in colls)
    assert biggest <= 1.1 * P, (biggest, P)


def test_zero2_grad_accumulation_boundary_split():
    """Per-micro (off-boundary) traffic is gather(P) + grad
    reduction(P): with sharded fp32 masters the forward re-gathers
    params each micro (the FSDP-style shape GSPMD produces from the
    sharding assignments) and ZeRO-2 reduces gradients every micro
    (reference IPG bucketing, zero/stage2.py:621 there). The OPTIMIZER
    UPDATE on the boundary lax.cond branch must be shard-local —
    optimizer state and masters never cross the wire."""
    engine, batch, P = _mlp_engine(gas=4)
    txt = _micro_step_hlo(engine, batch)
    colls = collect_collectives(txt)
    branch_comps = _conditional_branch_comps(txt)
    assert branch_comps, "gas=4 micro step compiled without the " \
                         "boundary conditional?"
    off_boundary = [c for c in colls if c[3] not in branch_comps]
    on_boundary = [c for c in colls if c[3] in branch_comps]
    per_micro = wire_elements(off_boundary)
    # gather(P) + reduce(P) + activation-strategy slack; optimizer
    # state (2 P fp32) appearing here would blow the bound
    assert P <= per_micro <= 2.4 * P, (per_micro, P,
                                       [c[:2] for c in off_boundary])
    # the update itself is shard-local: nothing param-scale on the
    # boundary branch (small resharding all-to-alls are tolerated)
    boundary = wire_elements(on_boundary)
    assert boundary <= 0.2 * P, (boundary, P,
                                 [c[:2] for c in on_boundary])


def test_zero2_param_gather_rides_compute_dtype_cast():
    """The compute-dtype cast sits AHEAD of the per-micro param
    all-gather — the bf16 value is what crosses the wire.

    With fp32 masters sharded ZeRO-style, GSPMD is in principle free to
    gather the f32 master values and cast downstream — 2x the wire
    bytes of a bf16 gather (the former docs/performance.md caveat).
    engine._cast_for_loss pins the compute-dtype cast to the master's
    sharded layout (with_sharding_constraint) so the cast runs
    shard-local. Two backend-invariant checks:

    1. StableHLO (pre-partitioning): every param leaf has an
       ``sdy.sharding_constraint`` on a BF16 tensor of its shape with a
       non-empty axis binding — the cast-then-constrain order is in the
       program, so the partitioner reshards the bf16 value.
    2. Partitioned HLO: no param-scale all-gather consumes a raw state
       parameter; each gather's operand chain contains the bf16
       rounding (the cast scheduled ahead of the wire).

    Byte-level dtype cannot be asserted on the CPU audit backend:
    FloatNormalization re-expands bf16 math to f32 (dots, tanh have no
    CPU bf16 kernels), so the gather result prints f32 here while the
    same program moves bf16 on TPU, where the constrained bf16 value
    feeds the MXU directly."""
    engine, batch, P = _mlp_engine(gas=4)
    lowered = (engine._get_compiled_micro_step()
               .lower(engine.state, batch))
    stable = lowered.as_text()
    for shape in ("256x512", "512x128"):
        # shardy partitioner (newer jax): sdy.sharding_constraint; GSPMD
        # (jax < 0.5): a @Sharding custom call with a non-replicated
        # mhlo.sharding — both prove the bf16 value is what gets resharded
        sdy = (r"sdy\.sharding_constraint[^\n]*<@mesh, \[\{\"data\"\}"
               r"[^\n]*tensor<" + shape + r"xbf16>")
        gspmd = (r"custom_call @Sharding[^\n]*devices=\[[^\n]*"
                 r"tensor<" + shape + r"xbf16>")
        assert re.search(sdy, stable) or re.search(gspmd, stable), \
            f"no sharded bf16 constraint for param {shape} in StableHLO"

    txt = lowered.compile().as_text()
    colls = collect_collectives(txt)
    param_gathers = [c for c in colls
                     if c[0] == "all-gather" and c[1] >= 0.2 * P]
    assert param_gathers, \
        "no param-scale all-gather in the compiled step?"
    defn = {m.group(1): line for line in txt.splitlines()
            for m in [re.match(r"^\s*(?:ROOT\s+)?%([\w.\-]+) = ",
                               line.strip())] if m}
    for op, e, line, _ in param_gathers:
        # collect_collectives returns sync `all-gather(` or async
        # `all-gather-done(` lines; for async, hop -done -> -start ->
        # the real data operand. The operand may carry a printed type
        # prefix (`all-gather(f32[...] %x)`) depending on jax version.
        m = re.search(r"all-gather(?:-done)?\((?:\S+(?:\{[\d,]*\})? )?"
                      r"%?([\w.\-]+)", line)
        assert m, line[:160]
        opd_line = defn.get(m.group(1), "")
        sm = re.search(r"all-gather-start\((?:\S+(?:\{[\d,]*\})? )?"
                       r"%?([\w.\-]+)", opd_line)
        if sm:
            opd_line = defn.get(sm.group(1), "")
        # a raw master crossing the wire would be parameter/gte directly
        assert (" parameter(" not in opd_line
                and "get-tuple-element(" not in opd_line), \
            (line[:120], opd_line[:120])
        cm = re.search(r"calls=%([\w.\-]+)", opd_line)
        body = (_hlo_computation_body(txt, cm.group(1))
                if cm else [opd_line])
        assert any("bf16[" in b for b in body), \
            ("gather operand has no bf16 rounding ahead of the wire",
             line[:120], opd_line[:120])


def _onebit_engine():
    """dp=8 OnebitAdam engine with a known param count P."""
    def loss_fn(params, batch, rngs=None):
        h = jnp.tanh(batch["x"] @ params["w1"])
        p = h @ params["w2"]
        return jnp.mean((p - batch["y"]) ** 2)

    key = jax.random.PRNGKey(0)
    params = {"w1": jax.random.normal(key, (256, 512)) * 0.1,
              "w2": jax.random.normal(key, (512, 128)) * 0.1}
    P = 256 * 512 + 512 * 128
    engine, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "steps_per_print": 10**9,
                "optimizer": {"type": "OneBitAdam",
                              "params": {"lr": 1e-3, "freeze_step": 4}}})
    from jax.sharding import NamedSharding, PartitionSpec
    shd = NamedSharding(engine.mesh, PartitionSpec("data"))
    rs = np.random.RandomState(0)
    batch = {
        "x": jax.device_put(rs.randn(32, 256).astype(np.float32), shd),
        "y": jax.device_put(rs.randn(32, 128).astype(np.float32), shd)}
    return engine, batch, P


def test_onebit_adam_compressed_wire_traffic():
    """The 1-bit Adam compression-phase exchange ships <= ~1/5 of the
    warmup (dense) exchange — the reference's headline claim
    (onebit-adam blog: 5x communication-volume reduction; BASELINE.md
    ladder item 5).

    Warmup phase: the momentum exchange is a dense pmean — all-reduce
    of P fp32 values = 2P ring wire elements. Compression phase: the
    packed sign bits ride an all-to-all (P/8 uint8 elements) plus the
    server-chunk all-gather (P/8) and per-rank fp32 scales — ~P/4
    total. In ELEMENTS (the backend-invariant unit, module docstring)
    that is an 8x reduction; in bytes on TPU it is 32x for the payload,
    so asserting elements-ratio >= 5 understates the wire saving."""
    engine, batch, P = _onebit_engine()
    assert engine._onebit_dist

    warm = _micro_step_hlo(engine, batch)
    warm_colls = collect_collectives(warm)
    warm_wire = wire_elements(warm_colls)
    # dense exchange present: pmean(P grads) ~ 2P (+ scalar terms)
    assert warm_wire >= 2 * P, (warm_wire, P,
                                [c[:2] for c in warm_colls])

    # flip to the compression phase exactly as the engine does at
    # freeze_step (recompile with the static phase flag)
    engine._onebit_compression = True
    engine._compiled_micro_step = None
    comp = _micro_step_hlo(engine, batch)
    comp_colls = collect_collectives(comp)
    comp_wire = wire_elements(comp_colls)
    assert comp_colls, "compression phase compiled without collectives?"
    # <= ~1/5 of the dense exchange (measured shape: ~P/4 vs 2P = 1/8)
    assert comp_wire * 5 <= warm_wire, \
        (comp_wire, warm_wire, P, [c[:2] for c in comp_colls])
    # and nothing dense-momentum-sized sneaks through per leaf: no
    # single collective moves more than the largest packed chunk
    # (P/8 elements) plus slack
    biggest = max(c[1] for c in comp_colls)
    assert biggest <= 0.2 * P, (biggest, P,
                                [c[:2] for c in comp_colls])


import functools


@functools.lru_cache(maxsize=1)
def _gpt2_3d_grad_hlo():
    from deepspeed_tpu.models.gpt2 import GPT2Config, gpt2_pipeline_spec
    from deepspeed_tpu.runtime.pipe.spmd import (build_pipeline_grad_fn,
                                                 interleave_stages)
    cfg = GPT2Config(vocab_size=128, max_position_embeddings=32,
                     hidden_size=64, num_layers=4, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    S, V, M, seq, mb = 2, 2, 4, 16, 4
    mesh = ds.build_mesh({"pipe": S, "data": 2, "model": 2})
    spec = gpt2_pipeline_spec(cfg, num_stages=S * V, dtype=jnp.float32)
    params = spec.init(jax.random.PRNGKey(0))
    params = dict(params)
    params["stages"] = interleave_stages(params["stages"], S, V)
    gf = build_pipeline_grad_fn(spec, mesh, num_micro=M, num_virtual=V)
    batch = {"input_ids": np.zeros((M, mb, seq + 1), np.int32)}
    rng = jax.random.PRNGKey(1)
    txt = (jax.jit(gf).lower(params, batch, rng, 1.0).compile().as_text())
    return txt, dict(S=S, V=V, M=M, seq=seq, mb=mb, hidden=cfg.hidden_size)


def test_3d_pipeline_permute_tile_sizes():
    """Every collective-permute in the compiled 2x2x2 step moves exactly
    one activation tile: mb_local x seq x hidden (or its model-sharded
    half) — never a params-sized or batch-replicated buffer. Combined
    with test_pipe.py::test_interleaved_bubble_tick_count (2 ppermutes
    per tick, scan-weighted) this pins total pipe traffic to
    2 x ticks x tile."""
    txt, d = _gpt2_3d_grad_hlo()
    colls = collect_collectives(txt)
    perms = [(e, line) for op, e, line, _ in colls
             if op == "collective-permute"]
    assert perms, "3D pipeline step compiled without collective-permute?"
    # per-device tile: batch dim sharded over data(2), hidden possibly
    # sharded over model(2) by GSPMD's choice
    tile = (d["mb"] // 2) * d["seq"] * d["hidden"]
    allowed = {tile, tile // 2}
    for e, line in perms:
        assert e in allowed, (e, sorted(allowed), line[:160])


def test_3d_pipeline_no_oversized_collectives():
    """No collective in the 3D step moves more than the largest single
    logical buffer (the stacked per-device stage params): catches a
    whole-model gather/reduce sneaking into the per-tick path."""
    txt, d = _gpt2_3d_grad_hlo()
    colls = collect_collectives(txt)
    # largest legitimate transfer: a full stage-stack grad reduction
    # over the data axis at batch end. hidden x 4*hidden QKV etc — bound
    # by total params per device ~ (L/S/V blocks) x 12 H^2 x V.
    h = d["hidden"]
    per_dev_params = 2 * 12 * h * h * 2 + 128 * h  # V x blocks + embed
    for op, e, line, _ in colls:
        assert e <= 1.5 * per_dev_params, (op, e, line[:160])
