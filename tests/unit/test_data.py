"""Dataloader tier (reference tests/unit/test_data.py): RepeatingLoader
restart semantics and DeepSpeedDataLoader sharded global batches."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)


def test_repeating_loader():
    """(reference test_data.py TestRepeatingLoader): wraps an iterable and
    restarts on exhaustion."""
    loader = [1, 2, 3]
    wrapped = RepeatingLoader(loader)
    for _ in range(2):
        assert next(wrapped) == 1
        assert next(wrapped) == 2
        assert next(wrapped) == 3


def test_repeating_loader_over_dataloader():
    ds = [{"x": np.full((2,), i, np.float32)} for i in range(4)]
    dl = DeepSpeedDataLoader(ds, batch_size=2, shuffle=False)
    rep = RepeatingLoader(dl)
    seen = [float(next(rep)["x"][0, 0]) for _ in range(6)]
    # 2 batches per epoch, repeating identically (shuffle off)
    assert seen == [0.0, 2.0] * 3


def test_batching_and_len():
    ds = [{"x": np.full((3,), i, np.float32)} for i in range(10)]
    dl = DeepSpeedDataLoader(ds, batch_size=4, shuffle=False)
    assert len(dl) == 2                      # drop_last
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (4, 3)
    np.testing.assert_array_equal(batches[0]["x"][:, 0], [0, 1, 2, 3])

    dl2 = DeepSpeedDataLoader(ds, batch_size=4, shuffle=False,
                              drop_last=False)
    assert len(dl2) == 3
    assert list(dl2)[-1]["x"].shape == (2, 3)


def test_shuffle_reproducible_and_epoch_varying():
    ds = [{"x": np.full((1,), i, np.float32)} for i in range(8)]
    a = [batch["x"][:, 0].tolist()
         for batch in DeepSpeedDataLoader(ds, 4, shuffle=True, seed=3)]
    b = [batch["x"][:, 0].tolist()
         for batch in DeepSpeedDataLoader(ds, 4, shuffle=True, seed=3)]
    assert a == b                            # same seed, same order
    dl = DeepSpeedDataLoader(ds, 4, shuffle=True, seed=3)
    e1 = [batch["x"][:, 0].tolist() for batch in dl]
    e2 = [batch["x"][:, 0].tolist() for batch in dl]
    assert e1 != e2                          # epoch advances the stream


def test_sharded_over_data_axis():
    """The TPU analog of the reference's DistributedSampler: one global
    batch device_put across the data axis."""
    from deepspeed_tpu.parallel.mesh import build_mesh
    mesh = build_mesh({"data": 8})
    ds = [{"x": np.full((2,), i, np.float32)} for i in range(16)]
    dl = DeepSpeedDataLoader(ds, batch_size=8, mesh=mesh, shuffle=False)
    batch = next(iter(dl))
    shardings = batch["x"].sharding
    assert shardings.spec == jax.sharding.PartitionSpec("data")
    assert len(batch["x"].addressable_shards) == 8
    # each device holds 1 row of the global batch of 8
    assert batch["x"].addressable_shards[0].data.shape == (1, 2)


def test_iterable_passthrough():
    stream = ({"x": np.ones((4, 2), np.float32) * i} for i in range(3))
    dl = DeepSpeedDataLoader(stream, batch_size=4, shuffle=False)
    with pytest.raises(TypeError):
        len(dl)
    out = list(dl)
    assert len(out) == 3 and out[2]["x"][0, 0] == 2.0
