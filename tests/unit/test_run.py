"""Launcher hostfile/filter tests (mirrors reference tests/unit/test_run.py)."""

import pytest

from deepspeed_tpu.launcher.runner import (
    fetch_hostfile,
    parse_resource_filter,
    encode_world_info,
    decode_world_info,
    parse_args,
)


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text(
        "# comment\n"
        "worker-0 slots=4\n"
        "worker-1 slots=4\n"
        "\n"
        "worker-2 slots=8\n")
    return str(p)


def test_fetch_hostfile(hostfile):
    pool = fetch_hostfile(hostfile)
    assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 8}


def test_fetch_hostfile_missing(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_bad_format(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("worker-0 gpus=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_fetch_hostfile_duplicate(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("worker-0 slots=4\nworker-0 slots=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


class TestResourceFilter:
    pool = {"worker-0": 2, "worker-1": 2}

    def test_no_filter(self):
        active = parse_resource_filter(self.pool)
        assert active == {"worker-0": [0, 1], "worker-1": [0, 1]}

    def test_include_host(self):
        active = parse_resource_filter(self.pool, include_str="worker-1")
        assert active == {"worker-1": [0, 1]}

    def test_include_slots(self):
        active = parse_resource_filter(self.pool, include_str="worker-0:1")
        assert active == {"worker-0": [1]}

    def test_exclude_host(self):
        active = parse_resource_filter(self.pool, exclude_str="worker-1")
        assert active == {"worker-0": [0, 1]}

    def test_exclude_slot(self):
        active = parse_resource_filter(self.pool, exclude_str="worker-1:0")
        assert active == {"worker-0": [0, 1], "worker-1": [1]}

    def test_mutually_exclusive(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.pool, include_str="worker-0",
                                  exclude_str="worker-1")

    def test_include_unknown_host(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.pool, include_str="worker-9")

    def test_include_unknown_slot(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.pool, include_str="worker-0:7")


def test_world_info_roundtrip():
    active = {"worker-0": [0, 1], "worker-1": [0]}
    assert decode_world_info(encode_world_info(active)) == active


def test_parse_args_remainder():
    args = parse_args(["train.py", "--deepspeed_config", "ds.json"])
    assert args.user_script == "train.py"
    assert args.user_args == ["--deepspeed_config", "ds.json"]
    assert args.launcher == "ssh"


# ---------------------------------------------------------------------------
# multinode runners (reference tests cover runner cmd construction implicitly
# via test_run; here explicitly, mirroring multinode_runner.py:35/78)
# ---------------------------------------------------------------------------

def _args(script="train.py", user_args=("--x", "1")):
    import argparse
    ns = argparse.Namespace()
    ns.user_script = script
    ns.user_args = list(user_args)
    return ns


def test_ssh_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import SSHRunner
    r = SSHRunner(_args(), {"worker-1": [0]})
    cmd = r.get_cmd("worker-1", 1, 4, "worker-0:29500", {"PATH": "/usr/bin"})
    assert cmd[0] == "ssh" and cmd[-2] == "worker-1"
    line = cmd[-1]
    assert "DSTPU_PROCESS_ID=1" in line
    assert "DSTPU_NUM_PROCESSES=4" in line
    assert "DSTPU_COORDINATOR=worker-0:29500" in line
    assert "train.py --x 1" in line
    # localhost shortcut: no ssh
    local = r.get_cmd("localhost", 0, 4, "worker-0:29500", {})
    assert local[0] == "/bin/sh"


def test_pdsh_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import PDSHRunner
    r = PDSHRunner(_args(), {})
    cmd = r.get_cmd("worker-2", 2, 4, "c:1", {})
    assert cmd[:4] == ["pdsh", "-R", "ssh", "-w"] and cmd[4] == "worker-2"


def test_openmpi_runner_cmd():
    from deepspeed_tpu.launcher.multinode_runner import OpenMPIRunner
    r = OpenMPIRunner(_args(), {})
    cmd = r.get_cmd_all(["a", "b", "c"], "a:29500", {"JAX_FOO": "1"})
    assert cmd[0] == "mpirun" and "-np" in cmd and "3" in cmd
    assert "--host" in cmd and "a,b,c" in cmd
    assert "-x" in cmd and "DSTPU_PROCESS_ID_FROM_MPI=1" in cmd
    import pytest
    with pytest.raises(RuntimeError):
        r.get_cmd("a", 0, 3, "a:29500", {})


def test_make_runner_unknown():
    import pytest
    from deepspeed_tpu.launcher.multinode_runner import make_runner
    with pytest.raises(ValueError):
        make_runner("mvapich", _args(), {})


def test_mpi_rank_env_mapping(monkeypatch):
    """init_distributed must derive its process_id from OMPI_COMM_WORLD_RANK
    when the openmpi launcher sets DSTPU_PROCESS_ID_FROM_MPI."""
    import jax
    import deepspeed_tpu.distributed as dist_mod
    monkeypatch.setenv("DSTPU_COORDINATOR", "head:29500")
    monkeypatch.setenv("DSTPU_NUM_PROCESSES", "4")
    monkeypatch.setenv("DSTPU_PROCESS_ID_FROM_MPI", "1")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.delenv("DSTPU_PROCESS_ID", raising=False)
    calls = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.update(kw))
    monkeypatch.setattr(dist_mod, "_initialized", False)
    dist_mod.init_distributed()
    assert calls == {"coordinator_address": "head:29500",
                     "num_processes": 4, "process_id": 3}
    monkeypatch.setattr(dist_mod, "_initialized", False)
