"""Launcher hostfile/filter tests (mirrors reference tests/unit/test_run.py)."""

import pytest

from deepspeed_tpu.launcher.runner import (
    fetch_hostfile,
    parse_resource_filter,
    encode_world_info,
    decode_world_info,
    parse_args,
)


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text(
        "# comment\n"
        "worker-0 slots=4\n"
        "worker-1 slots=4\n"
        "\n"
        "worker-2 slots=8\n")
    return str(p)


def test_fetch_hostfile(hostfile):
    pool = fetch_hostfile(hostfile)
    assert pool == {"worker-0": 4, "worker-1": 4, "worker-2": 8}


def test_fetch_hostfile_missing(tmp_path):
    assert fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_bad_format(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("worker-0 gpus=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


def test_fetch_hostfile_duplicate(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("worker-0 slots=4\nworker-0 slots=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(p))


class TestResourceFilter:
    pool = {"worker-0": 2, "worker-1": 2}

    def test_no_filter(self):
        active = parse_resource_filter(self.pool)
        assert active == {"worker-0": [0, 1], "worker-1": [0, 1]}

    def test_include_host(self):
        active = parse_resource_filter(self.pool, include_str="worker-1")
        assert active == {"worker-1": [0, 1]}

    def test_include_slots(self):
        active = parse_resource_filter(self.pool, include_str="worker-0:1")
        assert active == {"worker-0": [1]}

    def test_exclude_host(self):
        active = parse_resource_filter(self.pool, exclude_str="worker-1")
        assert active == {"worker-0": [0, 1]}

    def test_exclude_slot(self):
        active = parse_resource_filter(self.pool, exclude_str="worker-1:0")
        assert active == {"worker-0": [0, 1], "worker-1": [1]}

    def test_mutually_exclusive(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.pool, include_str="worker-0",
                                  exclude_str="worker-1")

    def test_include_unknown_host(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.pool, include_str="worker-9")

    def test_include_unknown_slot(self):
        with pytest.raises(ValueError):
            parse_resource_filter(self.pool, include_str="worker-0:7")


def test_world_info_roundtrip():
    active = {"worker-0": [0, 1], "worker-1": [0]}
    assert decode_world_info(encode_world_info(active)) == active


def test_parse_args_remainder():
    args = parse_args(["train.py", "--deepspeed_config", "ds.json"])
    assert args.user_script == "train.py"
    assert args.user_args == ["--deepspeed_config", "ds.json"]
    assert args.launcher == "ssh"
