"""Topology-aware collective autotuner + compute/comm overlap (ISSUE 6).

Three contracts pinned tier-1:

1. **Golden decision table** — the autotuner reproduces PR 2's pinned
   crossovers as *decisions*: dp=2 -> legacy allgather (one-hop latency
   win at equal bytes), flat W>=4 -> qgZ two-hop (O(n) wire), an
   inter×intra topology -> hierarchical 2D. Explicit
   ``quantized_comm.{algo,block,hierarchical}`` keys act as overrides.
2. **Cost-model drift guard** — ``wire_bytes``/``wire_bytes_by_axis``
   predictions match the compiled-HLO byte accounting
   (``hlo_audit.send_bytes_of``) for each algo×topology config, so the
   autotuner's inputs can't silently rot (the mfu_cost_model pattern).
3. **Overlap parity** — the double-buffered overlapped fused step is
   BITWISE equal to the serial-exchange fused step: fp32/bf16 losses
   and params, fp16 loss-scale skips. Exchange inputs, math, and
   accumulation order are identical; only the issue point moves.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.runtime.comm_autotune import (
    LinkModel, calibrate_wire_model, candidate_label, exchange_time_us,
    plan_comm)
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)
from deepspeed_tpu.runtime.quantized_collectives import (
    ALGO_ALLGATHER, ALGO_TWOHOP, wire_bytes, wire_hops)

SIZES = [1 << 20, 1 << 18, 4096]          # typical gradient histogram


def _qc(**over):
    qc = {"enabled": True, "algo": "twohop", "block": 256,
          "hierarchical": 0, "quantize_weights": False,
          "secondary_partition": False,
          "explicit": {"algo": False, "block": False,
                       "hierarchical": False}}
    ex = over.pop("explicit", {})
    qc.update(over)
    qc["explicit"] = {**qc["explicit"], **ex}
    return qc


def _ca(**over):
    ca = {"enabled": True, "overlap": "auto", "calibrate": False,
          "intra_size": 0, "intra_gbps": 75.0, "inter_gbps": 12.5,
          "intra_latency_us": 1.0, "inter_latency_us": 10.0,
          "block_candidates": [64, 128, 256]}
    ca.update(over)
    return ca


# ------------------------------------------------ golden decision table


def test_decision_dp2_prefers_legacy_allgather():
    """At dp=2 allgather and two-hop move the same bytes; the single
    hop wins on latency — the PR 2 'allgather only sane at dp=2' rule,
    now derived instead of hand-configured."""
    plan = plan_comm(SIZES, 2, _qc(), _ca())
    assert plan.algo == ALGO_ALLGATHER and plan.hierarchical == 0, plan


@pytest.mark.parametrize("world", [4, 8])
def test_decision_flat_w4_plus_prefers_twohop(world):
    """Flat W>=4: allgather is O(W*n), two-hop O(n) — the qgZ shape
    wins regardless of block choice."""
    plan = plan_comm(SIZES, world, _qc(), _ca())
    assert plan.algo == ALGO_TWOHOP and plan.hierarchical == 0, plan
    assert plan.block == 256            # large leaves: fewest scale bytes


def test_decision_split_topology_prefers_hierarchical():
    """A 2x4 inter×intra fabric: flat collectives price at the slow
    wire end-to-end, the 2D shape ships only the reduced 1/W_intra
    chunk across it -> hierarchical twohop at the physical split."""
    plan = plan_comm(SIZES, 8, _qc(), _ca(intra_size=4))
    assert plan.algo == ALGO_TWOHOP and plan.hierarchical == 4, plan
    assert "2x4" in plan.reason
    # every candidate was priced and the table is part of the evidence
    assert candidate_label(ALGO_TWOHOP, 256, 4) in plan.modeled_us
    assert candidate_label(ALGO_TWOHOP, 256, 0) in plan.modeled_us


def test_decision_uniform_fabric_stays_flat():
    """No topology signal (intra_size 0, single process): hierarchical
    costs an extra requantize round-trip for nothing — never chosen."""
    plan = plan_comm(SIZES, 8, _qc(), _ca(intra_size=0))
    assert plan.hierarchical == 0


def test_decision_block_tuning_follows_padding():
    """Small tensors pay pad_to_multiple(n, W*block): a sub-block-sized
    histogram picks a smaller block than the large-tensor default."""
    small = plan_comm([600, 300, 900], 8, _qc(), _ca())
    big = plan_comm([1 << 20], 8, _qc(), _ca())
    assert small.block < big.block == 256, (small.block, big.block)


def test_explicit_config_acts_as_override():
    """Static quantized_comm keys pin the candidate set — the
    pre-autotuner behavior, now opt-out (and flagged in the plan)."""
    plan = plan_comm(SIZES, 8, _qc(algo="allgather",
                                   explicit={"algo": True}), _ca())
    assert plan.algo == ALGO_ALLGATHER and plan.overridden
    assert "pinned" in plan.reason
    plan = plan_comm(SIZES, 8, _qc(block=128, explicit={"block": True}),
                     _ca())
    assert plan.block == 128 and plan.overridden
    # pinned hierarchy: planned even without an intra_size hint
    plan = plan_comm(SIZES, 8, _qc(hierarchical=2,
                                   explicit={"hierarchical": True}),
                     _ca())
    assert plan.hierarchical == 2 and plan.algo == ALGO_TWOHOP


# ------------------------------------------------------- cost model


def test_cost_model_reproduces_wire_crossovers():
    link = LinkModel()
    n = [1 << 20]
    # W=8 flat: two-hop beats allgather by ~W/2x in bytes
    t2 = exchange_time_us(n, 8, algo=ALGO_TWOHOP, link=link)
    tl = exchange_time_us(n, 8, algo=ALGO_ALLGATHER, link=link)
    assert t2 < 0.5 * tl, (t2, tl)
    # W=2: equal bytes, allgather saves one hop latency
    t2 = exchange_time_us(n, 2, algo=ALGO_TWOHOP, link=link)
    tl = exchange_time_us(n, 2, algo=ALGO_ALLGATHER, link=link)
    assert tl < t2
    # split fabric: hierarchical keeps the bulk off the slow wire
    flat = exchange_time_us(n, 8, algo=ALGO_TWOHOP, topo_intra=4,
                            link=link)
    hier = exchange_time_us(n, 8, algo=ALGO_TWOHOP, hierarchical=4,
                            topo_intra=4, link=link)
    assert hier < flat, (hier, flat)
    # uniform fabric: the flat shape is at least as good (fewer hops)
    flat_u = exchange_time_us(n, 8, algo=ALGO_TWOHOP, link=link)
    hier_u = exchange_time_us(n, 8, algo=ALGO_TWOHOP, hierarchical=4,
                              link=link)
    assert flat_u <= hier_u


def test_wire_hops_totals_match_wire_bytes():
    """The hop-level view must sum to the total-bytes model exactly —
    they are two projections of the same accounting."""
    n = 1 << 20
    for W in (2, 4, 8):
        for algo in (ALGO_TWOHOP, ALGO_ALLGATHER):
            total, _ = wire_bytes(n, W, algo=algo)
            assert sum(b for _, b in wire_hops(n, W, algo=algo)) == total
    from deepspeed_tpu.runtime.quantized_collectives import \
        wire_bytes_by_axis
    per_axis = wire_bytes_by_axis(n, 2, 4)
    hops = wire_hops(n, 8, hierarchical=(2, 4))
    assert sum(b for a, b in hops if a == "intra") == per_axis["intra"]
    assert sum(b for a, b in hops if a == "inter") == per_axis["inter"]


# ------------------------------------- cost-model drift guard (tier-1)


@pytest.mark.parametrize("algo,world,hier", [
    (ALGO_ALLGATHER, 4, 0),
    (ALGO_ALLGATHER, 8, 0),
    (ALGO_TWOHOP, 4, 0),
    (ALGO_TWOHOP, 8, 0),
    (ALGO_TWOHOP, 8, 4),       # 2x4 hierarchical
    (ALGO_TWOHOP, 8, 2),       # 4x2 hierarchical
])
def test_wire_model_matches_compiled_hlo(algo, world, hier):
    """wire_bytes / wire_bytes_by_axis predictions vs partitioned-HLO
    send-byte accounting, per algo×topology — the autotuner's inputs
    can't silently rot (mfu_cost_model pattern)."""
    cal = calibrate_wire_model(world=world, algo=algo, hierarchical=hier,
                               n=1 << 16)
    assert abs(cal["drift"]) <= 0.05, cal


# ------------------------------------------------------------- config


def test_config_validation():
    base = {"train_micro_batch_size_per_gpu": 1}
    for bad in [{"overlap": "yes"}, {"intra_size": 1},
                {"intra_gbps": 0}, {"inter_gbps": -1},
                {"intra_latency_us": -1},
                {"block_candidates": []},
                {"block_candidates": [4]},
                # malformed values get the curated error too, not a
                # raw TypeError/ValueError from the parse-time coercion
                {"block_candidates": 256},
                {"intra_gbps": "fast"}]:
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({**base, "comm_autotune": bad})
    cfg = DeepSpeedConfig({**base, "comm_autotune": {"enabled": True},
                           "quantized_comm": {"enabled": True}})
    assert cfg.comm_autotune_config["enabled"]
    assert cfg.comm_autotune_config["overlap"] == "auto"
    # JSON 0/1 normalize to real bools: the overlap decision tests
    # identity (`is False`), so 0 must actually DISABLE overlap
    assert DeepSpeedConfig({**base, "comm_autotune": {"overlap": 0}}
                           ).comm_autotune_config["overlap"] is False
    assert DeepSpeedConfig({**base, "comm_autotune": {"overlap": 1}}
                           ).comm_autotune_config["overlap"] is True
    # explicitness tracking feeds the override behavior
    qc = cfg.quantized_comm_config
    assert not qc["explicit"]["algo"] and not qc["explicit"]["block"]
    qc2 = DeepSpeedConfig({**base, "quantized_comm": {
        "enabled": True, "algo": "allgather"}}).quantized_comm_config
    assert qc2["explicit"]["algo"] and not qc2["explicit"]["hierarchical"]
    # the legacy alias's block counts as explicit
    qc3 = DeepSpeedConfig({**base, "compressed_allreduce": {
        "enabled": True, "block": 128}}).quantized_comm_config
    assert qc3["explicit"]["block"]


# ------------------------------------------------- engine integration


def _mlp(seed=0, hidden=(64, 256, 64)):
    d_in, d_h, d_out = hidden

    def loss_fn(params, batch, rngs=None):
        h = jnp.tanh(batch["x"] @ params["w1"])
        return jnp.mean((h @ params["w2"] - batch["y"]) ** 2)

    key = jax.random.PRNGKey(seed)
    params = {"w1": jax.random.normal(key, (d_in, d_h)) * 0.1,
              "w2": jax.random.normal(key, (d_h, d_out)) * 0.1}
    return loss_fn, params


def _engine(cfg_extra, seed=0):
    loss_fn, params = _mlp(seed)
    engine, *_ = ds.initialize(
        model=loss_fn, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 4,
                "steps_per_print": 10**9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                **cfg_extra})
    return engine


def _batches(engine, n, seed=0, d=64):
    rs = np.random.RandomState(seed)
    shd = NamedSharding(engine.mesh, P(engine._dp_axis_entry))
    bs = 4 * engine.dp_world_size
    return [{"x": jax.device_put(rs.randn(bs, d).astype(np.float32), shd),
             "y": jax.device_put(rs.randn(bs, d).astype(np.float32), shd)}
            for _ in range(n)]


def test_engine_applies_plan_dp2():
    engine = _engine({"quantized_comm": {"enabled": True},
                      "comm_autotune": {"enabled": True},
                      "mesh": {"axes": {"data": 2}}})
    assert engine.dp_world_size == 2
    assert engine._comm_plan is not None
    assert engine._quant_algo == ALGO_ALLGATHER


def test_engine_applies_plan_hierarchical():
    """comm_autotune.intra_size shapes the MESH itself: the plan's
    hierarchy split runs before build_mesh."""
    engine = _engine({"quantized_comm": {"enabled": True},
                      "comm_autotune": {"enabled": True, "intra_size": 4}})
    assert engine._dp_hierarchical
    assert dict(engine.mesh.shape) == {"data_inter": 2, "data_intra": 4}
    assert engine._quant_algo == ALGO_TWOHOP
    assert engine._comm_plan.hierarchical == 4


def test_engine_static_algo_overrides_plan():
    engine = _engine({"quantized_comm": {"enabled": True,
                                         "algo": "allgather"},
                      "comm_autotune": {"enabled": True}})
    assert engine._quant_algo == ALGO_ALLGATHER
    assert engine._comm_plan.overridden


def test_engine_calibrate_records_drift():
    engine = _engine({"quantized_comm": {"enabled": True},
                      "comm_autotune": {"enabled": True,
                                        "calibrate": True}})
    cal = engine._comm_plan.calibration
    assert cal is not None and abs(cal["drift"]) <= 0.05, cal


def test_degenerate_pinned_hierarchy_equal_to_world_still_plans():
    """quantized_comm.hierarchical == dp world (inter=1) is the legal
    degenerate split — split_data_axis and the exchange both accept it,
    so turning the autotuner on must not brick the config."""
    plan = plan_comm(SIZES, 8, _qc(hierarchical=8,
                                   explicit={"hierarchical": True}),
                     _ca())
    assert plan.hierarchical == 8
    engine = _engine({"quantized_comm": {"enabled": True,
                                         "hierarchical": 8},
                      "comm_autotune": {"enabled": True}})
    assert engine._dp_hierarchical
    assert dict(engine.mesh.shape) == {"data_inter": 1, "data_intra": 8}


def test_invalid_pinned_combo_surfaces_the_config_error():
    """Planning runs before DeepSpeedConfig validation; an invalid
    quantized_comm combo must still raise the config layer's curated
    error, never a raw planner exception."""
    with pytest.raises(DeepSpeedConfigError, match="twohop"):
        _engine({"quantized_comm": {"enabled": True, "algo": "allgather",
                                    "hierarchical": 4},
                 "comm_autotune": {"enabled": True}})
    with pytest.raises(DeepSpeedConfigError, match="algo"):
        _engine({"quantized_comm": {"enabled": True, "algo": "typo"},
                 "comm_autotune": {"enabled": True}})


def test_sparse_and_onebit_configs_skip_the_plan():
    engine = _engine({"quantized_comm": {"enabled": True},
                      "comm_autotune": {"enabled": True},
                      "sparse_gradients": True})
    assert engine._comm_plan is None


# ------------------------------------------------ overlap parity (bitwise)


def _run_pair(cfg_extra, gas=3, steps=4, seed=0):
    """(losses, engine) for overlap=True and overlap=False on identical
    data — everything else about the two engines is the same."""
    out = []
    for overlap in (True, False):
        qc = {"enabled": True}
        qc.update(cfg_extra.get("quantized_comm", {}))
        extra = {k: v for k, v in cfg_extra.items()
                 if k != "quantized_comm"}
        engine = _engine({
            "gradient_accumulation_steps": gas,
            "quantized_comm": qc,
            "comm_autotune": {"enabled": True, "overlap": overlap},
            **extra}, seed=seed)
        assert engine._batch_path()
        assert engine._overlap_path() is overlap
        batches = _batches(engine, steps * gas, seed=seed + 1)
        losses = [engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
                  for i in range(steps)]
        out.append(([float(l) for l in losses], engine))
    return out


def _assert_bitwise_params(e1, e0):
    for a, b in zip(jax.tree_util.tree_leaves(e1.state.params),
                    jax.tree_util.tree_leaves(e0.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_overlap_parity_fp32_bitwise():
    (l1, e1), (l0, e0) = _run_pair({})
    assert l1 == l0, (l1, l0)            # bitwise, not approximately
    _assert_bitwise_params(e1, e0)
    assert e1.global_steps == e0.global_steps == 4


def test_overlap_parity_bf16_bitwise():
    (l1, e1), (l0, e0) = _run_pair({"bf16": {"enabled": True}})
    assert l1 == l0, (l1, l0)
    _assert_bitwise_params(e1, e0)


def test_overlap_parity_hierarchical_qwz_bitwise():
    """The hoisted weight gather + hierarchical 2D exchange: still
    bitwise (params constant within a window — one gather serves all
    gas micros)."""
    (l1, e1), (l0, e0) = _run_pair({
        "quantized_comm": {"enabled": True, "quantize_weights": True,
                           "hierarchical": 4},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2}})
    assert e1._dp_hierarchical and e1._qwz
    assert l1 == l0, (l1, l0)
    _assert_bitwise_params(e1, e0)


def test_overlap_fp16_loss_scale_skip_parity():
    """An overflowing first window (initial scale 2^32) must be skipped
    identically: same skipped_steps, same post-backoff scale, same
    params — the deferred exchange carries the nonfinite poison exactly
    like the serial one."""
    (l1, e1), (l0, e0) = _run_pair(
        {"fp16": {"enabled": True, "initial_scale_power": 32,
                  "loss_scale_window": 1000}}, steps=5)
    assert e1.skipped_steps == e0.skipped_steps > 0
    assert e1.loss_scale() == e0.loss_scale()
    assert e1.global_steps == e0.global_steps
    assert l1 == l0
    _assert_bitwise_params(e1, e0)


# ------------------------------------------------------- auto-fallback


def test_overlap_falls_back_without_quantized_exchange():
    """Dense GSPMD configs have no explicit exchange to defer: overlap
    auto-falls back (logged), training runs."""
    engine = _engine({"gradient_accumulation_steps": 2,
                      "comm_autotune": {"enabled": True}})
    assert engine._batch_path() and not engine._overlap_path()
    batches = _batches(engine, 4)
    loss = engine.train_batch(iter(batches[:2]))
    assert np.isfinite(float(loss))


def test_overlap_falls_back_at_gas1():
    engine = _engine({"quantized_comm": {"enabled": True},
                      "comm_autotune": {"enabled": True}})
    assert not engine._overlap_path()
    ov, why = engine._select_overlap_path()
    assert not ov and "gas=1" in why


def test_overlap_off_when_autotune_disabled():
    engine = _engine({"gradient_accumulation_steps": 2,
                      "quantized_comm": {"enabled": True}})
    assert engine._batch_path() and not engine._overlap_path()


# ------------------------------------------------------------ telemetry


def test_comm_plan_event_and_mode_land_in_events_log(tmp_path):
    import json
    engine = _engine({"gradient_accumulation_steps": 2,
                      "quantized_comm": {"enabled": True},
                      "comm_autotune": {"enabled": True},
                      "observability": {"enabled": True,
                                        "events_dir": str(tmp_path),
                                        "flops_profiler": False,
                                        "memory_watermarks": False}})
    batches = _batches(engine, 2)
    engine.train_batch(iter(batches))
    engine.last_loss()
    engine.close()
    rows = [json.loads(l) for l in
            (tmp_path / "events.jsonl").read_text().splitlines()]
    plans = [r for r in rows if r.get("event") == "comm_plan"]
    assert plans and plans[0]["algo"] == ALGO_TWOHOP
    assert plans[0]["block"] == 256 and "dp=8" in plans[0]["reason"]
    modes = [r for r in rows if r.get("event") == "comm_mode"]
    assert modes and modes[-1]["mode"] == "twohop+overlap"
    # and obs_report surfaces both
    import sys
    sys.path.insert(0, "tools")
    try:
        import obs_report
        s = obs_report.summarize(str(tmp_path))
    finally:
        sys.path.pop(0)
    assert s["comm"]["mode"] == "twohop+overlap"
    assert s["comm"]["plan"]["algo"] == ALGO_TWOHOP
    assert "comm_plan" in obs_report.render(s)


# --------------------------------------------------------------------- #
# measured-link-constants artifact (ISSUE 7 satellite: feed a prior
# run's calibrate_wire_model() measurements into LinkModel instead of
# the hardcoded nominal constants; explicit config keys still win)
# --------------------------------------------------------------------- #
class TestWireCalibrationArtifact:
    def _write(self, monkeypatch, tmp_path, cal):
        from deepspeed_tpu.runtime.comm_autotune import \
            save_wire_calibration
        path = str(tmp_path / "wire_model.json")
        monkeypatch.setenv("DSTPU_WIRE_MODEL", path)
        save_wire_calibration(cal, path)
        return path

    def test_save_load_roundtrip(self, monkeypatch, tmp_path):
        from deepspeed_tpu.runtime.comm_autotune import \
            load_wire_calibration
        self._write(monkeypatch, tmp_path,
                    {"intra_gbps": 99.5, "intra_latency_us": 2.25,
                     "backend": "tpu", "world": 8})
        cal = load_wire_calibration()
        # only the numeric link keys load; provenance stays on disk
        assert cal == {"intra_gbps": 99.5, "intra_latency_us": 2.25}

    def test_missing_or_malformed_artifact_is_none(self, monkeypatch,
                                                   tmp_path):
        from deepspeed_tpu.runtime.comm_autotune import \
            load_wire_calibration
        monkeypatch.setenv("DSTPU_WIRE_MODEL",
                           str(tmp_path / "nope.json"))
        assert load_wire_calibration() is None
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        monkeypatch.setenv("DSTPU_WIRE_MODEL", str(bad))
        assert load_wire_calibration() is None
        # numeric garbage is dropped, not propagated
        weird = tmp_path / "weird.json"
        weird.write_text('{"intra_gbps": "fast", "inter_gbps": -3}')
        monkeypatch.setenv("DSTPU_WIRE_MODEL", str(weird))
        assert load_wire_calibration() is None

    def test_precedence_explicit_beats_artifact_beats_default(
            self, monkeypatch, tmp_path):
        from deepspeed_tpu.runtime.comm_autotune import (
            DEFAULT_INTER_LATENCY_US, DEFAULT_INTRA_LATENCY_US,
            LinkModel)
        from deepspeed_tpu.runtime.config import \
            get_comm_autotune_config
        self._write(monkeypatch, tmp_path,
                    {"intra_gbps": 200.0, "inter_gbps": 20.0})
        # user pins intra_gbps explicitly; inter_gbps comes from the
        # artifact; latencies fall through to the nominal defaults
        ca = get_comm_autotune_config(
            {"comm_autotune": {"intra_gbps": 50.0}})
        link = LinkModel.from_config(ca)
        assert link.intra_gbps == 50.0          # explicit config wins
        assert link.inter_gbps == 20.0          # artifact beats default
        assert link.intra_latency_us == DEFAULT_INTRA_LATENCY_US
        assert link.inter_latency_us == DEFAULT_INTER_LATENCY_US

    def test_default_parse_keeps_nominal_constants(self, monkeypatch):
        # conftest points DSTPU_WIRE_MODEL at a nonexistent path: with
        # no artifact and no explicit keys, the nominal constants hold
        from deepspeed_tpu.runtime.comm_autotune import (
            DEFAULT_INTER_GBPS, DEFAULT_INTRA_GBPS, LinkModel)
        from deepspeed_tpu.runtime.config import \
            get_comm_autotune_config
        ca = get_comm_autotune_config({})
        assert not any(ca["explicit"].values())
        link = LinkModel.from_config(ca)
        assert link.intra_gbps == DEFAULT_INTRA_GBPS
        assert link.inter_gbps == DEFAULT_INTER_GBPS

    def test_hand_built_dict_treats_presence_as_explicit(
            self, monkeypatch, tmp_path):
        # pre-artifact callers pass {"intra_gbps": X} with no explicit
        # map: the value must keep winning over an artifact
        from deepspeed_tpu.runtime.comm_autotune import LinkModel
        self._write(monkeypatch, tmp_path, {"intra_gbps": 200.0})
        link = LinkModel.from_config({"intra_gbps": 42.0})
        assert link.intra_gbps == 42.0

    def test_plan_comm_reports_measured_constants(self, monkeypatch,
                                                  tmp_path):
        from deepspeed_tpu.runtime.comm_autotune import plan_comm
        from deepspeed_tpu.runtime.config import (
            get_comm_autotune_config, get_quantized_comm_config)
        qc = get_quantized_comm_config({"quantized_comm":
                                        {"enabled": True}})
        ca = get_comm_autotune_config({"comm_autotune":
                                       {"enabled": True}})
        base = plan_comm([1 << 20], 8, qc, ca)
        assert "measured link constants" not in base.reason
        # 10x faster measured wire -> 10x cheaper modeled step
        self._write(monkeypatch, tmp_path, {"intra_gbps": 750.0})
        cal = plan_comm([1 << 20], 8, qc, ca)
        assert "measured link constants" in cal.reason
        label = "twohop/b256"
        assert cal.modeled_us[label] < base.modeled_us[label] / 5

    def test_measured_reason_absent_when_explicit_covers_artifact(
            self, monkeypatch, tmp_path):
        # hand-built ca dict (no "explicit" map): key presence is
        # explicit, so an artifact whose only key is pinned by the
        # caller did NOT drive the decision — the reason must not
        # claim measured constants
        from deepspeed_tpu.runtime.comm_autotune import plan_comm
        from deepspeed_tpu.runtime.config import \
            get_quantized_comm_config
        qc = get_quantized_comm_config({"quantized_comm":
                                        {"enabled": True}})
        self._write(monkeypatch, tmp_path, {"intra_gbps": 750.0})
        plan = plan_comm([1 << 20], 8, qc, {"intra_gbps": 42.0})
        assert "measured link constants" not in plan.reason
        # but an artifact key the caller did NOT pin still counts
        self._write(monkeypatch, tmp_path,
                    {"intra_gbps": 750.0, "intra_latency_us": 0.5})
        plan = plan_comm([1 << 20], 8, qc, {"intra_gbps": 42.0})
        assert "measured link constants" in plan.reason

    def test_uniform_fabric_gate(self):
        # persistence gate for measured constants: KNOWN-uniform only —
        # unknown topology (0) must never pass (a flat probe on a split
        # fabric would masquerade DCN timings as the intra constants)
        from deepspeed_tpu.runtime.comm_autotune import uniform_fabric
        assert uniform_fabric(8, 8)
        assert uniform_fabric(16, 8)
        assert not uniform_fabric(4, 8)         # split fabric
        assert not uniform_fabric(0, 8)         # unknown topology
        assert not uniform_fabric(None, 8)      # unset hint

    def test_measure_link_constants_shape(self):
        # structural smoke on the CPU "mesh": returns positive gbps and
        # a nonnegative latency plus provenance (real numbers need real
        # wire; persistence is gated on backend == tpu by the caller)
        from deepspeed_tpu.runtime.comm_autotune import \
            measure_link_constants
        out = measure_link_constants(world=8, sizes=(1 << 10, 1 << 14),
                                     iters=1)
        assert out["intra_gbps"] > 0
        assert out["intra_latency_us"] >= 0
        assert out["backend"] == "cpu" and out["world"] == 8
