"""Adam numerics vs torch.optim.Adam (reference
tests/unit/test_adam_acuracy.py: DeepSpeedCPUAdam must track torch's
Adam trajectory bit-for-bit-ish) — both the native/numpy host Adam and
the in-jit XLA Adam are held to the same oracle."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.optimizers import Adam


def _torch_trajectory(w0, grads, lr, betas, eps, weight_decay, adamw,
                      steps):
    p = torch.nn.Parameter(torch.tensor(w0, dtype=torch.float64))
    cls = torch.optim.AdamW if adamw else torch.optim.Adam
    opt = cls([p], lr=lr, betas=betas, eps=eps, weight_decay=weight_decay)
    outs = []
    for g in grads:
        opt.zero_grad()
        p.grad = torch.tensor(g, dtype=torch.float64)
        opt.step()
        outs.append(p.detach().numpy().copy())
    return outs


@pytest.mark.parametrize("adamw,weight_decay", [(False, 0.0),
                                                (True, 0.01)])
def test_cpu_adam_matches_torch(adamw, weight_decay):
    rng = np.random.RandomState(0)
    n, steps = 257, 8            # odd size: exercises the SIMD tail
    w0 = rng.randn(n).astype(np.float32)
    grads = [rng.randn(n).astype(np.float32) for _ in range(steps)]
    lr, betas, eps = 1e-2, (0.9, 0.999), 1e-8

    opt = DeepSpeedCPUAdam({"w": w0.copy()}, lr=lr, betas=betas, eps=eps,
                           weight_decay=weight_decay, adamw_mode=adamw)
    ref = _torch_trajectory(w0, grads, lr, betas, eps, weight_decay,
                            adamw, steps)
    for g, r in zip(grads, ref):
        out = opt.step({"w": g})
        np.testing.assert_allclose(np.asarray(out["w"]).ravel(), r,
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("adamw,weight_decay", [(False, 0.0),
                                                (True, 0.01)])
def test_xla_adam_matches_torch(adamw, weight_decay):
    rng = np.random.RandomState(1)
    n, steps = 64, 8
    w0 = rng.randn(n).astype(np.float32)
    grads = [rng.randn(n).astype(np.float32) for _ in range(steps)]
    lr, betas, eps = 1e-2, (0.9, 0.999), 1e-8

    opt = Adam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay,
               adamw_mode=adamw)
    params = {"w": jnp.asarray(w0)}
    state = opt.init(params)
    ref = _torch_trajectory(w0, grads, lr, betas, eps, weight_decay,
                            adamw, steps)
    upd = jax.jit(opt.update)
    for g, r in zip(grads, ref):
        params, state = upd({"w": jnp.asarray(g)}, state, params)
        np.testing.assert_allclose(np.asarray(params["w"]), r,
                                   rtol=2e-5, atol=2e-6)
