"""FP16_Optimizer wrapper tests (mirror reference tests/unit/test_fp16.py's
wrapper-level coverage: step skip on overflow, scale dynamics, parity with
fp32 training, state round-trip)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.optimizers import Adam, Lamb
from deepspeed_tpu.runtime.fp16.fused_optimizer import FP16_Optimizer
from deepspeed_tpu.runtime.fp16.unfused_optimizer import FP16_UnfusedOptimizer


def _quad_loss(target):
    def loss_fn(p):
        d = p["w"].astype(jnp.float32) - target
        return jnp.sum(d * d)
    return loss_fn


def test_fp16_training_tracks_fp32():
    target = jnp.asarray(np.random.RandomState(0).randn(8), jnp.float32)
    loss_fn = _quad_loss(target)
    p16 = {"w": jnp.zeros((8,), jnp.float16)}

    fp16_opt = FP16_Optimizer(Adam(lr=0.05), static_loss_scale=128.0)
    fp16_opt.bind(p16)

    # fp32 oracle
    oracle = Adam(lr=0.05)
    p32 = {"w": jnp.zeros((8,), jnp.float32)}
    st32 = oracle.init(p32)

    for _ in range(50):
        fp16_opt.backward(None, loss_fn)
        skipped = fp16_opt.step()
        assert not skipped
        g = jax.grad(lambda p: loss_fn(p))(p32)
        p32, st32 = oracle.update(g, st32, p32)
    # fp16 path follows fp32 within half-precision tolerance
    np.testing.assert_allclose(np.asarray(fp16_opt.params["w"], np.float32),
                               np.asarray(p32["w"]), atol=2e-2)


def test_overflow_skips_and_halves_scale():
    opt = FP16_Optimizer(Adam(lr=0.1), dynamic_loss_scale=True,
                         initial_dynamic_scale=2 ** 16)
    p16 = {"w": jnp.ones((4,), jnp.float16)}
    state = opt.init(p16)
    w_before = np.asarray(state.master_params["w"]).copy()

    bad = {"w": jnp.array([1.0, jnp.inf, 0.0, 0.0], jnp.float16)}
    new_p, state = opt.update(bad, state)
    assert bool(state.overflow)
    np.testing.assert_array_equal(np.asarray(state.master_params["w"]),
                                  w_before)  # step skipped
    assert float(state.loss_scale.scale) == 2 ** 15  # halved

    good = {"w": jnp.full((4,), 0.5, jnp.float16)}
    new_p, state = opt.update(good, state)
    assert not bool(state.overflow)
    assert not np.allclose(np.asarray(state.master_params["w"]), w_before)


def test_scale_growth_after_window():
    opt = FP16_Optimizer(Adam(lr=0.01), dynamic_loss_scale=True,
                         initial_dynamic_scale=4.0,
                         dynamic_loss_args={"scale_window": 3})
    p16 = {"w": jnp.ones((2,), jnp.float16)}
    state = opt.init(p16)
    g = {"w": jnp.full((2,), 0.1, jnp.float16)}
    for i in range(3):
        _, state = opt.update(g, state)
    assert float(state.loss_scale.scale) == 8.0  # doubled after window


def test_clip_grad():
    opt = FP16_Optimizer(Adam(lr=1.0), static_loss_scale=1.0, clip_grad=0.5)
    p16 = {"w": jnp.zeros((2,), jnp.float16)}
    state = opt.init(p16)
    huge = {"w": jnp.full((2,), 100.0, jnp.float16)}
    new_p, state = opt.update(huge, state)
    # with clipping the raw update magnitude stays bounded (Adam normalizes
    # anyway; just confirm finite + step taken)
    assert np.all(np.isfinite(np.asarray(new_p["w"], np.float32)))
    assert not bool(state.overflow)


def test_state_dict_roundtrip():
    loss_fn = _quad_loss(jnp.arange(4.0))
    opt = FP16_Optimizer(Adam(lr=0.05), dynamic_loss_scale=True)
    opt.bind({"w": jnp.zeros((4,), jnp.float16)})
    for _ in range(3):
        opt.backward(None, loss_fn)
        opt.step()
    sd = opt.state_dict()
    assert "fp32_groups_flat" in sd and sd["dynamic_loss_scale"]

    opt2 = FP16_Optimizer(Adam(lr=0.05), dynamic_loss_scale=True)
    opt2.bind({"w": jnp.zeros((4,), jnp.float16)})
    opt2.load_state_dict(sd)
    # identical continuation
    for o in (opt, opt2):
        o.backward(None, loss_fn)
        o.step()
    np.testing.assert_array_equal(
        np.asarray(opt.params["w"], np.float32),
        np.asarray(opt2.params["w"], np.float32))


@pytest.mark.slow
def test_unfused_lamb_variant():
    loss_fn = _quad_loss(jnp.arange(6.0))
    # nonzero start: LAMB's trust ratio scales with ||w||, so w=0 barely
    # moves (correct LAMB behavior, not a wrapper property)
    opt = FP16_UnfusedOptimizer(Lamb(lr=0.1), static_loss_scale=8.0)
    opt.bind({"w": jnp.ones((6,), jnp.float16)})
    l0 = float(loss_fn(opt.params))
    for _ in range(60):
        opt.backward(None, loss_fn)
        opt.step_fused_lamb()
    assert float(loss_fn(opt.params)) < 0.2 * l0


def test_update_is_jittable():
    opt = FP16_Optimizer(Adam(lr=0.05), dynamic_loss_scale=True)
    state = opt.init({"w": jnp.zeros((4,), jnp.float16)})
    upd = jax.jit(opt.update)
    g = {"w": jnp.full((4,), 0.25, jnp.float16)}
    p, state = upd(g, state)
    p, state = upd(g, state)
    assert p["w"].dtype == jnp.float16
