"""Ring attention (sequence/context parallelism) numerics: the sharded
ring must reproduce full-sequence attention — outputs and all three
gradients — on the 8-device CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash import attention_reference
from deepspeed_tpu.ops.attention.ring import ring_attention
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

B, H, D = 2, 2, 8


def _qkv(S, seed=0):
    key = jax.random.PRNGKey(seed)
    return tuple(jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                   jnp.float32) for i in range(3))


def _ring_full(mesh, causal, P_seq, dropout_rate=0.0, rng=None):
    """Full-array wrapper: shard q/k/v over 'seq', run the ring inside
    shard_map, return the full output."""
    def inner(q, k, v):
        return ring_attention(q, k, v, axis_name="seq", causal=causal,
                              dropout_rate=dropout_rate, dropout_rng=rng)
    spec = P(None, None, "seq", None)
    return jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("axes", [{"seq": 8}, {"data": 2, "seq": 4}])
def test_ring_matches_reference_forward(causal, axes):
    mesh = build_mesh(axes)
    S = 16 * axes["seq"]
    q, k, v = _qkv(S)
    out = _ring_full(mesh, causal, axes["seq"])(q, k, v)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference_grads(causal):
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 16 * axes["seq"]
    q, k, v = _qkv(S, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, H, S, D), jnp.float32)

    ring = _ring_full(mesh, causal, axes["seq"])

    def ring_loss(q, k, v):
        return jnp.sum(ring(q, k, v) * w)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal)
                       .astype(jnp.float32) * w)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name}")


def test_ring_single_shard_degenerates_to_flash():
    mesh = build_mesh({"seq": 1, "data": 8})
    S = 32
    q, k, v = _qkv(S, seed=5)
    out = _ring_full(mesh, True, 1)(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_dropout_statistics_and_determinism():
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 16 * axes["seq"]
    q, k, v = _qkv(S, seed=7)
    rng = jax.random.PRNGKey(11)
    f = _ring_full(mesh, False, axes["seq"], dropout_rate=0.5, rng=rng)
    o1 = np.asarray(f(q, k, v))
    o2 = np.asarray(f(q, k, v))
    np.testing.assert_array_equal(o1, o2)  # same rng -> same mask
    ref = np.asarray(attention_reference(q, k, v, causal=False))
    # heavy dropout must actually change the output, but preserve the
    # expectation roughly (inverted scaling)
    assert not np.allclose(o1, ref, atol=1e-3)
    assert abs(o1.mean() - ref.mean()) < 0.05


def test_ring_with_streamed_flash_chunks():
    """Long-context compose: each ring chunk large enough that the flash
    kernel's DMA-streaming path engages INSIDE shard_map (forced via
    STREAM_THRESHOLD) — the layout transposes and HBM-pinned refs must
    survive manual-axes tracing. fwd + grads vs the dense oracle."""
    from deepspeed_tpu.ops.attention import flash as F
    axes = {"seq": 4}
    mesh = build_mesh(axes)
    S = 384 * axes["seq"]          # 384-long chunks -> three 128-wide
                                   # blocks each: a real multi-tile DMA loop
    q, k, v = _qkv(S, seed=5)
    old = F.STREAM_THRESHOLD
    try:
        F.STREAM_THRESHOLD = 128   # force streaming per chunk
        f = _ring_full(mesh, True, axes["seq"])
        out = f(q, k, v)
        g = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(f(a, b, c) ** 2), argnums=(0, 1, 2)))(
                q, k, v)
    finally:
        F.STREAM_THRESHOLD = old
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(attention_reference(a, b, c, causal=True)
                                ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
