"""Ring attention (sequence/context parallelism) numerics: the sharded
ring must reproduce full-sequence attention — outputs and all three
gradients — on the 8-device CPU mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash import attention_reference
from deepspeed_tpu.ops.attention.ring import ring_attention
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

B, H, D = 2, 2, 8


def _qkv(S, seed=0):
    key = jax.random.PRNGKey(seed)
    return tuple(jax.random.normal(jax.random.fold_in(key, i), (B, H, S, D),
                                   jnp.float32) for i in range(3))


def _ring_full(mesh, causal, P_seq, dropout_rate=0.0, rng=None):
    """Full-array wrapper: shard q/k/v over 'seq', run the ring inside
    shard_map, return the full output."""
    def inner(q, k, v):
        return ring_attention(q, k, v, axis_name="seq", causal=causal,
                              dropout_rate=dropout_rate, dropout_rng=rng)
    spec = P(None, None, "seq", None)
    return jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("axes", [{"seq": 8}, {"data": 2, "seq": 4}])
def test_ring_matches_reference_forward(causal, axes):
    mesh = build_mesh(axes)
    S = 16 * axes["seq"]
    q, k, v = _qkv(S)
    out = _ring_full(mesh, causal, axes["seq"])(q, k, v)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference_grads(causal):
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 16 * axes["seq"]
    q, k, v = _qkv(S, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, H, S, D), jnp.float32)

    ring = _ring_full(mesh, causal, axes["seq"])

    def ring_loss(q, k, v):
        return jnp.sum(ring(q, k, v) * w)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal)
                       .astype(jnp.float32) * w)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name}")


def test_ring_single_shard_degenerates_to_flash():
    mesh = build_mesh({"seq": 1, "data": 8})
    S = 32
    q, k, v = _qkv(S, seed=5)
    out = _ring_full(mesh, True, 1)(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_dropout_statistics_and_determinism():
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 16 * axes["seq"]
    q, k, v = _qkv(S, seed=7)
    rng = jax.random.PRNGKey(11)
    f = _ring_full(mesh, False, axes["seq"], dropout_rate=0.5, rng=rng)
    o1 = np.asarray(f(q, k, v))
    o2 = np.asarray(f(q, k, v))
    np.testing.assert_array_equal(o1, o2)  # same rng -> same mask
    ref = np.asarray(attention_reference(q, k, v, causal=False))
    # heavy dropout must actually change the output, but preserve the
    # expectation roughly (inverted scaling)
    assert not np.allclose(o1, ref, atol=1e-3)
    assert abs(o1.mean() - ref.mean()) < 0.05


def test_ring_with_streamed_flash_chunks():
    """Long-context compose: each ring chunk large enough that the flash
    kernel's DMA-streaming path engages INSIDE shard_map (forced via
    STREAM_THRESHOLD) — the layout transposes and HBM-pinned refs must
    survive manual-axes tracing. fwd + grads vs the dense oracle."""
    from deepspeed_tpu.ops.attention import flash as F
    axes = {"seq": 4}
    mesh = build_mesh(axes)
    S = 384 * axes["seq"]          # 384-long chunks -> three 128-wide
                                   # blocks each: a real multi-tile DMA loop
    q, k, v = _qkv(S, seed=5)
    old = F.STREAM_THRESHOLD
    try:
        F.STREAM_THRESHOLD = 128   # force streaming per chunk
        f = _ring_full(mesh, True, axes["seq"])
        out = f(q, k, v)
        g = jax.jit(jax.grad(
            lambda a, b, c: jnp.sum(f(a, b, c) ** 2), argnums=(0, 1, 2)))(
                q, k, v)
    finally:
        F.STREAM_THRESHOLD = old
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(attention_reference(a, b, c, causal=True)
                                ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


# ------------------------- zigzag schedule --------------------------- #
def _zz_full(mesh, P_seq, dropout_rate=0.0, rng=None):
    """Full-array wrapper for the zigzag schedule: permute the global
    sequence into the zigzag layout, shard over 'seq', run, un-permute."""
    from deepspeed_tpu.ops.attention.ring import zigzag_layout_indices

    def fn(q, k, v):
        S = q.shape[2]
        g = zigzag_layout_indices(P_seq, S)
        inv = np.argsort(g)

        def inner(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True,
                                  dropout_rate=dropout_rate,
                                  dropout_rng=rng, zigzag=True)
        spec = P(None, None, "seq", None)
        mapped = jax.shard_map(inner, mesh=mesh,
                               in_specs=(spec, spec, spec),
                               out_specs=spec, check_vma=False)
        out_z = mapped(q[:, :, g, :], k[:, :, g, :], v[:, :, g, :])
        return out_z[:, :, inv, :]
    return jax.jit(fn)


@pytest.mark.parametrize("axes", [{"seq": 8}, {"data": 2, "seq": 4}])
def test_zigzag_matches_reference_forward(axes):
    mesh = build_mesh(axes)
    S = 32 * axes["seq"]
    q, k, v = _qkv(S)
    out = _zz_full(mesh, axes["seq"])(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_zigzag_matches_reference_grads():
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 32 * axes["seq"]
    q, k, v = _qkv(S, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, H, S, D), jnp.float32)

    zz = _zz_full(mesh, axes["seq"])

    def zz_loss(q, k, v):
        return jnp.sum(zz(q, k, v) * w)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True)
                       .astype(jnp.float32) * w)

    g_zz = jax.grad(zz_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gf, name in zip(g_zz, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name}")


def test_zigzag_halves_causal_flops():
    """VERDICT r2 #5 'done' criterion: the balanced schedule does ~half
    the plain causal ring's attention work at P=4 (jaxpr dot FLOPs; scan
    bodies are weighted by trip count)."""
    from jax.extend import core as jex_core

    def dot_flops(jaxpr, mult=1):
        total = 0
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in ("dot_general", "dot"):
                lhs = eqn.invars[0].aval.shape
                rhs = eqn.invars[1].aval.shape
                dims = eqn.params["dimension_numbers"][0]
                contract = 1
                for d in dims[0]:
                    contract *= lhs[d]
                m = 1
                for s in lhs:
                    m *= s
                n = 1
                for s in rhs:
                    n *= s
                total += 2 * m * n // max(contract, 1)
            m2 = (eqn.params.get("length", 1)
                  if eqn.primitive.name == "scan" else 1)
            for v_ in eqn.params.values():
                subs = []
                if isinstance(v_, jex_core.ClosedJaxpr):
                    subs = [v_.jaxpr]
                elif hasattr(v_, "eqns"):
                    subs = [v_]
                elif isinstance(v_, (tuple, list)):
                    subs = [s.jaxpr if isinstance(s, jex_core.ClosedJaxpr)
                            else s for s in v_ if
                            isinstance(s, jex_core.ClosedJaxpr)
                            or hasattr(s, "eqns")]
                for s in subs:
                    total += mult * m2 * dot_flops(s)
        return total

    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 32 * axes["seq"]
    q, k, v = _qkv(S)

    def loss_plain(q, k, v):
        ring = _ring_full(mesh, True, axes["seq"])
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_zz(q, k, v):
        zz = _zz_full(mesh, axes["seq"])
        return jnp.sum(zz(q, k, v) ** 2)

    f_plain = dot_flops(jax.make_jaxpr(
        jax.grad(loss_plain, argnums=(0, 1, 2)))(q, k, v).jaxpr)
    f_zz = dot_flops(jax.make_jaxpr(
        jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v).jaxpr)
    # plain causal ring computes-and-discards future chunks; zigzag does
    # the minimal balanced work -> ~0.5x + per-call overhead
    assert f_zz < 0.65 * f_plain, (f_zz, f_plain, f_zz / f_plain)


def test_zigzag_key_padding_mask_matches_reference():
    """zigzag + rotating key-padding mask: the mask halves ride the
    zigzag layout with their K/V chunks."""
    from deepspeed_tpu.ops.attention.ring import zigzag_layout_indices
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 32 * axes["seq"]
    q, k, v = _qkv(S, seed=6)
    mrng = np.random.RandomState(8)
    kpm = jnp.asarray(
        np.where(mrng.rand(B, 1, 1, S) > 0.25, 0.0, -1e9), jnp.float32)

    g = zigzag_layout_indices(axes["seq"], S)
    inv = np.argsort(g)

    def inner(q, k, v, m):
        return ring_attention(q, k, v, axis_name="seq", causal=True,
                              key_padding_mask=m, zigzag=True)
    spec = P(None, None, "seq", None)
    mspec = P(None, None, None, "seq")
    mapped = jax.shard_map(inner, mesh=mesh,
                           in_specs=(spec, spec, spec, mspec),
                           out_specs=spec, check_vma=False)
    out = jax.jit(mapped)(q[:, :, g, :], k[:, :, g, :], v[:, :, g, :],
                          kpm[:, :, :, g])[:, :, inv, :]
    ref = attention_reference(q, k, v, mask=kpm, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_zigzag_dropout_deterministic_and_consistent():
    """zigzag + in-kernel dropout: seed-deterministic, seeds distinct per
    chunk pair (different rngs give different outputs), and the custom
    VJP runs (fwd/bwd regenerate the same per-pair masks)."""
    axes = {"seq": 4, "data": 2}
    mesh = build_mesh(axes)
    S = 32 * axes["seq"]
    q, k, v = _qkv(S, seed=7)
    r1, r2 = jax.random.PRNGKey(3), jax.random.PRNGKey(4)

    def run(rng):
        def inner(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True,
                                  dropout_rate=0.2, dropout_rng=rng,
                                  zigzag=True)
        spec = P(None, None, "seq", None)
        return jax.jit(jax.shard_map(
            inner, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False))

    o1a = run(r1)(q, k, v)
    o1b = run(r1)(q, k, v)
    o2 = run(r2)(q, k, v)
    np.testing.assert_array_equal(np.asarray(o1a), np.asarray(o1b))
    assert float(jnp.abs(o1a - o2).max()) > 1e-4

    def loss(q, k, v):
        def inner(q, k, v):
            return ring_attention(q, k, v, axis_name="seq", causal=True,
                                  dropout_rate=0.2, dropout_rng=r1,
                                  zigzag=True)
        spec = P(None, None, "seq", None)
        out = jax.shard_map(inner, mesh=mesh,
                            in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)(q, k, v)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    gs = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for a in gs:
        assert np.all(np.isfinite(np.asarray(a)))
