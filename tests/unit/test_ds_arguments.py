"""CLI argument-group tier (reference tests/unit/test_ds_arguments.py):
add_config_arguments must install the --deepspeed/--deepspeed_config
flags (plus deprecated aliases) without disturbing client args."""

import argparse

import pytest

import deepspeed_tpu


def _parser():
    p = argparse.ArgumentParser()
    p.add_argument("--num_epochs", type=int)
    return p


def test_no_ds_arguments_no_ds_parser():
    """(reference test_ds_arguments.py:no_ds_arguments)"""
    parser = _parser()
    args = parser.parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert not hasattr(args, "deepspeed")
    assert not hasattr(args, "deepspeed_config")


def test_no_ds_arguments():
    parser = deepspeed_tpu.add_config_arguments(_parser())
    args = parser.parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert args.deepspeed is False
    assert args.deepspeed_config is None


def test_ds_arguments():
    parser = deepspeed_tpu.add_config_arguments(_parser())
    args = parser.parse_args(
        ["--num_epochs", "2", "--deepspeed",
         "--deepspeed_config", "foo.json"])
    assert args.num_epochs == 2
    assert args.deepspeed is True
    assert args.deepspeed_config == "foo.json"


def test_ds_enable_only():
    parser = deepspeed_tpu.add_config_arguments(_parser())
    args = parser.parse_args(["--num_epochs", "2", "--deepspeed"])
    assert args.deepspeed is True
    assert args.deepspeed_config is None


def test_deprecated_deepscale_aliases():
    """(reference kept deepscale spellings for backward compat)"""
    parser = deepspeed_tpu.add_config_arguments(_parser())
    args = parser.parse_args(
        ["--deepscale", "--deepscale_config", "bar.json"])
    assert args.deepscale is True
    assert args.deepscale_config == "bar.json"


def test_core_flags_reject_unknown_value():
    parser = deepspeed_tpu.add_config_arguments(_parser())
    with pytest.raises(SystemExit):
        parser.parse_args(["--deepspeed_config"])  # missing value
