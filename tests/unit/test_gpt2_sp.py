"""Sequence-parallel GPT-2 (ring attention over the 'seq' mesh axis):
loss and training parity against the dense single-shard model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_loss_fn,
                                       gpt2_sp_loss_fn, init_gpt2_params)
from deepspeed_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)

CFG = GPT2Config(vocab_size=128, max_position_embeddings=64,
                 hidden_size=32, num_layers=2, num_heads=2,
                 embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)


def _batch(bs=4, S=64, seed=0):
    rng = np.random.RandomState(seed)
    return {"input_ids": rng.randint(0, CFG.vocab_size,
                                     (bs, S + 1)).astype(np.int32)}


@pytest.mark.parametrize("axes", [{"seq": 8}, {"seq": 4, "data": 2}])
def test_sp_loss_matches_dense(axes):
    mesh = build_mesh(axes)
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    sp = gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.float32, deterministic=True)
    dense = gpt2_loss_fn(CFG, dtype=jnp.float32, deterministic=True)
    b = _batch()
    rng = jax.random.PRNGKey(1)
    l_sp = float(jax.jit(sp)(params, b, rng))
    l_d = float(jax.jit(dense)(params, b, rng))
    np.testing.assert_allclose(l_sp, l_d, rtol=2e-5)


def test_sp_grads_match_dense():
    mesh = build_mesh({"seq": 4, "data": 2})
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    sp = gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.float32, deterministic=True)
    dense = gpt2_loss_fn(CFG, dtype=jnp.float32, deterministic=True)
    b = _batch(seed=3)
    rng = jax.random.PRNGKey(1)
    g_sp = jax.jit(jax.grad(lambda p: sp(p, b, rng)))(params)
    g_d = jax.jit(jax.grad(lambda p: dense(p, b, rng)))(params)
    for (pa, a), (_, d) in zip(
            jax.tree_util.tree_flatten_with_path(g_sp)[0],
            jax.tree_util.tree_flatten_with_path(g_d)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(d),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=str(pa))


def test_sp_trains_through_engine():
    """End to end: the engine trains the SP loss on a seq x data mesh
    (bf16, ZeRO-2) and the loss decreases."""
    mesh_axes = {"seq": 4, "data": 2}
    mesh = build_mesh(mesh_axes)
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    sp = gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.bfloat16, deterministic=True)
    engine, *_ = ds.initialize(
        model=sp, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 2,
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {"axes": mesh_axes}})
    losses = []
    for i in range(6):
        losses.append(float(engine.train_batch(iter([_batch(seed=i)]))))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_sp_with_auto_model_axis_present():
    """A size-1 auto 'model' axis in the mesh must not break the SP path
    (regression guard for the XLA bf16-psum partitioner abort class)."""
    mesh = build_mesh({"seq": 4, "data": 2, "model": 1})
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    sp = gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.bfloat16, deterministic=True)
    b = _batch()
    rng = jax.random.PRNGKey(1)
    g = jax.jit(jax.grad(lambda p: sp(p, b, rng)))(params)
    assert all(np.all(np.isfinite(np.asarray(x)))
               for x in jax.tree_util.tree_leaves(g))


def test_bert_sp_matches_dense():
    """Sequence-parallel BERT MLM (bidirectional ring + padding mask)
    matches the dense model: loss and grads."""
    from deepspeed_tpu.models.bert import (BertConfig, bert_mlm_loss_fn,
                                           bert_mlm_sp_loss_fn,
                                           init_bert_params)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=64,
                     hidden_dropout=0.0, attn_dropout=0.0)
    mesh = build_mesh({"seq": 4, "data": 2})
    params = init_bert_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (4, 64)).astype(np.int32)
    labels = np.where(rng.rand(4, 64) < 0.15, ids, -100).astype(np.int32)
    am = np.ones((4, 64), np.int32)
    am[:, 56:] = 0  # padded tail
    batch = {"input_ids": ids, "labels": labels, "attention_mask": am}

    sp = bert_mlm_sp_loss_fn(cfg, mesh, dtype=jnp.float32,
                             deterministic=True)
    dense = bert_mlm_loss_fn(cfg, dtype=jnp.float32, deterministic=True)
    key = jax.random.PRNGKey(1)
    l_sp = float(jax.jit(sp)(params, batch, key))
    l_d = float(jax.jit(dense)(params, batch, key))
    np.testing.assert_allclose(l_sp, l_d, rtol=2e-5)

    g_sp = jax.jit(jax.grad(lambda p: sp(p, batch, key)))(params)
    g_d = jax.jit(jax.grad(lambda p: dense(p, batch, key)))(params)
    for (pa, a), (_, d) in zip(
            jax.tree_util.tree_flatten_with_path(g_sp)[0],
            jax.tree_util.tree_flatten_with_path(g_d)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(d),
                                   rtol=2e-4, atol=2e-5, err_msg=str(pa))


def test_sp_zigzag_loss_matches_dense():
    """zigzag=True: the load-balanced causal schedule computes the SAME
    LM loss (every token's loss lands once, whichever shard owns it)."""
    mesh = build_mesh({"seq": 4, "data": 2})
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    sp = gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.float32, deterministic=True,
                         zigzag=True)
    dense = gpt2_loss_fn(CFG, dtype=jnp.float32, deterministic=True)
    b = _batch(seed=11)
    rng = jax.random.PRNGKey(1)
    l_sp = float(jax.jit(sp)(params, b, rng))
    l_d = float(jax.jit(dense)(params, b, rng))
    np.testing.assert_allclose(l_sp, l_d, rtol=2e-5)


def test_sp_zigzag_grads_match_dense():
    mesh = build_mesh({"seq": 4, "data": 2})
    params = init_gpt2_params(CFG, jax.random.PRNGKey(0))
    sp = gpt2_sp_loss_fn(CFG, mesh, dtype=jnp.float32, deterministic=True,
                         zigzag=True)
    dense = gpt2_loss_fn(CFG, dtype=jnp.float32, deterministic=True)
    b = _batch(seed=12)
    rng = jax.random.PRNGKey(1)
    g_sp = jax.jit(jax.grad(lambda p: sp(p, b, rng)))(params)
    g_d = jax.jit(jax.grad(lambda p: dense(p, b, rng)))(params)
    for (pa, a), (_, d) in zip(
            jax.tree_util.tree_flatten_with_path(g_sp)[0],
            jax.tree_util.tree_flatten_with_path(g_d)[0]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(d), rtol=1e-4, atol=1e-5,
            err_msg=str(pa))
