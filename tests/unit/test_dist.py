"""The distributed substrate itself — mirrors the reference's
tests/unit/test_dist.py (which validates its @distributed_test NCCL
fixture and a bare all_reduce) for the TPU-native design: the named-axis
Mesh replaces process groups, in-jit XLA collectives replace
torch.distributed calls, and ``init_distributed`` replaces the MPI/env
rendezvous (reference tests/unit/test_dist.py:10-31, engine.py:134-139).
"""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import (axis_size, build_mesh,
                                         data_sharding, replicated)
from deepspeed_tpu import distributed as dist


# --------------------------------------------------------------------- #
# mesh construction (the process-group analog)
# --------------------------------------------------------------------- #
def test_default_mesh_all_data():
    mesh = build_mesh()
    assert mesh.axis_names == ("data",)
    assert axis_size(mesh, "data") == 8


def test_mesh_infer_one_axis():
    mesh = build_mesh({"pipe": 2, "data": -1, "model": 2})
    assert axis_size(mesh, "data") == 2
    assert mesh.devices.size == 8


def test_mesh_two_unknown_axes_rejected():
    with pytest.raises(ValueError, match="at most one"):
        build_mesh({"data": -1, "model": -1})


def test_mesh_subset_for_elastic_resume():
    # explicit smaller world: runs on a device subset (elastic reload)
    mesh = build_mesh({"data": 4})
    assert mesh.devices.size == 4


def test_mesh_indivisible_rejected():
    with pytest.raises(ValueError, match="not divisible"):
        build_mesh({"pipe": 3, "data": -1})


# --------------------------------------------------------------------- #
# collectives (the all_reduce/broadcast analog of test_dist.py:24-31)
# --------------------------------------------------------------------- #
def _ranked(mesh, axis):
    """Per-shard (1,) array holding the shard's axis index."""
    n = axis_size(mesh, axis)
    return jax.device_put(
        jnp.arange(n, dtype=jnp.float32),
        jax.sharding.NamedSharding(mesh, P(axis)))


def test_psum_matches_sum_of_ranks():
    mesh = build_mesh({"data": 8})
    x = _ranked(mesh, "data")

    @jax.jit
    def f(x):
        def body(x):
            return jax.lax.psum(x, "data")
        return shard_map(body, mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"))(x)

    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, np.full(8, 28.0))  # sum 0..7


def test_all_gather_and_reduce_scatter_roundtrip():
    mesh = build_mesh({"data": 8})
    x = _ranked(mesh, "data")

    @jax.jit
    def f(x):
        def body(x):
            g = jax.lax.all_gather(x, "data")          # (8, 1) per shard
            return jax.lax.psum_scatter(g.reshape(8), "data",
                                        scatter_dimension=0, tiled=True)
        return shard_map(body, mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"))(x)

    # all_gather then reduce-scatter of identical vectors = 8 * rank_r
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, 8.0 * np.arange(8))


def test_ppermute_ring_rotation():
    # the pipe p2p analog (reference p2p.py:31-55 2-rank broadcast)
    mesh = build_mesh({"pipe": 8})
    x = _ranked(mesh, "pipe")

    @jax.jit
    def f(x):
        def body(x):
            n = jax.lax.axis_size("pipe")
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, "pipe", perm)
        return shard_map(body, mesh=mesh, in_specs=P("pipe"),
                         out_specs=P("pipe"))(x)

    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, np.roll(np.arange(8), 1))


def test_all_to_all_transpose():
    # the MoE dispatch primitive: shard i sends slice j to shard j
    mesh = build_mesh({"expert": 4})
    vals = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
    x = jax.device_put(vals, jax.sharding.NamedSharding(mesh, P("expert")))

    @jax.jit
    def f(x):
        def body(x):                                   # (1, 4) per shard
            return jax.lax.all_to_all(x, "expert", split_axis=1,
                                      concat_axis=0, tiled=False)
        return shard_map(body, mesh=mesh, in_specs=P("expert"),
                         out_specs=P("expert"))(x)

    out = np.asarray(f(x)).reshape(4, 4)
    np.testing.assert_array_equal(out, np.asarray(vals).T.reshape(4, 4))


def test_sharding_helpers():
    mesh = build_mesh({"data": 8})
    ds = data_sharding(mesh)
    rep = replicated(mesh)
    x = jax.device_put(jnp.zeros((16, 4)), ds)
    y = jax.device_put(jnp.zeros((4,)), rep)
    assert x.sharding.spec == P("data")
    assert y.sharding.is_fully_replicated


# --------------------------------------------------------------------- #
# host bootstrap (the MPI/env rendezvous analog, engine.py:198-235)
# --------------------------------------------------------------------- #
def test_init_distributed_single_process_noop(monkeypatch):
    for k in ("DSTPU_COORDINATOR", "DSTPU_NUM_PROCESSES",
              "DSTPU_PROCESS_ID", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(k, raising=False)
    before = dist.is_initialized()
    dist.init_distributed()
    # single process: must stay un-initialized rather than hang on a
    # coordinator that does not exist
    assert dist.is_initialized() == before


# --------------------------------------------------------------------- #
# REAL multi-process bootstrap (the reference's @distributed_test forks
# N processes against 127.0.0.1:29503, tests/unit/common.py:14; here: 2
# subprocesses rendezvous via jax.distributed and run one global psum)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_two_process_bootstrap_and_global_psum():
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    child = r"""
import os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from deepspeed_tpu.distributed import init_distributed, is_initialized
init_distributed()
assert is_initialized()
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()       # global view
assert jax.local_device_count() == 1
import numpy as np, jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec
mesh = Mesh(np.array(jax.devices()), ("data",))
pid = jax.process_index()
# each process contributes its rank+1; the global sum must be 3
local = np.full((1, 4), float(pid + 1), np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, PartitionSpec("data")), local, (2, 4))
total = jax.jit(lambda x: jnp.sum(x),
                out_shardings=NamedSharding(mesh, PartitionSpec()))(garr)
np.testing.assert_allclose(np.asarray(total), 12.0)      # (1+2)*4
print(f"proc {pid} ok", flush=True)
"""
    env = dict(os.environ,
               DSTPU_COORDINATOR=f"127.0.0.1:{port}",
               DSTPU_NUM_PROCESSES="2")
    env.pop("JAX_PLATFORMS", None)
    procs = []
    for pid in range(2):
        e = dict(env, DSTPU_PROCESS_ID=str(pid))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", child], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any("Multiprocess computations aren't implemented on the CPU "
           "backend" in out for out in outs):
        # older jaxlib CPU runtimes have no cross-process collectives
        # (gloo backend landed later) — the bootstrap handshake itself
        # succeeded (coordinator logs printed), only the collective is
        # unimplemented on this backend
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}:\n{out[-2000:]}"
        assert f"proc {i} ok" in out
