"""Health plane (ISSUE 15): flight recorder, stall watchdog, numeric
anomaly detectors, and cross-run regression diffing.

Covers the acceptance bar: an injected ``health.stall`` in a real CPU
train step produces a ``stall_detected`` row naming the pinned phase
plus an atomic ``flight.json`` with the pre-stall ring and all-thread
stacks (and ``obs_report --health`` renders it); an injected NaN-loss
streak produces a ``health`` row with the pinned reason; the fully
enabled plane perturbs NOTHING (bitwise losses/params, identical
dispatch counts, zero steady-state recompiles); and ``--diff`` exits
nonzero naming the regressed metric on a deliberately slowed run while
two identical runs diff clean.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.runtime import fault
from deepspeed_tpu.utils.health import (HEALTH_PHASES, HEALTH_REASONS,
                                        STALL_EXIT_CODE, FlightRecorder,
                                        HealthPlane, NumericHealth,
                                        Watchdog)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.reset()
    yield
    fault.reset()


def _load_obs_report():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _events(path):
    rows = [json.loads(l) for l in open(path)]
    return rows


# ================================================================== #
# flight recorder units
# ================================================================== #


def test_flight_ring_is_bounded(tmp_path):
    rec = FlightRecorder(str(tmp_path / "flight.json"), ring_events=16)
    for i in range(100):
        rec.record({"tag": "x", "value": float(i), "step": i})
    assert len(rec.ring) == 16
    # oldest rows fell off; the LAST 16 survive
    assert [r["step"] for r in rec.ring] == list(range(84, 100))


def test_mirror_tap_is_transparent(tmp_path):
    """Install + remove the tap around a fake mirror: the inner writer
    sees the exact same calls, and untap restores the original object
    (the Observer's close-time identity check depends on it)."""

    class FakeMirror:
        def __init__(self):
            self.scalars, self.events, self.flushes = [], [], 0

        def add_scalar(self, tag, value, step):
            self.scalars.append((tag, value, step))

        def add_event(self, kind, **fields):
            self.events.append((kind, fields))

        def flush(self):
            self.flushes += 1

    class FakeMonitor:
        pass

    mon = FakeMonitor()
    inner = FakeMirror()
    mon.mirror = inner
    rec = FlightRecorder(str(tmp_path / "flight.json"), ring_events=8)
    rec.tap(mon)
    assert mon.mirror is not inner
    mon.mirror.add_scalar("Train/Samples/train_loss", 2.5, 32)
    mon.mirror.add_event("health", reason="nan_loss", step=32)
    mon.mirror.flush()
    # forwarded unchanged
    assert inner.scalars == [("Train/Samples/train_loss", 2.5, 32)]
    assert inner.events == [("health", {"reason": "nan_loss",
                                        "step": 32})]
    assert inner.flushes == 1
    # AND copied into the ring
    rows = list(rec.ring)
    assert rows[0]["tag"] == "Train/Samples/train_loss"
    assert rows[1]["event"] == "health"
    rec.untap()
    assert mon.mirror is inner


def test_flight_dump_atomic_roundtrip(tmp_path):
    path = str(tmp_path / "sub" / "flight.json")
    rec = FlightRecorder(path, ring_events=8)
    rec.record({"tag": "x", "value": 1.0, "step": 1})
    out = rec.dump("drain", extra={"reason": "test"}, stacks=True)
    assert out == path
    payload = json.load(open(path))
    assert payload["trigger"] == "drain"
    assert payload["reason"] == "test"
    assert payload["rows"] == [{"tag": "x", "value": 1.0, "step": 1}]
    assert payload["ring_events"] == 8
    # all-thread stacks name this (the main) thread
    assert any("MainThread" in k for k in payload["stacks"])
    # no torn tmp file left behind
    assert not os.path.exists(path + ".tmp")
    # best-effort: an unwritable path returns None instead of raising
    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    bad = FlightRecorder(str(blocker / "x" / "flight.json"))
    assert bad.dump("drain") is None


def test_excepthook_chains_and_dumps(tmp_path):
    path = str(tmp_path / "flight.json")
    rec = FlightRecorder(path, ring_events=8)
    rec.record({"tag": "x", "value": 1.0, "step": 1})
    seen = []
    prev_hook = sys.excepthook
    sys.excepthook = lambda t, e, tb: seen.append((t, str(e)))
    try:
        rec.install_excepthook()
        try:
            raise RuntimeError("boom at step 7")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
        payload = json.load(open(path))
        assert payload["trigger"] == "exception"
        assert payload["exception"]["type"] == "RuntimeError"
        assert "boom at step 7" in payload["exception"]["value"]
        assert payload["rows"]          # pre-crash ring rode along
        # the PREVIOUS hook still ran (chained, not replaced)
        assert seen == [(RuntimeError, "boom at step 7")]
        rec.uninstall_excepthook()
        assert sys.excepthook is not getattr(rec, "_hook", None)
    finally:
        sys.excepthook = prev_hook


# ================================================================== #
# watchdog units
# ================================================================== #


def test_watchdog_trips_in_warn_mode_and_rearms():
    trips = []
    wd = Watchdog(0.15, on_stall="warn",
                  on_trip=lambda **kw: trips.append(kw))
    wd.start()
    try:
        wd.beat("train_batch")
        deadline = time.monotonic() + 3.0
        while not trips and time.monotonic() < deadline:
            time.sleep(0.02)
        assert trips, "watchdog never tripped"
        t = trips[0]
        assert t["phase"] == "train_batch"
        assert t["silent_s"] >= 0.15
        assert any("MainThread" in k for k in t["stacks"])
        assert wd.trips >= 1
    finally:
        wd.stop()


def test_watchdog_trip_names_awaited_replica():
    """The router beats ``rpc_call`` with ``detail="replica N"``
    before every blocking wait — a trip during a hung RPC must carry
    that detail so the postmortem names WHICH replica was awaited."""
    trips = []
    wd = Watchdog(0.15, on_stall="warn",
                  on_trip=lambda **kw: trips.append(kw))
    wd.start()
    try:
        wd.beat("rpc_call", detail="replica 2")
        deadline = time.monotonic() + 3.0
        while not trips and time.monotonic() < deadline:
            time.sleep(0.02)
        assert trips, "watchdog never tripped"
        assert trips[0]["phase"] == "rpc_call"
        assert trips[0]["detail"] == "replica 2"
    finally:
        wd.stop()


def test_watchdog_heartbeats_prevent_trip():
    trips = []
    wd = Watchdog(0.25, on_stall="warn",
                  on_trip=lambda **kw: trips.append(kw))
    wd.start()
    try:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 0.6:
            wd.beat("decode")
            time.sleep(0.03)
        assert trips == [] and wd.trips == 0
    finally:
        wd.stop()


def test_stall_exit_code_is_distinguishable():
    """87 must never collide with the elastic resumable code (85) or an
    uncaught SIGTERM (143) — supervisors dispatch on it."""
    from deepspeed_tpu.runtime.elastic import RESUMABLE_EXIT_CODE
    assert STALL_EXIT_CODE == 87
    assert STALL_EXIT_CODE not in (RESUMABLE_EXIT_CODE, 143, 0, 1, 2)


# ================================================================== #
# pinned vocabularies
# ================================================================== #


def test_heartbeat_phase_vocabulary_pinned(tmp_path):
    """The phase names ARE the stall-postmortem contract: renames break
    every consumer (obs_report, bench salvage, docs), so the set is
    pinned and unknown phases raise even on an ENABLED plane."""
    assert HEALTH_PHASES == (
        "train_batch", "prefill", "decode", "handoff_claim",
        "chunk_prefill", "checkpoint_commit", "fleet_step",
        "bench_metric", "rpc_call")
    hp = HealthPlane({"enabled": True, "stall_timeout_s": 60.0},
                     events_dir=str(tmp_path))
    try:
        for phase in HEALTH_PHASES:
            hp.heartbeat(phase)           # every pinned phase accepted
        with pytest.raises(ValueError, match="unknown heartbeat phase"):
            hp.heartbeat("totally_new_phase")
    finally:
        hp.close()


def test_health_reason_vocabulary_pinned():
    assert HEALTH_REASONS == (
        "nan_loss", "loss_spike", "grad_norm_explosion",
        "loss_scale_collapse", "recompile_storm")
    det = NumericHealth({})
    with pytest.raises(AssertionError):
        det._alert("made_up_reason", 0)


# ================================================================== #
# numeric detectors (synthetic streams, pure host floats)
# ================================================================== #


def _collector():
    alerts = []
    return alerts, (lambda reason, step, detail:
                    alerts.append((reason, step, detail)))


def test_nonfinite_streak_alerts_once_per_episode():
    alerts, cb = _collector()
    det = NumericHealth({"nonfinite_streak": 3}, on_alert=cb)
    det.observe_loss(float("nan"), 1)
    det.observe_loss(float("nan"), 2)
    assert alerts == []                      # below the streak floor
    det.observe_loss(float("inf"), 3)        # inf counts as nonfinite
    assert [(r, s) for r, s, _ in alerts] == [("nan_loss", 3)]
    for step in range(4, 50):                # 46 MORE bad steps...
        det.observe_loss(float("nan"), step)
    assert len(alerts) == 1                  # ...one row, not 46
    det.observe_loss(2.0, 50)                # recovery resets the episode
    for step in range(51, 54):
        det.observe_loss(float("nan"), step)
    assert len(alerts) == 2                  # second episode = second row
    assert det.alerts_by_reason["nan_loss"] == 2


def test_loss_spike_zscore():
    alerts, cb = _collector()
    det = NumericHealth({"spike_zscore": 6.0, "spike_window": 32},
                        on_alert=cb)
    rng = np.random.RandomState(0)
    for step in range(20):                   # tight, healthy plateau
        det.observe_loss(2.0 + 0.01 * rng.randn(), step)
    assert alerts == []
    det.observe_loss(9.0, 20)                # z >> 6
    assert [(r, s) for r, s, _ in alerts] == [("loss_spike", 20)]
    assert alerts[0][2]["z"] > 6.0
    det.observe_loss(2.0, 21)                # back on the plateau: quiet
    det.observe_loss(2.0, 22)
    assert len(alerts) == 1


def test_grad_norm_and_scale_collapse_detectors():
    alerts, cb = _collector()
    det = NumericHealth({"grad_norm_max": 100.0,
                         "scale_collapse_below": 2.0}, on_alert=cb)
    det.observe_grad_norm(5.0, 1)
    det.observe_grad_norm(5000.0, 2)
    det.observe_grad_norm(7000.0, 3)         # still the same episode
    det.observe_loss_scale(65536.0, 3)
    det.observe_loss_scale(1.0, 4)           # ground into the floor
    assert [(r, s) for r, s, _ in alerts] == [
        ("grad_norm_explosion", 2), ("loss_scale_collapse", 4)]
    assert alerts[0][2]["ceiling"] == 100.0
    assert alerts[1][2]["loss_scale"] == 1.0
    # NaN grad norm is an explosion too
    det.observe_grad_norm(1.0, 5)            # episode reset
    det.observe_grad_norm(float("nan"), 6)
    assert alerts[-1][0] == "grad_norm_explosion"


def test_recompile_storm_from_cumulative_counter():
    alerts, cb = _collector()
    det = NumericHealth({"recompile_storm_count": 3,
                         "recompile_storm_window": 16}, on_alert=cb)
    det.observe_recompiles(1.0, 0)           # warmup baseline
    det.observe_recompiles(1.0, 10)          # steady state: no growth
    det.observe_recompiles(2.0, 20)          # one recompile — fine
    assert alerts == []
    det.observe_recompiles(3.0, 22)
    det.observe_recompiles(4.0, 24)          # 3 inside 16 steps: storm
    assert [(r, s) for r, s, _ in alerts] == [("recompile_storm", 24)]
    # marks outside the window age out — no second alert on quiet steps
    det.observe_recompiles(4.0, 100)
    assert len(alerts) == 1


# ================================================================== #
# config validation
# ================================================================== #


def test_health_config_defaults_and_validation():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1},
                          world_size=1)
    hl = cfg.observability_config["health"]
    assert hl["enabled"] is False
    assert hl["ring_events"] == 256
    assert hl["stall_timeout_s"] == 0.0
    assert hl["on_stall"] == "warn"
    assert hl["detectors"]["nonfinite_streak"] == 3
    assert hl["detectors"]["spike_zscore"] == 6.0
    for bad in ({"on_stall": "panic"}, {"ring_events": 0},
                {"stall_timeout_s": -1},
                {"detectors": {"nonfinite_streak": 0}},
                {"detectors": {"spike_zscore": 0}}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                             "observability": {"health": bad}},
                            world_size=1)


def test_disabled_plane_is_inert(tmp_path):
    hp = HealthPlane({}, events_dir=str(tmp_path))
    assert not hp.enabled
    hp.heartbeat("train_batch")              # no watchdog: pure no-op
    with pytest.raises(ValueError):
        hp.heartbeat("nonsense")             # contract holds even off
    hp.observe_loss(float("nan"), 1)
    hp.observe_grad_norm(1e9, 1)
    assert hp.alerts_total == 0
    assert hp.dump("drain") is None
    hp.close()
    assert not list(tmp_path.iterdir())      # zero filesystem traffic


# ================================================================== #
# end-to-end: injected stall + NaN streak in a real CPU train loop
# ================================================================== #


def _train_engine(tmp_path, health):
    import jax
    import deepspeed_tpu as ds
    from tests.unit.simple_model import init_simple_params, simple_loss_fn
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 4,
            "steps_per_print": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "observability": {
                "enabled": True, "events_dir": str(tmp_path),
                "health": health},
        })
    return engine


def test_injected_stall_produces_postmortem(tmp_path):
    """The acceptance scenario: health.stall wedges a train step past
    its heartbeat; the watchdog (warn mode) trips mid-stall, dumps the
    black box, and emits a stall_detected row naming the pinned phase
    — and obs_report --health renders the whole postmortem."""
    from tests.unit.simple_model import random_batches
    engine = _train_engine(tmp_path, {
        "enabled": True, "stall_timeout_s": 0.25, "on_stall": "warn"})
    assert engine.health.enabled
    b0, b1 = random_batches(2, 4, 8)
    engine.train_batch(iter([b0]))           # healthy step feeds the ring
    fault.arm("health.stall", times=1,
              callback=lambda **ctx: time.sleep(1.0))
    engine.train_batch(iter([b1]))           # wedged past the beat
    assert engine.health.watchdog.trips >= 1

    rows = _events(tmp_path / "events.jsonl")
    stalls = [r for r in rows if r.get("event") == "stall_detected"]
    assert stalls, "no stall_detected row in events.jsonl"
    st = stalls[0]
    assert st["phase"] == "train_batch"      # the pinned phase name
    assert st["silent_s"] >= 0.25
    assert st["component"] == "train"

    # the black box: atomic flight.json with the pre-stall ring and
    # every thread's stack
    flight = st["flight"]
    assert flight and os.path.exists(flight)
    payload = json.load(open(flight))
    assert payload["trigger"] == "watchdog"
    assert payload["stall"]["phase"] == "train_batch"
    assert payload["rows"], "pre-stall telemetry missing from the ring"
    assert any("train_loss" in str(r.get("tag", ""))
               for r in payload["rows"])
    assert any("MainThread" in k for k in payload["stacks"])
    # the wedged main thread's stack shows WHERE it was stuck
    main_stack = "".join(v for k, s in payload["stacks"].items()
                         if "MainThread" in k for v in s)
    assert "time.sleep" in main_stack or "sleep" in main_stack

    # obs_report renders the postmortem from the same log
    obs_report = _load_obs_report()
    s = obs_report.summarize(str(tmp_path))
    assert s["health"]["stalls"] >= 1
    assert s["health"]["last_stall"]["phase"] == "train_batch"
    text = obs_report.render_health(s)
    assert "train_batch" in text and "flight" in text
    # the one-line pointer in the DEFAULT report too
    assert "--health" in obs_report.render(s)
    engine.close()


def test_injected_nan_streak_produces_health_row(tmp_path):
    """health.nan_loss poisons the TELEMETRY loss (values the engine
    already materialized host-side) for 5 steps: the streak detector
    fires one pinned-reason row plus the Health/alerts scalar."""
    from tests.unit.simple_model import random_batches
    engine = _train_engine(tmp_path, {"enabled": True})
    fault.arm("health.nan_loss", exc=fault.InjectedCrash("poison"),
              times=5)
    for b in random_batches(6, 4, 8):
        engine.train_batch(iter([b]))
    assert engine.health.alerts_total >= 1

    rows = _events(tmp_path / "events.jsonl")
    alerts = [r for r in rows if r.get("event") == "health"]
    assert len(alerts) == 1                  # once per episode
    assert alerts[0]["reason"] == "nan_loss"
    assert alerts[0]["component"] == "train"
    assert alerts[0]["streak"] == 3
    scalar = [r for r in rows if r.get("tag") == "Health/alerts"]
    assert scalar and scalar[-1]["value"] == 1.0

    obs_report = _load_obs_report()
    s = obs_report.summarize(str(tmp_path))
    assert s["health"]["alerts"] == 1
    assert s["health"]["by_reason"] == {"nan_loss": 1}
    assert "nan_loss" in obs_report.render_health(s)
    engine.close()


def test_preemption_drain_dumps_flight(tmp_path):
    """HealthPlane.dump on an explicit trigger: the flight_dump event
    row and the black box land together."""
    from tests.unit.simple_model import random_batches
    engine = _train_engine(tmp_path, {"enabled": True})
    engine.train_batch(iter([random_batches(1, 4, 8)[0]]))
    path = engine.health.dump("drain", reason="preempt-sim", step=1)
    assert path and os.path.exists(path)
    payload = json.load(open(path))
    assert payload["trigger"] == "drain"
    assert payload["reason"] == "preempt-sim"
    rows = _events(tmp_path / "events.jsonl")
    dumps = [r for r in rows if r.get("event") == "flight_dump"]
    assert dumps and dumps[0]["trigger"] == "drain"
    engine.close()


# ================================================================== #
# zero perturbation: the fully enabled plane changes NOTHING
# ================================================================== #


def test_health_plane_zero_perturbation(tmp_path):
    """Bitwise contract: health fully on (ring tap + armed watchdog +
    all detectors) vs off — identical per-step losses, identical final
    params, identical recompile counts. The plane reads what the engine
    already materialized; it must never add a device sync or change
    dispatch order."""
    import jax
    from tests.unit.simple_model import random_batches
    batches = random_batches(3, 4, 8)

    def run(health, sub):
        engine = _train_engine(tmp_path / sub, health)
        losses = [float(engine.train_batch(iter([b]))) for b in batches]
        params = jax.tree_util.tree_map(np.asarray, engine.state.params)
        recompiles = engine.observability.compile_tracker.total_compiles
        engine.close()
        return losses, params, recompiles

    l_off, p_off, rc_off = run({"enabled": False}, "off")
    l_on, p_on, rc_on = run(
        {"enabled": True, "stall_timeout_s": 60.0, "on_stall": "warn",
         "detectors": {"enabled": True}}, "on")
    assert l_on == l_off                     # bitwise, not approx
    flat_off, _ = jax.tree_util.tree_flatten(p_off)
    flat_on, _ = jax.tree_util.tree_flatten(p_on)
    for a, b in zip(flat_off, flat_on):
        np.testing.assert_array_equal(a, b)
    assert rc_on == rc_off
    # and the healthy run raised zero alerts
    events = _events(tmp_path / "on" / "events.jsonl")
    assert [r for r in events if r.get("event") == "health"] == []
    assert [r for r in events if r.get("event") == "stall_detected"] == []


# ================================================================== #
# cross-run regression diff (--diff RUN_A RUN_B)
# ================================================================== #


def _diff_log(tmp_path, name, step_ms, sps, recompiles=1, stalls=0):
    d = tmp_path / name
    d.mkdir()
    rows = []
    for i, ms in enumerate(step_ms):
        step = (i + 1) * 32
        rows.append({"tag": "Train/Samples/step_time_ms", "value": ms,
                     "step": step})
        rows.append({"tag": "Train/Samples/samples_per_sec",
                     "value": sps, "step": step})
        rows.append({"tag": "Observability/recompiles",
                     "value": float(recompiles), "step": step})
    for i in range(stalls):
        rows.append({"event": "stall_detected", "phase": "train_batch",
                     "silent_s": 1.0, "timeout_s": 0.5,
                     "component": "train", "flight": None})
    with open(d / "events.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return str(d)


def test_diff_flags_regression_and_improvement(tmp_path):
    obs_report = _load_obs_report()
    a = _diff_log(tmp_path, "a", [100.0] * 8, 320.0)
    b = _diff_log(tmp_path, "b", [150.0] * 8, 210.0, recompiles=5,
                  stalls=1)
    d = obs_report.diff_runs(a, b)
    assert d["verdict"] == "REGRESSED"
    by = {m["metric"]: m for m in d["metrics"]}
    assert by["step_time_ms_p50"]["verdict"] == "REGRESSED"
    assert by["step_time_ms_p50"]["rel_change"] == pytest.approx(0.5)
    assert by["samples_per_sec_best"]["verdict"] == "REGRESSED"
    assert by["recompiles"]["verdict"] == "REGRESSED"
    assert by["stalls"]["verdict"] == "REGRESSED"
    assert set(d["regressed"]) >= {"step_time_ms_p50",
                                   "samples_per_sec_best",
                                   "recompiles", "stalls"}
    # absent-on-both metrics are N/A, never REGRESSED
    assert by["goodput_tokens_per_s"]["verdict"] == "N/A"
    # the reverse direction reads as IMPROVED
    rev = obs_report.diff_runs(b, a)
    assert rev["verdict"] == "OK"
    by_rev = {m["metric"]: m for m in rev["metrics"]}
    assert by_rev["step_time_ms_p50"]["verdict"] == "IMPROVED"
    # small noise inside the threshold: OK both ways
    c = _diff_log(tmp_path, "c", [104.0] * 8, 315.0)
    assert obs_report.diff_runs(a, c)["verdict"] == "OK"
    text = obs_report.render_diff(d)
    assert "verdict: REGRESSED" in text
    assert "step_time_ms_p50" in text


def test_diff_cli_exit_codes(tmp_path):
    """The regression gate: exit 1 naming the regressed metric, exit 0
    on identical runs, exit 2 on a missing log — scriptable in CI."""
    a = _diff_log(tmp_path, "a", [100.0] * 8, 320.0)
    b = _diff_log(tmp_path, "b", [150.0] * 8, 210.0)
    script = os.path.join(REPO, "tools", "obs_report.py")
    r = subprocess.run([sys.executable, script, "--diff", a, b],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, (r.stdout, r.stderr)
    assert "REGRESSED" in r.stdout and "step_time_ms_p50" in r.stdout
    # identical runs: clean exit 0
    r0 = subprocess.run([sys.executable, script, "--diff", a, a],
                        capture_output=True, text=True, timeout=60)
    assert r0.returncode == 0 and "verdict: OK" in r0.stdout
    # JSON mode round-trips the same verdict
    rj = subprocess.run([sys.executable, script, "--diff", a, b,
                         "--json"],
                        capture_output=True, text=True, timeout=60)
    assert rj.returncode == 1
    dj = json.loads(rj.stdout)
    assert dj["verdict"] == "REGRESSED" and dj["schema"] == 3
    # missing log: explicit error, exit 2
    r2 = subprocess.run(
        [sys.executable, script, "--diff", a, str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 2 and "error" in r2.stderr


def test_health_cli_smoke(tmp_path):
    a = _diff_log(tmp_path, "a", [100.0] * 4, 320.0, stalls=1)
    script = os.path.join(REPO, "tools", "obs_report.py")
    r = subprocess.run([sys.executable, script, a, "--health"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "health report:" in r.stdout
    assert "train_batch" in r.stdout
    # clean log renders the explicit no-events line, not an empty report
    c = _diff_log(tmp_path, "c", [100.0] * 4, 320.0)
    rc = subprocess.run([sys.executable, script, c, "--health"],
                        capture_output=True, text=True, timeout=60)
    assert "no health events" in rc.stdout


# ================================================================== #
# registry sync + schema
# ================================================================== #


def test_health_tag_registry_in_sync():
    """One tag, three homes: monitor (canonical), profiling registry
    (re-export), obs_report (mirrored string)."""
    from deepspeed_tpu import profiling as prof
    from deepspeed_tpu.utils import monitor as m
    obs_report = _load_obs_report()
    assert m.TAG_HEALTH_ALERTS == prof.TAG_HEALTH_ALERTS == \
        obs_report.T_HEALTH_ALERTS == "Health/alerts"


def test_obs_report_schema_v3_keeps_v2_keys(tmp_path):
    """Schema bump is ADDITIVE: every schema-2 consumer key survives
    unchanged next to the new health section."""
    obs_report = _load_obs_report()
    assert obs_report.SCHEMA_VERSION == 3
    a = _diff_log(tmp_path, "a", [100.0] * 4, 320.0)
    s = obs_report.summarize(a)
    assert s["schema"] == 3
    for key in ("steps", "step_time_ms", "samples_per_sec", "mfu",
                "flops_per_step", "comm", "recompiles", "memory",
                "checkpoints", "elastic", "loss", "host_overhead",
                "serving", "health"):
        assert key in s, key
    assert s["health"]["alerts"] == 0 and s["health"]["stalls"] == 0
