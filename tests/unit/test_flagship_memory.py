"""Flagship-shaped compile + memory proof (VERDICT r3 #5).

Compiles (never runs) the REAL GPT-2 1.5B 3D step — the
examples/megatron_gpt2/ds_config_3d.json workload: pipe=2 x data=2 x
model=2, bf16 compute, interleaved virtual stages — on the virtual
8-device CPU mesh via ABSTRACT avals (no 6 GB param materialization),
and asserts the compiler's own per-device memory analysis fits v5p HBM
(the test_zero3.py technique at full scale). Reference workload:
BASELINE.md ladder (GPT-2 1.5B pipeline 3D-parallel).

Also records the V=2 vs V=4 interleave trade the docs commit to
(docs/pipeline.md): at pipe=2 the normalized bubble is V-invariant
(bubble = S + (S-2)/V ticks), so V buys ONLY memory — the V=4
recompute window is half the V=2 one — at the price of 2x the
collective-permute traffic. v5p (95 GB HBM) therefore runs the
flagship at V=2; V=4 is the HBM-bound fallback (it is what fits
comfortably on a 16 GB v5e).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import GPT2Config, gpt2_pipeline_spec
from deepspeed_tpu.runtime.pipe.spmd import (build_pipeline_grad_fn,
                                             microbatch_sharding,
                                             pipeline_param_specs,
                                             pipeline_tick_counts)

pytestmark = pytest.mark.slow      # ~30 s compile per interleave factor

V5P_HBM = 95 * 2**30               # bytes per v5p chip
HEADROOM = 0.85                    # leave 15% for runtime/fragmentation

# GPT-2 1.5B: 48 layers x hidden 1600 (20 heads, d=80 — a tuned block
# table shape), 50304-aligned vocab, seq 1024 — 1.56e9 params
CFG = GPT2Config(vocab_size=50304, max_position_embeddings=1024,
                 hidden_size=1600, num_layers=48, num_heads=20,
                 embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
S, M, SEQ, MB = 2, 4, 1024, 4      # ds_config_3d: micro 2/gpu x data 2


def _flagship_memory(V):
    mesh = ds.build_mesh({"pipe": S, "data": 2, "model": 2})
    spec = gpt2_pipeline_spec(CFG, num_stages=S * V, dtype=jnp.bfloat16)
    ap = jax.eval_shape(spec.init, jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(s.shape))
                   for s in jax.tree_util.tree_leaves(ap))
    pspecs = pipeline_param_specs(spec, ap)
    aparams = jax.tree_util.tree_map(
        lambda s, sp: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=NamedSharding(mesh, sp)),
        ap, pspecs)
    gf = build_pipeline_grad_fn(spec, mesh, num_micro=M, num_virtual=V)
    batch = {"input_ids": jax.ShapeDtypeStruct(
        (M, MB, SEQ + 1), jnp.int32, sharding=microbatch_sharding(mesh))}
    ma = (jax.jit(gf)
          .lower(aparams, batch, jax.random.PRNGKey(1), 1.0)
          .compile().memory_analysis())
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("backend provides no memory analysis")
    return n_params, {
        "args": ma.argument_size_in_bytes,
        "out": ma.output_size_in_bytes,
        "temp": ma.temp_size_in_bytes,
    }


def test_flagship_1p5b_fits_v5p_hbm():
    sizes = {}
    for V in (2, 4):
        n_params, m = _flagship_memory(V)
        assert n_params >= 1.4e9, n_params       # actually flagship-sized
        # per-device grad step footprint (outputs counted alias-less,
        # worst case) + the engine's ZeRO-1 state the grad fn does not
        # see: fp32 master + Adam m/v, sharded pipe x model x data = /8
        state = 3 * n_params * 4 // 8
        total = m["args"] + m["out"] + m["temp"] + state
        sizes[V] = (m, total)
        assert total <= HEADROOM * V5P_HBM, (V, total / 2**30, m)
    # the documented interleave trade: V=4 halves the recompute window
    assert sizes[4][0]["temp"] < sizes[2][0]["temp"], sizes
    # and the V=4 fallback really is v5e-feasible (16 GB HBM), as
    # docs/pipeline.md claims
    assert sizes[4][1] <= HEADROOM * 16 * 2**30, sizes[4]
    # at pipe=2 the normalized bubble is V-invariant: V buys memory only
    t2, n2 = pipeline_tick_counts(S, M, 2)
    t4, n4 = pipeline_tick_counts(S, M, 4)
    assert n2 == n4
    assert t4 == 2 * t2                          # 2x permute traffic
