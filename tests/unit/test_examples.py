"""Examples smoke tests: every workload in examples/ must run end-to-end on
the CPU mesh (the reference's tests/model harnesses launched workloads via
the CLI; these run them in-process for speed)."""

import os
import runpy
import sys

import pytest

pytestmark = pytest.mark.slow  # CLI e2e compiles (VERDICT r2 #8 tiering)

_ROOT = os.path.normpath(os.path.join(os.path.dirname(__file__), "..", ".."))


def _run(script, *args):
    argv = [os.path.join(_ROOT, script), *args]
    old = sys.argv
    sys.argv = argv
    try:
        runpy.run_path(argv[0], run_name="__main__")
    finally:
        sys.argv = old


def test_cifar_example(capsys):
    _run("examples/cifar/train.py", "--steps", "6")
    assert "done" in capsys.readouterr().out


def test_bing_bert_example(capsys):
    _run("examples/bing_bert/train.py", "--model", "tiny",
         "--steps", "2", "--seq", "64")
    assert "done" in capsys.readouterr().out


def test_megatron_gpt2_zero2_example(capsys):
    _run("examples/megatron_gpt2/train.py", "--mode", "zero2",
         "--tiny", "--steps", "2", "--seq", "64")
    out = capsys.readouterr().out
    assert "done" in out and "lm loss" in out


def test_megatron_gpt2_3d_example(capsys):
    _run("examples/megatron_gpt2/train.py", "--mode", "3d",
         "--tiny", "--steps", "2", "--seq", "32")
    assert "done" in capsys.readouterr().out


def test_onebit_adam_example(capsys):
    _run("examples/onebit_adam/train.py", "--steps", "10", "--seq", "32")
    out = capsys.readouterr().out
    assert "done" in out and "[compressed]" in out and "[warmup]" in out


def test_megatron_gpt2_moe_example(capsys):
    _run("examples/megatron_gpt2/train.py", "--mode", "moe",
         "--tiny", "--steps", "2", "--seq", "32")
    out = capsys.readouterr().out
    assert "done" in out and "(MoE)" in out


def test_megatron_gpt2_offload_example(capsys):
    _run("examples/megatron_gpt2/train.py", "--mode", "offload",
         "--tiny", "--steps", "2", "--seq", "32")
    out = capsys.readouterr().out
    assert "done" in out and "lm loss" in out


def test_megatron_gpt2_sp_example(capsys):
    _run("examples/megatron_gpt2/train.py", "--mode", "sp",
         "--tiny", "--steps", "2", "--seq", "64")
    out = capsys.readouterr().out
    assert "done" in out and "lm loss" in out


def test_bing_bert_sp_example(capsys):
    import json as _json
    import tempfile
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "steps_per_print": 1,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "mesh": {"axes": {"seq": 4, "data": 2}},
    }
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        _json.dump(cfg, f)
    _run("examples/bing_bert/train.py", "--model", "tiny", "--mode", "sp",
         "--steps", "2", "--seq", "64", "--deepspeed_config", f.name)
    assert "done" in capsys.readouterr().out


def test_bing_bert_sparse_example(capsys):
    """JSON-config-driven block-sparse attention (the reference's
    bing_bert + sparse_attention deployment path)."""
    _run("examples/bing_bert/train.py", "--model", "tiny", "--mode",
         "sparse", "--steps", "2", "--seq", "64", "--deepspeed_config",
         os.path.join(_ROOT, "examples/bing_bert/ds_config_sparse.json"))
    assert "done" in capsys.readouterr().out


def test_llama_tp_example(capsys):
    _run("examples/llama/train.py", "--mode", "tp", "--tiny",
         "--scan-layers", "--steps", "4", "--generate", "4")
    out = capsys.readouterr().out
    assert "final loss" in out and "generated:" in out
