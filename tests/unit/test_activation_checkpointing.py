"""Activation checkpointing tests (reference has no dedicated unit file —
the subsystem is exercised via Megatron model tests; here we test directly:
gradient equivalence under remat, config plumbing, RNG tracker semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ck


@pytest.fixture(autouse=True)
def _reset():
    ck.reset()
    yield
    ck.reset()


def _mlp(params, x):
    h = jnp.tanh(x @ params["w1"])
    return jnp.sum((h @ params["w2"]) ** 2)


def _params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {"w1": jax.random.normal(k1, (16, 32)),
            "w2": jax.random.normal(k2, (32, 8))}


def test_checkpoint_matches_plain_grads():
    params = _params()
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))

    def loss_plain(p):
        return _mlp(p, x)

    def loss_ckpt(p):
        return ck.checkpoint(_mlp, p, x)

    g_plain = jax.grad(loss_plain)(params)
    g_ckpt = jax.grad(jax.jit(loss_ckpt))(params)
    for k in params:
        np.testing.assert_allclose(g_plain[k], g_ckpt[k], rtol=1e-3, atol=1e-4)


def test_checkpoint_function_apply_shim():
    params = _params()
    x = jnp.ones((2, 16))
    out = ck.CheckpointFunction.apply(_mlp, params, x)
    assert jnp.isfinite(out)


def test_configure_from_dict_and_overrides():
    cfg = {
        "train_batch_size": 1,
        "activation_checkpointing": {
            "partition_activations": True,
            "cpu_checkpointing": True,
            "number_checkpoints": 4,
            "profile": True,
        },
    }
    ck.configure(None, deepspeed_config=cfg)
    assert ck.is_configured()
    assert ck.PARTITION_ACTIVATIONS and ck.PA_TO_CPU
    assert ck.num_layers == 4 and ck.PROFILE_TIME
    # explicit kwarg overrides config (reference configure docstring)
    ck.configure(None, deepspeed_config=cfg, partition_activations=False)
    assert not ck.PARTITION_ACTIVATIONS


def test_contiguous_requires_partition():
    with pytest.raises(AssertionError):
        ck.configure(None, contiguous_checkpointing=True,
                     partition_activations=False)


def test_partition_activations_grads_unchanged():
    """partition_activations only changes placement of the stash; grads must
    be identical. Run under a mesh so the model axis exists."""
    from deepspeed_tpu.parallel.mesh import build_mesh
    mesh = build_mesh({"data": 2, "model": 4})
    params = _params()
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    g_plain = jax.grad(lambda p: _mlp(p, x))(params)
    ck.configure(None, partition_activations=True)
    with mesh:
        g = jax.jit(jax.grad(lambda p: ck.checkpoint(_mlp, p, x)))(params)
    for k in params:
        np.testing.assert_allclose(g_plain[k], np.asarray(g[k]), rtol=1e-3, atol=1e-4)


def test_cpu_checkpointing_grads_unchanged():
    params = _params()
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    g_plain = jax.grad(lambda p: _mlp(p, x))(params)
    ck.configure(None, checkpoint_in_cpu=True, partition_activations=True)
    g = jax.jit(jax.grad(lambda p: ck.checkpoint(_mlp, p, x)))(params)
    for k in params:
        np.testing.assert_allclose(g_plain[k], np.asarray(g[k]), rtol=1e-3, atol=1e-4)


def test_rng_tracker_fork_streams():
    ck.model_parallel_seed(1234)
    tr = ck.get_rng_tracker()
    with tr.fork() as k1:
        a = jax.random.normal(k1, (4,))
    with tr.fork() as k2:
        b = jax.random.normal(k2, (4,))
    # stream advances: successive forks give different keys
    assert not np.allclose(a, b)
    # model-parallel stream differs per MP rank
    ck.model_parallel_seed(1234, model_parallel_rank=1)
    with ck.get_rng_tracker().fork() as k3:
        c = jax.random.normal(k3, (4,))
    assert not np.allclose(a, c)
    # data-parallel stream is rank-independent
    ck.model_parallel_seed(1234, model_parallel_rank=0)
    d0 = jax.random.normal(ck.get_rng_tracker().key("data-parallel-rng"), (4,))
    ck.model_parallel_seed(1234, model_parallel_rank=3)
    d1 = jax.random.normal(ck.get_rng_tracker().key("data-parallel-rng"), (4,))
    np.testing.assert_allclose(d0, d1)


def test_rng_tracker_duplicate_add_raises():
    tr = ck.RNGStatesTracker()
    tr.add("s", 0)
    with pytest.raises(Exception):
        tr.add("s", 1)
    with pytest.raises(Exception):
        tr.key("missing")


def test_exported_as_deepspeed_checkpointing():
    assert deepspeed_tpu.checkpointing is ck
