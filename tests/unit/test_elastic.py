"""Elastic-resilience tests (ISSUE 10): async snapshot checkpointing,
graceful preemption drain, the launcher supervisor, and — the pinned
tentpole contract — kill-the-save-at-every-commit-stage on a dp=2 CPU
mesh, then resume on dp=1 AND dp=4 meshes with loss/params matching the
uninterrupted run.

The contract is pinned in two exact halves:

- the RESTORE point: params loaded after a torn save are BITWISE equal
  to the reference run's params at the newest committed step, on every
  resume mesh (resharding is pure data movement);
- the CONTINUATION: training on from the torn-save resume is bitwise
  identical to training on from an uninterrupted checkpoint of the same
  step on the same mesh (same restored bytes + same program + same data
  -> f32-ulp/bitwise equality, with no cross-mesh reduction-order
  excuse available).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.launcher import runner
from deepspeed_tpu.runtime import checkpoint as ckpt
from deepspeed_tpu.runtime import elastic, fault
from deepspeed_tpu.utils import health
from tests.unit.simple_model import (
    base_config, init_simple_params, random_batches, simple_loss_fn)

pytestmark = pytest.mark.faulty

HIDDEN = 16
SEED_A, SEED_B, SEED_C = 2, 3, 5     # steps 1-2 / 3-4 / continuation


@pytest.fixture(autouse=True)
def _reset_injector():
    fault.reset()
    yield
    fault.reset()


def make_engine(config=None, seed=0):
    params = init_simple_params(jax.random.PRNGKey(seed), HIDDEN)
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_parameters=params,
        config=config or base_config())
    return engine


def dp_config(dp, **overrides):
    """Same GLOBAL batch (8) on any mesh, so dp=1/2/4 runs consume an
    identical data stream and the math is mesh-shape-independent."""
    cfg = {
        "train_batch_size": 8,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10**9,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "mesh": {"axes": {"data": dp}},
    }
    cfg.update(overrides)
    return cfg


def run_steps(engine, n, seed):
    batches = iter(random_batches(n, 8, HIDDEN, seed=seed))
    return [float(engine.train_batch(batches)) for _ in range(n)]


def host_params(engine):
    from deepspeed_tpu.runtime.checkpoint import _to_host_global
    return [np.asarray(_to_host_global(x))
            for x in jax.tree_util.tree_leaves(engine.state.params)]


# ===================================================================== #
# tentpole: the pinned elastic contract
# ===================================================================== #

@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """The uninterrupted dp=2 run: clean committed checkpoints at steps
    2 and 4, host copies of the params at both."""
    d = str(tmp_path_factory.mktemp("elastic_ref"))
    e = make_engine(dp_config(2), seed=1)
    run_steps(e, 2, SEED_A)
    e.save_checkpoint(d)
    p2 = host_params(e)
    run_steps(e, 2, SEED_B)
    e.save_checkpoint(d)
    p4 = host_params(e)
    e.close()
    return {"dir": d, "params": {2: p2, 4: p4}}


@pytest.fixture(scope="module")
def clean_resume(reference):
    """Lazy cache of uninterrupted-resume trajectories: fresh dp=N
    engine loads the CLEAN checkpoint of `step` and trains 2 more steps
    — the ground truth every torn-save resume must match bitwise."""
    cache = {}

    def get(dp, step):
        if (dp, step) not in cache:
            e = make_engine(dp_config(dp), seed=7)
            path, _ = e.load_checkpoint(reference["dir"],
                                        tag=f"global_step{step}")
            assert path is not None
            losses = run_steps(e, 2, SEED_C)
            cache[(dp, step)] = {"losses": losses,
                                 "params": host_params(e)}
            e.close()
        return cache[(dp, step)]

    return get


# (fault point, arm kwargs, step the fallback must resume at). Every
# stage of the commit protocol dies once; only latest_tmp_written leaves
# step 4 committed (the save "finished", the pointer didn't).
CONTRACT_STAGES = [
    ("ckpt.snapshot", {}, 2),
    ("ckpt.after_shard",
     {"filter": lambda **c: c.get("name") == "model_states"}, 2),
    ("ckpt.before_marker", {}, 2),
    ("ckpt.before_rename", {}, 2),
    ("ckpt.latest_tmp_written", {}, 4),
]


@pytest.mark.parametrize("point,arm_kw,resume_step", CONTRACT_STAGES,
                         ids=[s[0] for s in CONTRACT_STAGES])
def test_kill_at_stage_resumes_on_any_mesh(tmp_path, reference,
                                           clean_resume, point, arm_kw,
                                           resume_step):
    # the to-be-killed dp=2 run retraces the reference data trajectory
    e = make_engine(dp_config(2), seed=1)
    run_steps(e, 2, SEED_A)
    e.save_checkpoint(str(tmp_path))          # committed baseline
    run_steps(e, 2, SEED_B)
    fault.arm(point, exc=fault.InjectedCrash(point), **arm_kw)
    with pytest.raises(fault.InjectedCrash):
        e.save_checkpoint(str(tmp_path))
    fault.reset()
    e.close()

    for dp in (1, 4):
        r = make_engine(dp_config(dp), seed=9)
        path, _ = r.load_checkpoint(str(tmp_path))
        assert path is not None, \
            f"{point}: fallback found nothing on dp={dp}"
        assert r.global_steps == resume_step, \
            f"{point}: resumed step {r.global_steps} != {resume_step}"
        # restore point: bitwise equal to the uninterrupted run's
        # params at that step, regardless of the resume mesh
        for a, b in zip(host_params(r),
                        reference["params"][resume_step]):
            np.testing.assert_array_equal(a, b)
        # continuation: bitwise identical to resuming an uninterrupted
        # checkpoint of the same step on the same mesh
        losses = run_steps(r, 2, SEED_C)
        want = clean_resume(dp, resume_step)
        np.testing.assert_allclose(losses, want["losses"],
                                   rtol=0, atol=0)
        for a, b in zip(host_params(r), want["params"]):
            np.testing.assert_array_equal(a, b)
        r.close()


def test_snapshot_kill_leaves_no_staging(tmp_path):
    """A save killed at the snapshot stage dies before ANY filesystem
    effect — not even a staging dir."""
    e = make_engine(seed=1)
    run_steps_simple(e, 1)
    fault.arm("ckpt.snapshot", exc=fault.InjectedCrash("snapshot"))
    with pytest.raises(fault.InjectedCrash):
        e.save_checkpoint(str(tmp_path))
    fault.reset()
    assert os.listdir(str(tmp_path)) == []
    e.close()


def run_steps_simple(engine, n, seed=0):
    batches = iter(random_batches(
        n * engine.gradient_accumulation_steps, 16, HIDDEN, seed=seed))
    return [float(engine.train_batch(batches)) for _ in range(n)]


# ===================================================================== #
# async snapshot checkpointing
# ===================================================================== #

class TestAsyncSave:
    def test_roundtrip_and_commit(self, tmp_path):
        e = make_engine(seed=1)
        run_steps_simple(e, 3, seed=2)
        want = host_params(e)
        d = e.save_checkpoint(str(tmp_path), async_=True)
        e.wait_pending_saves()
        ok, problems = ckpt.verify_checkpoint_dir(d)
        assert ok, problems
        assert ckpt.read_latest(str(tmp_path)) == "global_step3"
        e2 = make_engine(seed=9)
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert path == d and e2.global_steps == 3
        for a, b in zip(host_params(e2), want):
            np.testing.assert_array_equal(a, b)
        e.close()
        e2.close()

    def test_config_default_async(self, tmp_path):
        """checkpoint.async_save makes plain save_checkpoint async."""
        e = make_engine(base_config(checkpoint={"async_save": True}),
                        seed=1)
        run_steps_simple(e, 1)
        fault.arm("ckpt.writer_crash", times=None,
                  callback=lambda **k: time.sleep(0.05))
        e.save_checkpoint(str(tmp_path))
        assert e._ckpt_writer is not None and \
            e._ckpt_writer.pending_saves() >= 1
        e.wait_pending_saves()
        assert ckpt.is_committed(str(tmp_path / "global_step1"))
        e.close()

    def test_snapshot_is_donation_safe(self, tmp_path):
        """The step loop keeps training (donating its state buffers)
        while the writer commits — the checkpoint must hold the
        snapshot-time values, not torn/freed memory."""
        e = make_engine(base_config(gradient_accumulation_steps=2),
                        seed=1)
        run_steps_simple(e, 2, seed=2)
        want_step = e.global_steps
        want = host_params(e)
        # slow the writer so training overlaps the write
        fault.arm("ckpt.writer_crash", times=None,
                  callback=lambda **k: time.sleep(0.1))
        e.save_checkpoint(str(tmp_path), async_=True)
        run_steps_simple(e, 3, seed=4)     # donates state repeatedly
        e.wait_pending_saves()
        fault.reset()
        e2 = make_engine(seed=9)
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert e2.global_steps == want_step
        for a, b in zip(host_params(e2), want):
            np.testing.assert_array_equal(a, b)
        e.close()
        e2.close()

    def test_zero_extra_dispatches_and_syncs(self, tmp_path):
        """The dispatch-count pin: an async save adds no dispatches and
        no forced host syncs to the steady-state step loop."""
        import tempfile
        e = make_engine(base_config(
            gradient_accumulation_steps=4,
            observability={"enabled": True,
                           "events_dir": tempfile.mkdtemp(),
                           "flops_profiler": False,
                           "memory_watermarks": False}), seed=1)
        run_steps_simple(e, 1, seed=2)     # compile
        tracker = e.observability.compile_tracker
        d0 = tracker.total_dispatches
        s0 = e._host_sync_count
        run_steps_simple(e, 2, seed=3)
        assert e._host_sync_count == s0    # steady loop: sync-free
        e.save_checkpoint(str(tmp_path), async_=True)
        s1 = e._host_sync_count            # the save boundary itself may
        #                                    flush the telemetry ring
        run_steps_simple(e, 2, seed=4)
        assert tracker.total_dispatches - d0 == 4   # 1 per train_batch
        assert e._host_sync_count == s1    # post-save loop: still 0
        e.wait_pending_saves()
        assert ckpt.is_committed(str(tmp_path / "global_step3"))
        e.close()

    def test_collision_supersede_and_join(self, tmp_path):
        """A save submitted while one is writing joins (same tag) or
        supersedes (newer tag) the waiting one — never interleaves."""
        import threading
        e = make_engine(seed=1)
        run_steps_simple(e, 1)
        started = threading.Event()

        def slow_start(**_):
            started.set()
            time.sleep(0.2)

        fault.arm("ckpt.writer_crash", times=None, callback=slow_start)
        e.save_checkpoint(str(tmp_path), tag="s1", async_=True)  # runs
        assert started.wait(2)   # s1 is IN the writer before s2 lands
        e.save_checkpoint(str(tmp_path), tag="s2", async_=True)  # waits
        w = e._ckpt_writer
        assert w.submit("s2", lambda: None) == "joined"
        e.save_checkpoint(str(tmp_path), tag="s3", async_=True)  # wins
        assert w.superseded >= 1
        fault.reset()
        e.wait_pending_saves()
        assert ckpt.is_committed(str(tmp_path / "s1"))
        assert ckpt.is_committed(str(tmp_path / "s3"))
        assert not os.path.exists(str(tmp_path / "s2"))  # superseded
        e.close()

    def test_writer_error_surfaces_on_next_save(self, tmp_path):
        e = make_engine(seed=1)
        run_steps_simple(e, 1)
        fault.arm("ckpt.writer_crash",
                  exc=fault.InjectedCrash("writer died"))
        e.save_checkpoint(str(tmp_path), async_=True)
        e._drain_saves()
        with pytest.raises(RuntimeError, match="async checkpoint"):
            e.save_checkpoint(str(tmp_path))
        # error is popped once; the retried save goes through
        e.save_checkpoint(str(tmp_path))
        e.close()

    def test_writer_error_surfaces_on_close(self, tmp_path):
        e = make_engine(seed=1)
        run_steps_simple(e, 1)
        fault.arm("ckpt.writer_crash",
                  exc=fault.InjectedCrash("writer died"))
        e.save_checkpoint(str(tmp_path), async_=True)
        with pytest.raises(RuntimeError, match="async checkpoint"):
            e.close()

    def test_close_and_eval_drain(self, tmp_path):
        e = make_engine(seed=1)
        run_steps_simple(e, 1)
        fault.arm("ckpt.writer_crash", times=None,
                  callback=lambda **k: time.sleep(0.05))
        e.save_checkpoint(str(tmp_path), async_=True)
        batch = random_batches(1, 16, HIDDEN)[0]
        e.eval_batch(batch)                   # eval barrier drains
        assert e._ckpt_writer.pending_saves() == 0
        assert ckpt.is_committed(str(tmp_path / "global_step1"))
        fault.reset()
        e.save_checkpoint(str(tmp_path), async_=True)
        e.close()                             # close drains too
        assert ckpt.read_latest(str(tmp_path)) == "global_step1"

    def test_load_drains_pending_save(self, tmp_path):
        """save(async) -> load must see the committed save (ordering)."""
        e = make_engine(seed=1)
        run_steps_simple(e, 2, seed=2)
        fault.arm("ckpt.writer_crash", times=None,
                  callback=lambda **k: time.sleep(0.1))
        e.save_checkpoint(str(tmp_path), async_=True)
        path, _ = e.load_checkpoint(str(tmp_path))
        assert path is not None and path.endswith("global_step2")
        e.close()

    def test_writer_unit_semantics(self):
        """AsyncCheckpointWriter in isolation: queued/joined/superseded
        verdicts, drain, error pop-once."""
        w = ckpt.AsyncCheckpointWriter()
        import threading
        gate = threading.Event()
        done = []
        assert w.submit("a", lambda: (gate.wait(2), done.append("a"))) \
            == "queued"
        time.sleep(0.05)                      # let 'a' start
        assert w.submit("b", lambda: done.append("b")) == "queued"
        assert w.submit("b", lambda: done.append("b2")) == "joined"
        assert w.submit("c", lambda: done.append("c")) == "superseded"
        gate.set()
        assert w.drain(timeout=5)
        assert done == ["a", "c"]             # 'b' superseded, never ran
        assert w.superseded == 1

        def boom():
            raise ValueError("x")
        w.submit("d", boom)
        w.drain(timeout=5)
        with pytest.raises(RuntimeError, match="'d'"):
            w.raise_pending_error()
        w.raise_pending_error()               # popped: second call no-op
        w.close()
        with pytest.raises(RuntimeError):
            w.submit("e", lambda: None)


# ===================================================================== #
# graceful preemption drain
# ===================================================================== #

class TestPreemptionDrain:
    def _engine(self, tmp_path, **ckpt_over):
        cfg = base_config(checkpoint={"drain_on_preemption": True,
                                      "save_dir": str(tmp_path),
                                      **ckpt_over})
        return make_engine(cfg, seed=1)

    def test_sigterm_finishes_window_then_commits(self, tmp_path):
        """A real SIGTERM mid-window: the window completes, a
        preemption-tagged checkpoint commits, and Preempted (SystemExit
        with the resumable code) propagates."""
        e = self._engine(tmp_path)
        run_steps_simple(e, 1, seed=2)
        fault.arm("elastic.sigterm_mid_window",
                  callback=lambda **k: os.kill(os.getpid(),
                                               signal.SIGTERM))
        with pytest.raises(elastic.Preempted) as ei:
            run_steps_simple(e, 1, seed=3)
        assert ei.value.code == elastic.RESUMABLE_EXIT_CODE
        assert ei.value.reason == "SIGTERM"
        tag_dir = str(tmp_path / "preempt_step2")
        assert ckpt.is_committed(tag_dir)
        assert ckpt.is_preemption_tag(tag_dir)
        assert ckpt.read_latest(str(tmp_path)) == "preempt_step2"
        # the drain's close() uninstalled the signal handlers
        assert not e._elastic.installed
        # and a fresh run resumes from it
        e2 = make_engine(seed=9)
        path, _ = e2.load_checkpoint(str(tmp_path))
        assert path.endswith("preempt_step2") and e2.global_steps == 2
        e2.close()

    def test_software_trigger_drains(self, tmp_path):
        e = self._engine(tmp_path)
        run_steps_simple(e, 1, seed=2)
        e._elastic.trigger("pod-resize")
        with pytest.raises(elastic.Preempted) as ei:
            run_steps_simple(e, 1, seed=3)
        assert ei.value.reason == "pod-resize"
        assert ckpt.is_committed(str(tmp_path / "preempt_step2"))

    def test_drain_waits_for_pending_async_save(self, tmp_path):
        """A preemption with an async save in flight: the drain joins it
        before committing the preemption tag — never interleaves."""
        e = self._engine(tmp_path, async_save=True)
        run_steps_simple(e, 1, seed=2)
        fault.arm("ckpt.writer_crash", times=None,
                  callback=lambda **k: time.sleep(0.1))
        e.save_checkpoint(str(tmp_path))      # async per config
        e._elastic.trigger()
        with pytest.raises(elastic.Preempted):
            run_steps_simple(e, 1, seed=3)
        assert ckpt.is_committed(str(tmp_path / "global_step1"))
        assert ckpt.is_committed(str(tmp_path / "preempt_step2"))

    def test_no_save_dir_still_exits_resumable(self, tmp_path):
        cfg = base_config(checkpoint={"drain_on_preemption": True})
        e = make_engine(cfg, seed=1)
        run_steps_simple(e, 1, seed=2)
        e._elastic.trigger()
        with pytest.raises(elastic.Preempted) as ei:
            run_steps_simple(e, 1, seed=3)
        assert ei.value.tag is None
        assert ei.value.code == elastic.RESUMABLE_EXIT_CODE

    def test_offload_facade_step_drains(self, tmp_path):
        """Regression: the ZeRO-Offload facade forward/backward/step
        path returns early in step() — the boundary check must still
        run there, or an installed (flag-only) handler would swallow
        SIGTERM outright."""
        cfg = base_config(
            zero_optimization={"stage": 2, "cpu_offload": True},
            checkpoint={"drain_on_preemption": True,
                        "save_dir": str(tmp_path)})
        e = make_engine(cfg, seed=1)
        batches = random_batches(4, 16, HIDDEN, seed=2)
        e.forward(batches[0])
        e.backward()
        e.step()
        e._elastic.trigger("SIGTERM")
        e.forward(batches[1])
        e.backward()
        with pytest.raises(elastic.Preempted):
            e.step()
        assert ckpt.is_committed(str(tmp_path / "preempt_step2"))

    def test_preemption_event_row(self, tmp_path):
        import tempfile
        obs_dir = tempfile.mkdtemp()
        cfg = base_config(
            checkpoint={"drain_on_preemption": True,
                        "save_dir": str(tmp_path)},
            observability={"enabled": True, "events_dir": obs_dir,
                           "flops_profiler": False,
                           "memory_watermarks": False})
        e = make_engine(cfg, seed=1)
        run_steps_simple(e, 1, seed=2)
        e._elastic.trigger("SIGTERM")
        with pytest.raises(elastic.Preempted):
            run_steps_simple(e, 1, seed=3)
        rows = [json.loads(l) for l in
                open(os.path.join(obs_dir, "events.jsonl"))]
        pre = [r for r in rows if r.get("event") == "preemption"]
        assert len(pre) == 1
        assert pre[0]["tag"] == "preempt_step2"
        assert pre[0]["committed"] is True
        # snapshot/write telemetry rode along with the drain's save
        tags = {r.get("tag") for r in rows}
        assert "Checkpoint/snapshot_ms" in tags
        assert "Checkpoint/write_ms" in tags

    def test_resume_event_carries_restart_count(self, tmp_path,
                                                monkeypatch):
        import tempfile
        e = make_engine(seed=1)
        run_steps_simple(e, 2, seed=2)
        e.save_checkpoint(str(tmp_path))
        e.close()
        monkeypatch.setenv(elastic.RESTART_COUNT_ENV, "2")
        obs_dir = tempfile.mkdtemp()
        cfg = base_config(
            observability={"enabled": True, "events_dir": obs_dir,
                           "flops_profiler": False,
                           "memory_watermarks": False})
        e2 = make_engine(cfg, seed=9)
        assert e2._restart_count == 2
        e2.load_checkpoint(str(tmp_path))
        e2.close()
        rows = [json.loads(l) for l in
                open(os.path.join(obs_dir, "events.jsonl"))]
        res = [r for r in rows if r.get("event") == "resume"]
        assert len(res) == 1
        assert res[0]["restarts"] == 2
        assert res[0]["tag"] == "global_step2"
        assert any(r.get("tag") == "Checkpoint/restarts"
                   and r.get("value") == 2.0 for r in rows)


class TestPreemptionGuard:
    def test_trigger_and_clear(self):
        g = elastic.PreemptionGuard(signals=())
        assert not g.preempted
        g.trigger("x")
        assert g.preempted and g.reason == "x"
        g.trigger("y")                        # first reason wins
        assert g.reason == "x"
        g.clear()
        assert not g.preempted and g.reason is None

    def test_install_uninstall_restores_handlers(self):
        prev = signal.getsignal(signal.SIGTERM)
        g = elastic.PreemptionGuard(signals=(signal.SIGTERM,))
        assert g.install()
        assert signal.getsignal(signal.SIGTERM) == g._handler
        os.kill(os.getpid(), signal.SIGTERM)
        # deliver: a pure-python no-op forces the interpreter to run
        # pending signal handlers
        time.sleep(0.01)
        assert g.preempted and g.reason == "SIGTERM"
        g.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_request_preemption_flags_installed_guards(self):
        with elastic.PreemptionGuard(signals=()) as g:
            n = elastic.request_preemption("env")
            assert n >= 1 and g.preempted and g.reason == "env"
        assert elastic.request_preemption("late") == 0 or not g.installed

    def test_restart_count_parse(self):
        assert elastic.restart_count({}) == 0
        assert elastic.restart_count(
            {elastic.RESTART_COUNT_ENV: "3"}) == 3
        assert elastic.restart_count(
            {elastic.RESTART_COUNT_ENV: "junk"}) == 0
        assert elastic.restart_count(
            {elastic.RESTART_COUNT_ENV: "-2"}) == 0


# ===================================================================== #
# env-armed fault injection (DSTPU_FAULT_ARM)
# ===================================================================== #

class TestEnvArm:
    def test_crash_action(self):
        armed = fault.arm_from_env({fault.ENV_ARM: "x.point:crash"})
        assert armed == ["x.point"]
        with pytest.raises(fault.InjectedCrash):
            fault.fire("x.point")
        fault.fire("x.point")                 # times=1: spent

    def test_times_and_multiple_specs(self):
        armed = fault.arm_from_env(
            {fault.ENV_ARM: "a:oserror:2, b:crash"})
        assert armed == ["a", "b"]
        with pytest.raises(OSError):
            fault.fire("a")
        with pytest.raises(OSError):
            fault.fire("a")
        fault.fire("a")                       # spent after 2
        with pytest.raises(fault.InjectedCrash):
            fault.fire("b")

    def test_once_file_consumed_across_incarnations(self, tmp_path):
        once = tmp_path / "armed"
        once.write_text("1")
        spec = {fault.ENV_ARM: f"p:crash@{once}"}
        assert fault.arm_from_env(spec) == ["p"]
        with pytest.raises(fault.InjectedCrash):
            fault.fire("p")
        assert not once.exists()              # consumed on first fire
        fault.reset()
        # the "relaunched process" arms from the same env: no-op now
        assert fault.arm_from_env(spec) == []
        fault.fire("p")

    def test_unset_and_malformed(self):
        assert fault.arm_from_env({}) == []
        with pytest.raises(ValueError):
            fault.arm_from_env({fault.ENV_ARM: "justapoint"})
        with pytest.raises(ValueError):
            fault.arm_from_env({fault.ENV_ARM: "p:frobnicate"})

    def test_engine_path_arms_once_per_process(self, monkeypatch):
        """Regression: a second engine's init must not re-arm (and
        reset the fired counter of) a `times:1` spec — env arming is
        per process, not per engine."""
        monkeypatch.setattr(fault, "_ENV_ARMED", False)
        monkeypatch.setenv(fault.ENV_ARM, "q.point:crash")
        assert fault.arm_from_env() == ["q.point"]
        with pytest.raises(fault.InjectedCrash):
            fault.fire("q.point")
        assert fault.arm_from_env() == []     # second engine init
        fault.fire("q.point")                 # still spent


# ===================================================================== #
# launcher supervisor
# ===================================================================== #

class TestSupervisor:
    def test_relaunches_on_resumable_exit_with_backoff(self):
        codes = iter([elastic.RESUMABLE_EXIT_CODE,
                      elastic.RESUMABLE_EXIT_CODE, 0])
        seen, sleeps = [], []
        rc = runner.supervise(
            lambda r: (seen.append(r), next(codes))[1],
            max_restarts=3, backoff=1.0, sleep=sleeps.append)
        assert rc == 0
        assert seen == [0, 1, 2]              # restart count exported
        assert sleeps == [1.0, 2.0]           # exponential backoff

    def test_gives_up_on_genuine_failure(self):
        codes = iter([elastic.RESUMABLE_EXIT_CODE, 17])
        rc = runner.supervise(lambda r: next(codes), max_restarts=5,
                              backoff=0.0, sleep=lambda s: None)
        assert rc == 17

    def test_gives_up_after_max_restarts(self):
        calls = []
        rc = runner.supervise(
            lambda r: (calls.append(r),
                       elastic.RESUMABLE_EXIT_CODE)[1],
            max_restarts=2, backoff=0.0, sleep=lambda s: None)
        assert rc == elastic.RESUMABLE_EXIT_CODE
        assert calls == [0, 1, 2]             # initial + 2 restarts

    def test_zero_exit_passes_through(self):
        assert runner.supervise(lambda r: 0, max_restarts=3,
                                backoff=0.0) == 0

    def test_restart_decision_matrix(self):
        """The restart taxonomy is API: the preemption drain (85) and
        the hang watchdog's distinguished kill (87) are the ONLY exit
        codes worth another life — both certify a committed checkpoint
        chain. Everything else is a genuine failure."""
        assert runner.RESTARTABLE_EXIT_CODES == (85, 87)
        assert runner.RESTARTABLE_EXIT_CODES == (
            elastic.RESUMABLE_EXIT_CODE, health.STALL_EXIT_CODE)
        for rc, eligible in [(85, True), (87, True), (143, False),
                             (1, False), (0, False), (None, False)]:
            assert runner.restart_eligible(rc) is eligible, rc

    def test_watchdog_kill_is_restartable_end_to_end(self):
        # 87 then clean exit: one relaunch, one backoff sleep
        codes = iter([health.STALL_EXIT_CODE, 0])
        sleeps = []
        rc = runner.supervise(lambda r: next(codes), max_restarts=3,
                              backoff=1.0, sleep=sleeps.append)
        assert rc == 0
        assert sleeps == [1.0]
        # 87 then SIGTERM-ish 143: relaunched once, then give up
        codes = iter([health.STALL_EXIT_CODE, 143])
        rc = runner.supervise(lambda r: next(codes), max_restarts=3,
                              backoff=0.0, sleep=lambda s: None)
        assert rc == 143
        # constant genuine failure: returned immediately, no restarts
        calls = []
        rc = runner.supervise(lambda r: (calls.append(r), 1)[1],
                              max_restarts=3, backoff=0.0,
                              sleep=lambda s: None)
        assert rc == 1
        assert calls == [0]


CHILD_SCRIPT = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.jax_compat import install
    install()
    import deepspeed_tpu
    from tests.unit.simple_model import (
        base_config, init_simple_params, random_batches, simple_loss_fn)

    save_dir, target = sys.argv[1], int(sys.argv[2])
    cfg = base_config(checkpoint={{"drain_on_preemption": True,
                                   "save_dir": save_dir}})
    e, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn,
        model_parameters=init_simple_params(jax.random.PRNGKey(0), 16),
        config=cfg)
    e.load_checkpoint(save_dir)
    start = e.global_steps
    batches = iter(random_batches(16, 16, 16, seed=start))
    while e.global_steps < target:
        e.train_batch(batches)
    e.save_checkpoint(save_dir)
    e.close()
    print("CHILD-DONE", e.global_steps, flush=True)
""")


def test_supervisor_restarts_preempted_child(tmp_path):
    """The full drill across a REAL process boundary: incarnation 1 is
    env-arm-SIGTERMed mid-window, drains, commits a preemption tag and
    exits with the resumable code; the supervisor relaunches; the
    one-shot arm file is consumed so incarnation 2 resumes from the
    preemption checkpoint, trains to the target and exits 0."""
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    script = tmp_path / "child.py"
    script.write_text(CHILD_SCRIPT.format(repo=repo))
    save_dir = tmp_path / "ckpt"
    save_dir.mkdir()
    once = tmp_path / "armed_once"
    once.write_text("1")

    attempts = []

    def run_once(restarts):
        attempts.append(restarts)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env[fault.ENV_ARM] = f"elastic.sigterm_mid_window:sigterm@{once}"
        env[elastic.RESTART_COUNT_ENV] = str(restarts)
        proc = subprocess.run(
            [sys.executable, str(script), str(save_dir), "3"],
            env=env, cwd=repo, capture_output=True, text=True,
            timeout=240)
        return proc.returncode

    rc = runner.supervise(run_once, max_restarts=2, backoff=0.0,
                          sleep=lambda s: None)
    assert rc == 0
    assert attempts == [0, 1]                 # exactly one relaunch
    assert not once.exists()                  # arm consumed by life 1
    # life 1 left a committed preemption tag; life 2 finished at step 3
    tags = ckpt.list_tags(str(save_dir))
    assert any(t.startswith("preempt_step") for t in tags)
    assert ckpt.newest_committed_step(str(save_dir)) == 3


# ===================================================================== #
# retention safety (satellite)
# ===================================================================== #

def _commit_fake_tag(save_dir, tag, preempted=False):
    d = os.path.join(str(save_dir), tag)
    os.makedirs(d)
    meta = {"global_step": max(ckpt.tag_step(tag), 0)}
    if preempted:
        meta["preempted"] = True
    ckpt.write_meta(d, meta)
    ckpt.write_commit_marker(d)
    return d


class TestRetentionSafety:
    def test_gc_protects_preempt_tags_newer_than_latest(self, tmp_path):
        """keep_n=1 + stale pointer after a preemption drain: committed
        preemption tags newer than `latest` must survive GC — they are
        exactly what the relaunch resumes."""
        _commit_fake_tag(tmp_path, "global_step2")
        _commit_fake_tag(tmp_path, "preempt_step4", preempted=True)
        _commit_fake_tag(tmp_path, "preempt_step6", preempted=True)
        ckpt.write_latest(str(tmp_path), "global_step2")
        doomed = ckpt.gc_old_tags(str(tmp_path), keep_n=1)
        assert doomed == []
        for t in ("global_step2", "preempt_step4", "preempt_step6"):
            assert os.path.isdir(str(tmp_path / t)), t

    def test_gc_still_collects_old_preempt_tags(self, tmp_path):
        """A preemption tag OLDER than latest is ordinary history."""
        _commit_fake_tag(tmp_path, "preempt_step1", preempted=True)
        _commit_fake_tag(tmp_path, "global_step4")
        _commit_fake_tag(tmp_path, "global_step6")
        ckpt.write_latest(str(tmp_path), "global_step6")
        doomed = ckpt.gc_old_tags(str(tmp_path), keep_n=1)
        assert sorted(doomed) == ["global_step4", "preempt_step1"]

    def test_gc_keep_n1_fallback_race_regression(self, tmp_path):
        """keep_n=1 with a stale pointer (save committed, crash before
        the pointer update): BOTH the newest committed tag and latest's
        target survive, so the fallback loader always finds a copy."""
        _commit_fake_tag(tmp_path, "global_step2")
        _commit_fake_tag(tmp_path, "global_step4")
        ckpt.write_latest(str(tmp_path), "global_step2")
        doomed = ckpt.gc_old_tags(str(tmp_path), keep_n=1)
        assert doomed == []
        assert os.path.isdir(str(tmp_path / "global_step2"))
        assert os.path.isdir(str(tmp_path / "global_step4"))


# ===================================================================== #
# telemetry registry sync + obs_report (satellite)
# ===================================================================== #

def _load_tool(name):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_elastic_tag_registry_in_sync():
    """One tag, three homes: monitor (canonical), profiling registry
    (re-export), obs_report (mirrored strings)."""
    from deepspeed_tpu import profiling as prof
    from deepspeed_tpu.utils import monitor as m
    obs_report = _load_tool("obs_report")
    assert m.TAG_CKPT_SNAPSHOT_MS == prof.TAG_CKPT_SNAPSHOT_MS == \
        obs_report.T_CKPT_SNAPSHOT
    assert m.TAG_CKPT_WRITE_MS == prof.TAG_CKPT_WRITE_MS == \
        obs_report.T_CKPT_WRITE
    assert m.TAG_CKPT_PENDING == prof.TAG_CKPT_PENDING == \
        obs_report.T_CKPT_PENDING
    assert m.TAG_CKPT_RESTARTS == prof.TAG_CKPT_RESTARTS == \
        obs_report.T_CKPT_RESTARTS


def test_obs_report_renders_elastic_section(tmp_path):
    obs_report = _load_tool("obs_report")
    rows = [
        {"tag": "Train/Samples/train_loss", "value": 1.0, "step": 8},
        {"tag": "Checkpoint/snapshot_ms", "value": 4.0, "step": 8},
        {"tag": "Checkpoint/snapshot_ms", "value": 6.0, "step": 16},
        {"tag": "Checkpoint/write_ms", "value": 50.0, "step": 16},
        {"tag": "Checkpoint/pending_saves", "value": 1.0, "step": 16},
        {"tag": "Checkpoint/restarts", "value": 2.0, "step": 16},
        {"event": "preemption", "reason": "SIGTERM", "step": 4,
         "tag": "preempt_step4", "committed": True, "restarts": 1},
        {"event": "resume", "step": 4, "tag": "preempt_step4",
         "restarts": 2, "preempted": True},
    ]
    p = tmp_path / "events.jsonl"
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    s = obs_report.summarize(str(p))
    el = s["elastic"]
    assert el["snapshot_ms_mean"] == 5.0
    assert el["write_ms_mean"] == 50.0
    assert el["pending_saves_peak"] == 1.0
    assert el["restarts"] == 2.0
    assert el["preemptions"] == 1 and el["resumes"] == 1
    assert el["last_preemption"]["tag"] == "preempt_step4"
    text = obs_report.render(s)
    assert "elastic" in text and "restarts=2" in text
    assert "preempt_step4" in text
    assert obs_report.main([str(p)]) == 0
    assert obs_report.main([str(p), "--json"]) == 0


def test_monitor_write_elastic_metrics(tmp_path):
    from deepspeed_tpu.utils.monitor import TensorBoardMonitor, \
        _JsonlWriter
    mon = TensorBoardMonitor(enabled=False)
    mon.mirror = _JsonlWriter(str(tmp_path))
    mon.write_elastic_metrics(snapshot_ms=3.5, write_ms=40.0,
                              pending_saves=2, restarts=1, samples=64)
    mon.mirror.close()
    rows = [json.loads(l)
            for l in open(str(tmp_path / "events.jsonl"))]
    got = {r["tag"]: r["value"] for r in rows}
    assert got == {"Checkpoint/snapshot_ms": 3.5,
                   "Checkpoint/write_ms": 40.0,
                   "Checkpoint/pending_saves": 2.0,
                   "Checkpoint/restarts": 1.0}
    assert all(r["step"] == 64 for r in rows)


# ===================================================================== #
# verify_checkpoint CLI: preemption display + --expect-step (satellite)
# ===================================================================== #

class TestVerifyCLI:
    def test_expect_step_and_preempt_report(self, tmp_path, capsys):
        vc = _load_tool("verify_checkpoint")
        e = make_engine(seed=1)
        run_steps_simple(e, 2, seed=2)
        e.save_checkpoint(str(tmp_path))
        e._elastic = elastic.PreemptionGuard(signals=())
        e._ckpt_cfg["save_dir"] = str(tmp_path)
        e._restart_count = 0
        run_steps_simple(e, 1, seed=3)
        e._elastic.trigger("SIGTERM")
        with pytest.raises(elastic.Preempted):
            run_steps_simple(e, 1, seed=4)
        # newest committed is preempt_step4 -> expect-step 4 passes
        assert vc.main([str(tmp_path), "--expect-step", "4",
                        "--all"]) == 0
        out = capsys.readouterr().out
        assert "PREEMPTION checkpoint" in out
        assert "(preemption)" in out
        assert "expect-step OK" in out
        # demanding a newer step than exists fails nonzero
        assert vc.main([str(tmp_path), "--expect-step", "9"]) != 0

    def test_expect_step_on_tag_dir(self, tmp_path, capsys):
        vc = _load_tool("verify_checkpoint")
        e = make_engine(seed=1)
        run_steps_simple(e, 1, seed=2)
        e.save_checkpoint(str(tmp_path))
        e.close()
        tag_dir = str(tmp_path / "global_step1")
        assert vc.main([tag_dir, "--expect-step", "1"]) == 0
        assert vc.main([tag_dir, "--expect-step", "5"]) == 1


# ===================================================================== #
# config plumbing (satellite)
# ===================================================================== #

def test_checkpoint_config_parsing():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    cfg = DeepSpeedConfig(base_config()).checkpoint_config
    assert cfg["async_save"] is False
    assert cfg["drain_on_preemption"] is False
    assert cfg["save_dir"] is None
    assert cfg["supervisor"] == {"max_restarts": 3, "backoff": 1.0}
    cfg = DeepSpeedConfig(base_config(checkpoint={
        "async_save": True, "drain_on_preemption": True,
        "save_dir": "/tmp/x",
        "supervisor": {"max_restarts": 7, "backoff": 0.5},
    })).checkpoint_config
    assert cfg["async_save"] and cfg["drain_on_preemption"]
    assert cfg["save_dir"] == "/tmp/x"
    assert cfg["supervisor"] == {"max_restarts": 7, "backoff": 0.5}
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(base_config(checkpoint={
            "supervisor": {"max_restarts": -1}}))
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(base_config(checkpoint={
            "supervisor": {"backoff": -0.1}}))
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(base_config(checkpoint={"save_dir": 3}))
