"""Pallas paged-attention decode kernel (ops/attention/paged.py) —
ISSUE 8: serve from pages in place, O(live tokens) instead of
O(max_len).

Tier-1 acceptance pins:
- kernel parity vs the gather oracle across page_size {8, 16, 128},
  GQA ratios {1, 4}, and the cache_position edge cases (position 0,
  exactly page-aligned, one-past-page, last slot of the table);
- greedy engine outputs from the pallas decode path EXACTLY match the
  gather path for gpt2 AND llama under continuous batching with prefix
  reuse, warmup program count and steady_state_recompiles == 0
  unchanged;
- the compiled pallas decode program contains no max_len-sized gather
  (the gather program's per-layer stripe is the contrast);
- the which-decode-attention telemetry (Serve/decode_attn_path +
  decode_attn_path event) lands in events.jsonl and obs_report.

All kernel runs here are interpret-mode (CPU): scalar prefetch, HBM
refs, dynamic-index DMA and semaphores interpret exactly, which is
what makes the TPU kernel's numerics testable without hardware.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.unit.test_inference import (TINY_INF, tiny_gpt2, tiny_llama)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _pool_case(rng, kv_heads, gqa, page_size, pages_per_seq, hd=16,
               num_pages=None, batch=5):
    """One kernel test case: random pool + per-row tables of distinct
    non-null pages + queries."""
    H = kv_heads * gqa
    num_pages = num_pages or (batch * pages_per_seq + 1)
    kpool = jnp.asarray(rng.randn(num_pages, kv_heads, page_size, hd),
                        jnp.float32)
    vpool = jnp.asarray(rng.randn(num_pages, kv_heads, page_size, hd),
                        jnp.float32)
    q = jnp.asarray(rng.randn(batch, H, hd), jnp.float32)
    tables = np.zeros((batch, pages_per_seq), np.int32)
    avail = list(range(1, num_pages))
    rng.shuffle(avail)
    for b in range(batch):
        tables[b] = [avail.pop() for _ in range(pages_per_seq)]
    return q, kpool, vpool, tables


class TestKernelParity:
    @pytest.mark.parametrize("gqa", [1, 4])
    @pytest.mark.parametrize("page_size", [8, 16, 128])
    def test_parity_sweep_vs_gather_oracle(self, page_size, gqa):
        """ISSUE 8 satellite: parity across page sizes and GQA ratios,
        with cache_position edges in one batch — position 0 (only the
        just-written token visible), last slot of page 0 (exactly
        page-aligned context), first slot of page 1 (one-past-page),
        and the table's final position."""
        from deepspeed_tpu.ops.attention.paged import (
            paged_decode_attention, paged_decode_reference)
        rng = np.random.RandomState(page_size + gqa)
        P = 3
        q, kpool, vpool, tables = _pool_case(rng, kv_heads=2, gqa=gqa,
                                             page_size=page_size,
                                             pages_per_seq=P, batch=5)
        pos = jnp.asarray([0, page_size - 1, page_size, page_size + 1,
                           P * page_size - 1], jnp.int32)
        tables = jnp.asarray(tables)
        out = paged_decode_attention(q, kpool, vpool, tables, pos,
                                     interpret=True)
        ref = paged_decode_reference(q, kpool, vpool, tables, pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_shared_prefix_pages_two_rows_one_batch(self):
        """Prefix-cache sharing at the kernel level: two rows whose
        tables point at the SAME physical pages (one prefilled prefix,
        two readers in one decode batch) read identical K/V — identical
        queries at identical positions produce identical context."""
        from deepspeed_tpu.ops.attention.paged import (
            paged_decode_attention, paged_decode_reference)
        rng = np.random.RandomState(0)
        q, kpool, vpool, tables = _pool_case(rng, kv_heads=2, gqa=2,
                                             page_size=8, pages_per_seq=3,
                                             batch=3)
        tables = np.asarray(tables)
        tables[1, :2] = tables[0, :2]       # rows 0/1 share 2 prefix pages
        q = q.at[1].set(q[0])
        pos = jnp.asarray([17, 17, 5], jnp.int32)
        tables = jnp.asarray(tables)
        out = paged_decode_attention(q, kpool, vpool, tables, pos,
                                     interpret=True)
        ref = paged_decode_reference(q, kpool, vpool, tables, pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        # divergence only past the shared pages: rows 0/1 differ (their
        # third page differs) but both match the oracle exactly
        assert not np.allclose(np.asarray(out[0]), np.asarray(out[2]))

    def test_null_table_rows_stay_finite(self):
        """Inactive slots carry all-null tables: everything is masked
        inside the kernel, and the output must be finite garbage (the
        host discards it), never NaN."""
        from deepspeed_tpu.ops.attention.paged import \
            paged_decode_attention
        rng = np.random.RandomState(1)
        q, kpool, vpool, _ = _pool_case(rng, kv_heads=2, gqa=1,
                                        page_size=8, pages_per_seq=2,
                                        batch=2)
        out = paged_decode_attention(
            q, kpool, vpool, jnp.zeros((2, 2), jnp.int32),
            jnp.zeros((2,), jnp.int32), interpret=True)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_reads_only_live_pages(self):
        """The O(live tokens) contract: garbage (NaN) planted in pages
        past each row's live count — including the row's OWN reserved
        but unreached pages — must not leak into the output."""
        from deepspeed_tpu.ops.attention.paged import (
            paged_decode_attention, paged_decode_reference)
        rng = np.random.RandomState(2)
        q, kpool, vpool, tables = _pool_case(rng, kv_heads=2, gqa=2,
                                             page_size=8, pages_per_seq=4,
                                             batch=2)
        pos = jnp.asarray([9, 3], jnp.int32)    # live pages: 2 and 1
        ref = paged_decode_reference(q, kpool, vpool,
                                     jnp.asarray(tables), pos)
        kpool_n, vpool_n = np.array(kpool), np.array(vpool)
        kpool_n[tables[0, 2:]] = np.nan          # row 0: pages 2,3 dead
        kpool_n[tables[1, 1:]] = np.nan          # row 1: pages 1..3 dead
        vpool_n[tables[0, 2:]] = np.nan
        vpool_n[tables[1, 1:]] = np.nan
        out = paged_decode_attention(q, jnp.asarray(kpool_n),
                                     jnp.asarray(vpool_n),
                                     jnp.asarray(tables), pos,
                                     interpret=True)
        assert bool(jnp.all(jnp.isfinite(out)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def _quantize_pools(kpool, vpool, scale_blocks=1):
    """int8 pools + per-token-row fp32 scales from fp pools, via the
    same quantize_kv the models' paged write path uses."""
    from deepspeed_tpu.ops.attention.paged import quantize_kv
    kq, ks = quantize_kv(kpool, scale_blocks)
    vq, vs = quantize_kv(vpool, scale_blocks)
    return kq, vq, ks, vs


class TestQuantizedPoolParity:
    """ISSUE 17 satellite: the int8-pool kernel arity (per-token-row
    fp32 scales DMA'd alongside the payload, dequant in VMEM) against
    TWO oracles — the dequantized-pool gather reference (must be tight:
    same math, different data path) and the original fp pool (pinned
    quantization-error budget; the values-level analogue of the e2e
    logit budget)."""

    # int8 round-trip error at absmax scaling is ~absmax/254 per value;
    # on randn pools the attention-output error stays well inside this
    QUANT_ATOL = 0.05

    @pytest.mark.parametrize("scale_blocks", [1, 4])
    @pytest.mark.parametrize("gqa", [1, 4])
    @pytest.mark.parametrize("page_size", [8, 16, 128])
    def test_int8_parity_sweep(self, page_size, gqa, scale_blocks):
        from deepspeed_tpu.ops.attention.paged import (
            paged_decode_attention, paged_decode_reference)
        rng = np.random.RandomState(100 + page_size + gqa)
        P = 3
        q, kpool, vpool, tables = _pool_case(rng, kv_heads=2, gqa=gqa,
                                             page_size=page_size,
                                             pages_per_seq=P, batch=5)
        pos = jnp.asarray([0, page_size - 1, page_size, page_size + 1,
                           P * page_size - 1], jnp.int32)
        tables = jnp.asarray(tables)
        kq, vq, ks, vs = _quantize_pools(kpool, vpool, scale_blocks)
        out = paged_decode_attention(q, kq, vq, tables, pos,
                                     interpret=True,
                                     k_scales=ks, v_scales=vs)
        # oracle 1: gather reference over the SAME int8 pool — pins the
        # kernel's in-VMEM dequant against the host-side dequant math
        ref_q = paged_decode_reference(q, kq, vq, tables, pos,
                                       k_scales=ks, v_scales=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_q),
                                   atol=2e-5)
        # oracle 2: the original fp pool — the quantization-error budget
        ref_fp = paged_decode_reference(q, kpool, vpool, tables, pos)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_fp),
                                   atol=self.QUANT_ATOL)

    def test_nan_poisoned_dead_page_scales_stay_masked(self):
        """The O(live tokens) contract for the quantized arity: int8
        payload can't hold NaN, so dead pages are poisoned through
        their fp32 SCALES — NaN scales on pages past each row's live
        count (including the row's own reserved-but-unreached pages)
        must not leak into the output."""
        from deepspeed_tpu.ops.attention.paged import (
            paged_decode_attention, paged_decode_reference)
        rng = np.random.RandomState(102)
        q, kpool, vpool, tables = _pool_case(rng, kv_heads=2, gqa=2,
                                             page_size=8, pages_per_seq=4,
                                             batch=2)
        pos = jnp.asarray([9, 3], jnp.int32)    # live pages: 2 and 1
        kq, vq, ks, vs = _quantize_pools(kpool, vpool)
        ref = paged_decode_reference(q, kq, vq, jnp.asarray(tables),
                                     pos, k_scales=ks, v_scales=vs)
        ks_n, vs_n = np.array(ks), np.array(vs)
        ks_n[tables[0, 2:]] = np.nan             # row 0: pages 2,3 dead
        ks_n[tables[1, 1:]] = np.nan             # row 1: pages 1..3 dead
        vs_n[tables[0, 2:]] = np.nan
        vs_n[tables[1, 1:]] = np.nan
        out = paged_decode_attention(q, kq, vq, jnp.asarray(tables),
                                     pos, interpret=True,
                                     k_scales=jnp.asarray(ks_n),
                                     v_scales=jnp.asarray(vs_n))
        assert bool(jnp.all(jnp.isfinite(out)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_shared_prefix_pages_share_scales(self):
        """Prefix sharing on the quantized pool: two rows whose tables
        point at the same physical pages read the same payload AND the
        same scales — identical queries at identical positions produce
        identical context, and both match the oracle."""
        from deepspeed_tpu.ops.attention.paged import (
            paged_decode_attention, paged_decode_reference)
        rng = np.random.RandomState(103)
        q, kpool, vpool, tables = _pool_case(rng, kv_heads=2, gqa=2,
                                             page_size=8, pages_per_seq=3,
                                             batch=3)
        tables = np.asarray(tables)
        tables[1, :2] = tables[0, :2]       # rows 0/1 share 2 prefix pages
        q = q.at[1].set(q[0])
        # both readers inside the shared prefix (live pages = 2): the
        # full context — payload AND scales — is physically shared
        pos = jnp.asarray([15, 15, 5], jnp.int32)
        tables = jnp.asarray(tables)
        kq, vq, ks, vs = _quantize_pools(kpool, vpool)
        out = paged_decode_attention(q, kq, vq, tables, pos,
                                     interpret=True,
                                     k_scales=ks, v_scales=vs)
        ref = paged_decode_reference(q, kq, vq, tables, pos,
                                     k_scales=ks, v_scales=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
        np.testing.assert_array_equal(np.asarray(out[0]),
                                      np.asarray(out[1]))
        assert not np.allclose(np.asarray(out[0]), np.asarray(out[2]))

    def test_quantize_kv_roundtrip_and_bytes(self):
        """quantize_kv/dequantize_pool round-trip error is bounded by
        absmax/254 per value, and at the serving head_dim (128) the
        int8 pool + scales beat the equivalent bf16 pool by >= 1.8x
        (the quant_serving_bytes KV lever)."""
        from deepspeed_tpu.ops.attention.paged import (
            dequantize_pool, quantize_kv)
        rng = np.random.RandomState(104)
        x = jnp.asarray(rng.randn(6, 2, 8, 16), jnp.float32)
        for nb in (1, 4):
            qv, s = quantize_kv(x, nb)
            assert qv.dtype == jnp.int8 and s.dtype == jnp.float32
            assert s.shape == x.shape[:-1] + (nb,)
            back = dequantize_pool(qv, s)
            blk = x.shape[-1] // nb
            bound = np.repeat(np.asarray(
                jnp.max(jnp.abs(x.reshape(x.shape[:-1] + (nb, blk))),
                        axis=-1)), blk, -1) / 254.0 + 1e-7
            assert bool(jnp.all(jnp.abs(back - x) <= bound))
        xs = jnp.asarray(rng.randn(4, 2, 8, 128), jnp.float32)
        qv, s = quantize_kv(xs, 1)
        int8_bytes = qv.size + 4 * s.size
        bf16_bytes = 2 * xs.size
        assert bf16_bytes / int8_bytes >= 1.8


class TestSupportPredicate:
    def test_interpret_path_always_supported(self):
        from deepspeed_tpu.ops.attention.paged import \
            paged_decode_supported
        ok, why = paged_decode_supported(4, 8, jnp.float32,
                                         backend="cpu")
        assert ok and "interpret" in why

    def test_tpu_legality_matrix(self):
        """Compiled-TPU DMA legality: head_dim must 128-align (lane
        dim), page_size must fill the dtype's sublane tile."""
        from deepspeed_tpu.ops.attention.paged import \
            paged_decode_supported
        assert paged_decode_supported(16, 128, jnp.bfloat16,
                                      backend="tpu")[0]
        assert paged_decode_supported(8, 128, jnp.float32,
                                      backend="tpu")[0]
        ok, why = paged_decode_supported(16, 64, jnp.bfloat16,
                                         backend="tpu")
        assert not ok and "head_dim" in why
        ok, why = paged_decode_supported(8, 128, jnp.bfloat16,
                                         backend="tpu")
        assert not ok and "page_size" in why

    def test_live_pages_and_bytes_model(self):
        from deepspeed_tpu.ops.attention.paged import (decode_read_bytes,
                                                       live_pages)
        assert live_pages(0, 16) == 1
        assert live_pages(15, 16) == 1
        assert live_pages(16, 16) == 2
        pallas, gather = decode_read_bytes(
            [0, 15, 16], page_size=16, pages_per_seq=8, kv_heads=2,
            head_dim=64, dtype_bytes=2)
        per_page = 16 * 2 * 64 * 2 * 2                  # K and V
        assert pallas == (1 + 1 + 2) * per_page
        assert gather == 3 * 8 * per_page
        assert gather / pallas > 2.0


# --------------------------------------------------------------------- #
# engine integration: the pallas path is the DEFAULT paged decode
# --------------------------------------------------------------------- #
PAGED_PALLAS = {"page_size": 4, "num_pages": 14, "attn_kernel": "pallas"}
PAGED_GATHER = {"page_size": 4, "num_pages": 14, "attn_kernel": "gather"}


class TestEngineParity:
    @pytest.mark.parametrize("family", ["gpt2", "llama"])
    def test_pallas_greedy_exactly_matches_gather(self, family):
        """ISSUE 8 acceptance: greedy outputs from the pallas decode
        path exactly match the gather path for both families under
        continuous batching with prefix reuse (shared system prompt),
        mixed lengths, tiny pool."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2() if family == "gpt2" else tiny_llama()
        rng = np.random.RandomState(8)
        sys_prompt = rng.randint(1, 61, (4,)).tolist()   # one full page
        # the sys-prompt pair goes first so both are in flight together
        # (prefix pages are shared while the owner still holds them)
        prompts = [sys_prompt + [int(t)]
                   for t in rng.randint(1, 61, (2,))]    # prefix reuse
        prompts += [rng.randint(1, 61, (n,)).tolist()
                    for n in (3, 5, 7, 2, 8)]
        pallas = InferenceEngine(cfg, params,
                                 dict(TINY_INF, paged_kv=PAGED_PALLAS),
                                 dtype=jnp.float32)
        assert pallas._decode_attn_path == "pallas"
        gather = InferenceEngine(cfg, params,
                                 dict(TINY_INF, paged_kv=PAGED_GATHER),
                                 dtype=jnp.float32)
        assert gather._decode_attn_path == "gather"
        got = pallas.generate(prompts, max_new_tokens=4, temperature=0.0)
        ref = gather.generate(prompts, max_new_tokens=4, temperature=0.0)
        assert got == ref
        assert pallas.scheduler.allocator.prefix_hit_tokens >= 4

    def test_default_config_routes_decode_through_pallas(self):
        """attn_kernel defaults to "pallas": an engine built from the
        stock paged config resolves the kernel path (interpret mode on
        CPU) — the O(live tokens) path is the default, not opt-in."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params, TINY_INF,
                                 dtype=jnp.float32)
        assert engine.config["paged_kv"]["attn_kernel"] == "pallas"
        assert engine._decode_attn_path == "pallas"

    def test_warmup_programs_and_zero_recompiles_unchanged(self):
        """ISSUE 8 acceptance: the pallas default preserves PR 5/7's
        program-set invariant — warmup compiles exactly
        len(batch_buckets) x len(prompt_buckets) prefills + 1 decode,
        and churn stays at 0 steady-state recompiles."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(cfg, params,
                                 dict(TINY_INF, paged_kv=PAGED_PALLAS),
                                 dtype=jnp.float32)
        programs = engine.warmup()
        assert programs == 2 * 2 + 1
        assert engine.compile_tracker.counts == {"prefill": 4,
                                                 "decode": 1}
        rng = np.random.RandomState(5)
        churn = [rng.randint(1, 61, (n,)).tolist()
                 for n in (1, 4, 5, 8, 3, 6)]
        engine.generate(churn, max_new_tokens=3)
        engine.generate(churn[:2], max_new_tokens=5, temperature=0.5)
        assert engine.steady_state_recompiles == 0
        assert engine.compile_tracker.total_compiles == programs

    def test_mesh_serving_keeps_pallas_via_shard_map(self):
        """ISSUE 11 acceptance: with ``inference.mesh`` set and legal
        geometry, the decode path stays on the Pallas kernel — wrapped
        in shard_map over the model axis (parallel/pallas_shard) — and
        the compiled sharded decode program is GATHER-FREE, pinned by
        hlo_audit.gather_ops. No silent gather fallback at pod scale."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.utils.hlo_audit import max_gather_elems
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(
            cfg, params, dict(TINY_INF, mesh={"axes": {"model": 2}}),
            dtype=jnp.float32)
        assert engine._decode_attn_path == "pallas"
        assert "shard_map" in engine._decode_attn_reason
        # greedy parity: sharded pallas == unsharded pallas == gather
        rng = np.random.RandomState(11)
        prompts = [rng.randint(1, 61, (n,)).tolist() for n in (3, 6, 2)]
        got = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
        ref_eng = InferenceEngine(cfg, params,
                                  dict(TINY_INF, paged_kv=PAGED_GATHER),
                                  dtype=jnp.float32)
        assert got == ref_eng.generate(prompts, max_new_tokens=4,
                                       temperature=0.0)
        # the compiled sharded decode program contains no stripe gather
        spec = engine.paged_spec
        rows = engine.num_slots + 1
        stripe_elems = (rows * spec.pages_per_seq * spec.kv_heads
                        * spec.page_size * spec.head_dim)
        hlo = engine._decode.lower(
            engine.params, engine._cache,
            jnp.zeros((rows,), jnp.int32), jnp.zeros((rows,), jnp.int32),
            jnp.zeros((rows, spec.pages_per_seq), jnp.int32),
            jnp.zeros((rows, 2), jnp.uint32),
            jnp.zeros((rows,), jnp.float32)).compile().as_text()
        assert max_gather_elems(hlo) < stripe_elems

    def test_mesh_illegal_geometry_rejected_at_init(self):
        """A model axis that does not divide the head counts cannot put
        whole GQA groups on a shard. The engine rejects it at
        CONSTRUCTION (the PR 7 cache-sharding rule), so the shard_map
        decode wrap never sees an indivisible geometry — pinned here
        along with the predicate it relies on."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.parallel.pallas_shard import \
            head_shard_supported
        assert head_shard_supported(2, 4, 4)
        assert not head_shard_supported(3, 4, 4)
        cfg, params = tiny_gpt2()                     # 4 heads
        with pytest.raises(ValueError, match="must divide"):
            InferenceEngine(
                cfg, params, dict(TINY_INF, mesh={"axes": {"model": 3}}),
                dtype=jnp.float32)


class TestDecodeWidthBuckets:
    """ISSUE 8 satellite: the gather fallback's decode reads are
    bounded by the batch's LIVE page bucket, not pages_per_seq."""

    def test_width_bucketed_warmup_and_zero_recompiles(self):
        """decode_page_buckets=[2] compiles one decode program per
        width (2 and full) at warmup; mixed-length churn crossing the
        bucket boundary compiles nothing more."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        engine = InferenceEngine(
            cfg, params,
            dict(TINY_INF, paged_kv=dict(PAGED_GATHER,
                                         decode_page_buckets=[2])),
            dtype=jnp.float32)
        assert engine._decode_page_buckets == (2, 8)
        programs = engine.warmup()
        assert programs == 2 * 2 + 2
        assert engine.compile_tracker.counts == {"prefill": 4,
                                                 "decode": 2}
        rng = np.random.RandomState(6)
        # short requests decode at width 2; the 8-token prompts cross
        # into the full-width program
        prompts = [rng.randint(1, 61, (n,)).tolist()
                   for n in (2, 3, 8, 7, 1, 8)]
        outs = engine.generate(prompts, max_new_tokens=4)
        assert engine.steady_state_recompiles == 0
        assert engine.compile_tracker.total_compiles == programs
        # numerics: identical to the single-width engine
        ref = InferenceEngine(cfg, params,
                              dict(TINY_INF, paged_kv=PAGED_GATHER),
                              dtype=jnp.float32).generate(
                                  prompts, max_new_tokens=4)
        assert outs == ref

    def test_scheduler_max_live_pages_and_table_clamp(self):
        from deepspeed_tpu.inference.kv_cache import PageAllocator
        from deepspeed_tpu.inference.scheduler import Request, Scheduler
        s = Scheduler(3, (4, 16), (1, 2), 32,
                      allocator=PageAllocator(20, 4))
        assert s.max_live_pages() == 1          # idle: null column only
        s.submit(Request(prompt=[1] * 9, max_new_tokens=4))   # pos 9
        s.submit(Request(prompt=[2, 3], max_new_tokens=4))    # pos 2
        s.admit()
        # positions 9 and 2 -> 9//4+1 = 3 live pages max
        assert s.max_live_pages() == 3
        full = s.block_table_rows(4, 4)
        clamped = s.block_table_rows(4, 3)
        np.testing.assert_array_equal(clamped, full[:, :3])


class TestDecodeAttnTelemetry:
    def test_path_lands_in_events_and_report(self, tmp_path):
        """Serve/decode_attn_path scalar + the decode_attn_path event
        row (with the WHY) land in events.jsonl; obs_report renders the
        path — a silent fallback to gather is visible in run
        reports."""
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        icfg = dict(TINY_INF, events_dir=str(tmp_path),
                    paged_kv=PAGED_PALLAS)
        engine = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        engine.generate([[1, 2, 3], [4, 5]], max_new_tokens=3)
        engine.close()
        rows = [json.loads(line)
                for line in open(tmp_path / "events.jsonl")]
        vals = [r["value"] for r in rows
                if r.get("tag") == "Serve/decode_attn_path"]
        assert vals and all(v == 1.0 for v in vals)
        ev = next(r for r in rows
                  if r.get("event") == "decode_attn_path")
        assert ev["path"] == "pallas" and ev["requested"] == "pallas"
        assert ev["reason"]
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(tmp_path))
        assert s["serving"]["paged_kv"]["decode_attn_path"] == "pallas"
        assert "decode_attn     : pallas" in obs_report.render(s)

    def test_gather_fallback_flagged_in_report(self, tmp_path):
        from deepspeed_tpu.inference import InferenceEngine
        cfg, params = tiny_gpt2()
        icfg = dict(TINY_INF, events_dir=str(tmp_path),
                    paged_kv=PAGED_GATHER)
        engine = InferenceEngine(cfg, params, icfg, dtype=jnp.float32)
        engine.generate([[1, 2, 3]], max_new_tokens=2)
        engine.close()
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(str(tmp_path))
        assert s["serving"]["paged_kv"]["decode_attn_path"] == "gather"
        assert "fallback" in obs_report.render(s)

    def test_tag_registry_in_sync(self):
        from deepspeed_tpu import profiling as prof
        from deepspeed_tpu.utils import monitor as m
        obs_report = _load_tool("obs_report")
        assert m.TAG_SERVE_DECODE_ATTN == prof.TAG_SERVE_DECODE_ATTN == \
            obs_report.T_DECODE_ATTN


class TestCompiledProgramAudit:
    def test_pallas_decode_program_free_of_stripe_gathers(self):
        """ISSUE 8 acceptance (tier-1 half of the paged_decode_bytes
        bench row): the compiled pallas decode program contains no
        gather anywhere near the per-layer stripe size; the gather
        program materializes it."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.utils.hlo_audit import max_gather_elems
        cfg, params = tiny_gpt2()

        def decode_hlo(pk):
            eng = InferenceEngine(cfg, params,
                                  dict(TINY_INF, paged_kv=pk),
                                  dtype=jnp.float32)
            rows = eng.num_slots + 1
            pps = eng.paged_spec.pages_per_seq
            args = (eng.params, eng._cache,
                    jnp.zeros((rows,), jnp.int32),
                    jnp.zeros((rows,), jnp.int32),
                    jnp.zeros((rows, pps), jnp.int32),
                    jnp.zeros((rows, 2), jnp.uint32),
                    jnp.zeros((rows,), jnp.float32))
            hlo = jax.jit(eng._decode_paged_impl).lower(
                *args).compile().as_text()
            return hlo, eng.paged_spec, rows

        hlo_p, spec, rows = decode_hlo(PAGED_PALLAS)
        hlo_g, _, _ = decode_hlo(PAGED_GATHER)
        stripe = (rows * spec.pages_per_seq * spec.kv_heads
                  * spec.page_size * spec.head_dim)
        assert max_gather_elems(hlo_g) >= stripe
        assert max_gather_elems(hlo_p) < stripe

    def test_quantized_decode_program_stays_gather_free(self):
        """ISSUE 17 acceptance: with int8-resident weights AND the
        int8 KV pool the compiled pallas decode program is still free
        of stripe-sized gathers — the dequant happens per streamed
        tile inside the kernel (and per matmul for weights), never by
        materializing a dequantized pool or stripe."""
        from deepspeed_tpu.inference import InferenceEngine
        from deepspeed_tpu.utils.hlo_audit import max_gather_elems
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(
            cfg, params,
            dict(TINY_INF, quantize_weights="int8",
                 paged_kv=dict(PAGED_PALLAS, kv_dtype="int8")),
            dtype=jnp.float32)
        assert len(eng._cache) == 4       # int8 pools + fp32 scales
        rows = eng.num_slots + 1
        pps = eng.paged_spec.pages_per_seq
        args = (eng.params, eng._cache,
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows,), jnp.int32),
                jnp.zeros((rows, pps), jnp.int32),
                jnp.zeros((rows, 2), jnp.uint32),
                jnp.zeros((rows,), jnp.float32))
        hlo = jax.jit(eng._decode_paged_impl).lower(
            *args).compile().as_text()
        spec = eng.paged_spec
        stripe = (rows * spec.pages_per_seq * spec.kv_heads
                  * spec.page_size * spec.head_dim)
        assert max_gather_elems(hlo) < stripe


class TestPagedAttnConfig:
    def test_defaults_and_validation(self):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_inference_config)
        cfg = get_inference_config({})
        assert cfg["paged_kv"]["attn_kernel"] == "pallas"
        assert cfg["paged_kv"]["decode_page_buckets"] == []
        with pytest.raises(DeepSpeedConfigError, match="attn_kernel"):
            get_inference_config(
                {"inference": {"paged_kv": {"attn_kernel": "cuda"}}})
        with pytest.raises(DeepSpeedConfigError,
                           match="decode_page_buckets"):
            get_inference_config(
                {"inference": {"paged_kv":
                               {"decode_page_buckets": [4, 2]}}})
        ok = get_inference_config(
            {"inference": {"paged_kv": {"decode_page_buckets": [2, 4],
                                        "attn_kernel": "gather"}}})
        assert ok["paged_kv"]["decode_page_buckets"] == [2, 4]
