"""utils/timer.py semantics (ISSUE-3 satellite): Timer_ elapsed/reset
behavior the engine's wall_clock_breakdown ladder depends on, the
structured memory_stats + memory_usage fallback, and ThroughputTimer
averaging."""

import time

import pytest

from deepspeed_tpu.utils.timer import (SynchronizedWallClockTimer,
                                       ThroughputTimer, Timer_)


def _spin(ms):
    t0 = time.perf_counter()
    while (time.perf_counter() - t0) * 1e3 < ms:
        pass


class TestTimer:
    def test_start_stop_accumulates(self):
        t = Timer_("t", synchronize=False)
        t.start(); _spin(2); t.stop()
        first = t.elapsed(reset=False)
        assert first >= 0.002
        t.start(); _spin(2); t.stop()
        assert t.elapsed(reset=False) >= first + 0.002

    def test_stop_reset_replaces_instead_of_accumulating(self):
        t = Timer_("t", synchronize=False)
        t.start(); _spin(5); t.stop()
        t.start(); _spin(1); t.stop(reset=True)
        assert t.elapsed(reset=False) < 0.005

    def test_elapsed_reset_true_zeroes(self):
        t = Timer_("t", synchronize=False)
        t.start(); _spin(2); t.stop()
        assert t.elapsed(reset=True) >= 0.002
        assert t.elapsed(reset=False) == 0.0

    def test_elapsed_reset_false_preserves(self):
        t = Timer_("t", synchronize=False)
        t.start(); _spin(2); t.stop()
        v = t.elapsed(reset=False)
        assert t.elapsed(reset=False) == v

    def test_elapsed_while_running_restarts_the_timer(self):
        """elapsed() on a RUNNING timer stops, reads, and restarts — the
        reference's mid-window read semantics (timer.py:56-65)."""
        t = Timer_("t", synchronize=False)
        t.start()
        _spin(2)
        v = t.elapsed(reset=True)
        assert v >= 0.002
        assert t.started_            # restarted after the read
        t.stop()

    def test_double_start_asserts(self):
        t = Timer_("t", synchronize=False)
        t.start()
        with pytest.raises(AssertionError):
            t.start()
        t.stop()
        with pytest.raises(AssertionError):
            t.stop()

    def test_group_creates_and_caches(self):
        timers = SynchronizedWallClockTimer(synchronize=False)
        a = timers("fwd")
        assert timers("fwd") is a
        a.start(); a.stop()
        timers.log(["fwd", "missing-is-skipped"], ranks=[0])


class TestMemoryUsage:
    def test_memory_stats_structured(self):
        stats = SynchronizedWallClockTimer.memory_stats()
        assert stats is not None
        assert stats["source"] in ("device", "host")
        assert stats["bytes_in_use"] > 0
        assert stats["peak_bytes_in_use"] >= stats["bytes_in_use"] or \
            stats["peak_bytes_in_use"] > 0

    def test_memory_usage_string(self):
        s = SynchronizedWallClockTimer.memory_usage()
        assert "mem in_use=" in s and "peak=" in s

    def test_memory_usage_fallback_when_everything_fails(self, monkeypatch):
        import deepspeed_tpu.utils.timer as timer_mod
        monkeypatch.setattr(
            timer_mod.SynchronizedWallClockTimer, "memory_stats",
            staticmethod(lambda: None))
        assert SynchronizedWallClockTimer.memory_usage() == \
            "mem stats unavailable"

    def test_memory_usage_labels_host_fallback(self, monkeypatch):
        import deepspeed_tpu.utils.timer as timer_mod
        monkeypatch.setattr(
            timer_mod.SynchronizedWallClockTimer, "memory_stats",
            staticmethod(lambda: {"bytes_in_use": 2 << 30,
                                  "peak_bytes_in_use": 3 << 30,
                                  "source": "host"}))
        s = SynchronizedWallClockTimer.memory_usage()
        assert s == "mem in_use=2.00 GB peak=3.00 GB (host)"


class TestThroughputTimer:
    def test_avg_samples_per_sec(self):
        t = ThroughputTimer(batch_size=8, num_workers=2, start_step=1,
                            steps_per_output=10**9,
                            logging_fn=lambda *a, **k: None)
        assert t.avg_samples_per_sec() == float("-1")   # before warmup
        for _ in range(4):
            t.start(); _spin(1); t.stop()
        sps = t.avg_samples_per_sec()
        assert sps > 0
        # 16 samples per >=1ms step: bounded above by 16/1ms
        assert sps <= 16 / 0.001

    def test_stop_without_start_is_noop(self):
        t = ThroughputTimer(batch_size=4, logging_fn=lambda *a, **k: None)
        t.stop()
        assert t.total_step_count == 0
