"""Async step pipeline (docs/performance.md "Async step pipeline"):
scan-fused accumulation, the prefetching device-put loader, and the
sync-free telemetry contract.

The acceptance pins (ISSUE 4): exactly ONE compiled execution per
``train_batch`` at gas>=2 on the fused path with zero forced host syncs
in steady state; losses/updates/loss-scale skips equivalent to the
per-micro loop on the same data; offload/1-bit/sparse configs
auto-fall back to the loop.
"""

import threading
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              PrefetchLoader,
                                              normalize_eval_input,
                                              stack_micro_batches)
from tests.unit.simple_model import (base_config, init_simple_params,
                                     random_batches, random_dataset,
                                     simple_loss_fn)

HIDDEN = 16


def make_engine(config, seed=0, **init_kw):
    params = init_simple_params(jax.random.PRNGKey(seed), HIDDEN)
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_parameters=params, config=config,
        **init_kw)
    return engine


def window_batches(steps, gas, seed=0):
    bs = 2 * 8  # micro batch per chip x conftest dp=8
    return random_batches(steps * gas, bs, HIDDEN, seed=seed)


# ------------------------------------------------- fused accumulation


def test_fused_single_dispatch_and_zero_syncs(tmp_path):
    """gas=4: one batch_step execution per train_batch, one compile
    total, no micro_step dispatches, and — with deferred telemetry —
    zero forced host syncs until the explicit last_loss() sync."""
    gas, steps = 4, 3
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        steps_per_print=10**9,
        observability={"enabled": True, "events_dir": str(tmp_path),
                       "flops_profiler": False,
                       "memory_watermarks": False}))
    tracker = engine.observability.compile_tracker
    batches = window_batches(steps, gas)
    for i in range(steps):
        engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))

    assert tracker.dispatch_counts.get("batch_step") == steps
    assert "micro_step" not in tracker.dispatch_counts
    assert tracker.counts.get("batch_step") == 1  # steady state: 1 compile
    assert engine._host_sync_count == 0  # no device round-trip per step

    loss = engine.last_loss()            # the explicit sync point
    assert loss is not None and np.isfinite(loss)
    assert engine._host_sync_count == 1
    assert engine.global_steps == steps


def test_fused_matches_per_micro_loop():
    """Same data, same seed: the scan-fused program computes the same
    losses and parameters as gas separate micro dispatches. (Equality
    is to float32 ulp level — XLA fuses the scanned body and the
    standalone program differently, so the last bit can flip; the math
    and accumulation order are identical.)"""
    gas, steps = 4, 5
    batches = window_batches(steps, gas, seed=7)

    def run(fused):
        cfg = base_config(gradient_accumulation_steps=gas)
        if not fused:
            cfg["async_pipeline"] = {"fused_accumulation": False}
        engine = make_engine(cfg, seed=3)
        assert engine._batch_path() is fused
        losses = [float(engine.train_batch(
            iter(batches[i * gas:(i + 1) * gas]))) for i in range(steps)]
        return losses, engine

    fused_losses, e1 = run(True)
    loop_losses, e2 = run(False)
    np.testing.assert_allclose(fused_losses, loop_losses, rtol=1e-6)
    assert e1.global_steps == e2.global_steps == steps
    for a, b in zip(jax.tree_util.tree_leaves(e1.state.params),
                    jax.tree_util.tree_leaves(e2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_fused_fp16_overflow_skip_parity():
    """Loss-scale skip behavior is identical: an overflowing first
    window is skipped (not applied) on both paths, with the same
    skipped_steps counter and the same post-backoff loss scale."""
    gas, steps = 2, 6
    batches = window_batches(steps, gas, seed=11)

    def run(fused):
        cfg = base_config(
            gradient_accumulation_steps=gas,
            fp16={"enabled": True, "initial_scale_power": 32,
                  "loss_scale_window": 1000})
        if not fused:
            cfg["async_pipeline"] = {"fused_accumulation": False}
        engine = make_engine(cfg, seed=3)
        for i in range(steps):
            engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
        return engine

    e1, e2 = run(True), run(False)
    assert e1.skipped_steps == e2.skipped_steps > 0
    assert e1.global_steps == e2.global_steps == steps - e1.skipped_steps
    assert e1.loss_scale() == e2.loss_scale()


def test_fallback_paths_select_per_micro_loop():
    """Configs that need the host between micros keep the loop, chosen
    automatically (and still train)."""
    # ZeRO-Offload: host Adam at the boundary
    eng = make_engine(base_config(
        gradient_accumulation_steps=2,
        zero_optimization={"stage": 2, "cpu_offload": True},
        bf16={"enabled": True}))
    fused, why = eng._select_batch_path()
    assert not fused and "Offload" in why
    # 1-bit Adam: python-side phase switch
    eng = make_engine(base_config(
        gradient_accumulation_steps=2,
        optimizer={"type": "OneBitAdam",
                   "params": {"lr": 1e-3, "freeze_step": 2}}))
    fused, why = eng._select_batch_path()
    assert not fused and "1-bit" in why
    gas = 2
    batches = window_batches(2, gas)
    for i in range(2):
        eng.train_batch(iter(batches[i * gas:(i + 1) * gas]))
    assert eng.global_steps == 2


def test_sync_loss_every_step_restores_per_step_sync(tmp_path):
    gas = 2
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        steps_per_print=10**9,
        async_pipeline={"sync_loss_every_step": True},
        observability={"enabled": True, "events_dir": str(tmp_path),
                       "flops_profiler": False,
                       "memory_watermarks": False}))
    batches = window_batches(3, gas)
    for i in range(3):
        engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
    assert engine._host_sync_count == 3  # one flush per step


def test_deferred_telemetry_flushes_complete_record(tmp_path):
    """Loss/lr records deferred in the ring land in events.jsonl at the
    steps_per_print boundary, one per step, at the right samples x."""
    import json
    gas, steps_per_print = 2, 3
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        steps_per_print=steps_per_print,
        observability={"enabled": True, "events_dir": str(tmp_path),
                       "flops_profiler": False,
                       "memory_watermarks": False}))
    batches = window_batches(6, gas)
    for i in range(6):
        engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
    rows = [json.loads(l) for l in
            open(tmp_path / "events.jsonl") if l.strip()]
    losses = [r for r in rows
              if r.get("tag") == "Train/Samples/train_loss"]
    assert len(losses) == 6                      # two flushes of 3
    assert [r["step"] for r in losses] == \
        [engine.train_batch_size() * (i + 1) for i in range(6)]
    # host-side scalars were never deferred
    steps_ms = [r for r in rows
                if r.get("tag") == "Train/Samples/step_time_ms"]
    assert len(steps_ms) == 6
    # dispatch/host-overhead counters ride along
    assert any(r.get("tag") == "Observability/dispatches" for r in rows)
    assert any(r.get("tag") == "Observability/host_gap_ms" for r in rows)
    assert any(r.get("tag") == "Observability/host_syncs" for r in rows)


def test_save_checkpoint_flushes_deferred_ring(tmp_path):
    """A save is a sync point: the loss records queued in the ring land
    in the event log with the checkpoint, not at some later flush."""
    import json
    gas = 2
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        steps_per_print=10**9,
        observability={"enabled": True,
                       "events_dir": str(tmp_path / "obs"),
                       "flops_profiler": False,
                       "memory_watermarks": False}))
    batches = window_batches(2, gas)
    for i in range(2):
        engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
    assert engine._host_sync_count == 0 and len(engine._monitor_ring) == 2
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    assert not engine._monitor_ring
    rows = [json.loads(l) for l in
            open(tmp_path / "obs" / "events.jsonl") if l.strip()]
    losses = [r for r in rows
              if r.get("tag") == "Train/Samples/train_loss"]
    assert len(losses) == 2


def test_deferred_scale_matches_per_step_sync_records(tmp_path):
    """Dynamic fp16: the flushed per-step loss_scale trajectory is
    identical to a sync_loss_every_step run — backoffs attribute to
    the step they happened at, not to the flush boundary."""
    import json
    gas, steps = 2, 6

    def run(sub, deferred):
        engine = make_engine(base_config(
            gradient_accumulation_steps=gas,
            steps_per_print=steps if deferred else 1,
            async_pipeline={"sync_loss_every_step": not deferred},
            fp16={"enabled": True, "initial_scale_power": 32,
                  "loss_scale_window": 1000},
            observability={"enabled": True, "events_dir": str(sub),
                           "flops_profiler": False,
                           "memory_watermarks": False}))
        batches = window_batches(steps, gas, seed=11)
        for i in range(steps):
            engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
        assert engine.skipped_steps > 0
        rows = [json.loads(l) for l in
                open(sub / "events.jsonl") if l.strip()]
        return [r["value"] for r in rows
                if r.get("tag") == "Train/Samples/loss_scale"]

    (tmp_path / "a").mkdir(), (tmp_path / "b").mkdir()
    deferred = run(tmp_path / "a", True)
    synced = run(tmp_path / "b", False)
    assert len(deferred) == steps
    assert deferred == synced
    assert len(set(deferred)) > 1   # the premise: backoffs happened


def test_deferred_lr_reanchors_on_device_step_after_skips(tmp_path):
    """fp16 overflow skips make the host step mirror over-count the
    optimizer step; flushed lr records must re-anchor on the device
    counter (the schedule index actually applied), not drift for the
    rest of the run."""
    import json
    gas, steps = 2, 6
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        steps_per_print=steps,           # one flush, at the end
        fp16={"enabled": True, "initial_scale_power": 32,
              "loss_scale_window": 1000},
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0,
                              "warmup_max_lr": 1e-2,
                              "warmup_num_steps": 100,
                              "warmup_type": "linear"}},
        observability={"enabled": True, "events_dir": str(tmp_path),
                       "flops_profiler": False,
                       "memory_watermarks": False}))
    batches = window_batches(steps, gas, seed=11)
    for i in range(steps):
        engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
    assert engine.skipped_steps > 0      # the premise: skips happened
    rows = [json.loads(l) for l in
            open(tmp_path / "events.jsonl") if l.strip()]
    lrs = [r["value"] for r in rows
           if r.get("tag") == "Train/Samples/lr"]
    assert len(lrs) == steps
    # the newest record indexes the device optimizer step exactly
    assert lrs[-1] == pytest.approx(
        float(engine._lr_at(engine.global_steps)))


# ------------------------------------------------- prefetch loader


def host_batches(n, tag=0):
    return [{"x": np.full((4, 2), 10 * tag + i, np.float32)} for i in
            range(n)]


def test_prefetch_preserves_order_and_values():
    src = host_batches(7)
    pf = PrefetchLoader(src, depth=2)
    out = list(pf)
    assert len(out) == 7
    for got, want in zip(out, src):
        np.testing.assert_array_equal(got["x"], want["x"])
    pf.close()


def test_prefetch_stacks_micro_groups_and_drops_partial_tail():
    src = host_batches(7)
    pf = PrefetchLoader(src, stack_micros=3, depth=2)
    assert pf.stacks_micro_batches
    out = list(pf)                 # 7 micros -> 2 full groups, 1 dropped
    assert len(out) == 2
    assert out[0]["x"].shape == (3, 4, 2)
    np.testing.assert_array_equal(out[1]["x"][0], src[3]["x"])


def test_prefetch_device_put_with_sharding():
    from deepspeed_tpu.parallel.mesh import build_mesh
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = build_mesh({"data": 8})
    shd = NamedSharding(mesh, PartitionSpec(None, "data"))
    src = [{"x": np.full((8, 2), i, np.float32)} for i in range(4)]
    pf = PrefetchLoader(src, sharding=shd, stack_micros=2)
    out = list(pf)
    assert len(out) == 2
    assert isinstance(out[0]["x"], jax.Array)
    assert out[0]["x"].sharding == shd


def test_prefetch_exception_propagates_to_consumer():
    def bad_iter():
        yield {"x": np.zeros((2,), np.float32)}
        raise ValueError("boom in worker")

    pf = PrefetchLoader(bad_iter())
    assert next(pf) is not None
    with pytest.raises(ValueError, match="boom in worker"):
        # the error may land on this or the next pull depending on
        # prefetch depth — drain until it surfaces
        for _ in range(4):
            next(pf)
    assert pf._thread is None      # worker reclaimed after the error
    # the error is STICKY: another next() must not silently restart the
    # source from batch 0 (that would re-serve already-trained data)
    with pytest.raises(ValueError, match="boom in worker"):
        next(pf)
    pf.close()                     # the explicit reset clears the error
    # the one-shot source generator is spent: a clean exhaustion now,
    # not the stale ValueError
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetch_close_is_clean_and_leaks_no_thread():
    n_before = threading.active_count()

    def slow_iter():
        while True:
            time.sleep(0.01)
            yield {"x": np.zeros((2,), np.float32)}

    pf = PrefetchLoader(slow_iter(), depth=2)
    next(pf)
    assert pf._thread is not None and pf._thread.is_alive()
    pf.close()
    assert pf._thread is None
    deadline = time.monotonic() + 5
    while threading.active_count() > n_before and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= n_before
    pf.close()                     # idempotent
    # __del__ after close must not raise
    del pf


def test_prefetch_restarts_on_reiteration():
    """Like DeepSpeedDataLoader epochs: a fresh iteration after
    exhaustion restarts from iter(loader)."""
    src = host_batches(2)
    pf = PrefetchLoader(src)
    assert len(list(pf)) == 2
    assert len(list(pf)) == 2
    pf.close()


def test_engine_prefetches_training_data():
    """train_batch() with engine-owned training_data runs through the
    prefetch stage: stacked device batches on the fused path, clean
    close()."""
    gas = 2
    ds = random_dataset(64, HIDDEN)
    engine = make_engine(base_config(gradient_accumulation_steps=gas,
                                     async_pipeline={"prefetch_depth": 2}),
                         training_data=ds)
    l0 = float(engine.train_batch())
    l1 = float(engine.train_batch())
    assert np.isfinite([l0, l1]).all()
    assert engine._prefetcher is not None
    assert engine._prefetcher.stacks_micro_batches
    # the inner loader handed H2D ownership to the prefetch worker
    assert engine.training_dataloader.device_put_enabled is False
    engine.close()
    assert engine._prefetcher is None


# ------------------------------------------------- loader satellites


def test_dataloader_sharding_cached_and_noop_put():
    from deepspeed_tpu.parallel.mesh import build_mesh
    mesh = build_mesh({"data": 8})
    ds = [{"x": np.full((2,), i, np.float32)} for i in range(16)]
    dl = DeepSpeedDataLoader(ds, batch_size=8, mesh=mesh, shuffle=False)
    s1 = dl._sharding()
    assert s1 is dl._sharding()            # cached, not rebuilt per batch
    batch = next(iter(dl))
    # re-putting an already-resident batch is a no-op (same objects)
    again = dl._put(batch)
    assert again["x"] is batch["x"]


def test_stack_micro_batches_layout():
    micros = host_batches(3)
    stacked = stack_micro_batches(micros)
    assert stacked["x"].shape == (3, 4, 2)
    np.testing.assert_array_equal(stacked["x"][2], micros[2]["x"])


# ------------------------------------------------- eval API unification


def test_base_eval_accepts_batch_or_iterator():
    engine = make_engine(base_config())
    batch = random_batches(1, 16, HIDDEN)[0]
    a = float(engine.eval_batch(batch))
    b = float(engine.eval_batch(iter([batch])))
    assert a == pytest.approx(b)


def test_base_eval_iterator_averages_micro_window():
    """Pipe-style eval on the base engine: an iterator is drained up to
    gas micros and the MEAN loss returned — not just the first micro."""
    gas = 4
    engine = make_engine(base_config(gradient_accumulation_steps=gas))
    micros = random_batches(gas, 16, HIDDEN, seed=5)
    per_micro = [float(engine.eval_batch(m)) for m in micros]
    window = float(engine.eval_batch(iter(micros)))
    assert window == pytest.approx(np.mean(per_micro), rel=1e-6)
    assert window != pytest.approx(per_micro[0])  # not first-micro-only


def test_fused_training_data_without_prefetch_skips_loader_put():
    """prefetch_depth=0 + fused: the engine-owned loader yields HOST
    batches (one sharded put at stacking) — no device->host->device
    round-trip per micro."""
    gas = 2
    ds = random_dataset(64, HIDDEN)
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        async_pipeline={"prefetch_depth": 0}), training_data=ds)
    loss = float(engine.train_batch())
    assert np.isfinite(loss)
    assert engine._prefetcher is None
    assert engine.training_dataloader.device_put_enabled is False


def test_normalize_eval_input_shapes():
    batch = {"x": np.zeros((2,), np.float32)}
    it = normalize_eval_input(batch, micro_batches=3)
    got = list(it)
    assert len(got) == 3 and all(g is batch for g in got)
    src = iter([batch])
    assert normalize_eval_input(src, micro_batches=3) is src
    # a list of container micros is a SEQUENCE of micro batches...
    lst = [batch, batch]
    assert list(normalize_eval_input(lst, micro_batches=4)) == lst
    # ...but a list of array leaves is one batch pytree (base engine's
    # historical contract)
    arr_batch = [np.zeros((2,), np.float32), np.ones((2,), np.float32)]
    got = list(normalize_eval_input(arr_batch, micro_batches=2))
    assert len(got) == 2 and all(g is arr_batch for g in got)
    # loader-like iterables (no __next__, no container/array shape) are
    # iterated, never replicated as an opaque "batch"
    class Loader:
        def __iter__(self):
            return iter([batch, batch, batch])
    got = list(normalize_eval_input(Loader(), micro_batches=2))
    assert len(got) == 3 and got[0] is batch


def test_base_eval_accepts_list_of_micros():
    gas = 2
    engine = make_engine(base_config(gradient_accumulation_steps=gas))
    micros = random_batches(gas, 16, HIDDEN, seed=9)
    from_list = float(engine.eval_batch(micros))
    from_iter = float(engine.eval_batch(iter(micros)))
    assert from_list == pytest.approx(from_iter)


def test_fused_stacks_device_resident_micros_without_host_roundtrip():
    """User iterators yielding already-device_put micro batches stack
    on-device (jnp.stack), never through np.asarray D2H pulls."""
    gas = 2
    engine = make_engine(base_config(gradient_accumulation_steps=gas))
    micro_shd = engine._micro_batch_sharding()
    batches = [jax.tree_util.tree_map(
        lambda x: jax.device_put(x, micro_shd), b)
        for b in window_batches(2, gas, seed=13)]
    import numpy as _np
    calls = []
    orig = _np.asarray

    def spy(x, *a, **k):
        if isinstance(x, jax.Array):
            calls.append(type(x))
        return orig(x, *a, **k)

    _np.asarray = spy
    try:
        l0 = float(engine.train_batch(iter(batches[:gas])))
        l1 = float(engine.train_batch(iter(batches[gas:])))
    finally:
        _np.asarray = orig
    assert np.isfinite([l0, l1]).all()
    assert not calls, "device micro batches were pulled to host"


def test_close_then_train_restarts_cleanly():
    """train_batch after close() must not resurrect the closed,
    untracked prefetch worker — a fresh tracked one is built."""
    gas = 2
    ds = random_dataset(64, HIDDEN)
    engine = make_engine(base_config(gradient_accumulation_steps=gas,
                                     async_pipeline={"prefetch_depth": 2}),
                         training_data=ds)
    float(engine.train_batch())
    engine.close()
    assert engine._train_iter is None and engine._prefetcher is None
    float(engine.train_batch())          # rebuilds the pipeline
    assert engine._prefetcher is not None
    engine.close()
    assert engine._prefetcher is None


def _load_obs_report():
    import importlib.util
    import os
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "obs_report_async", os.path.join(repo, "tools", "obs_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_surfaces_host_overhead(tmp_path):
    """The run report renders the new dispatch/sync/host-gap counters
    and flags a host-bound run."""
    gas = 2
    engine = make_engine(base_config(
        gradient_accumulation_steps=gas,
        steps_per_print=2,
        observability={"enabled": True, "events_dir": str(tmp_path),
                       "flops_profiler": False,
                       "memory_watermarks": False}))
    batches = window_batches(4, gas)
    for i in range(4):
        engine.train_batch(iter(batches[i * gas:(i + 1) * gas]))
    obs_report = _load_obs_report()
    s = obs_report.summarize(str(tmp_path))
    ho = s["host_overhead"]
    assert ho["dispatches_per_step"] == pytest.approx(1.0)  # fused path
    assert ho["host_syncs"] == 2            # steps_per_print=2, 4 steps
    assert ho["gap_ms_p50"] is not None and ho["gap_ms_p50"] >= 0
    assert "host_overhead" in obs_report.render(s)

    # synthetic host-bound log: gap p50 above the threshold flags it
    import json
    log = tmp_path / "flagged" / "events.jsonl"
    log.parent.mkdir()
    with open(log, "w") as f:
        for i in range(4):
            f.write(json.dumps({"tag": "Train/Samples/step_time_ms",
                                "value": 100.0, "step": i}) + "\n")
            f.write(json.dumps({"tag": "Observability/host_gap_ms",
                                "value": 50.0, "step": i}) + "\n")
    s2 = obs_report.summarize(str(log))
    assert s2["host_overhead"]["flagged"]
    assert "WARNING" in obs_report.render(s2)
    s3 = obs_report.summarize(str(log), host_gap_threshold=0.9)
    assert not s3["host_overhead"]["flagged"]


def test_async_pipeline_config_validation():
    from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                              DeepSpeedConfigError)
    with pytest.raises(DeepSpeedConfigError, match="prefetch_depth"):
        DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                         "async_pipeline": {"prefetch_depth": -1}},
                        world_size=1)
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                           "async_pipeline": {"prefetch_depth": 0}},
                          world_size=1)
    assert cfg.async_pipeline_config["prefetch_depth"] == 0
    assert cfg.async_pipeline_config["fused_accumulation"] is True
    assert cfg.async_pipeline_config["sync_loss_every_step"] is False
