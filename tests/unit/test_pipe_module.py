"""PipelineModule tests (mirrors reference tests/unit/test_pipe_module.py:
partitioning, lazy build, forward equivalence, per-layer checkpoints)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.pipe.module import (
    LayerSpec, PipelineModule, TiedLayerSpec)


class Linear:
    def __init__(self, d_in, d_out, relu=True):
        self.d_in, self.d_out, self.relu = d_in, d_out, relu

    def init(self, key):
        return {"w": jax.random.normal(key, (self.d_in, self.d_out),
                                       jnp.float32) / np.sqrt(self.d_in),
                "b": jnp.zeros((self.d_out,), jnp.float32)}

    def __call__(self, p, x, rng=None):
        y = x @ p["w"] + p["b"]
        return jax.nn.relu(y) if self.relu else y


class Scale:
    """Param-less layer built from a plain callable."""
    pass


def _mse(out, batch):
    return jnp.mean((out - batch["y"]) ** 2)


def make_module(n_layers=4, h=8, num_stages=2, **kw):
    return PipelineModule([LayerSpec(Linear, h, h) for _ in range(n_layers)],
                          num_stages=num_stages, loss_fn=_mse, **kw)


def test_layerspec_lazy_build():
    built = []

    class Tracked(Linear):
        def __init__(self, *a):
            built.append(1)
            super().__init__(*a)

    spec = LayerSpec(Tracked, 4, 4)
    assert not built
    layer = spec.build()
    assert built == [1]
    assert isinstance(layer, Tracked)
    with pytest.raises(RuntimeError):
        LayerSpec("not-callable")


def test_partition_uniform():
    mod = make_module(n_layers=8, num_stages=4, partition_method="uniform")
    assert mod.parts == [0, 2, 4, 6, 8]
    assert mod.stage_layers(1) == [2, 3]
    assert mod.stage_of_layer(5) == 2


def test_partition_parameters_balances_weighted():
    """partition_method='parameters' puts the fat layer alone."""
    h = 8
    layers = [LayerSpec(Linear, h, h),          # small
              LayerSpec(Linear, h, 16 * h),     # fat
              LayerSpec(Linear, 16 * h, h),     # fat
              LayerSpec(Linear, h, h)]          # small
    mod = PipelineModule(layers, num_stages=2, loss_fn=_mse,
                         partition_method="parameters")
    # balanced split puts the two fat layers on different stages
    sizes = [sum(1 for _ in mod.stage_layers(s)) for s in range(2)]
    assert sum(sizes) == 4
    w = mod._layer_weights()
    part_weights = [sum(w[i] for i in mod.stage_layers(s)) for s in range(2)]
    assert max(part_weights) < sum(w)  # not everything on one stage


def test_partition_type_regex():
    class Emb(Linear):
        pass

    class Block(Linear):
        pass

    layers = [LayerSpec(Emb, 8, 8), LayerSpec(Block, 8, 8),
              LayerSpec(Block, 8, 8), LayerSpec(Block, 8, 8),
              LayerSpec(Block, 8, 8)]
    mod = PipelineModule(layers, num_stages=2, loss_fn=_mse,
                         partition_method="type:Block")
    # only Block layers carry weight: 4 blocks -> 2 per stage
    w = mod._layer_weights()
    assert w == [0.0, 1.0, 1.0, 1.0, 1.0]
    blocks_per_stage = [sum(1 for i in mod.stage_layers(s)
                            if mod.specs[i].name == "Block")
                        for s in range(2)]
    assert blocks_per_stage == [2, 2]


def test_forward_matches_manual_composition():
    mod = make_module(n_layers=3, h=8, num_stages=1)
    params = mod.init_params(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    out = mod.forward(params, x)
    ref = x
    for i in range(3):
        ref = mod.layers[i](params[f"layer_{i:02d}"], ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_forward_with_activation_checkpointing():
    mod_plain = make_module(n_layers=4, h=8, num_stages=1)
    mod_ckpt = make_module(n_layers=4, h=8, num_stages=1,
                           activation_checkpoint_interval=2)
    params = mod_plain.init_params(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(4, 8).astype(np.float32)

    def loss_plain(p):
        return jnp.sum(mod_plain.forward(p, x))

    def loss_ckpt(p):
        return jnp.sum(mod_ckpt.forward(p, x))

    v1, g1 = jax.value_and_grad(loss_plain)(params)
    v2, g2 = jax.value_and_grad(loss_ckpt)(params)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5), g1, g2)


def test_paramless_callable_layer():
    mod = PipelineModule([LayerSpec(Linear, 8, 8), lambda x: x * 2.0],
                         num_stages=1, loss_fn=_mse)
    params = mod.init_params(jax.random.PRNGKey(0))
    assert "layer_01" not in params
    x = np.ones((2, 8), np.float32)
    out = mod.forward(params, x)
    half = mod.layers[0](params["layer_00"], x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(half) * 2.0,
                               rtol=1e-6)


def test_stack_stage_params_homogeneous():
    mod = make_module(n_layers=4, h=8, num_stages=2,
                      partition_method="uniform")
    params = mod.init_params(jax.random.PRNGKey(0))
    stacked = mod.stack_stage_params(params)
    leaves = jax.tree_util.tree_leaves(stacked)
    assert all(l.shape[0] == 2 for l in leaves)
    assert mod.stackable(params)


def test_stack_stage_params_heterogeneous_raises():
    layers = [LayerSpec(Linear, 8, 8), LayerSpec(Linear, 8, 16),
              LayerSpec(Linear, 16, 8), LayerSpec(Linear, 8, 8)]
    mod = PipelineModule(layers, num_stages=2, loss_fn=_mse,
                         partition_method="uniform")
    params = mod.init_params(jax.random.PRNGKey(0))
    assert not mod.stackable(params)
    with pytest.raises(ValueError, match="stage"):
        mod.stack_stage_params(params)


def test_tied_layer_params_shared():
    class Emb:
        def init(self, key):
            return {"w": jax.random.normal(key, (16, 8), jnp.float32)}

        def __call__(self, p, x, rng=None):
            return x @ p["w"]

    specs = [TiedLayerSpec("emb", Emb),
             LayerSpec(Linear, 8, 16),
             TiedLayerSpec("emb", Emb,
                           forward_fn=lambda p, x: x @ p["w"])]
    mod = PipelineModule(specs, num_stages=1, loss_fn=_mse)
    params = mod.init_params(jax.random.PRNGKey(0))
    assert set(params["tied"]) == {"emb"}
    assert "layer_00" not in params and "layer_02" not in params
    x = np.random.RandomState(0).randn(2, 16).astype(np.float32)
    out = mod.forward(params, x)  # (2,16)@(16,8) -> (2,8) -> (2,16) -> (2,8)
    assert out.shape == (2, 8)


def test_per_layer_checkpoint_roundtrip(tmp_path):
    mod = make_module(n_layers=4, h=8, num_stages=2)
    params = mod.init_params(jax.random.PRNGKey(0))
    mod.save_state_dict(params, str(tmp_path))
    # load into a module partitioned DIFFERENTLY (repartitioning across
    # stage counts, reference module.py:548)
    mod4 = make_module(n_layers=4, h=8, num_stages=4)
    fresh = mod4.init_params(jax.random.PRNGKey(99))
    loaded = mod4.load_state_dir(fresh, str(tmp_path))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b)),
        params, loaded)
