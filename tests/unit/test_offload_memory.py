"""ZeRO-Offload single-chip scale proof (VERDICT r4 #2).

The reference demonstrates 13B params trained on one 32 GB V100 via
ZeRO-Offload (docs/_posts/2020-09-09-ZeRO-Offload.md:10): 16-bit params
+ grads in device memory, fp32 master + Adam moments + the optimizer
step on the host. The TPU analog here is the offload flagship from
examples/megatron_gpt2 (--mode offload --size 2b): GPT-2 2.1B on one
16 GB v5e — bf16 params in HBM, grads leaving the micro step as a
compute-dtype OUTPUT (at ga=1 the engine allocates no accumulator at
all; the host snapshots the output right after the dispatch — the
reference's 16-bit grad transfer without a params-sized staging buffer
resident in HBM), scan_layers + remat activations, host AVX Adam on
the fp32 master.

Like test_flagship_memory.py, the proof compiles the REAL device
program at full scale from ABSTRACT avals (no 5 GB materialization) and
asserts the compiler's own memory analysis fits v5e HBM. The device
program mirrors engine._micro_step's offload-ga1 branch exactly: one
fused value_and_grad emitting compute-dtype grads, params untouched
(the update happens on the host); the tiny-scale composition tests
below and in test_cpu_adam.py pin that this is the program the engine
actually runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_loss_fn,
                                       init_gpt2_params)

pytestmark = pytest.mark.slow

V5E_HBM = 16 * 2**30
HEADROOM = 0.85

# GPT-2 2.1B (examples/megatron_gpt2 GPT2_2B): 40 x hidden 2048
# (16 heads, d=128 — a tuned block-table shape), 50304-aligned vocab
CFG = GPT2Config(vocab_size=50304, max_position_embeddings=1024,
                 hidden_size=2048, num_layers=40, num_heads=16,
                 embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0,
                 scan_layers=True)
SEQ, MB = 1024, 1


def test_offload_2p5b_fits_v5e_hbm():
    loss_fn = gpt2_loss_fn(CFG, dtype=jnp.bfloat16, remat=True)
    ap = jax.eval_shape(lambda k: init_gpt2_params(CFG, k),
                        jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(s.shape))
                   for s in jax.tree_util.tree_leaves(ap))
    assert n_params >= 2.0e9, n_params          # the >=2B bar
    abf16 = jax.tree_util.tree_map(
        lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16), ap)
    abatch = {"input_ids": jax.ShapeDtypeStruct((MB, SEQ + 1), jnp.int32)}
    arng = jax.ShapeDtypeStruct((2,), jnp.uint32)

    def micro(params, batch, rng):
        # engine._micro_step offload-ga1 branch: fwd+bwd fused, grads
        # leave as a compute-dtype output; params flow through
        # unchanged — the optimizer step happens on the host
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, rng))(params)
        return loss, jax.tree_util.tree_map(
            lambda g: g.astype(jnp.bfloat16), grads)

    ma = (jax.jit(micro)
          .lower(abf16, abatch, arng)
          .compile().memory_analysis())
    if ma is None or not hasattr(ma, "temp_size_in_bytes"):
        pytest.skip("backend provides no memory analysis")
    args = ma.argument_size_in_bytes
    temp = ma.temp_size_in_bytes
    out = ma.output_size_in_bytes
    # CPU-backend correction, conservative for TPU: FloatNormalization
    # widens the scan's stacked dgrad buffer to f32 on CPU (no bf16 CPU
    # kernels), so `temp` carries a 4*N_h copy that compiles as bf16
    # (2*N_h) on TPU — each stacked slice is written once per scan
    # step, no f32 accumulation is ever needed. Replace the widened
    # copy with its bf16 size; do NOT claim the further TPU saving that
    # this buffer aliases the grad output.
    n_h = sum(int(np.prod(s.shape))
              for s in jax.tree_util.tree_leaves(ap["h"]))
    f32_dgrads = 4 * n_h
    assert temp > f32_dgrads, (temp, f32_dgrads)   # the copy is there
    temp_tpu = temp - f32_dgrads + 2 * n_h
    total = args + temp_tpu + out
    assert total <= HEADROOM * V5E_HBM, (
        total / 2**30, dict(args=args / 2**30, temp=temp / 2**30,
                            temp_tpu=temp_tpu / 2**30, out=out / 2**30))
    # the recipe really is load-bearing: params + grad output + the
    # bf16 dgrad buffer are ~6*n_params bytes, so activations (the
    # remainder) must stay small — catches a remat/scan regression
    # silently materializing per-layer activations
    acts = temp - f32_dgrads
    assert acts <= 1.5 * 2**30, acts / 2**30
    # host-side state the proof moves off-device: fp32 master + m + v
    host_state_gb = 3 * n_params * 4 / 2**30
    assert host_state_gb > 20            # ~24 GB: the reason offload wins


def test_offload_ga1_direct_grads_and_training():
    """At ga=1 + cpu_offload the engine allocates NO device grad
    accumulator (the params-sized HBM saving): grads leave the micro
    step as a compute-dtype output, and training still converges. With
    ga>1 the fp32 accumulator stays (real accumulation)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)

    def build(ga):
        params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
        engine, *_ = ds.initialize(
            model=simple_loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": ga,
                    "bf16": {"enabled": True},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                    "zero_optimization": {"stage": 2, "cpu_offload": True}})
        return engine

    e1 = build(1)
    assert e1.state.accum_grads == ()
    batches = random_batches(8, 4, 8, seed=0)
    losses = []
    for i in range(8):
        losses.append(float(e1.train_batch(iter(batches[i:i + 1]))))
    assert losses[-1] < losses[0], losses
    # the grads crossed as compute dtype (D2H at 16-bit)
    dts = {g.dtype for g in
           jax.tree_util.tree_leaves(e1._offload_grads_device)} \
        if e1._offload_grads_device is not None else None
    # consumed by the boundary snapshot — the stash must be drained
    assert e1._offload_grads_device is None, dts

    e2 = build(2)
    dtypes2 = {a.dtype for a in
               jax.tree_util.tree_leaves(e2.state.accum_grads)}
    assert dtypes2 == {np.dtype(np.float32)}, dtypes2


def test_offload_ga1_matches_ga1_device_adam_bf16():
    """Offload-ga1 direct-grad path vs on-device Adam at bf16: same
    data, trajectories agree to bf16-grad tolerance (the compute-dtype
    D2H rounds grads exactly once, like the reference's fp16 grad
    transfer)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    batches = random_batches(6, 4, 8, seed=1)
    runs = {}
    for mode in ("offload", "device"):
        cfg = {"train_micro_batch_size_per_gpu": 4,
               "gradient_accumulation_steps": 1,
               "bf16": {"enabled": True},
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
        if mode == "offload":
            cfg["zero_optimization"] = {"stage": 2, "cpu_offload": True}
        engine, *_ = ds.initialize(model=simple_loss_fn,
                                   model_parameters=params, config=cfg)
        for i in range(6):
            engine.train_batch(iter(batches[i:i + 1]))
        engine.synchronize()
        runs[mode] = jax.device_get(engine.state.params)
    for a, b in zip(jax.tree_util.tree_leaves(runs["offload"]),
                    jax.tree_util.tree_leaves(runs["device"])):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-2, atol=2e-3)
