"""Fault-injection durability tests: kill the save at a specific point,
prove resume still works (ISSUE 1 tentpole; reference treats checkpoints
as the recovery backbone, engine.py:1329/:1173 — on preemptible TPU pods
a crash mid-save is the expected failure mode).

Every test arms `deepspeed_tpu.runtime.fault` at one named fault point,
lets the save die there, then asserts a fresh engine resumes from the
newest *committed and verified* checkpoint — never from torn bytes.
"""

import json
import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime import checkpoint as ckpt
from deepspeed_tpu.runtime import fault
from tests.unit.simple_model import (
    base_config, init_simple_params, random_batches, simple_loss_fn)

pytestmark = pytest.mark.faulty

HIDDEN = 16


@pytest.fixture(autouse=True)
def _reset_injector():
    fault.reset()
    yield
    fault.reset()


def make_engine(config=None, seed=0):
    params = init_simple_params(jax.random.PRNGKey(seed), HIDDEN)
    engine, *_ = deepspeed_tpu.initialize(
        model=simple_loss_fn, model_parameters=params,
        config=config or base_config())
    return engine


def train_steps(engine, n, seed=0):
    batches = iter(random_batches(
        n * engine.gradient_accumulation_steps, 16, HIDDEN, seed=seed))
    return [float(engine.train_batch(batches)) for _ in range(n)]


def save_step2_then_crash(tmp_path, point, **arm_kw):
    """Commit a checkpoint at step 2, then kill the next save (step 4)
    at `point`. Returns the engine that suffered the crash."""
    e = make_engine(seed=1)
    train_steps(e, 2, seed=2)
    e.save_checkpoint(str(tmp_path))            # committed baseline
    train_steps(e, 2, seed=3)
    fault.arm(point, exc=fault.InjectedCrash(point), **arm_kw)
    with pytest.raises(fault.InjectedCrash):
        e.save_checkpoint(str(tmp_path))
    fault.reset()
    return e


def assert_resumes_at(tmp_path, step, seed=9):
    e2 = make_engine(seed=seed)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None, "fallback found no loadable checkpoint"
    assert e2.global_steps == step
    assert all(np.isfinite(train_steps(e2, 1, seed=11)))
    return e2, path


# --------------------------------------------------------------------- #
# crash-during-save: four distinct injected fault points
# --------------------------------------------------------------------- #

def test_crash_after_model_shard_write_falls_back(tmp_path):
    """Die after model_states shards land but before optim_states —
    the classic crash-after-shard-0 torn save."""
    save_step2_then_crash(
        tmp_path, "ckpt.after_shard",
        filter=lambda **ctx: ctx.get("name") == "model_states")
    # the torn attempt stayed in the staging dir, never became a tag
    assert os.path.isdir(str(tmp_path / "global_step4.tmp"))
    assert not os.path.isdir(str(tmp_path / "global_step4"))
    _, path = assert_resumes_at(tmp_path, 2)
    assert path.endswith("global_step2")


def test_crash_before_commit_marker_falls_back(tmp_path):
    """All shards + meta durable, COMMITTED never written: the save must
    be invisible to resume."""
    save_step2_then_crash(tmp_path, "ckpt.before_marker")
    tmp_dir = str(tmp_path / "global_step4.tmp")
    assert os.path.isfile(os.path.join(tmp_dir, "meta.json"))
    assert not os.path.isfile(os.path.join(tmp_dir, ckpt.COMMIT_MARKER))
    assert_resumes_at(tmp_path, 2)


def test_crash_before_rename_falls_back(tmp_path):
    """COMMITTED written inside the staging dir but the rename never
    ran: still not a tag, still invisible."""
    save_step2_then_crash(tmp_path, "ckpt.before_rename")
    assert os.path.isfile(
        str(tmp_path / "global_step4.tmp" / ckpt.COMMIT_MARKER))
    assert ckpt.read_latest(str(tmp_path)) == "global_step2"
    assert_resumes_at(tmp_path, 2)


def test_crash_during_latest_update_resumes_newest_committed(tmp_path):
    """Die between writing latest.tmp and os.replace: global_step4 is
    fully committed but `latest` still names global_step2 — the scan
    resumes the newest committed tag as if the save had finished."""
    save_step2_then_crash(tmp_path, "ckpt.latest_tmp_written")
    assert ckpt.read_latest(str(tmp_path)) == "global_step2"  # not torn
    assert os.path.isfile(
        str(tmp_path / "global_step4" / ckpt.COMMIT_MARKER))
    assert_resumes_at(tmp_path, 4)


def test_torn_empty_latest_pointer_recovers(tmp_path):
    """A zero-byte `latest` (in-place truncate-write torn by a crash)
    must not brick resume: read_latest yields None, the scan finds the
    committed tag anyway."""
    e = make_engine(seed=1)
    train_steps(e, 2)
    e.save_checkpoint(str(tmp_path))
    with open(str(tmp_path / "latest"), "w") as f:
        f.write("  \n")
    assert ckpt.read_latest(str(tmp_path)) is None
    assert_resumes_at(tmp_path, 2)


# --------------------------------------------------------------------- #
# corruption: checksums must catch what the filesystem won't
# --------------------------------------------------------------------- #

def test_bitflip_in_shard_detected_and_falls_back(tmp_path):
    """A single flipped byte in a committed shard npz must fail CRC32
    verification and trigger fallback — never load silently."""
    e = make_engine(seed=1)
    train_steps(e, 2, seed=2)
    e.save_checkpoint(str(tmp_path))
    train_steps(e, 2, seed=3)
    e.save_checkpoint(str(tmp_path))
    victim = str(tmp_path / "global_step4" / "model_states.shard_0.npz")
    fault.flip_byte(victim)
    ok, problems = ckpt.verify_checkpoint_dir(
        str(tmp_path / "global_step4"))
    assert not ok and any("CRC32" in p for p in problems)
    _, path = assert_resumes_at(tmp_path, 2)
    assert path.endswith("global_step2")


def test_missing_fragment_detected_and_falls_back(tmp_path):
    """A shard file listed in COMMITTED but absent (partial copy, lost
    object) fails verification; resume falls back."""
    e = make_engine(seed=1)
    train_steps(e, 2, seed=2)
    e.save_checkpoint(str(tmp_path))
    train_steps(e, 2, seed=3)
    e.save_checkpoint(str(tmp_path))
    os.remove(str(tmp_path / "global_step4" / "optim_states.shard_0.npz"))
    assert_resumes_at(tmp_path, 2)


def test_explicit_tag_integrity_failure_raises(tmp_path):
    """With an explicit tag the user asked for *that* checkpoint —
    corruption is an error, not a silent fallback."""
    e = make_engine(seed=1)
    train_steps(e, 2)
    e.save_checkpoint(str(tmp_path))
    fault.flip_byte(str(tmp_path / "global_step2" /
                        "model_states.shard_0.npz"))
    e2 = make_engine(seed=9)
    with pytest.raises(RuntimeError, match="integrity"):
        e2.load_checkpoint(str(tmp_path), tag="global_step2")


# --------------------------------------------------------------------- #
# transient filesystem flakes: retry with exponential backoff
# --------------------------------------------------------------------- #

def test_transient_oserror_on_write_is_retried(tmp_path):
    """First two write attempts raise OSError (GCS/NFS flake); the
    retry wrapper absorbs them and the save commits normally."""
    e = make_engine(seed=1)
    train_steps(e, 2, seed=2)
    fault.arm("io_write", exc=OSError("simulated transient flake"),
              times=2)
    d = e.save_checkpoint(str(tmp_path))
    assert fault.get_injector().fired("io_write") == 2
    assert os.path.isfile(os.path.join(d, ckpt.COMMIT_MARKER))
    assert_resumes_at(tmp_path, 2)


def test_persistent_oserror_exhausts_retries():
    """Non-transient errors still surface after the retry budget."""
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise OSError("disk on fire")

    with pytest.raises(OSError, match="disk on fire"):
        fault.retry_io(boom, retries=2, backoff=0, sleep=lambda _: None)
    assert calls["n"] == 3  # first try + 2 retries


def test_injected_crash_is_never_retried():
    calls = {"n": 0}

    def die():
        calls["n"] += 1
        raise fault.InjectedCrash("preempted")

    with pytest.raises(fault.InjectedCrash):
        fault.retry_io(die, retries=5, backoff=0, sleep=lambda _: None)
    assert calls["n"] == 1


# --------------------------------------------------------------------- #
# a crashed save must not poison the NEXT save (stale staging cleanup)
# --------------------------------------------------------------------- #

def test_resave_after_crash_reuses_tag_cleanly(tmp_path):
    e = save_step2_then_crash(tmp_path, "ckpt.before_marker")
    d = e.save_checkpoint(str(tmp_path))  # same tag, retried save
    assert d.endswith("global_step4")
    assert not os.path.isdir(d + ckpt.TMP_SUFFIX)
    ok, problems = ckpt.verify_checkpoint_dir(d)
    assert ok, problems
    assert_resumes_at(tmp_path, 4)


def test_custom_latest_tag_is_preferred(tmp_path):
    """A healthy `latest` naming a non-step tag ('best') wins over
    numerically-ranked tags — it is the last completed save."""
    e = make_engine(seed=1)
    train_steps(e, 2, seed=2)
    e.save_checkpoint(str(tmp_path))               # global_step2
    train_steps(e, 1, seed=3)
    e.save_checkpoint(str(tmp_path), tag="best")   # latest -> 'best'
    assert ckpt.candidate_tags(str(tmp_path))[0] == "best"
    e2 = make_engine(seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("best")
    assert e2.global_steps == 3


def test_crash_between_tag_renames_keeps_old_copy_loadable(tmp_path):
    """Re-saving an existing tag renames the old copy aside before the
    new one lands; a crash in between leaves '<tag>.old' as a committed
    candidate ranked at its base tag's step — resume restores it rather
    than silently dropping back to an older step."""
    e = make_engine(seed=1)
    train_steps(e, 2, seed=2)
    e.save_checkpoint(str(tmp_path))      # global_step2
    train_steps(e, 2, seed=3)
    e.save_checkpoint(str(tmp_path))      # global_step4
    # simulate dying between rename(final -> .old) and replace(tmp -> final)
    os.rename(str(tmp_path / "global_step4"),
              str(tmp_path / "global_step4.old"))
    assert ckpt.candidate_tags(str(tmp_path))[0] == "global_step4.old"
    e2 = make_engine(seed=9)
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None and path.endswith("global_step4.old")
    assert e2.global_steps == 4


# --------------------------------------------------------------------- #
# marker contents
# --------------------------------------------------------------------- #

def test_verify_checkpoint_cli(tmp_path, capsys):
    """tools/verify_checkpoint.py: rc 0 on a healthy committed tag, rc 1
    after a bit-flip, with the corruption named in the report."""
    import importlib.util
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    spec = importlib.util.spec_from_file_location(
        "verify_checkpoint",
        os.path.join(repo_root, "tools", "verify_checkpoint.py"))
    vc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(vc)

    e = make_engine(seed=1)
    train_steps(e, 2)
    e.save_checkpoint(str(tmp_path))
    assert vc.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "COMMITTED+VERIFIED" in out

    fault.flip_byte(str(tmp_path / "global_step2" /
                        "optim_states.shard_0.npz"))
    assert vc.main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "CRC32 mismatch" in out


def test_commit_marker_records_sizes_and_checksums(tmp_path):
    e = make_engine(seed=1)
    train_steps(e, 1)
    d = e.save_checkpoint(str(tmp_path))
    with open(os.path.join(d, ckpt.COMMIT_MARKER)) as f:
        marker = json.load(f)
    assert marker["process_count"] == jax.process_count()
    files = marker["files"]
    assert "model_states.shard_0.npz" in files
    assert "meta.json" in files
    for fn, info in files.items():
        p = os.path.join(d, fn)
        assert os.path.getsize(p) == info["size"]
        assert fault.crc32_file(p) == info["crc32"]
