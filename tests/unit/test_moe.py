"""MoE layer + expert parallelism (beyond-reference extension; the
DeepSpeed v0.3.0 snapshot has no MoE — SURVEY §2.3). Three tiers like
the rest of the suite: oracle numerics, gradient sanity, and the
EP-sharded path on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.moe import (MoEConfig, expert_capacity,
                                   init_moe_params, moe_layer,
                                   moe_layer_reference)

pytestmark = pytest.mark.slow  # multi-minute e2e compiles (VERDICT r2 #8 tiering)


def _setup(top_k, e=4, h=16, f=32, b=2, s=8, cf=1.25, seed=0):
    cfg = MoEConfig(hidden_size=h, intermediate_size=f, num_experts=e,
                    top_k=top_k, capacity_factor=cf)
    params = init_moe_params(cfg, jax.random.PRNGKey(seed))
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (b, s, h),
                          jnp.float32)
    return cfg, params, x


class TestMoENumerics:

    @pytest.mark.parametrize("top_k", [1, 2, 4])
    def test_matches_token_loop_oracle(self, top_k):
        cfg, params, x = _setup(top_k)
        y, aux = moe_layer(params, cfg, x, dtype=jnp.float32)
        y_ref = moe_layer_reference(params, cfg, x)
        np.testing.assert_allclose(np.asarray(y), y_ref,
                                   atol=1e-5, rtol=1e-5)
        assert np.isfinite(float(aux)) and float(aux) > 0.0

    @pytest.mark.parametrize("top_k", [1, 2, 4])
    def test_capacity_drops_match_oracle(self, top_k):
        # tight capacity: forced drops must agree with the oracle's
        # token-order priority rule
        cfg, params, x = _setup(top_k, cf=0.5)
        assert expert_capacity(cfg, 16) < 16 * top_k // 4 + 1
        y, _ = moe_layer(params, cfg, x, dtype=jnp.float32)
        y_ref = moe_layer_reference(params, cfg, x)
        np.testing.assert_allclose(np.asarray(y), y_ref,
                                   atol=1e-5, rtol=1e-5)

    def test_gradients_finite_and_flow(self):
        cfg, params, x = _setup(2)

        def loss(params, x):
            y, aux = moe_layer(params, cfg, x, dtype=jnp.float32)
            return jnp.sum(y ** 2) + aux

        g = jax.grad(loss)(params, x)
        for name in ("router", "wi", "wo"):
            arr = np.asarray(g[name])
            assert np.all(np.isfinite(arr)), name
            assert np.abs(arr).max() > 0.0, name  # router learns via gates


class TestMoEExpertParallel:

    def test_ep_sharded_matches_replicated(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        cfg, params, x = _setup(2, e=4, b=4, s=16)
        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("data", "expert"))

        y_rep, aux_rep = moe_layer(params, cfg, x, dtype=jnp.float32)

        with mesh:
            f = jax.jit(lambda p, xx: moe_layer(
                p, cfg, xx, expert_axis="expert", dtype=jnp.float32))
            ps = jax.device_put(params, NamedSharding(mesh, P()))
            xs = jax.device_put(x, NamedSharding(mesh, P("data")))
            y_ep, aux_ep = f(ps, xs)
        np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_rep),
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(float(aux_ep), float(aux_rep),
                                   rtol=1e-6)

    def test_ep_training_through_engine(self):
        """End-to-end: a toy MoE model trains through the engine on an
        expert x data mesh — the ep member of the parallelism family."""
        import deepspeed_tpu as ds
        cfg = MoEConfig(hidden_size=16, intermediate_size=32,
                        num_experts=4, top_k=2)
        key = jax.random.PRNGKey(0)
        params = {"moe": init_moe_params(cfg, key),
                  "head": jax.random.normal(key, (16, 4)) * 0.1}

        engine_mesh = [None]   # filled after initialize builds the mesh

        def loss_fn(params, batch, rng):
            y, aux = moe_layer(params["moe"], cfg, batch["x"],
                               expert_axis="expert", mesh=engine_mesh[0],
                               dtype=jnp.float32)
            logits = jnp.mean(y, axis=1) @ params["head"]
            lab = jax.nn.one_hot(batch["y"], 4)
            ce = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * lab, -1))
            return ce + aux

        engine, *_ = ds.initialize(
            model=loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 8,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {"stage": 1},
                    "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
                    "steps_per_print": 10**9,
                    "mesh": {"axes": {"data": 2, "expert": 4}}})
        engine_mesh[0] = engine.mesh
        rng = np.random.RandomState(0)
        from jax.sharding import NamedSharding, PartitionSpec as P
        shd = NamedSharding(engine.mesh, P("data"))
        losses = []
        for _ in range(30):
            x = rng.randn(16, 8, 16).astype(np.float32)
            y = (x[:, 0, :4].argmax(-1)).astype(np.int32)
            b = {"x": jax.device_put(x, shd), "y": jax.device_put(y, shd)}
            losses.append(float(engine.train_batch(iter([b]))))
        assert losses[-1] < losses[0], losses[::10]


class TestMoEGPT2:

    def test_moe_gpt2_trains_through_engine(self):
        """A MoE GPT-2 (sparse FFN every other block) trains end to end
        on a data x expert mesh through the engine; loss decreases and
        stays finite (router aux losses included)."""
        import deepspeed_tpu as ds
        from deepspeed_tpu.models.gpt2 import (GPT2Config,
                                               gpt2_moe_loss_fn,
                                               init_gpt2_moe_params)
        cfg = GPT2Config(vocab_size=128, max_position_embeddings=32,
                         hidden_size=32, num_layers=4, num_heads=4,
                         embd_dropout=0.0, attn_dropout=0.0,
                         resid_dropout=0.0)
        moe_cfg = MoEConfig(hidden_size=32, intermediate_size=64,
                            num_experts=4, top_k=2)
        params = init_gpt2_moe_params(cfg, moe_cfg, jax.random.PRNGKey(0))
        assert "router" in params["h_1"]["mlp"]      # MoE block
        assert "fc_w" in params["h_0"]["mlp"]        # dense block

        mesh_box = [None]

        def model(params, batch, rng):
            return gpt2_moe_loss_fn(cfg, moe_cfg, mesh=mesh_box[0],
                                    deterministic=True)(params, batch, rng)

        engine, *_ = ds.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {"stage": 2},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 10**9,
                    "mesh": {"axes": {"data": 4, "expert": 2}}})
        mesh_box[0] = engine.mesh
        rng = np.random.RandomState(0)
        from jax.sharding import NamedSharding, PartitionSpec as P
        shd = NamedSharding(engine.mesh, P("data"))
        ids = rng.randint(0, 128, (16, 17)).astype(np.int32)
        b = {"input_ids": jax.device_put(ids, shd)}  # fixed batch:
        losses = []                                  # memorization target
        for _ in range(20):
            losses.append(float(engine.train_batch(iter([b]))))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0] - 0.3, (losses[0], losses[-1])


class TestMoEShardedDispatch:
    """moe_layer_sharded: per-shard routing + explicit all_to_all — the
    capacity-bound-collective form of the layer."""

    def _mesh(self):
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:4]), ("expert",))

    def test_matches_global_form_when_nothing_drops(self):
        from deepspeed_tpu.ops.moe import moe_layer_sharded
        # ample capacity: per-shard routing == global routing exactly
        cfg, params, x = _setup(2, e=4, b=4, s=8, cf=8.0)
        mesh = self._mesh()
        y_g, _ = moe_layer(params, cfg, x, dtype=jnp.float32)
        y_s, aux_s = jax.jit(lambda p, xx: moe_layer_sharded(
            p, cfg, xx, mesh, dtype=jnp.float32))(params, x)
        np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_g),
                                   atol=1e-6, rtol=1e-6)
        assert np.isfinite(float(aux_s))

    def test_gradients_flow_through_all_to_all(self):
        from deepspeed_tpu.ops.moe import moe_layer_sharded
        cfg, params, x = _setup(2, e=4, b=4, s=8)
        mesh = self._mesh()

        def loss(p, xx):
            y, aux = moe_layer_sharded(p, cfg, xx, mesh,
                                       dtype=jnp.float32)
            return jnp.sum(y ** 2) + aux

        g = jax.jit(jax.grad(loss))(params, x)
        for name in ("router", "wi", "wo"):
            arr = np.asarray(g[name])
            assert np.all(np.isfinite(arr)) and np.abs(arr).max() > 0, name

    def test_per_shard_capacity_is_local(self):
        from deepspeed_tpu.ops.moe import expert_capacity, moe_layer_sharded
        # tight capacity: per-shard dispatch drops per LOCAL counts; the
        # layer must still produce finite outputs of the right shape
        cfg, params, x = _setup(2, e=4, b=4, s=8, cf=0.5)
        mesh = self._mesh()
        y, aux = jax.jit(lambda p, xx: moe_layer_sharded(
            p, cfg, xx, mesh, dtype=jnp.float32))(params, x)
        assert y.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(y))) and np.isfinite(float(aux))
        # local capacity really is smaller than the global one
        assert expert_capacity(cfg, 8) < expert_capacity(cfg, 32)


def test_moe_checkpoint_resume_bit_identical(tmp_path):
    """Sharded checkpoint round-trip with the MoE pytree (router + expert
    banks replacing dense MLPs) on the data x expert mesh: the resumed
    engine's next-step loss must equal the unbroken run's exactly."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_moe_loss_fn,
                                           init_gpt2_moe_params)
    from deepspeed_tpu.parallel.mesh import build_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = GPT2Config(vocab_size=64, max_position_embeddings=16,
                     hidden_size=16, num_layers=2, num_heads=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    mc = MoEConfig(hidden_size=16, intermediate_size=32, num_experts=4,
                   top_k=2)
    axes = {"data": 2, "expert": 4}   # one spec for mesh AND config

    def make_engine():
        params = init_gpt2_moe_params(cfg, mc, jax.random.PRNGKey(0))
        mesh = build_mesh(axes)
        lf = gpt2_moe_loss_fn(cfg, mc, mesh=mesh, deterministic=True)
        e, *_ = ds.initialize(
            model=lf, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "gradient_accumulation_steps": 1,
                    "zero_optimization": {"stage": 2},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 10**9,
                    "mesh": {"axes": axes}})
        return e

    e = make_engine()
    ids = np.random.RandomState(0).randint(0, 64, (8, 17)).astype(np.int32)
    shd = NamedSharding(e.mesh, P("data"))
    b = {"input_ids": jax.device_put(ids, shd)}
    for _ in range(3):
        e.train_batch(iter([b]))
    e.save_checkpoint(str(tmp_path))
    l_straight = float(e.train_batch(iter([b])))

    e2 = make_engine()
    e2.load_checkpoint(str(tmp_path))
    assert e2.global_steps == 3
    l_resumed = float(e2.train_batch(iter([b])))
    assert l_straight == l_resumed, (l_straight, l_resumed)


def test_moe_param_specs_shard_expert_weights():
    """gpt2_moe_param_specs: expert banks PHYSICALLY shard over the
    expert axis (each device owns E/ep experts' weights + opt state),
    composing with ZeRO-2 over data; training runs and loss decreases."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models.gpt2 import (GPT2Config, gpt2_moe_loss_fn,
                                           gpt2_moe_param_specs,
                                           init_gpt2_moe_params)
    from deepspeed_tpu.parallel.mesh import build_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = GPT2Config(vocab_size=64, max_position_embeddings=16,
                     hidden_size=16, num_layers=2, num_heads=2,
                     embd_dropout=0.0, attn_dropout=0.0, resid_dropout=0.0)
    mc = MoEConfig(hidden_size=16, intermediate_size=32, num_experts=4,
                   top_k=2)
    axes = {"data": 2, "expert": 4, "model": 1}  # TP specs need 'model'
    params = init_gpt2_moe_params(cfg, mc, jax.random.PRNGKey(0))
    mesh = build_mesh(axes)
    lf = gpt2_moe_loss_fn(cfg, mc, mesh=mesh, deterministic=True)
    engine, *_ = ds.initialize(
        model=lf, model_parameters=params,
        param_specs=gpt2_moe_param_specs(cfg),
        config={"train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 1,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10**9, "mesh": {"axes": axes}})
    wi_spec = engine._state_shardings.params["h_1"]["mlp"]["wi"].spec
    assert wi_spec[0] == "expert", wi_spec        # expert dim owned
    dense_spec = engine._state_shardings.params["h_0"]["mlp"]["fc_w"].spec
    assert "expert" not in tuple(dense_spec), dense_spec

    ids = np.random.RandomState(0).randint(0, 64, (8, 17)).astype(np.int32)
    shd = NamedSharding(engine.mesh, P("data"))
    b = {"input_ids": jax.device_put(ids, shd)}
    losses = [float(engine.train_batch(iter([b]))) for _ in range(5)]
    assert losses[-1] < losses[0], losses


def test_sharded_overflow_matches_per_shard_reference():
    """VERDICT r2 weak #4: moe_layer_sharded's documented semantics under
    overflow — capacity/priority are PER SHARD. With a router biased to
    overload one expert and capacity_factor < 1 (guaranteed drops), the
    sharded layer must equal the token-loop oracle run independently on
    each shard's tokens with the LOCAL capacity."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.ops.moe import (MoEConfig, init_moe_params,
                                       moe_layer_reference,
                                       moe_layer_sharded)

    cfg = MoEConfig(hidden_size=16, intermediate_size=32, num_experts=4,
                    top_k=2, capacity_factor=0.5)
    key = jax.random.PRNGKey(0)
    params = init_moe_params(cfg, key)
    # bias the router hard toward expert 0 so its slots overflow
    params["router"] = params["router"].at[:, 0].add(0.5)
    P_sz = 4
    mesh = ds.build_mesh({"expert": P_sz})
    x = jax.random.normal(jax.random.fold_in(key, 9), (8, 4, 16),
                          jnp.float32) * 0.5

    y, aux = jax.jit(lambda p, xx: moe_layer_sharded(
        p, cfg, xx, mesh, dtype=jnp.float32))(params, x)

    shard_b = x.shape[0] // P_sz
    refs = [moe_layer_reference(params, cfg,
                                np.asarray(x[s * shard_b:(s + 1) * shard_b]))
            for s in range(P_sz)]
    ref = np.concatenate(refs, axis=0)
    np.testing.assert_allclose(np.asarray(y), ref, atol=2e-5, rtol=2e-4)
    # sanity: drops really happened (dense-capacity run would differ)
    cfg_full = MoEConfig(hidden_size=16, intermediate_size=32,
                         num_experts=4, top_k=2, capacity_factor=8.0)
    y_full = moe_layer_reference(params, cfg_full, np.asarray(x).reshape(
        8, 4, 16))
    assert not np.allclose(np.asarray(y), y_full, atol=1e-3)
