"""Topology/mesh/partition rank-math tests (mirrors reference
tests/unit/test_topology.py and test_partition.py — pure logic tier)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.parallel.topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    ParallelGrid,
)
from deepspeed_tpu.parallel.mesh import (
    build_mesh, mesh_from_topology, axis_size,
)
from deepspeed_tpu.utils.partition import (
    partition_uniform, partition_balanced,
)


class TestProcessTopology:

    def test_2d_mapping(self):
        topo = ProcessTopology(axes=["x", "y"], dims=[2, 2])
        assert topo.world_size() == 4
        assert topo.get_rank(x=0, y=0) == 0
        assert topo.get_rank(x=0, y=1) == 1
        assert topo.get_rank(x=1, y=0) == 2
        assert topo.get_rank(x=1, y=1) == 3
        assert topo.get_coord(1) == topo.ProcessCoord(x=0, y=1)

    def test_roundtrip(self):
        topo = ProcessTopology(axes=["a", "b", "c"], dims=[2, 3, 4])
        for r in range(topo.world_size()):
            coord = topo.get_coord(r)
            assert topo.get_rank(**coord._asdict()) == r

    def test_axis_comm_lists(self):
        topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
        data_lists = topo.get_axis_comm_lists("data")
        pipe_lists = topo.get_axis_comm_lists("pipe")
        assert sorted(map(tuple, data_lists)) == [(0, 1), (2, 3)]
        assert sorted(map(tuple, pipe_lists)) == [(0, 2), (1, 3)]

    def test_filter_match(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        assert topo.filter_match(pipe=0, model=0) == [0, 2]
        assert topo.filter_match(pipe=1) == [4, 5, 6, 7]

    def test_axis_list(self):
        topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
        assert topo.get_axis_list("pipe", 1) == [4, 5, 6, 7]

    def test_rank_repr(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        # data omitted by default (DP replicas share weights)
        assert topo.get_rank_repr(0) == "pipe_0-model_0"
        assert topo.get_rank_repr(7) == "pipe_1-model_1"

    def test_errors(self):
        topo = ProcessTopology(axes=["x"], dims=[2])
        with pytest.raises(ValueError):
            topo.get_rank(x=5)
        with pytest.raises(ValueError):
            topo.get_coord(99)
        with pytest.raises(ValueError):
            ProcessTopology(axes=["x", "x"], dims=[2, 2])

    def test_split_axis_preserves_rank_positions(self):
        """Splitting 'data' (8) into inter(2) x intra(4) keeps every
        rank's position: old coord c -> (c // 4, c % 4), and intra
        peers stay rank-adjacent (ICI neighbors)."""
        topo = PipeDataParallelTopology(num_pp=2, num_dp=8)
        split = topo.split_axis("data", "data_inter", "data_intra", 4)
        assert split.axes == ["pipe", "data_inter", "data_intra"]
        assert split.dims == [2, 2, 4]
        assert split.world_size() == topo.world_size()
        for rank in range(topo.world_size()):
            old = topo.get_coord(rank)
            new = split.get_coord(rank)
            assert new.pipe == old.pipe
            assert new.data_inter == old.data // 4
            assert new.data_intra == old.data % 4
        # intra groups are contiguous rank runs (the fast-wire property)
        for group in split.get_axis_comm_lists("data_intra"):
            assert group == list(range(group[0], group[0] + 4))

    def test_split_axis_errors(self):
        topo = PipeDataParallelTopology(num_pp=1, num_dp=8)
        with pytest.raises(ValueError):
            topo.split_axis("nope", "a", "b", 2)
        with pytest.raises(ValueError):
            topo.split_axis("data", "a", "b", 3)    # 8 % 3 != 0
        with pytest.raises(ValueError):
            topo.split_axis("data", "pipe", "b", 2)  # name collision


class TestParallelGrid:

    def test_3d_grid_sizes(self):
        topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
        grid = ParallelGrid(topo, process_index=0)
        assert grid.get_pipe_parallel_world_size() == 2
        assert grid.get_data_parallel_world_size() == 2
        assert grid.get_model_parallel_world_size() == 2
        assert grid.get_data_parallel_group() == "data"
        assert grid.get_model_parallel_group() == "model"

    def test_stage_mapping(self):
        topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
        grid = ParallelGrid(topo, process_index=0)
        assert grid.is_first_stage()
        assert not grid.is_last_stage()
        assert grid.stage_to_global(stage_id=3) == 6
        grid7 = ParallelGrid(topo, process_index=7)
        assert grid7.is_last_stage()
        assert grid7.get_data_parallel_rank() == 1

    def test_p2p_pairs_adjacent(self):
        topo = PipeDataParallelTopology(num_pp=4, num_dp=1)
        grid = ParallelGrid(topo, process_index=0)
        pairs = grid.p2p_pairs()
        assert [0, 1] in pairs and [1, 2] in pairs and [2, 3] in pairs
        assert [0, 3] in pairs  # wraparound


class TestMesh:

    def test_default_mesh_all_data(self):
        mesh = build_mesh()
        assert axis_size(mesh, "data") == jax.device_count()

    def test_explicit_axes(self):
        mesh = build_mesh({"data": 4, "model": 2})
        assert axis_size(mesh, "data") == 4
        assert axis_size(mesh, "model") == 2
        assert axis_size(mesh, "pipe") == 1  # absent => 1

    def test_canonical_ordering(self):
        mesh = build_mesh({"model": 2, "pipe": 2, "data": 2})
        assert mesh.axis_names == ("pipe", "data", "model")

    def test_hierarchical_data_axes(self):
        from deepspeed_tpu.parallel.mesh import (data_axis_names,
                                                 data_axis_size,
                                                 split_data_axis)
        axes = split_data_axis({"data": 8}, 4)
        assert axes == {"data_inter": 2, "data_intra": 4}
        mesh = build_mesh(axes)
        # canonical order: inter (major/slow) before intra (minor/fast)
        assert mesh.axis_names == ("data_inter", "data_intra")
        assert data_axis_names(mesh) == ("data_inter", "data_intra")
        assert data_axis_size(mesh) == 8
        flat = build_mesh({"data": 8})
        assert data_axis_names(flat) == ("data",)
        assert data_axis_size(flat) == 8
        with pytest.raises(ValueError):
            split_data_axis({"data": 8}, 3)       # not divisible
        with pytest.raises(ValueError):
            split_data_axis({"model": 8}, 2)      # no data axis
        with pytest.raises(ValueError):
            split_data_axis({"data": 8}, 1)       # degenerate split

    def test_infer_axis(self):
        mesh = build_mesh({"data": -1, "model": 2})
        assert axis_size(mesh, "data") == jax.device_count() // 2

    def test_mismatch_raises(self):
        # more devices than exist: error
        with pytest.raises(ValueError):
            build_mesh({"data": 16})

    def test_explicit_subset_allowed(self):
        # explicit axes smaller than the device count run on a subset —
        # the elastic-resume case (dp=8 checkpoint loaded under dp=3)
        mesh = build_mesh({"data": 3})
        assert mesh.shape["data"] == 3

    def test_mesh_from_topology(self):
        topo = PipeDataParallelTopology(num_pp=2, num_dp=4)
        mesh = mesh_from_topology(topo)
        assert mesh.axis_names == ("pipe", "data")
        assert mesh.shape["pipe"] == 2 and mesh.shape["data"] == 4


class TestPartition:

    def test_uniform_even(self):
        assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]

    def test_uniform_remainder(self):
        parts = partition_uniform(10, 4)
        sizes = [parts[i + 1] - parts[i] for i in range(4)]
        assert sum(sizes) == 10
        assert max(sizes) - min(sizes) <= 1

    def test_balanced_uniform_weights(self):
        parts = partition_balanced([1.0] * 8, 4)
        assert parts == [0, 2, 4, 6, 8]

    def test_balanced_skewed(self):
        weights = [10.0, 1.0, 1.0, 1.0, 1.0, 10.0]
        parts = partition_balanced(weights, 2)
        sizes = [sum(weights[parts[i]:parts[i + 1]]) for i in range(2)]
        assert max(sizes) == 12.0  # optimal bottleneck

    def test_balanced_more_parts_than_items(self):
        parts = partition_balanced([5.0, 5.0], 4)
        assert parts[0] == 0 and parts[-1] == 2
        assert len(parts) == 5
        # each item in its own part
        covered = [parts[i + 1] - parts[i] for i in range(4)]
        assert sum(covered) == 2

    def test_balanced_single_part(self):
        assert partition_balanced([3.0, 1.0, 4.0], 1) == [0, 3]
