"""ZeRO-Offload CPU Adam tests (mirror reference tests/unit/test_adam_acuracy
+ the cpu-offload variants in test_fp16.py and tests/perf/adam_test*):
native-kernel numerics vs the jnp Adam oracle, bf16 output path, engine
offload training + checkpoint round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.adam.cpu_adam import load_library
from deepspeed_tpu.ops.optimizers import Adam


def test_native_library_builds_and_loads():
    lib = load_library()
    assert lib is not None, "native libdstpu_adam.so failed to build/load"
    assert lib.ds_adam_simd_width() in (1, 8, 16)


@pytest.mark.parametrize("wd,adamw", [(0.0, True), (0.01, True),
                                      (0.01, False)])
def test_native_matches_jnp_adam(wd, adamw):
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(2049).astype(np.float32),  # odd: scalar tail
              "b": rng.randn(3).astype(np.float32)}
    opt = DeepSpeedCPUAdam(params, lr=1e-2, weight_decay=wd,
                           adamw_mode=adamw)
    assert opt.uses_native_kernel
    oracle = Adam(lr=1e-2, weight_decay=wd, adamw_mode=adamw)
    jp = {k: jnp.asarray(v) for k, v in params.items()}
    st = oracle.init(jp)
    for i in range(10):
        grads = {k: rng.randn(*v.shape).astype(np.float32)
                 for k, v in params.items()}
        out = opt.step(grads)
        jp, st = oracle.update(
            {k: jnp.asarray(v) for k, v in grads.items()}, st, jp)
    for k in params:
        np.testing.assert_allclose(out[k], np.asarray(jp[k]),
                                   rtol=1e-4, atol=1e-5)


def test_bf16_output_matches_cast():
    import ml_dtypes
    rng = np.random.RandomState(1)
    params = {"w": rng.randn(64).astype(np.float32)}
    opt = DeepSpeedCPUAdam(params, lr=1e-2)
    out16 = opt.step({"w": rng.randn(64).astype(np.float32)},
                     bf16_out=True)
    assert out16["w"].dtype == ml_dtypes.bfloat16
    expected = opt.master_params[0].astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        out16["w"].view(np.uint16), expected.view(np.uint16))


def test_state_dict_roundtrip():
    rng = np.random.RandomState(2)
    params = {"w": rng.randn(32).astype(np.float32)}
    opt = DeepSpeedCPUAdam(params, lr=1e-2)
    g = {"w": rng.randn(32).astype(np.float32)}
    opt.step(g)
    sd = opt.state_dict()
    opt2 = DeepSpeedCPUAdam(params, lr=1e-2)
    opt2.load_state_dict(sd)
    a = opt.step(g)
    b = opt2.step(g)
    np.testing.assert_array_equal(a["w"], b["w"])


def _offload_config(**over):
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2, "cpu_offload": True},
        "gradient_clipping": 1.0,
    }
    cfg.update(over)
    return cfg


def test_engine_offload_trains():
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, opt, _, _ = ds.initialize(model=simple_loss_fn,
                                      model_parameters=params,
                                      config=_offload_config())
    assert engine.zero_cpu_offload
    assert isinstance(opt, DeepSpeedCPUAdam)
    assert engine.state.opt_state == ()  # no device moments: the HBM win
    batches = random_batches(8, 4, 8, seed=0)
    losses = []
    for i in range(0, 8, 2):
        loss = engine.train_batch(iter(batches[i:i + 2]))
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert engine.global_steps == 4


def test_engine_offload_matches_device_adam():
    """Same data, offload vs on-device Adam: trajectories must agree to
    fp32 tolerance (bf16 disabled)."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    batches = random_batches(6, 4, 8, seed=1)

    runs = {}
    for mode in ("offload", "device"):
        cfg = _offload_config() if mode == "offload" else {
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "gradient_clipping": 1.0,
        }
        engine, *_ = ds.initialize(model=simple_loss_fn,
                                   model_parameters=params, config=cfg)
        for i in range(0, 6, 2):
            engine.train_batch(iter(batches[i:i + 2]))
        runs[mode] = jax.device_get(engine.state.params)

    a_leaves = jax.tree_util.tree_leaves(runs["offload"])
    b_leaves = jax.tree_util.tree_leaves(runs["device"])
    assert len(a_leaves) == len(b_leaves)
    for a, b in zip(a_leaves, b_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_engine_offload_checkpoint_roundtrip(tmp_path):
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    batches = random_batches(8, 4, 8, seed=2)
    engine, *_ = ds.initialize(model=simple_loss_fn,
                               model_parameters=params,
                               config=_offload_config())
    for i in range(0, 4, 2):
        engine.train_batch(iter(batches[i:i + 2]))
    engine.save_checkpoint(str(tmp_path))

    engine2, *_ = ds.initialize(model=simple_loss_fn,
                                model_parameters=params,
                                config=_offload_config())
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.optimizer.step_count == engine.optimizer.step_count
    # identical continuation
    for i in range(4, 8, 2):
        l1 = engine.train_batch(iter(batches[i:i + 2]))
        l2 = engine2.train_batch(iter(batches[i:i + 2]))
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


# --------------------------------------------------------------------- #
# overlapped offload (zero_optimization.overlap_comm): host Adam runs
# concurrently with the next window's device compute, updates delayed by
# one window (reference stream overlap, stage2.py:291-294)
# --------------------------------------------------------------------- #

def test_engine_offload_overlap_one_window_delay():
    """After 2 overlapped windows, device params must equal a synchronous
    engine's params after 1 window on the same data — the defining
    one-window-delay semantics."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    batches = random_batches(4, 4, 8, seed=3)

    eo, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config=_offload_config(
            zero_optimization={"stage": 2, "cpu_offload": True,
                               "overlap_comm": True}))
    assert eo._offload_overlap
    es, *_ = ds.initialize(model=simple_loss_fn, model_parameters=params,
                           config=_offload_config())

    eo.train_batch(iter(batches[0:2]))   # window 1: update pending
    for a, b in zip(jax.tree_util.tree_leaves(eo.state.params),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b), rtol=1e-6)

    eo.train_batch(iter(batches[2:4]))   # window 2: applies window-1 update
    es.train_batch(iter(batches[0:2]))   # sync engine: one window
    for a, b in zip(jax.tree_util.tree_leaves(eo.state.params),
                    jax.tree_util.tree_leaves(es.state.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), rtol=1e-6)


def test_engine_offload_overlap_synchronize_and_converge():
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config=_offload_config(
            zero_optimization={"stage": 2, "cpu_offload": True,
                               "overlap_comm": True}))
    batches = random_batches(16, 4, 8, seed=0)
    losses = []
    for i in range(0, 16, 2):
        losses.append(float(engine.train_batch(iter(batches[i:i + 2]))))
    engine.synchronize()
    assert engine._offload_pending is None
    assert engine.global_steps == 8  # every window's update applied
    assert losses[-1] < losses[0]


def test_engine_offload_overlap_checkpoint_drains(tmp_path):
    """save_checkpoint must apply the in-flight update first, so a resume
    sees the drained state."""
    from tests.unit.simple_model import (init_simple_params, simple_loss_fn,
                                         random_batches)
    params = init_simple_params(jax.random.PRNGKey(0), hidden_dim=8)
    engine, *_ = ds.initialize(
        model=simple_loss_fn, model_parameters=params,
        config=_offload_config(
            zero_optimization={"stage": 2, "cpu_offload": True,
                               "overlap_comm": True}))
    batches = random_batches(2, 4, 8, seed=5)
    engine.train_batch(iter(batches))
    assert engine._offload_pending is not None
    engine.save_checkpoint(str(tmp_path))
    assert engine._offload_pending is None
    assert engine.global_steps == 1
