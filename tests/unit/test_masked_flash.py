"""ONE mask-parameterized flash kernel (ops/attention/masked_flash.py)
— ISSUE 11: dense, causal, banded and BigBird training attention are
BlockMask choices of a single Pallas kernel.

Tier-1 acceptance pins:
- interpret-mode parity sweep (dense / causal / banded / BigBird) x GQA
  x dropout x stream-vs-resident against the existing oracles
  (attention_reference, block_sparse_attention_reference);
- custom-vjp gradients vs the jnp oracle;
- the sparse + dense dispatches route through the unified kernel by
  default, legacy kernels stay reachable behind flags, and the v1
  per-triple kernels are never auto-selected;
- banded layouts coarsen their walk tile (fine structure in register
  predicates) without changing numerics;
- the shard_map head wrap (parallel/pallas_shard) preserves numerics
  and gradients on a 2-way CPU mesh;
- flash.py's old mutable warn/force globals are gone: options are a
  dataclass knob, fallbacks log once per (reason, shape).

All kernel runs are interpret-mode (CPU) — scalar prefetch, HBM refs
and dynamic-index DMA interpret exactly, so the TPU kernel's numerics
are testable without hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import flash as F
from deepspeed_tpu.ops.attention import masked_flash as M
from deepspeed_tpu.ops.attention.masked_flash import (BlockMask,
                                                      masked_flash_attention,
                                                      masked_flash_cost,
                                                      masked_flash_reference)
from deepspeed_tpu.ops.sparse_attention import blocksparse as bs
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig)

S, D = 128, 16
BLOCK = 16


@pytest.fixture(autouse=True)
def _clean_state():
    old_stream = M._FORCE_STREAM
    yield
    M._FORCE_STREAM = old_stream
    bs._FN_CACHE.clear()


def _qkv(B=2, H=4, hkv=None, s=S, d=D, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, s, d), dtype) * 0.3
    k = jnp.asarray(rng.randn(B, hkv or H, s, d), dtype) * 0.3
    v = jnp.asarray(rng.randn(B, hkv or H, s, d), dtype) * 0.3
    return q, k, v


def _mask_for(family, heads=4, s=S, block=BLOCK):
    if family == "dense":
        return BlockMask.dense(s, s, block)
    if family == "causal":
        return BlockMask.causal(s, block)
    if family == "banded":
        cfg = BSLongformerSparsityConfig(num_heads=heads, block=block,
                                         num_sliding_window_blocks=3)
        return BlockMask.from_layout(cfg.make_layout(s), block)
    if family == "bigbird":
        cfg = BigBirdSparsityConfig(num_heads=heads, block=block,
                                    num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        return BlockMask.from_layout(cfg.make_layout(s), block)
    raise AssertionError(family)


# --------------------------------------------------------------------- #
# the new jnp oracle is tied to the EXISTING oracles first
# --------------------------------------------------------------------- #
class TestReferenceTies:
    def test_dense_and_causal_match_attention_reference(self):
        q, k, v = _qkv()
        for family, causal in (("dense", False), ("causal", True)):
            got = masked_flash_reference(q, k, v, _mask_for(family))
            want = F.attention_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       atol=2e-6)

    @pytest.mark.parametrize("family", ["banded", "bigbird"])
    def test_layouts_match_blocksparse_reference(self, family):
        q, k, v = _qkv()
        cfg_cls = (BSLongformerSparsityConfig if family == "banded"
                   else BigBirdSparsityConfig)
        cfg = (cfg_cls(num_heads=4, block=BLOCK,
                       num_sliding_window_blocks=3) if family == "banded"
               else cfg_cls(num_heads=4, block=BLOCK, num_random_blocks=1,
                            num_sliding_window_blocks=3,
                            num_global_blocks=1))
        layout = cfg.make_layout(S)
        got = masked_flash_reference(
            q, k, v, BlockMask.from_layout(layout, BLOCK),
            sm_scale=D ** -0.5)
        want = bs.block_sparse_attention_reference(q, k, v, layout,
                                                   sm_scale=D ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-6)


# --------------------------------------------------------------------- #
# ISSUE 11 acceptance: the parity sweep
# --------------------------------------------------------------------- #
class TestKernelParity:
    @pytest.mark.parametrize("stream", [False, True])
    @pytest.mark.parametrize("family",
                             ["dense", "causal", "banded", "bigbird"])
    def test_parity_sweep(self, family, stream):
        """dense/causal/banded/BigBird x GQA x dropout x
        stream-vs-resident, all against the oracle."""
        M._FORCE_STREAM = stream
        mask = _mask_for(family)
        rng_key = jax.random.PRNGKey(5)
        seed = F.dropout_seed_from_rng(rng_key).reshape(())
        for hkv in (4, 2):
            for rate in (0.0, 0.25):
                q, k, v = _qkv(hkv=hkv, seed=hkv)
                got = masked_flash_attention(
                    q, k, v, mask, dropout_rate=rate,
                    dropout_rng=rng_key if rate else None,
                    interpret=True)
                want = masked_flash_reference(
                    q, k, v, mask, dropout_rate=rate,
                    dropout_seed=seed if rate else None)
                np.testing.assert_allclose(
                    np.asarray(got), np.asarray(want), atol=5e-5,
                    err_msg=f"{family} stream={stream} hkv={hkv} "
                            f"rate={rate}")

    def test_stream_and_resident_agree_exactly(self):
        mask = _mask_for("causal")
        q, k, v = _qkv()
        M._FORCE_STREAM = True
        o_s = masked_flash_attention(q, k, v, mask, interpret=True)
        M._FORCE_STREAM = False
        o_r = masked_flash_attention(q, k, v, mask, interpret=True)
        np.testing.assert_array_equal(np.asarray(o_s), np.asarray(o_r))

    def test_key_mask_parity(self):
        q, k, v = _qkv(seed=3)
        kpm = np.zeros((2, S), np.float32)
        kpm[:, 100:] = -1e9
        mask = _mask_for("banded")
        got = masked_flash_attention(q, k, v, mask,
                                     key_mask=jnp.asarray(kpm),
                                     interpret=True)
        want = masked_flash_reference(q, k, v, mask,
                                      key_mask=jnp.asarray(kpm))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-5)

    def test_bf16(self):
        q, k, v = _qkv(dtype=jnp.bfloat16, seed=6)
        mask = _mask_for("banded")
        got = masked_flash_attention(q, k, v, mask, interpret=True)
        want = masked_flash_reference(q, k, v, mask)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   atol=3e-2)

    def test_empty_rows_zero_output(self):
        """Rows whose block-row has no active tile produce exact-zero
        output (blocksparse oracle semantics)."""
        active = np.ones((1, S // BLOCK, S // BLOCK), bool)
        active[0, 2] = False
        mask = BlockMask(active, np.zeros_like(active, np.uint8), BLOCK,
                         S, S)
        q, k, v = _qkv()
        out = masked_flash_attention(q, k, v, mask, interpret=True)
        rows = np.asarray(out)[:, :, 2 * BLOCK:3 * BLOCK]
        assert np.all(rows == 0.0)
        want = masked_flash_reference(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=5e-5)

    def test_per_head_layout_supported(self):
        cfg = BigBirdSparsityConfig(num_heads=4, block=BLOCK,
                                    different_layout_per_head=True,
                                    num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        layout = cfg.make_layout(S)
        mask = BlockMask.from_layout(layout, BLOCK)
        assert mask.heads == 4                    # no collapse
        q, k, v = _qkv()
        got = masked_flash_attention(q, k, v, mask, sm_scale=D ** -0.5,
                                     interpret=True)
        want = bs.block_sparse_attention_reference(q, k, v, layout,
                                                   sm_scale=D ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-5)


# --------------------------------------------------------------------- #
# ISSUE 11 acceptance: custom-vjp gradients vs the jnp oracle
# --------------------------------------------------------------------- #
class TestGradients:
    @pytest.mark.parametrize("family",
                             ["dense", "causal", "banded", "bigbird"])
    def test_grads_match_oracle(self, family):
        mask = _mask_for(family)
        q, k, v = _qkv(seed=9)

        def f_m(q, k, v):
            return jnp.sum(masked_flash_attention(
                q, k, v, mask, interpret=True) ** 2)

        def f_r(q, k, v):
            return jnp.sum(masked_flash_reference(q, k, v, mask) ** 2)

        gm = jax.grad(f_m, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_r, argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(gm, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"{family} d{n}")

    @pytest.mark.parametrize("stream", [False, True])
    def test_gqa_dropout_grads(self, stream):
        """fwd/bwd dropout-mask consistency under GQA in both K/V
        paths: the backward kernels must regenerate the identical hash
        bits."""
        M._FORCE_STREAM = stream
        mask = _mask_for("causal")
        q, k, v = _qkv(hkv=2, seed=4)
        rng = jax.random.PRNGKey(21)
        seed = F.dropout_seed_from_rng(rng).reshape(())

        def f_m(q, k, v):
            return jnp.sum(masked_flash_attention(
                q, k, v, mask, dropout_rate=0.2, dropout_rng=rng,
                interpret=True) ** 2)

        def f_r(q, k, v):
            return jnp.sum(masked_flash_reference(
                q, k, v, mask, dropout_rate=0.2,
                dropout_seed=seed) ** 2)

        gm = jax.grad(f_m, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_r, argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(gm, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-3, rtol=2e-3,
                                       err_msg=f"d{n}")

    def test_key_mask_cotangent_is_zero(self):
        q, k, v = _qkv()
        kpm = jnp.zeros((2, S), jnp.float32)
        mask = _mask_for("dense")
        g = jax.grad(lambda m: jnp.sum(masked_flash_attention(
            q, k, v, mask, key_mask=m, interpret=True)))(kpm)
        assert float(jnp.abs(g).max()) == 0.0


# --------------------------------------------------------------------- #
# banded coarsening: big walk tiles, fine structure in registers
# --------------------------------------------------------------------- #
class TestCoarsening:
    def _longformer(self, s=2048, fb=128):
        cfg = BSLongformerSparsityConfig(num_heads=2, block=fb,
                                         num_sliding_window_blocks=3)
        return cfg.make_layout(s), s, fb

    def test_banded_layout_coarsens(self):
        layout, s, fb = self._longformer()
        mask = BlockMask.from_layout(layout, fb)
        assert mask.block > fb, mask.describe()
        assert mask.band is not None and mask.has_partials
        # the expansion must reproduce the layout's fine bits exactly
        dense = mask.dense_additive()
        want = bs.layout_additive_mask(layout, fb)[:1]
        np.testing.assert_array_equal(dense == 0.0, want == 0.0)

    def test_coarse_matches_fine_and_oracle(self):
        layout, s, fb = self._longformer()
        q, k, v = _qkv(B=1, H=2, s=s, seed=2)
        coarse = BlockMask.from_layout(layout, fb)
        fine = BlockMask.from_layout(layout, fb, walk_block=0)
        assert fine.block == fb and coarse.block > fb
        o_c = masked_flash_attention(q, k, v, coarse,
                                     sm_scale=D ** -0.5, interpret=True)
        o_f = masked_flash_attention(q, k, v, fine, sm_scale=D ** -0.5,
                                     interpret=True)
        want = bs.block_sparse_attention_reference(q, k, v, layout,
                                                   sm_scale=D ** -0.5)
        np.testing.assert_allclose(np.asarray(o_c), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(o_f), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_causal_banded_clip(self):
        """A causally-clipped band (unidirectional Longformer-class
        realized bits) coarsens with the clip folded into the register
        predicate."""
        n = 16
        idx = np.arange(n)
        rb, cb = idx[:, None], idx[None, :]
        pred = ((rb < 1) | (cb < 1) | (np.abs(rb - cb) <= 1)) & (cb <= rb)
        layout = np.broadcast_to(pred.astype(np.int32),
                                 (2, n, n)).copy()
        fb = 128
        s = n * fb
        mask = BlockMask.from_layout(layout, fb)
        assert mask.block > fb and mask.band is not None
        assert mask.band[-1] is True              # clip folded in
        q, k, v = _qkv(B=1, H=2, s=s, seed=8)
        got = masked_flash_attention(q, k, v, mask, sm_scale=D ** -0.5,
                                     interpret=True)
        want = bs.block_sparse_attention_reference(q, k, v, layout,
                                                   sm_scale=D ** -0.5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)

    def test_bigbird_declines_coarsening(self):
        mask = _mask_for("bigbird")
        assert mask.block == BLOCK and mask.band is None

    def test_sparsity_config_resolves_to_block_mask(self):
        cfg = BSLongformerSparsityConfig(num_heads=2, block=128,
                                         num_sliding_window_blocks=3)
        mask = cfg.make_block_mask(2048)
        assert isinstance(mask, BlockMask) and mask.heads == 1
        assert mask.block > 128                    # coarsened
        assert cfg.make_block_mask(2048, walk_block=0).block == 128


# --------------------------------------------------------------------- #
# dispatch: ONE kernel serves every path; v1 retired
# --------------------------------------------------------------------- #
class TestDispatch:
    def test_sparse_dispatch_defaults_to_masked(self):
        cfg = BSLongformerSparsityConfig(num_heads=2, block=32,
                                         num_sliding_window_blocks=3)
        L = cfg.make_layout(512)
        assert bs.planned_kernel(L, 32, interpret=True).startswith(
            "masked")
        q, k, v = _qkv(B=1, H=2, s=512, seed=1)
        got = bs.block_sparse_attention(q, k, v, L)
        want = bs.block_sparse_attention_reference(q, k, v, L)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-5, rtol=5e-5)

    def test_legacy_flag_restores_old_dispatch(self):
        cfg = BSLongformerSparsityConfig(num_heads=2, block=32,
                                         num_sliding_window_blocks=3)
        L = cfg.make_layout(512)
        old = bs.USE_MASKED_FLASH
        try:
            bs.USE_MASKED_FLASH = False
            assert bs.planned_kernel(L, 32, interpret=True) == "banded"
        finally:
            bs.USE_MASKED_FLASH = old

    def test_v1_never_auto_selected(self):
        """ISSUE 11 satellite: the per-triple v1 kernels are retired as
        a dispatch target — even the historical silent-fallback case
        (compiled mode, unstreamable block, no coarse tile) resolves to
        the masked kernel; only an explicit USE_SPLASH_V2=False (test
        oracle use) reaches v1."""
        layout = np.ones((1, 5, 5), np.int32)      # block 96, S=480:
        assert bs.planned_kernel(layout, 96, interpret=False) \
            .startswith("masked")
        old_m, old_v2 = bs.USE_MASKED_FLASH, bs.USE_SPLASH_V2
        try:
            bs.USE_MASKED_FLASH = False
            # 96 % 128 != 0 and no coarse tile divides 480 -> the old
            # code picked v1 here; now it must route to masked
            assert bs.planned_kernel(layout, 96, interpret=False) == \
                "masked-fallback"
            f = bs._sparse_attention_fn(layout, 96, 0.125, has_am=False,
                                        interpret=False)
            assert f is not None
            bs.USE_SPLASH_V2 = False               # explicit oracle use
            bs._FN_CACHE.clear()
            assert bs.planned_kernel(layout, 96, interpret=False) == "v1"
        finally:
            bs.USE_MASKED_FLASH, bs.USE_SPLASH_V2 = old_m, old_v2
            bs._FN_CACHE.clear()

    def test_flash_attention_routes_masked_by_default(self):
        assert F.get_attention_options().kernel == "masked"
        q, k, v = _qkv(seed=12)
        o = F.flash_attention(q, k, v, causal=True, interpret=True)
        want = F.attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)

    def test_kernel_knob_switches_paths(self):
        q, k, v = _qkv(seed=13)
        old = F.set_attention_options(kernel="flash")
        try:
            o_legacy = F.flash_attention(q, k, v, causal=True,
                                         interpret=True)
        finally:
            F._OPTIONS = old
        o_masked = F.flash_attention(q, k, v, causal=True,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(o_legacy),
                                   np.asarray(o_masked), atol=2e-5)

    def test_bad_kernel_name_rejected(self):
        with pytest.raises(AssertionError):
            F.set_attention_options(kernel="cuda")
        assert F.get_attention_options().kernel == "masked"


# --------------------------------------------------------------------- #
# satellite: module-global hygiene — options + once-logging
# --------------------------------------------------------------------- #
class TestOnceLogging:
    def test_log_once_per_shape_reason(self):
        F.reset_once_logging()
        F.log_once(("x", 128), "m1")
        F.log_once(("x", 128), "m1")
        F.log_once(("x", 256), "m2")
        assert len(F._ONCE_KEYS) == 2
        F.reset_once_logging()
        assert not F._ONCE_KEYS

    def test_unknown_masked_block_logs_single_line(self):
        F.reset_once_logging()
        b1 = F.pick_masked_block(192, 192, 48)
        b2 = F.pick_masked_block(192, 192, 48)
        assert b1 == b2 and 192 % b1 == 0
        keys = [k for k in F._ONCE_KEYS if k[0] == "masked-block"]
        assert len(keys) == 1

    def test_no_mutable_warn_globals_remain(self):
        for name in ("_FORCE_REFERENCE", "_WARNED_IRREGULAR_FALLBACK",
                     "_WARNED_IRREGULAR_STREAM", "_WARNED_REF_STREAM"):
            assert not hasattr(F, name), name

    def test_reference_knob(self):
        q, k, v = _qkv(seed=14)
        old = F.set_attention_options(kernel="reference")
        try:
            o = F.flash_attention(q, k, v, causal=True, interpret=True)
            want = F.attention_reference(q, k, v, causal=True,
                                         mxu_bf16=True)
            np.testing.assert_array_equal(np.asarray(o),
                                          np.asarray(want))
        finally:
            F._OPTIONS = old


# --------------------------------------------------------------------- #
# shard_map head wrap (parallel/pallas_shard)
# --------------------------------------------------------------------- #
class TestShardedMaskedFlash:
    def _mesh(self):
        from deepspeed_tpu.parallel.mesh import build_mesh
        return build_mesh({"model": 2})

    def test_sharded_parity_and_grads(self):
        from deepspeed_tpu.parallel.pallas_shard import \
            sharded_masked_flash
        mesh = self._mesh()
        mask = _mask_for("banded")
        q, k, v = _qkv(seed=15)

        def f_sh(q, k, v):
            return jnp.sum(sharded_masked_flash(
                q, k, v, mask, mesh=mesh, interpret=True) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(masked_flash_reference(q, k, v, mask) ** 2)

        o = sharded_masked_flash(q, k, v, mask, mesh=mesh,
                                 interpret=True)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(masked_flash_reference(q, k, v,
                                                             mask)),
            atol=5e-5)
        gs = jax.grad(f_sh, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, n in zip(gs, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=1e-3,
                                       err_msg=f"d{n}")

    def test_sharded_gqa_under_jit(self):
        from deepspeed_tpu.parallel.pallas_shard import \
            sharded_masked_flash
        mesh = self._mesh()
        mask = _mask_for("causal")
        q, k, v = _qkv(hkv=2, seed=16)
        f = jax.jit(lambda q, k, v: sharded_masked_flash(
            q, k, v, mask, mesh=mesh, interpret=True))
        np.testing.assert_allclose(
            np.asarray(f(q, k, v)),
            np.asarray(masked_flash_reference(q, k, v, mask)),
            atol=5e-5)

    def test_per_head_mask_rejected(self):
        from deepspeed_tpu.parallel.pallas_shard import \
            sharded_masked_flash
        mesh = self._mesh()
        active = np.ones((4, S // BLOCK, S // BLOCK), bool)
        active[1, 0, 0] = False                    # heads differ
        mask = BlockMask(active, np.zeros_like(active, np.uint8),
                         BLOCK, S, S)
        q, k, v = _qkv()
        with pytest.raises(AssertionError, match="head-uniform"):
            sharded_masked_flash(q, k, v, mask, mesh=mesh,
                                 interpret=True)


# --------------------------------------------------------------------- #
# cost model (the masked_flash_flops_bytes bench row's engine)
# --------------------------------------------------------------------- #
class TestCostModel:
    def test_work_proportional_to_nonzero_blocks(self):
        dense = _mask_for("dense")
        bird = _mask_for("bigbird")
        cd = masked_flash_cost(dense, batch=1, heads=4, head_dim=64)
        cb = masked_flash_cost(bird, batch=1, heads=4, head_dim=64)
        # FLOPs scale exactly with items at equal block size
        assert cd["flops"] / cb["flops"] == pytest.approx(
            cd["items"] / cb["items"])
        assert cb["bytes"] < cd["bytes"]

    def test_item_counts_match_csr(self):
        mask = _mask_for("bigbird")
        offs, cnts, cols, kinds = mask.csr()
        assert int(cnts.sum()) == mask.nnz == len(cols)
        coffs, ccnts, crows, ckinds = mask.csc()
        assert int(ccnts.sum()) == mask.nnz
