"""End-to-end `bin/dstpu` CLI tests (VERDICT r4 #5).

The reference's model tests drive real training through the deepspeed
CLI (tests/model/Megatron_GPT2/run_func_test.py:20-36). These do the
same for `bin/dstpu`: a real subprocess of the installed entry point —
argv parsing, launcher selection, env propagation (DSTPU_* identity
vars, `.deepspeed_env` exports, DSTPU_WORLD_INFO), and exit-code
plumbing — none of which the in-process `runpy` example smokes
(test_examples.py) exercise.

Children force the CPU backend via DSTPU_PLATFORM (the examples'
apply_platform_env), never the tunnel.
"""

import base64
import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow          # real subprocesses, fresh jax init

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DSTPU = os.path.join(REPO, "bin", "dstpu")


def _env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["DSTPU_PLATFORM"] = "cpu"
    env["DSTPU_HOST_DEVICES"] = "1"
    env.update(extra or {})
    return env


def _run(argv, cwd=None, extra_env=None, timeout=420):
    return subprocess.run(
        [sys.executable, DSTPU] + argv, cwd=cwd or REPO, env=_env(extra_env),
        capture_output=True, text=True, timeout=timeout)


def test_dstpu_local_launcher_trains():
    """`dstpu --launcher local <script>` must run real training end to
    end: the tiny megatron example takes steps and reports losses."""
    r = _run(["--launcher", "local",
              os.path.join(REPO, "examples", "megatron_gpt2", "train.py"),
              "--mode", "zero2", "--tiny", "--steps", "2"])
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "step 0: lm loss" in r.stdout, r.stdout[-2000:]
    assert "step 1: lm loss" in r.stdout, r.stdout[-2000:]


def test_dstpu_propagates_exit_code(tmp_path):
    """A failing user script's exit code must surface as dstpu's own
    (reference runner.py:356)."""
    script = tmp_path / "fail.py"
    script.write_text("import sys; sys.exit(3)\n")
    r = _run(["--launcher", "local", str(script)])
    assert r.returncode == 3, (r.returncode, r.stderr[-500:])


def test_dstpu_hostfile_env_propagation(tmp_path):
    """A localhost hostfile drives the ssh-runner command construction
    (env export line, DSTPU_* identity vars, world info, .deepspeed_env
    pickup) executed via the /bin/sh local shortcut — and the launched
    script trains a real step through deepspeed_tpu.initialize."""
    (tmp_path / "hostfile").write_text("localhost slots=1\n")
    (tmp_path / ".deepspeed_env").write_text("DSTPU_TEST_ENVVAR=42\n")
    script = tmp_path / "user.py"
    script.write_text(textwrap.dedent("""
        import base64, json, os
        from deepspeed_tpu.utils.platform import apply_platform_env
        apply_platform_env()
        assert os.environ["DSTPU_TEST_ENVVAR"] == "42"      # .deepspeed_env
        assert os.environ["DSTPU_NUM_PROCESSES"] == "1"
        assert os.environ["DSTPU_PROCESS_ID"] == "0"
        assert "DSTPU_COORDINATOR" in os.environ
        wi = json.loads(base64.urlsafe_b64decode(
            os.environ["DSTPU_WORLD_INFO"]))
        assert wi == {"localhost": [0]}, wi     # host -> slot indices
        import jax
        import jax.numpy as jnp
        import numpy as np
        import deepspeed_tpu as ds
        ds.init_distributed()          # 1 process: documented no-op
        def loss_fn(params, batch, rngs=None):
            p = jnp.tanh(batch["x"] @ params["w"])
            return jnp.mean((p - batch["y"]) ** 2)
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8, 4))}
        engine, *_ = ds.initialize(
            model=loss_fn, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
        rs = np.random.RandomState(0)
        b = {"x": rs.randn(4, 8).astype(np.float32),
             "y": rs.randn(4, 4).astype(np.float32)}
        loss = engine.train_batch(iter([b]))
        print("CLI_E2E_TRAIN_OK", float(loss))
    """))
    r = _run(["--hostfile", str(tmp_path / "hostfile"), str(script)],
             cwd=str(tmp_path))
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert "CLI_E2E_TRAIN_OK" in r.stdout, r.stdout[-2000:]
