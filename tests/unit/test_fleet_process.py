"""Process-isolated serving fleet (ISSUE 16): RPC replicas, live
KV-page migration, supervised restart, goodput-driven autoscale.

Tier-1 acceptance pins:
- killing a replica CHILD PROCESS mid-decode (env-armed
  ``serve.replica_kill``, fired only while a request holds a pending
  token) preserves every output BITWISE via live page migration — the
  dying child exports each in-flight request's live KV pages in its
  deathbed frame, a survivor imports them and resumes decode at the
  same cache_position, no re-prefill; zero dropped uids, zero
  steady-state recompiles on survivors, the dead child's flight
  recorder salvaged into the router's event trail, and the child
  relaunched under the launcher's 85/87 restart policy;
- the RPC framing / pinned error taxonomy / bounded-backoff retry
  policy is testable jax-free over a socketpair in microseconds;
- ``FleetRouter.drain()`` is idempotent — a double drain is ONE
  episode, exactly one FinishedRequest per uid;
- death supervision honors ``restart_eligible`` (85/87 relaunch,
  anything else retires) and the ``max_restarts`` budget;
- autoscale: sustained shedding spawns a replica, sustained idleness
  drains one, hysteresis + cooldown, never below ``min_replicas``.
"""

import json
import os
import socket
import threading

import numpy as np
import pytest

from deepspeed_tpu.inference import rpc
from deepspeed_tpu.inference.disagg import MigrationRecord
from deepspeed_tpu.inference.rpc import (ReplicaDeadError, RpcClient,
                                         RpcRemoteError, RpcServer,
                                         RpcTimeoutError,
                                         RpcTransportError, ServerExit)
from deepspeed_tpu.runtime import fault

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _load_tool(name):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mig_record(uid=7, pages=2, page_bytes=64):
    k = np.arange(2 * pages * 2 * 4 * 4, dtype=np.float32
                  ).reshape(2, pages, 2, 4, 4)
    return MigrationRecord(
        uid=uid, prompt=[1, 2, 3], max_new_tokens=8, temperature=0.5,
        seed=11, eos_id=None, priority=1, position=5, pending_tok=42,
        tokens=[42, 17], live_pages=pages, page_bytes=page_bytes,
        ttft_ms=1.5, queue_wait_ms=0.25, elapsed_ms=3.0,
        kslab=k, vslab=k + 1000.0)


# ===================================================================== #
# wire format (jax-free, socketpair)
# ===================================================================== #

class TestRpcWire:
    def test_frame_roundtrip_with_payload(self):
        a, b = socket.socketpair()
        try:
            rpc.send_frame(a, {"method": "x", "params": {"n": 3}},
                           b"\x00\x01slab")
            head, payload = rpc.recv_frame(b)
            assert head == {"method": "x", "params": {"n": 3}}
            assert payload == b"\x00\x01slab"
        finally:
            a.close()
            b.close()

    def test_eof_is_replica_dead(self):
        a, b = socket.socketpair()
        a.close()
        try:
            with pytest.raises(ReplicaDeadError):
                rpc.recv_frame(b)
        finally:
            b.close()

    def test_desynced_header_is_transport_error(self):
        a, b = socket.socketpair()
        try:
            # garbage bytes parse as an absurd length prefix
            a.sendall(b"\xff\xff\xff\xff\xff\xff\xff\xff")
            with pytest.raises(RpcTransportError):
                rpc.recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_array_codec_roundtrip(self):
        arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
                  np.array([[1, 2], [3, 4]], dtype=np.int32)]
        metas, blob = rpc.encode_arrays(arrays)
        back = rpc.decode_arrays(metas, blob)
        for orig, got in zip(arrays, back):
            assert got.dtype == orig.dtype and got.shape == orig.shape
            np.testing.assert_array_equal(got, orig)

    def test_array_codec_bfloat16(self):
        # KV slabs ship in the serving dtype; bf16 resolves through
        # ml_dtypes without importing jax
        import ml_dtypes
        a = np.arange(8).astype(ml_dtypes.bfloat16).reshape(2, 4)
        metas, blob = rpc.encode_arrays([a])
        assert metas[0]["dtype"] == "bfloat16"
        (back,) = rpc.decode_arrays(metas, blob)
        np.testing.assert_array_equal(
            back.astype(np.float32), a.astype(np.float32))

    def test_request_wire_roundtrip_keeps_uid_and_seed(self):
        from deepspeed_tpu.inference import Request
        req = Request(prompt=[5, 6, 7], max_new_tokens=9,
                      temperature=0.3, seed=123, priority=2, uid=77)
        back = rpc.request_from_wire(rpc.request_to_wire(req))
        assert (back.uid, back.seed, back.priority) == (77, 123, 2)
        assert back.prompt == [5, 6, 7]
        assert back.max_new_tokens == 9
        assert back.temperature == pytest.approx(0.3)

    def test_migration_wire_roundtrip_bitwise(self):
        rec = _mig_record()
        head, payload = rpc.migration_to_wire(rec)
        back = rpc.migration_from_wire(head, payload)
        assert back.uid == rec.uid and back.position == rec.position
        assert back.pending_tok == rec.pending_tok
        assert back.tokens == rec.tokens
        assert back.live_pages == rec.live_pages
        np.testing.assert_array_equal(back.kslab, rec.kslab)
        np.testing.assert_array_equal(back.vslab, rec.vslab)
        assert back.nbytes == rec.nbytes

    def test_decode_migrations_unpacks_concatenated_deathbed(self):
        r1, r2 = _mig_record(uid=1, pages=1), _mig_record(uid=2,
                                                          pages=3)
        h1, p1 = rpc.migration_to_wire(r1)
        h2, p2 = rpc.migration_to_wire(r2)
        back = rpc.decode_migrations([h1, h2], p1 + p2)
        assert [b.uid for b in back] == [1, 2]
        np.testing.assert_array_equal(back[1].vslab, r2.vslab)


# ===================================================================== #
# client policy: timeout, retry/backoff, taxonomy fault points
# ===================================================================== #

def _serve_in_thread(dispatch):
    """An RpcServer on one end of a socketpair, client on the other."""
    a, b = socket.socketpair()
    t = threading.Thread(target=lambda: RpcServer(b).serve(dispatch),
                         daemon=True)
    t.start()
    return a, b, t


class TestRpcClient:
    def test_call_roundtrip_and_payload(self):
        def dispatch(method, params, payload):
            return {"echo": method, "n": params["n"] + 1}, payload * 2
        a, b, t = _serve_in_thread(dispatch)
        try:
            c = RpcClient(a, timeout_s=10.0)
            res, payload = c.call("ping", {"n": 1}, b"xy")
            assert res == {"echo": "ping", "n": 2}
            assert payload == b"xyxy"
            assert c.calls == 1 and c.retried == 0
        finally:
            a.close()
            b.close()

    def test_remote_error_keeps_channel_alive(self):
        def dispatch(method, params, payload):
            if method == "bad":
                raise ValueError("handler exploded")
            return {"ok_method": method}, b""
        a, b, t = _serve_in_thread(dispatch)
        try:
            c = RpcClient(a, timeout_s=10.0)
            with pytest.raises(RpcRemoteError) as ei:
                c.call("bad")
            assert ei.value.kind == "remote"
            # the engine survived the handler failure — next call works
            res, _ = c.call("good")
            assert res == {"ok_method": "good"}
        finally:
            a.close()
            b.close()

    def test_server_exit_replies_then_stops(self):
        def dispatch(method, params, payload):
            raise ServerExit(result={"bye": True}, payload=b"last")
        a, b, t = _serve_in_thread(dispatch)
        try:
            c = RpcClient(a, timeout_s=10.0)
            res, payload = c.call("shutdown")
            assert res == {"bye": True} and payload == b"last"
            t.join(timeout=5.0)
            assert not t.is_alive()
        finally:
            a.close()
            b.close()

    def test_transport_fault_retried_with_exponential_backoff(self):
        def dispatch(method, params, payload):
            return {"served": True}, b""
        a, b, t = _serve_in_thread(dispatch)
        sleeps = []
        try:
            fault.arm("rpc.transport",
                      exc=OSError("injected flake"), times=2)
            c = RpcClient(a, timeout_s=10.0, retries=2, backoff_s=0.05,
                          sleep=sleeps.append)
            res, _ = c.call("step")
            assert res == {"served": True}
            assert c.retried == 2
            assert sleeps == [0.05, 0.1]      # backoff_s * 2**attempt
        finally:
            fault.reset()
            a.close()
            b.close()

    def test_transport_fault_exhausts_retries(self):
        a, b = socket.socketpair()
        try:
            fault.arm("rpc.transport", exc=OSError("flake"), times=99)
            c = RpcClient(a, timeout_s=10.0, retries=1, backoff_s=0.0,
                          sleep=lambda s: None)
            with pytest.raises(RpcTransportError):
                c.call("step")
            assert c.retried == 1
        finally:
            fault.reset()
            a.close()
            b.close()

    @pytest.mark.parametrize("point,err", [
        ("rpc.timeout", RpcTimeoutError),
        ("rpc.replica_dead", ReplicaDeadError),
    ])
    def test_timeout_and_death_are_never_retried(self, point, err):
        a, b = socket.socketpair()
        sleeps = []
        try:
            fault.arm(point, exc=fault.InjectedCrash(point), times=9)
            c = RpcClient(a, timeout_s=10.0, retries=5, backoff_s=0.1,
                          sleep=sleeps.append)
            with pytest.raises(err) as ei:
                c.call("step")
            assert ei.value.kind == point.split(".", 1)[1]
            assert ei.value.method == "step"
            assert sleeps == [] and c.retried == 0
            assert fault.get_injector().fired(point) == 1
        finally:
            fault.reset()
            a.close()
            b.close()

    def test_real_deadline_is_timeout_error(self):
        a, b = socket.socketpair()   # nobody ever replies
        try:
            c = RpcClient(a, timeout_s=0.05, retries=3,
                          sleep=lambda s: None)
            with pytest.raises(RpcTimeoutError):
                c.call("step")
            assert c.retried == 0    # timeouts are terminal, no retry
        finally:
            a.close()
            b.close()


# ===================================================================== #
# death supervision + autoscale on duck-typed fakes (fleet.py is
# jax-free: policy is unit-testable in microseconds)
# ===================================================================== #

class _Events:
    def __init__(self):
        self.rows = []

    def add_event(self, kind, **fields):
        self.rows.append({"event": kind, **fields})

    def kinds(self):
        return [r["event"] for r in self.rows]

    def of(self, kind):
        return [r for r in self.rows if r["event"] == kind]


class _FakeSched:
    def __init__(self):
        self.queue = []
        self.total_tokens = 0
        self.occupancy = 0.0

    @property
    def queue_depth(self):
        return len(self.queue)

    def active_slots(self):
        return []

    def idle(self):
        return not self.queue


class _FakeProcEngine:
    """The ReplicaProcess surface the router supervises: dies on
    command with a deathbed ReplicaDeadError, then supports
    poll_exit/orphans/relaunch."""

    def __init__(self, exit_code=85, relaunch_ok=True,
                 can_migrate=False):
        self.scheduler = _FakeSched()
        self.exit_code = exit_code
        self.relaunch_ok = relaunch_ok
        self.can_migrate = can_migrate
        self.die_next_step = False
        self.deathbed_exports = []
        self.relaunches = 0
        self.imported = []
        self.flight_path = None
        self.pid = 4242
        self.monitor = None
        self._log = None
        self.steady_state_recompiles = 0
        self.weight_version = "initial"
        self.weight_ordinal = 0

    def submit(self, req):
        self.scheduler.queue.append(req)
        return req.uid

    def step(self):
        from deepspeed_tpu.inference import FinishedRequest
        if self.die_next_step:
            self.die_next_step = False
            # mirror ReplicaProcess._call: deathbed-exported uids answer
            # through migration, never through orphans()
            gone = {r.uid for r in self.deathbed_exports}
            self.scheduler.queue = [r for r in self.scheduler.queue
                                    if r.uid not in gone]
            raise ReplicaDeadError(
                "fake child died", exports=list(self.deathbed_exports),
                reason="kill")
        fins = [FinishedRequest(
            uid=r.uid, prompt=list(r.prompt),
            tokens=[1] * r.max_new_tokens, finish_reason="length",
            ttft_ms=1.0, latency_ms=1.0)
            for r in self.scheduler.queue]
        self.scheduler.queue = []
        self.scheduler.total_tokens += sum(len(f.tokens) for f in fins)
        return fins

    def cancel(self, uid, reason="evicted"):
        from deepspeed_tpu.inference import FinishedRequest
        for i, r in enumerate(self.scheduler.queue):
            if r.uid == uid:
                del self.scheduler.queue[i]
                return FinishedRequest(
                    uid=uid, prompt=list(r.prompt), tokens=[],
                    finish_reason=reason, ttft_ms=None, latency_ms=0.0)
        return None

    def set_speculation(self, on):
        return False

    def poll_exit(self, timeout_s=10.0):
        return self.exit_code

    def orphans(self):
        return list(self.scheduler.queue)

    def relaunch(self):
        if not self.relaunch_ok:
            raise OSError("spawn failed")
        self.relaunches += 1
        self.scheduler = _FakeSched()

    def import_request(self, rec):
        if not self.can_migrate:
            return None
        from deepspeed_tpu.inference import Request
        self.imported.append(rec)
        self.scheduler.queue.append(Request(
            prompt=list(rec.prompt),
            max_new_tokens=rec.max_new_tokens,
            temperature=rec.temperature, seed=rec.seed,
            eos_id=rec.eos_id, priority=rec.priority, uid=rec.uid))
        return len(self.imported) - 1


def _req(uid, prompt=(1, 2, 3), max_new=4):
    from deepspeed_tpu.inference import Request
    return Request(prompt=list(prompt), max_new_tokens=max_new,
                   temperature=0.0, seed=0, uid=uid)


def _router(engines, fleet_config=None, **kw):
    from deepspeed_tpu.inference import FleetRouter
    ev = _Events()
    return FleetRouter(engines, fleet_config or {}, writer=ev,
                       **kw), ev


class TestDeathSupervision:
    def test_exit_85_relaunches_and_redistributes(self):
        dying = _FakeProcEngine(exit_code=85)
        survivor = _FakeProcEngine()
        router, ev = _router([dying, survivor],
                             {"process_mode": {"max_restarts": 1,
                                               "restart_backoff_s": 0.5}},
                             sleep=lambda s: None)
        uids = [router.submit(_req(u)) for u in range(4)]
        dying.die_next_step = True
        fins = router.run()
        # zero dropped, exactly one answer per uid — the dead child's
        # queued requests moved to the survivor with the same uids
        assert sorted(f.uid for f in fins) == sorted(uids)
        r0 = router.replicas[0]
        assert r0.status == "live" and r0.restarts == 1
        assert r0.last_exit_code == 85
        assert dying.relaunches == 1
        assert router.total_restarts == 1
        death = ev.of("fleet_replica_death")
        assert death and death[0]["exit_code"] == 85
        restart = ev.of("fleet_replica_restart")
        assert restart[0]["decision"] == "restarted"
        assert restart[0]["backoff_s"] == pytest.approx(0.5)
        # relaunched replica serves again
        more = [router.submit(_req(u)) for u in (10, 11)]
        fins2 = router.run()
        assert sorted(f.uid for f in fins2) == sorted(more)

    @pytest.mark.parametrize("code", [87])
    def test_exit_87_is_restart_eligible(self, code):
        dying = _FakeProcEngine(exit_code=code)
        router, ev = _router([dying, _FakeProcEngine()],
                             {"process_mode": {"max_restarts": 1,
                                               "restart_backoff_s": 0.0}})
        router.submit(_req(0))
        dying.die_next_step = True
        router.run()
        assert router.replicas[0].status == "live"
        assert dying.relaunches == 1

    @pytest.mark.parametrize("code", [1, 143, None])
    def test_non_resumable_exit_gives_up(self, code):
        dying = _FakeProcEngine(exit_code=code)
        router, ev = _router([dying, _FakeProcEngine()],
                             {"process_mode": {"max_restarts": 3,
                                               "restart_backoff_s": 0.0}})
        uids = [router.submit(_req(u)) for u in range(2)]
        dying.die_next_step = True
        fins = router.run()
        assert sorted(f.uid for f in fins) == sorted(uids)  # no drops
        assert router.replicas[0].status == "retired"
        assert dying.relaunches == 0
        assert ev.of("fleet_replica_restart")[0]["decision"] == \
            "give_up"

    def test_restart_budget_exhausts(self):
        dying = _FakeProcEngine(exit_code=85)
        router, ev = _router([dying, _FakeProcEngine()],
                             {"process_mode": {"max_restarts": 0}})
        router.submit(_req(0))
        dying.die_next_step = True
        router.run()
        assert router.replicas[0].status == "retired"
        assert ev.of("fleet_replica_restart")[0]["decision"] == \
            "exhausted"

    def test_deathbed_exports_resume_on_survivor(self):
        rec = _mig_record(uid=5)
        dying = _FakeProcEngine(exit_code=85, relaunch_ok=False)
        dying.deathbed_exports = [rec]
        survivor = _FakeProcEngine(can_migrate=True)
        router, ev = _router(
            [dying, survivor],
            {"process_mode": {"max_restarts": 1,
                              "restart_backoff_s": 0.0}})
        router.submit(_req(5))
        dying.die_next_step = True
        fins = router.run()
        # the export landed on the survivor (no resubmit fallback)
        assert [r.uid for r in survivor.imported] == [5]
        assert router.total_migrated == 1
        assert router.migration_bytes == rec.nbytes
        assert [f.uid for f in fins] == [5]
        mig = ev.of("serve_migration")
        assert mig and mig[0]["uid"] == 5 and mig[0]["dst"] == 1
        # per-replica ledger feeds the fleet_replica_state rows
        assert router.replicas[0].migrations_out == 1
        assert router.replicas[1].migrations_in == 1
        # relaunch failed -> stays retired, event says so
        assert router.replicas[0].status == "retired"
        assert ev.of("fleet_replica_restart")[0]["decision"] == \
            "failed"

    def test_flight_recorder_salvaged(self, tmp_path):
        flight = tmp_path / "flight_serve.json"
        flight.write_text(json.dumps(
            {"trigger": "replica_death", "pid": 999,
             "reason": "kill", "rows": [{"kind": "heartbeat"}] * 3}))
        dying = _FakeProcEngine(exit_code=1)
        dying.flight_path = str(flight)
        router, ev = _router([dying, _FakeProcEngine()])
        router.submit(_req(0))
        dying.die_next_step = True
        router.run()
        assert router.total_salvaged == 1
        sal = ev.of("fleet_flight_salvage")
        assert sal[0]["replica"] == 0
        assert sal[0]["trigger"] == "replica_death"
        assert sal[0]["dead_pid"] == 999 and sal[0]["rows"] == 3

    def test_torn_flight_file_salvages_nothing(self, tmp_path):
        flight = tmp_path / "flight_serve.json"
        flight.write_text('{"trigger": "repl')   # torn write
        dying = _FakeProcEngine(exit_code=1)
        dying.flight_path = str(flight)
        router, ev = _router([dying, _FakeProcEngine()])
        router.submit(_req(0))
        dying.die_next_step = True
        router.run()
        assert router.total_salvaged == 0
        assert not ev.of("fleet_flight_salvage")


class TestDrainIdempotent:
    def test_double_drain_is_one_episode(self):
        """Bugfix pin: drain() called twice on the same replica must
        not restart the episode or redistribute twice — exactly one
        FinishedRequest per uid, one fleet_drain begin row."""
        fakes = [_FakeProcEngine(), _FakeProcEngine()]
        router, ev = _router(fakes)
        uids = [router.submit(_req(u)) for u in range(4)]
        router.drain(0, reason="manual")
        router.drain(0, reason="manual")        # idempotent: no-op
        fins = router.run()
        assert sorted(f.uid for f in fins) == sorted(uids)
        assert len(fins) == len(uids)           # EXACTLY one per uid
        begins = [r for r in ev.of("fleet_drain")
                  if r["phase"] == "begin"]
        assert len(begins) == 1
        assert router.replicas[0].status == "retired"
        # draining a retired replica is also a no-op
        router.drain(0)
        assert router.replicas[0].status == "retired"
        assert len([r for r in ev.of("fleet_drain")
                    if r["phase"] == "begin"]) == 1


class TestAutoscale:
    ASC = {"enabled": True, "min_replicas": 1, "max_replicas": 3,
           "scale_up_patience": 2, "scale_down_patience": 3,
           "cooldown_steps": 0}

    def test_sustained_shed_spawns_replica(self):
        spawned = []

        def factory(idx):
            e = _FakeProcEngine()
            spawned.append(idx)
            return e

        router, ev = _router([_FakeProcEngine()],
                             {"autoscale": dict(self.ASC)},
                             replica_factory=factory)
        router.shed_level = lambda: 1            # pin the ladder hot
        router.step()
        assert spawned == []                     # patience: not yet
        router.step()
        assert spawned == [1]                    # streak hit patience
        assert len(router.replicas) == 2
        up = ev.of("fleet_autoscale")
        assert up[0]["action"] == "up" and up[0]["replica"] == 1

    def test_scale_up_respects_max_replicas(self):
        router, ev = _router(
            [_FakeProcEngine() for _ in range(3)],
            {"autoscale": dict(self.ASC)},
            replica_factory=lambda i: _FakeProcEngine())
        router.shed_level = lambda: 2
        # pin one replica busy so the idle rung never competes
        router.replicas[0].engine.scheduler.active_slots = lambda: [1]
        for _ in range(8):
            router.step()
        assert len(router.replicas) == 3         # already at max
        assert not ev.of("fleet_autoscale")

    def test_sustained_idle_drains_one_never_below_min(self):
        router, ev = _router([_FakeProcEngine(), _FakeProcEngine()],
                             {"autoscale": dict(self.ASC)})
        for _ in range(10):
            router.step()
        live = [r for r in router.replicas if r.status == "live"]
        assert len(live) == 1                    # one drained away...
        downs = ev.of("fleet_autoscale")
        assert downs and downs[0]["action"] == "down"
        for _ in range(10):
            router.step()
        live = [r for r in router.replicas if r.status == "live"]
        assert len(live) == 1                    # ...but never below min

    def test_cooldown_spaces_actions(self):
        asc = dict(self.ASC, cooldown_steps=5, scale_up_patience=1,
                   max_replicas=4)
        router, ev = _router([_FakeProcEngine()],
                             {"autoscale": asc},
                             replica_factory=lambda i:
                             _FakeProcEngine())
        router.shed_level = lambda: 1
        for _ in range(6):
            router.step()
        # 6 steps, patience 1, cooldown 5: one spawn, not five
        assert len(ev.of("fleet_autoscale")) == 1

    def test_disabled_by_default(self):
        router, ev = _router([_FakeProcEngine(), _FakeProcEngine()])
        for _ in range(100):
            router.step()
        assert not ev.of("fleet_autoscale")
        assert all(r.status == "live" for r in router.replicas)


class TestProcessModeConfig:
    def test_defaults(self):
        from deepspeed_tpu.runtime.config import get_inference_config
        fl = get_inference_config({"inference": {}})["fleet"]
        pm = fl["process_mode"]
        assert pm["enabled"] is False
        assert pm["max_restarts"] == 1
        assert pm["rpc_retries"] == 2
        asc = fl["autoscale"]
        assert asc["enabled"] is False
        assert asc["min_replicas"] == 1
        assert asc["max_replicas"] == 4
        assert asc["scale_up_patience"] < asc["scale_down_patience"]

    @pytest.mark.parametrize("section,bad", [
        ("process_mode", {"rpc_timeout_s": 0}),
        ("process_mode", {"rpc_retries": -1}),
        ("process_mode", {"max_restarts": -2}),
        ("autoscale", {"min_replicas": 0}),
        ("autoscale", {"min_replicas": 3, "max_replicas": 2}),
        ("autoscale", {"scale_up_patience": 0}),
        ("autoscale", {"cooldown_steps": -1}),
    ])
    def test_rejects_bad_values(self, section, bad):
        from deepspeed_tpu.runtime.config import (DeepSpeedConfigError,
                                                  get_inference_config)
        with pytest.raises(DeepSpeedConfigError):
            get_inference_config(
                {"inference": {"fleet": {section: bad}}})


# ===================================================================== #
# the real thing: child processes, kill mid-decode, live migration
# ===================================================================== #

MCFG = {"vocab_size": 61, "max_position_embeddings": 64,
        "hidden_size": 32, "num_layers": 2, "num_heads": 4,
        "embd_dropout": 0.0, "attn_dropout": 0.0, "resid_dropout": 0.0}
ICFG = {"max_batch_size": 2, "prompt_buckets": [8, 16],
        "batch_buckets": [1, 2], "max_seq_len": 48}


def _mixed_requests(uids):
    """Half greedy, half seeded-sampled — migration must preserve both
    bitwise (sampling keys fold in the absolute position, so a resumed
    decode draws the same tokens)."""
    from deepspeed_tpu.inference import Request
    return [Request(prompt=[1 + u, 2, 3, 4, (5 + u) % 61],
                    max_new_tokens=8,
                    temperature=0.0 if i % 2 == 0 else 0.7,
                    seed=100 + u, uid=u)
            for i, u in enumerate(uids)]


@pytest.fixture(scope="module")
def proc_fleet_run(tmp_path_factory):
    """One expensive end-to-end run shared by the assertions below:
    3 replica children; child 0 armed to crash mid-decode (phase A),
    then a double-drain of child 1 mid-decode (phase B)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.inference.fleet import (FleetRouter,
                                               launch_replica_processes)
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    from deepspeed_tpu.utils.monitor import _JsonlWriter

    cfg = GPT2Config(**MCFG)
    params = init_gpt2_params(cfg, jax.random.PRNGKey(3))

    # single-engine baseline, same uids/seeds/temps
    eng = InferenceEngine(cfg, params, ICFG, dtype=jnp.float32)
    eng.warmup()
    for r in _mixed_requests(range(4)):
        eng.submit(r)
    base_a = {f.uid: tuple(f.tokens) for f in eng.run()}
    for r in _mixed_requests(range(10, 14)):
        eng.submit(r)
    base_b = {f.uid: tuple(f.tokens) for f in eng.run()}
    eng.close()

    fdir = str(tmp_path_factory.mktemp("flights"))
    evdir = str(tmp_path_factory.mktemp("fleet_proc_events"))
    rbase = str(tmp_path_factory.mktemp("fleet_proc_replica_events"))
    hdir = str(tmp_path_factory.mktemp("fleet_proc_router_health"))
    # children must sample from the SAME prng stream as this process:
    # conftest.py flips jax_threefry_partitionable via jax.config (an
    # in-process setting a spawned child never sees), so mirror it as
    # an env var — XLA_FLAGS (8-device host platform) already inherits
    # through os.environ. Without this the baseline and the replicas
    # draw different tokens for every temperature>0 request.
    env = {"JAX_PLATFORMS": "cpu", "JAX_THREEFRY_PARTITIONABLE": "1"}
    kill_env = dict(env, DSTPU_FAULT_ARM="serve.replica_kill:crash:1")
    spec = {"family": "gpt2", "model_config": MCFG, "init_seed": 3,
            "dtype": "float32", "inference": ICFG}
    # fleet tracing fully ON (ISSUE 18): each child writes its own
    # serve trail (per-replica events.jsonl) stamped with replica_id;
    # the bitwise-parity assertions below double as the tracing-
    # enabled zero-perturbation pin
    obs = lambda i: {  # noqa: E731
        "observability": {
            "enabled": True, "serve": {"enabled": True},
            "health": {
                "enabled": True,
                "flight_path": os.path.join(fdir, f"flight_r{i}.json")}},
        "inference": dict(ICFG, events_dir=os.path.join(rbase,
                                                        f"r{i}"))}
    reps = launch_replica_processes(
        spec, 3, env_by_replica={0: kill_env, 1: env, 2: env},
        spec_by_replica={i: obs(i) for i in range(3)})
    writer = _JsonlWriter(evdir)
    # the router owns its own HealthPlane in process mode (children's
    # planes live across the process boundary) — its rpc_call beats
    # name which replica each blocking wait was on
    from deepspeed_tpu.utils.health import HealthPlane
    hp = HealthPlane({"enabled": True, "stall_timeout_s": 300.0},
                     events_dir=hdir)
    router = FleetRouter(
        reps, {"process_mode": {"enabled": True, "max_restarts": 1,
                                "restart_backoff_s": 0.0}},
        writer=writer, health=hp)
    out = {"evdir": evdir, "fdir": fdir, "base_a": base_a,
           "base_b": base_b,
           "rdirs": [os.path.join(rbase, f"r{i}") for i in range(3)]}
    try:
        out["pid0_before"] = reps[0].pid
        # the armed kill must fire exactly once: relaunch re-merges
        # _env into the child environment, so drop the arm now or the
        # phase-A replacement child re-arms and dies again in phase B
        reps[0]._env.pop("DSTPU_FAULT_ARM", None)
        # ---- phase A: armed child 0 crashes at its first mid-decode
        # step; deathbed exports migrate, child relaunches
        uids_a = [router.submit(r) for r in _mixed_requests(range(4))]
        fins_a = router.run()
        out["uids_a"] = uids_a
        out["fins_a"] = [(f.uid, tuple(f.tokens), f.finish_reason)
                         for f in fins_a]
        out["migrated_a"] = router.total_migrated
        out["restarts"] = router.total_restarts
        out["salvaged"] = router.total_salvaged
        out["r0"] = (router.replicas[0].status,
                     router.replicas[0].last_exit_code,
                     router.replicas[0].restarts)
        out["pid0_after"] = reps[0].pid
        # ---- phase B: drain replica 1 mid-decode, twice (idempotent);
        # its in-flight requests migrate over the RPC channel
        uids_b = [router.submit(r)
                  for r in _mixed_requests(range(10, 14))]
        fins_b = list(router.step())     # prefills land, decode starts
        router.drain(1, reason="manual")
        router.drain(1, reason="manual")          # must be a no-op
        fins_b += router.run()
        out["uids_b"] = uids_b
        out["fins_b"] = [(f.uid, tuple(f.tokens), f.finish_reason)
                         for f in fins_b]
        out["migrated_b"] = router.total_migrated
        out["migration_bytes"] = router.migration_bytes
        out["recompiles"] = [r.steady_state_recompiles for r in reps]
        out["statuses"] = [r.status for r in router.replicas]
        out["debug"] = router.debug_state()
    finally:
        router.close()
        writer.close()
        hp.close()
    rows = [json.loads(l) for l in
            open(os.path.join(evdir, "events.jsonl")) if l.strip()]
    out["events"] = rows
    return out


class TestProcessFleetKill:
    def test_child_really_died_and_relaunched(self, proc_fleet_run):
        status, exit_code, restarts = proc_fleet_run["r0"]
        assert exit_code == 85            # deathbed exit: resumable
        assert status == "live" and restarts == 1
        assert proc_fleet_run["restarts"] == 1
        # a NEW process, not a revived socket
        assert proc_fleet_run["pid0_after"] != \
            proc_fleet_run["pid0_before"]

    def test_kill_mid_decode_outputs_bitwise_zero_dropped(
            self, proc_fleet_run):
        got = {u: t for u, t, _ in proc_fleet_run["fins_a"]}
        assert sorted(got) == sorted(proc_fleet_run["uids_a"])
        assert len(proc_fleet_run["fins_a"]) == \
            len(proc_fleet_run["uids_a"])       # exactly one per uid
        assert got == proc_fleet_run["base_a"]  # BITWISE
        assert proc_fleet_run["migrated_a"] >= 1

    def test_double_drain_migrates_in_flight_bitwise(
            self, proc_fleet_run):
        got = {u: t for u, t, _ in proc_fleet_run["fins_b"]}
        assert sorted(got) == sorted(proc_fleet_run["uids_b"])
        assert len(proc_fleet_run["fins_b"]) == \
            len(proc_fleet_run["uids_b"])
        assert got == proc_fleet_run["base_b"]
        # drain moved live pages (phase B migrated on top of phase A)
        assert proc_fleet_run["migrated_b"] > \
            proc_fleet_run["migrated_a"]
        assert proc_fleet_run["statuses"][1] == "retired"
        begins = [r for r in proc_fleet_run["events"]
                  if r.get("event") == "fleet_drain"
                  and r.get("phase") == "begin"
                  and r.get("replica") == 1]
        assert len(begins) == 1           # double drain, ONE episode

    def test_zero_steady_state_recompiles(self, proc_fleet_run):
        # migration import/export ran from the warmed program set on
        # every replica — including the relaunched child
        assert proc_fleet_run["recompiles"] == [0, 0, 0]

    def test_flight_recorder_salvaged_into_router_trail(
            self, proc_fleet_run):
        assert proc_fleet_run["salvaged"] == 1
        sal = [r for r in proc_fleet_run["events"]
               if r.get("event") == "fleet_flight_salvage"]
        assert sal and sal[0]["replica"] == 0
        assert sal[0]["trigger"] == "replica_death"
        # the black box itself: written by the dying child
        flight = json.load(open(
            os.path.join(proc_fleet_run["fdir"], "flight_r0.json")))
        assert flight["trigger"] == "replica_death"
        assert flight["reason"].startswith("InjectedCrash")

    def test_event_trail_and_obs_report(self, proc_fleet_run):
        kinds = {r.get("event") for r in proc_fleet_run["events"]}
        assert {"fleet_replica_death", "fleet_replica_restart",
                "serve_migration", "fleet_replica_state",
                "fleet_state"} <= kinds
        mig = [r for r in proc_fleet_run["events"]
               if r.get("event") == "serve_migration"]
        assert all(r["nbytes"] > 0 and r["pages"] >= 1 for r in mig)
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize(proc_fleet_run["evdir"])
        proc = s["serving"]["fleet"]["process"]
        assert proc is not None
        assert proc["migrations"]["count"] == \
            proc_fleet_run["migrated_b"]
        assert proc["migrations"]["bytes"] == \
            proc_fleet_run["migration_bytes"]
        assert proc["restarts"] == 1
        assert proc["deaths"] == 1 and proc["salvaged_flights"] == 1
        by_idx = {r["replica"]: r for r in proc["replicas"]}
        assert by_idx[0]["restarts"] == 1
        assert by_idx[0]["last_exit_code"] == 85
        assert by_idx[0]["pid"] is not None
        text = obs_report.render_serve(s)
        assert "process_fleet" in text and "migration" in text
        assert obs_report.main([proc_fleet_run["evdir"],
                                "--serve"]) == 0
        assert obs_report.main([proc_fleet_run["evdir"],
                                "--json"]) == 0

    def test_migration_ledger_in_debug_state(self, proc_fleet_run):
        dbg = proc_fleet_run["debug"]
        assert dbg["migrations"]["total"] == \
            proc_fleet_run["migrated_b"]
        assert dbg["migrations"]["bytes"] > 0
        assert dbg["restarts"] == 1
        assert dbg["salvaged_flights"] == 1


# ===================================================================== #
# fleet-wide distributed tracing (ISSUE 18)
# ===================================================================== #

class TestFleetTracing:
    def test_every_dispatch_carries_a_trace_id(self, proc_fleet_run):
        disp = [r for r in proc_fleet_run["events"]
                if r.get("event") == "fleet_dispatch"]
        assert disp
        assert all(r.get("trace_id") for r in disp)
        by_uid = {}
        for r in disp:
            by_uid.setdefault(r["uid"], set()).add(r["trace_id"])
        # one trace id per client request, however many reroutes
        assert all(len(ids) == 1 for ids in by_uid.values())

    def test_clock_sync_rows_cover_the_fleet(self, proc_fleet_run):
        cs = [r for r in proc_fleet_run["events"]
              if r.get("event") == "clock_sync"]
        # initial sync at launch covers every replica; the post-
        # relaunch re-sync adds more rows
        assert {r["replica"] for r in cs} == {0, 1, 2}
        assert all(r["rtt_ms"] > 0 and r["uncertainty_ms"] >= 0
                   and r["uncertainty_ms"] <= r["rtt_ms"]
                   for r in cs)
        # tiny-model CPU children share our wall clock: the estimated
        # offset must be bounded by the RTT (sanity, not precision)
        assert all(abs(r["offset_ms"]) <= r["rtt_ms"] + 50.0
                   for r in cs)

    def test_migration_rows_share_the_trace_id(self, proc_fleet_run):
        mig = [r for r in proc_fleet_run["events"]
               if r.get("event") == "serve_migration"]
        assert mig and all(r.get("trace_id") for r in mig)

    def test_end_to_end_lineage_single_timeline(self, proc_fleet_run):
        """The acceptance pin: the kill-mid-decode request's scattered
        rows (router log + dead child's log + survivor's log) merge
        into ONE timeline under ONE trace id — submit, prefill on the
        dead replica, migrate_out/migrate_in pair, decode on the
        survivor, finish — with the latency decomposition summing
        exactly."""
        obs_report = _load_tool("obs_report")
        s = obs_report.summarize_fleet(
            [proc_fleet_run["evdir"]] + proc_fleet_run["rdirs"])
        assert s["fleet_schema"] == 1
        # clock offsets were recorded for every replica
        assert set(s["clock_offsets"]) == {"0", "1", "2"}
        migrated = [r for r in s["requests"]
                    if r["migrations"]
                    and any("migrate_out" in h for h in r["hops"])]
        assert migrated, [r["path"] for r in s["requests"]]
        r = migrated[0]
        hops = r["hops"]
        # hop 0: submitted + prefilled on the replica that died
        assert hops[0]["hop"] == 0
        assert hops[0].get("t_submit") is not None
        assert "migrate_out" in hops[0]
        # final hop: resumed and finished on a DIFFERENT replica
        assert hops[-1]["hop"] >= 1
        assert "migrate_in" in hops[-1]
        assert "finish" in hops[-1]
        assert hops[-1]["replica"] != hops[0]["replica"]
        # the migration hop is priced (LinkModel) on the router spine
        assert r["migration_priced_ms"] >= 0.0
        assert r["migrations"][0]["nbytes"] > 0
        # decomposition sums exactly: queue_wait + prefill == ttft
        # (no disagg handoff here) up to the tracer's independent
        # 3-decimal rounding of each term, ttft + decode == latency
        assert r["decomp_exact"] is True
        assert abs(r["replica_queue_ms"] + r["prefill_ms"]
                   - r["ttft_ms"]) < 2e-3
        assert abs(r["ttft_ms"] + r["decode_ms"]
                   - r["latency_ms"]) < 1e-3
        assert r["flags"] == []
        assert s["missing_replica_logs"] == []

    def test_fleet_cli_and_merged_chrome_trace(self, proc_fleet_run,
                                               tmp_path):
        obs_report = _load_tool("obs_report")
        out = str(tmp_path / "fleet_trace.json")
        argv = ["--fleet", proc_fleet_run["evdir"],
                *proc_fleet_run["rdirs"], "--trace-out", out]
        assert obs_report.main(argv) == 0
        assert obs_report.main(argv[:-2] + ["--json"]) == 0
        trace = json.load(open(out))
        meta = [e for e in trace["traceEvents"] if e.get("ph") == "M"]
        names = {e["args"]["name"] for e in meta}
        assert "router" in names
        assert any(n.startswith("replica ") for n in names)
        # one process lane per replica: distinct pids
        pids = {e["pid"] for e in meta}
        assert len(pids) == len(meta)
        assert any(e.get("ph") == "X" for e in trace["traceEvents"])

    def test_rpc_call_beats_reached_the_router_health_plane(
            self, proc_fleet_run):
        # the watchdog never tripped (no stall rows), but the phase
        # vocabulary accepted rpc_call beats throughout the run —
        # a rename would have raised inside the fixture
        stalls = [r for r in proc_fleet_run["events"]
                  if r.get("event") == "stall_detected"]
        assert stalls == []
