# Copyright The DeepSpeed-TPU authors. Licensed under Apache 2.0.
"""Chunked prefill + context-parallel long-prompt serving (ISSUE 19).

The acceptance contract, as tests:

- bitwise greedy parity chunked vs whole-prompt prefill for gpt2 AND
  llama, under continuous batching + prefix reuse + spec-decode;
- context-parallel chunks (ring K/V rotation over the serving mesh)
  keep the same bitwise parity while actually engaging the mesh;
- an over-length prompt is a graceful ``reject_too_long`` with
  chunking OFF and SERVES with chunking ON — never a crash, never a
  silent truncation;
- zero steady-state recompiles under mixed long/short churn (the
  prompt-bucket ladder collapse: one chunk width, any prompt length);
- the trail shows the chunk state machine: one ``serve_prefill_chunk``
  row per chunk, cum_ms monotone, and TTFT decomposing into
  ``queue + prefill`` with the chunk legs inside the prefill leg.
"""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def tiny_gpt2():
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2_params
    cfg = GPT2Config(vocab_size=61, max_position_embeddings=32,
                     hidden_size=32, num_layers=2, num_heads=4,
                     embd_dropout=0.0, attn_dropout=0.0,
                     resid_dropout=0.0)
    return cfg, init_gpt2_params(cfg, jax.random.PRNGKey(3))


def tiny_llama():
    from deepspeed_tpu.models.llama import LlamaConfig, init_llama_params
    cfg = LlamaConfig(vocab_size=61, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=2,
                      max_position_embeddings=32)
    return cfg, init_llama_params(cfg, jax.random.PRNGKey(4))


def family(name):
    return tiny_gpt2() if name == "gpt2" else tiny_llama()


# prompts exercising the mix the parity pin demands: one long prompt
# over every short bucket, a short ride-along, a prefix-sharing sibling
# of the long one (prefix cache reuse), and repetition so the n-gram
# spec drafter actually proposes
LONG = [1, 2, 3, 4] * 5                       # 20 tokens
PROMPTS = [LONG, [5, 6, 7], LONG[:8] + [9, 10], [8, 9, 8, 9, 8, 9]]

CHUNKED_INF = {"max_batch_size": 3, "prompt_buckets": [4],
               "batch_buckets": [2], "max_seq_len": 32,
               "max_new_tokens": 6,
               "paged_kv": {"page_size": 4, "num_pages": 24},
               "chunked_prefill": {"enabled": True, "chunk_tokens": 8}}
# the whole-prompt reference: a ladder tall enough to cover LONG
WHOLE_INF = dict(CHUNKED_INF, prompt_buckets=[4, 24],
                 chunked_prefill={"enabled": False})
SPEC = {"spec_decode": {"enabled": True, "k": 4}}


def serve(cfg, params, icfg, prompts, **eng_kw):
    from deepspeed_tpu.inference import InferenceEngine
    eng = InferenceEngine(cfg, params, icfg, dtype=jnp.float32, **eng_kw)
    eng.warmup()
    outs = eng.generate(prompts, max_new_tokens=6, temperature=0.0)
    rc = eng.steady_state_recompiles
    state = eng.debug_state()
    eng.close()
    return outs, rc, state


# one whole-prompt (spec-decode on) reference run per family, shared by
# the chunked and the context-parallel parity tests — the comparison
# target is identical, recomputing it would only re-pay the warmup
_REF = {}


def whole_prompt_ref(name):
    if name not in _REF:
        cfg, params = family(name)
        outs, rc, _ = serve(cfg, params, dict(WHOLE_INF, **SPEC),
                            PROMPTS)
        assert rc == 0
        _REF[name] = outs
    return _REF[name]


class TestChunkedParity:
    @pytest.mark.parametrize("name", ["gpt2", "llama"])
    def test_bitwise_parity_with_prefix_reuse_and_spec(self, name):
        """Chunked prefill vs whole-prompt prefill: greedy outputs
        bitwise equal for both model families, with the prefix cache
        live and spec-decode verifying drafts on both engines."""
        cfg, params = family(name)
        got, ck_rc, state = serve(cfg, params,
                                  dict(CHUNKED_INF, **SPEC), PROMPTS)
        assert got == whole_prompt_ref(name)
        assert ck_rc == 0
        ck = state["chunked_prefill"]
        assert ck["chunk_tokens"] == 8
        assert ck["dispatches"] > 0          # LONG really went chunked

    @pytest.mark.parametrize("name", ["gpt2", "llama"])
    def test_context_parallel_parity_on_mesh(self, name):
        """CP chunks (ring K/V rotation, 2-way over the conftest's
        virtual 8-device CPU backend) match the unsharded whole-prompt
        engine bitwise — spec-decode still on — and really engaged the
        mesh (no silent fallback)."""
        cfg, params = family(name)
        icfg = dict(CHUNKED_INF, mesh={"axes": {"model": 2}},
                    chunked_prefill={"enabled": True, "chunk_tokens": 8,
                                     "cp_threshold_tokens": 8}, **SPEC)
        got, rc, state = serve(cfg, params, icfg, PROMPTS)
        assert got == whole_prompt_ref(name)
        assert rc == 0
        ck = state["chunked_prefill"]
        assert ck["cp_shards"] == 2
        assert ck["cp_reason"].startswith("ring prefill")
        assert ck["dispatches"] > 0


class TestOverLengthPrompt:
    def test_rejected_gracefully_when_chunking_off(self):
        """A prompt over the largest bucket (or over max_len -
        max_new_tokens) must come back as a FinishedRequest with the
        pinned reason — generate() returns the prompt unextended."""
        from deepspeed_tpu.inference import InferenceEngine, Request
        from deepspeed_tpu.inference.tracing import SHED_REASONS
        assert "reject_too_long" in SHED_REASONS
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(cfg, params, WHOLE_INF, dtype=jnp.float32)
        eng.warmup()
        over = list(range(1, 27))             # 26 > bucket 24
        uid = eng.submit(Request(prompt=over, max_new_tokens=6,
                                 temperature=0.0, seed=0))
        fins = eng.run()
        mine = [f for f in fins if f.uid == uid]
        assert len(mine) == 1
        assert mine[0].finish_reason == "reject_too_long"
        assert mine[0].tokens == [] and mine[0].ttft_ms is None
        # generate() surfaces it as the prompt unextended, not a crash
        outs = eng.generate([over, [5, 6, 7]], max_new_tokens=6,
                            temperature=0.0)
        assert outs[0] == over
        assert len(outs[1]) == 3 + 6
        eng.close()

    def test_served_when_chunking_on(self):
        """The same over-bucket prompt SERVES once chunking is on —
        the ladder ceiling is gone; only max_len and the page pool
        bound admission."""
        cfg, params = tiny_gpt2()
        over = list(range(1, 27))             # 26 tokens, bucket max 4
        outs, rc, state = serve(cfg, params, CHUNKED_INF, [over])
        assert outs[0][:26] == over and len(outs[0]) == 26 + 6
        assert rc == 0
        ck = state["chunked_prefill"]
        assert ck["dispatches"] == math.ceil(26 / 8)
        assert ck["chunking_slots"] == 0      # drained
        assert ck["cp_shards"] == 1           # no mesh configured

    def test_beyond_max_len_rejected_even_with_chunking(self):
        cfg, params = tiny_gpt2()
        from deepspeed_tpu.inference import InferenceEngine, Request
        eng = InferenceEngine(cfg, params, CHUNKED_INF,
                              dtype=jnp.float32)
        uid = eng.submit(Request(prompt=list(range(1, 31)),
                                 max_new_tokens=6))   # 30 + 6 > 32
        fins = eng.step()
        assert [f.uid for f in fins] == [uid]
        assert fins[0].finish_reason == "reject_too_long"
        eng.close()


class TestSteadyState:
    def test_zero_recompiles_under_mixed_churn(self):
        """Waves of long and short prompts landing while earlier ones
        still decode: after warmup, not one new program — prompt length
        is no longer a compile axis."""
        from deepspeed_tpu.inference import InferenceEngine, Request
        cfg, params = tiny_gpt2()
        eng = InferenceEngine(cfg, params, CHUNKED_INF,
                              dtype=jnp.float32)
        eng.warmup()
        rng = np.random.RandomState(9)
        waves = [[rng.randint(1, 61, (n,)).tolist() for n in lens]
                 for lens in ((20, 3), (11, 2, 17), (26,), (5, 22))]
        finished = 0
        pending = list(waves)
        while pending or not eng.scheduler.idle():
            if pending:
                for p in pending.pop(0):
                    eng.submit(Request(prompt=p, max_new_tokens=4,
                                       temperature=0.0, seed=0))
            finished += len(eng.step())
        assert finished == sum(len(w) for w in waves)
        assert eng.steady_state_recompiles == 0
        eng.close()


class TestChunkTrail:
    def test_chunk_rows_and_ttft_decomposition(self, tmp_path):
        """One serve_prefill_chunk row per chunk (ceil(prompt/chunk)),
        ordinals 0..k-1, cum_ms monotone and summing the walls; the
        finish row carries the chunk count; TTFT = queue_wait +
        prefill with every chunk leg inside the prefill leg."""
        from deepspeed_tpu.inference import InferenceEngine, Request
        cfg, params = tiny_gpt2()
        icfg = dict(CHUNKED_INF, events_dir=str(tmp_path))
        eng = InferenceEngine(
            cfg, params, icfg, dtype=jnp.float32,
            observability_config={"serve": {"sample_rate": 1.0}})
        eng.warmup()
        uid = eng.submit(Request(prompt=LONG, max_new_tokens=4,
                                 temperature=0.0, seed=0))
        eng.run()
        eng.close()
        rows = []
        for fn in sorted(os.listdir(tmp_path)):
            if fn.startswith("events"):
                with open(os.path.join(tmp_path, fn)) as fh:
                    rows += [json.loads(line) for line in fh]
        chunks = [r for r in rows
                  if r.get("event") == "serve_prefill_chunk"
                  and r.get("uid") == uid]
        k = math.ceil(len(LONG) / 8)
        assert [c["chunk"] for c in chunks] == list(range(k))
        assert sum(c["tokens"] for c in chunks) == len(LONG)
        cums = [c["cum_ms"] for c in chunks]
        assert cums == sorted(cums)
        assert cums[-1] == pytest.approx(
            sum(c["wall_ms"] for c in chunks), rel=0.05)
        fin = next(r for r in rows if r.get("event") == "serve_finish"
                   and r.get("uid") == uid)
        assert fin["chunks"] == k
        ft = next(r for r in rows
                  if r.get("event") == "serve_first_token"
                  and r.get("uid") == uid)
        # the pinned decomposition: prefill leg = ttft - queue_wait,
        # and the k chunk dispatches all ran inside it
        adm = next(r for r in rows if r.get("event") == "serve_admit"
                   and r.get("uid") == uid)
        assert ft["prefill_ms"] == pytest.approx(
            ft["ttft_ms"] - adm["queue_wait_ms"], abs=0.05)
        assert cums[-1] <= ft["prefill_ms"] + 0.05

    def test_chunk_warmup_plan(self):
        from deepspeed_tpu.inference.buckets import chunk_warmup_plan
        assert chunk_warmup_plan([1, 2], 8) == [(1, 8), (2, 8)]
        assert chunk_warmup_plan([1, 2], 0) == []
