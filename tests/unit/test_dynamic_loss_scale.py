"""Dynamic loss scaler transition tests (mirrors reference
tests/unit/test_dynamic_loss_scale.py: overflow→halving sequences, growth
after scale_window, hysteresis)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.fp16.loss_scaler import (
    DynamicLossScaler, StaticLossScaler, has_overflow)


def test_initial_scale():
    s = DynamicLossScaler(init_scale=2.0**16)
    st = s.init()
    assert float(st.scale) == 2.0**16


def test_overflow_halves():
    s = DynamicLossScaler(init_scale=256.0, delayed_shift=1)
    st = s.init()
    for i in range(3):
        st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 32.0  # 256 / 2^3


def test_min_scale_floor():
    s = DynamicLossScaler(init_scale=4.0, min_scale=1.0, delayed_shift=1)
    st = s.init()
    for _ in range(10):
        st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 1.0


def test_growth_after_window():
    s = DynamicLossScaler(init_scale=256.0, scale_window=5)
    st = s.init()
    for _ in range(5):
        st = s.update(st, jnp.asarray(False))
    assert float(st.scale) == 512.0
    # good_steps resets after growth
    assert int(st.good_steps) == 0


def test_overflow_resets_good_steps():
    s = DynamicLossScaler(init_scale=256.0, scale_window=5, delayed_shift=1)
    st = s.init()
    for _ in range(4):
        st = s.update(st, jnp.asarray(False))
    st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 128.0
    assert int(st.good_steps) == 0


def test_hysteresis_tolerates_overflows():
    s = DynamicLossScaler(init_scale=256.0, delayed_shift=2)
    st = s.init()
    st = s.update(st, jnp.asarray(True))  # first overflow: consume hysteresis
    assert float(st.scale) == 256.0
    st = s.update(st, jnp.asarray(True))  # second: now halve
    assert float(st.scale) == 128.0


def test_static_scaler_never_changes():
    s = StaticLossScaler(128.0)
    st = s.init()
    st = s.update(st, jnp.asarray(True))
    assert float(st.scale) == 128.0


def test_has_overflow():
    good = {"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))}
    assert not bool(has_overflow(good))
    bad_inf = {"a": jnp.array([1.0, jnp.inf]), "b": jnp.zeros((2,))}
    assert bool(has_overflow(bad_inf))
    bad_nan = {"a": jnp.ones((4,)), "b": jnp.array([jnp.nan])}
    assert bool(has_overflow(bad_nan))
